"""Benchmark entry point (run by the driver on real TPU hardware).

ALWAYS prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"mfu", "error"} — even when setup or the run fails (then value=0.0 and
"error" carries the reason), mirroring the reference CI's always-report
benchmark discipline (reference benchmarks/test_collectors_benchmark.py).

Metric: PPO env-steps/sec on a single chip — the fused
collect+GAE+ClipPPO+Adam program (BASELINE.md config #1 path). The
reference publishes no absolute numbers (BASELINE.md: relative CI tracking
only), so ``vs_baseline`` is measured against the BASELINE.md north-star
target of 1M env-steps/s on a v5e-64 pod, i.e. 15625 env-steps/s/chip:
``vs_baseline = value / 15625``.

``mfu`` is an analytic model-FLOPs/s over chip-peak estimate (matmul FLOPs
of actor+critic over rollout + training epochs; tiny MLPs ⇒ tiny MFU — the
number tracks trend, not headline efficiency).
"""

import json
import os
import time
import traceback

_SMOKE = bool(os.environ.get("BENCH_SMOKE"))  # tiny shapes for local checks
NUM_ENVS = 64 if _SMOKE else 2048
ROLLOUT_STEPS = 4 if _SMOKE else 32
FRAMES_PER_BATCH = NUM_ENVS * ROLLOUT_STEPS  # 65536
TRAIN_STEPS = 2 if _SMOKE else 8
NUM_EPOCHS = 4
MINIBATCH = min(8192, FRAMES_PER_BATCH // 2)
PER_CHIP_TARGET = 1_000_000 / 64  # BASELINE.md: 1M steps/s on v5e-64

# Approximate peak dense f32/bf16 FLOP/s by TPU generation (public numbers);
# fall back to a conservative 100 TFLOP/s when the device kind is unknown.
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _model_flops_per_train_step() -> float:
    """Analytic matmul FLOPs of one fused train step.

    Actor MLP 4→64→64→2 and critic 4→64→64→1; fwd = 2*MACs, bwd ≈ 2*fwd.
    Rollout: actor fwd per frame. GAE: critic fwd per frame. Training:
    NUM_EPOCHS passes, each frame through actor+critic fwd+bwd.
    """
    actor_macs = 4 * 64 + 64 * 64 + 64 * 2
    critic_macs = 4 * 64 + 64 * 64 + 64 * 1
    fwd = 2 * (actor_macs + critic_macs)
    rollout = 2 * actor_macs * FRAMES_PER_BATCH
    gae = 2 * critic_macs * FRAMES_PER_BATCH
    train = 3 * fwd * FRAMES_PER_BATCH * NUM_EPOCHS
    return float(rollout + gae + train)


def _report(value=0.0, mfu=0.0, error=None):
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(value / PER_CHIP_TARGET, 3),
                "mfu": round(mfu, 6),
                "error": error,
            }
        ),
        flush=True,
    )


def main():
    import jax

    # This image's sitecustomize re-pins JAX_PLATFORMS=axon at interpreter
    # start, so an env var set by the caller is clobbered; jax.config wins.
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    from rl_tpu.collectors import Collector
    from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
    from rl_tpu.modules import (
        MLP,
        Categorical,
        ProbabilisticActor,
        TDModule,
        ValueOperator,
    )
    from rl_tpu.objectives import ClipPPOLoss
    from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram

    env = TransformedEnv(VmapEnv(CartPoleEnv(), NUM_ENVS), RewardSum())
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2, num_cells=(64, 64)), ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(64, 64)))
    loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=FRAMES_PER_BATCH
    )
    program = OnPolicyProgram(
        coll, loss, OnPolicyConfig(num_epochs=NUM_EPOCHS, minibatch_size=MINIBATCH)
    )

    ts = program.init(jax.random.key(0))
    # NOTE: no donate_argnums — the axon TPU backend rejects donated inputs on
    # a freshly-compiled executable (INVALID_ARGUMENT); donation gains little
    # at this model size.
    step = jax.jit(program.train_step)

    # warmup/compile
    ts, metrics = step(ts)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(TRAIN_STEPS):
        ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    steps_per_sec = TRAIN_STEPS * FRAMES_PER_BATCH / dt

    kind = jax.devices()[0].device_kind
    peak = next((v for k, v in _PEAK_FLOPS.items() if k.lower() in kind.lower()), 100e12)
    mfu = _model_flops_per_train_step() * TRAIN_STEPS / dt / peak
    _report(steps_per_sec, mfu)


def bench_attention():
    """BENCH_MODE=attention: Pallas flash attention vs plain XLA attention,
    forward + full backward (the training path; flash bwd kernels), on the
    real chip (VERDICT round-1 weak #4 — the kernel had never been timed
    on TPU). Reports the flash/XLA speedup; > 1 means the Pallas kernels
    win at this shape."""
    import jax
    import jax.numpy as jnp

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    from rl_tpu.ops.attention import flash_attention

    B, T, H, D = (2, 256, 4, 64) if _SMOKE else (4, 4096, 16, 128)
    dtype = jnp.bfloat16
    interpret = jax.devices()[0].platform == "cpu"  # Mosaic needs a TPU
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), dtype)
    k = jax.random.normal(kk, (B, T, H, D), dtype)
    v = jax.random.normal(kv, (B, T, H, D), dtype)

    def xla_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        causal = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(causal[None, None], s, -1e9)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def run(fn, reps=2 if _SMOKE else 20):
        # forward + FULL backward (dq, dk, dv) — the training path. Time N
        # chained iterations INSIDE one jit call: the axon relay adds tens
        # of ms of per-dispatch latency (and its async block_until_ready is
        # unreliable), so per-call host timing is garbage either way.
        from jax import lax

        def chain(carry0):
            def body(_, carry):
                g = jax.grad(
                    lambda t: fn(*t).astype(jnp.float32).sum()
                )(carry)
                eps = jnp.asarray(1e-8, dtype)
                return tuple(c + gi.astype(dtype) * eps for c, gi in zip(carry, g))
            out = lax.fori_loop(0, reps, body, carry0)
            return sum(o.astype(jnp.float32).sum() for o in out)

        jit_chain = jax.jit(chain)
        float(jit_chain((q, k, v)))  # compile + warm
        t0 = time.perf_counter()
        float(jit_chain((q, k, v)))
        return (time.perf_counter() - t0) / reps

    t_flash = run(
        lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=interpret)
    )
    t_xla = run(xla_attn)
    # causal attention fwd+bwd: (2 fwd + 5 bwd) matmuls x 2*B*H*T^2*D FLOPs
    # each, halved by the causal mask (ideal algorithm FLOPs, recompute not
    # counted — standard MFU accounting)
    flops = 7 * 2 * B * H * T * T * D / 2
    kind = jax.devices()[0].device_kind
    peak = next((v for kk_, v in _PEAK_FLOPS.items() if kk_.lower() in kind.lower()), 100e12)
    print(
        json.dumps(
            {
                "metric": "flash_attention_speedup_vs_xla",
                "value": round(t_xla / t_flash, 3),
                "unit": "x",
                "vs_baseline": round(t_xla / t_flash, 3),
                "flash_ms": round(t_flash * 1e3, 3),
                "xla_ms": round(t_xla * 1e3, 3),
                "flash_mfu": round(flops / t_flash / peak, 4),
                "shape": [B, T, H, D],
                "error": None,
            }
        ),
        flush=True,
    )


def bench_hostenv():
    """BENCH_MODE=hostenv: host-env collection throughput (gymnasium
    CartPole through ThreadedEnvPool + HostCollector with a jitted batched
    MLP policy served per step — the ParallelEnv-analog path; reference
    benchmarks/test_collectors_benchmark.py). vs_baseline compares against
    the reference's async collector throughput band (~4.4k fps, BASELINE.md
    config #6)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    from rl_tpu.collectors import HostCollector, ThreadedEnvPool
    from rl_tpu.envs.libs import GymEnv
    from rl_tpu.modules import MLP

    n_envs = 4 if _SMOKE else 16
    frames = 256 if _SMOKE else 8192
    pool = ThreadedEnvPool([lambda: GymEnv("CartPole-v1") for _ in range(n_envs)])
    net = MLP(out_features=2, num_cells=(64, 64))
    params = net.init(jax.random.key(1), jnp.zeros((1, 4)))["params"]

    def policy(p, td, key):
        logits = net.apply({"params": p}, td["observation"])
        return td.set("action", jax.random.categorical(key, logits))

    coll = HostCollector(pool, policy, frames_per_batch=frames)
    key = jax.random.key(0)
    coll.collect(params, key)  # warm (compile the policy, prime envs)
    t0 = time.perf_counter()
    batch = coll.collect(params, key)
    dt = time.perf_counter() - t0
    pool.close()
    fps = frames / dt
    print(
        json.dumps(
            {
                "metric": "host_env_steps_per_sec",
                "value": round(fps, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(fps / 4400.0, 3),
                "n_envs": n_envs,
                "error": None,
            }
        ),
        flush=True,
    )
    assert np.isfinite(float(batch["next"]["reward"].sum()))


def _watchdog(seconds: float):
    """Emit the failure JSON and hard-exit if the run wedges (e.g. the TPU
    relay hangs inside backend init, where no exception ever surfaces)."""
    import threading

    def fire():
        _report(error=f"bench timed out after {seconds}s (backend hang?)")
        os._exit(1)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


if __name__ == "__main__":
    timer = _watchdog(float(os.environ.get("BENCH_TIMEOUT", "900")))
    mode = os.environ.get("BENCH_MODE", "ppo")
    try:
        {"ppo": main, "attention": bench_attention, "hostenv": bench_hostenv}[mode]()
        timer.cancel()
    except BaseException:  # always emit the JSON line, whatever happened
        _report(error=traceback.format_exc(limit=5))
        raise SystemExit(1)
