"""Benchmark entry point (run by the driver on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: PPO env-steps/sec on a single chip — the fused
collect+GAE+ClipPPO+Adam program (BASELINE.md config #1 path). The
reference publishes no absolute numbers (BASELINE.md: relative CI tracking
only), so ``vs_baseline`` is measured against the BASELINE.md north-star
target of 1M env-steps/s on a v5e-64 pod, i.e. 15625 env-steps/s/chip:
``vs_baseline = value / 15625``.
"""

import json
import time

import jax

from rl_tpu.collectors import Collector
from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.modules import MLP, Categorical, ProbabilisticActor, TDModule, ValueOperator
from rl_tpu.objectives import ClipPPOLoss
from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram

NUM_ENVS = 2048
FRAMES_PER_BATCH = 65536  # 32 steps x 2048 envs
TRAIN_STEPS = 8
PER_CHIP_TARGET = 1_000_000 / 64  # BASELINE.md: 1M steps/s on v5e-64


def main():
    env = TransformedEnv(VmapEnv(CartPoleEnv(), NUM_ENVS), RewardSum())
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2, num_cells=(64, 64)), ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(64, 64)))
    loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=FRAMES_PER_BATCH
    )
    program = OnPolicyProgram(
        coll, loss, OnPolicyConfig(num_epochs=4, minibatch_size=8192)
    )

    ts = program.init(jax.random.key(0))
    # NOTE: no donate_argnums — the axon TPU backend rejects donated inputs on
    # a freshly-compiled executable (INVALID_ARGUMENT); donation gains little
    # at this model size.
    step = jax.jit(program.train_step)

    # warmup/compile
    ts, metrics = step(ts)
    jax.block_until_ready(metrics)

    t0 = time.perf_counter()
    for _ in range(TRAIN_STEPS):
        ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    steps_per_sec = TRAIN_STEPS * FRAMES_PER_BATCH / dt
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec_per_chip",
                "value": round(steps_per_sec, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(steps_per_sec / PER_CHIP_TARGET, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
