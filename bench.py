"""Benchmark entry point (run by the driver on real TPU hardware).

The HEADLINE (PPO env-steps/sec on a single chip — the fused
collect+GAE+ClipPPO+Adam program, BASELINE.md config #1 path) is measured
and printed FIRST, before anything else can fail or overrun (round-3
VERDICT weak #1). The north-star sub-benches (rlhf / pixel / sac / per)
then each run in their OWN subprocess under an explicit slice of the
remaining BENCH_TIMEOUT budget — a wedged or slow sub-bench is killed and
reported as an error field, never costing the headline. The final stdout
line is the headline dict again with the sub-bench results nested, so a
driver reading either the first or the last JSON line gets the real number.

Round-5 outage hardening (round-4 VERDICT weak #1: two rounds of 0.0 from
a hung TPU relay, indistinguishable from "too slow"):

* **Backend probe.** Before any slice is spent, a subprocess calls
  ``jax.devices()`` under a ~45s kill. A hang yields the distinct error
  ``"tpu backend unreachable (init hang)"`` — NOT an overrun — and the
  whole run falls back to clearly-labeled ``BENCH_PLATFORM=cpu``
  ``BENCH_SHAPES=cpu`` runs so a round is never evidence-free. Every
  result line carries ``platform`` and ``shapes`` so a CPU fallback
  number can never be mistaken for a chip number.
* **Persistent compilation cache.** Every sub-bench process points
  ``jax_compilation_cache_dir`` at ``.jax_cache/`` under the repo, so
  across driver runs compile seconds become measurement seconds.
* **Shape tiers.** ``BENCH_SHAPES`` = ``smoke`` (tiny, CI) / ``cpu``
  (medium — sized so the full suite completes on one CPU core; the
  labeled-fallback tier) / ``full`` (chip shapes). ``BENCH_SMOKE=1``
  keeps its old meaning (= smoke tier).

The reference publishes no absolute numbers (BASELINE.md: relative CI
tracking only), so ``vs_baseline`` is measured against the BASELINE.md
north-star target of 1M env-steps/s on a v5e-64 pod, i.e. 15625
env-steps/s/chip: ``vs_baseline = value / 15625``.

``mfu`` on the CartPole headline is tiny by construction (64-wide MLP —
tracks trend only). The MFU-meaningful modes are ``rlhf`` (110M
transformer GRPO step; ``train_mfu`` is a co-headline, target >= 0.30)
and ``pixel`` (Nature-CNN PPO on device-rendered 84x84 frames).
"""

import json
import os
import subprocess
import sys
import time
import traceback

_START = time.monotonic()
_TIMEOUT = float(os.environ.get("BENCH_TIMEOUT", "900"))

_TIER = (os.environ.get("BENCH_SHAPES") or (
    "smoke" if os.environ.get("BENCH_SMOKE") else "full"
)).lower()
if _TIER not in ("smoke", "cpu", "full"):
    # keep the always-emit-JSON contract even for a typo'd env var: the
    # _T selectors below would otherwise KeyError at import, before the
    # watchdog or the __main__ guard exist
    print(json.dumps({
        "metric": "ppo_cartpole_env_steps_per_sec_per_chip", "value": 0.0,
        "unit": "env_steps/s", "vs_baseline": 0.0, "mfu": 0.0,
        "error": f"invalid BENCH_SHAPES={_TIER!r} (want smoke|cpu|full)",
    }), flush=True)
    raise SystemExit(2)
_SMOKE = _TIER == "smoke"
_T = lambda **kw: kw[_TIER]  # noqa: E731 — shape-tier selector

NUM_ENVS = _T(smoke=64, cpu=256, full=2048)
ROLLOUT_STEPS = _T(smoke=4, cpu=16, full=32)
FRAMES_PER_BATCH = NUM_ENVS * ROLLOUT_STEPS  # full: 65536
TRAIN_STEPS = _T(smoke=2, cpu=4, full=8)
NUM_EPOCHS = 4
MINIBATCH = min(8192, FRAMES_PER_BATCH // 2)
PER_CHIP_TARGET = 1_000_000 / 64  # BASELINE.md: 1M steps/s on v5e-64

# Approximate peak dense f32/bf16 FLOP/s by TPU generation (public numbers);
# fall back to a conservative 100 TFLOP/s when the device kind is unknown.
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# HBM bandwidth by generation (public numbers), for the static roofline's
# compute-vs-transfer classification. Unknown device kind -> 0.0: the
# ir_audit section then reports intensity only rather than inventing a
# bandwidth and mislabeling programs as transfer-bound.
_PEAK_BW = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}


def _setup_jax():
    """Per-process JAX init: platform pin + persistent compilation cache.

    This image's sitecustomize re-pins JAX_PLATFORMS=axon at interpreter
    start, so an env var set by the caller is clobbered; jax.config wins.
    The compilation cache lives under the repo so it persists across the
    driver's bench invocations (round-4 VERDICT weak #3).
    """
    import jax

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax without the persistent-cache config flags
    return jax


def _platform_tag(jax) -> dict:
    d = jax.devices()[0]
    return {"platform": d.platform, "shapes": _TIER}


def bench_warmup(step, *, calls=2, assert_no_recompile=False):
    """Shared warm-up timing — ONE helper instead of a per-mode copy of
    the "two warmups" pattern (serve / anakin / multichip grew three).

    Calls ``step()`` ``calls`` times. Call 1 is timed (blocked on) as the
    returned ``compile_s`` — trace+compile for a raw ``jax.jit`` step, or
    an AOT store/memory hit for a :class:`rl_tpu.compile.CachedProgram`,
    which is exactly the cold-start number the compile bench tracks. The
    remaining calls run under :class:`rl_tpu.compile.CompileDelta`:

    * raw-jit callers keep ``calls=2`` — the historical second warmup
      that absorbs the donated-layout recompile before timing starts;
    * registry-backed callers pass ``assert_no_recompile=True`` — AOT
      executables commit layouts at compile time, so call 2 recompiling
      is a hard bug (a silent 2x cold-start tax), not noise to absorb.

    The assertion is skipped when compile counting is unsupported or AOT
    dispatch is disabled (``RL_TPU_NO_AOT`` falls back to plain jit,
    where the layout recompile is expected). Returns
    ``(compile_s, last_result)``; steady state starts at the next call.
    """
    import jax

    from rl_tpu.compile import CompileDelta

    t0 = time.perf_counter()
    out = step()
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    with CompileDelta() as d:
        for _ in range(calls - 1):
            out = step()
        jax.block_until_ready(out)
    if assert_no_recompile and d.supported and not os.environ.get("RL_TPU_NO_AOT"):
        assert d.delta == 0, f"post-warmup recompile: {d.explain()}"
    return compile_s, out


def _model_flops_per_train_step() -> float:
    """Analytic matmul FLOPs of one fused train step.

    Actor MLP 4→64→64→2 and critic 4→64→64→1; fwd = 2*MACs, bwd ≈ 2*fwd.
    Rollout: actor fwd per frame. GAE: critic fwd per frame. Training:
    NUM_EPOCHS passes, each frame through actor+critic fwd+bwd.
    """
    actor_macs = 4 * 64 + 64 * 64 + 64 * 2
    critic_macs = 4 * 64 + 64 * 64 + 64 * 1
    fwd = 2 * (actor_macs + critic_macs)
    rollout = 2 * actor_macs * FRAMES_PER_BATCH
    gae = 2 * critic_macs * FRAMES_PER_BATCH
    train = 3 * fwd * FRAMES_PER_BATCH * NUM_EPOCHS
    return float(rollout + gae + train)


def _headline_dict(value=0.0, mfu=0.0, error=None):
    return {
        "metric": "ppo_cartpole_env_steps_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "env_steps/s",
        "vs_baseline": round(value / PER_CHIP_TARGET, 3),
        "mfu": round(mfu, 6),
        "error": error,
    }


_headline: dict = {}  # filled by main(); read by the watchdog fallback


def _report(value=0.0, mfu=0.0, error=None):
    line = _headline_dict(value, mfu, error)
    line.update(_report_extras)
    print(json.dumps(line), flush=True)


def bench_probe():
    """BENCH_MODE=probe: backend reachability. Initializes JAX (which on
    this image means touching the axon TPU relay unless BENCH_PLATFORM
    overrides) and prints the device identity. The parent runs this under
    a hard ~45s kill: the relay's failure mode is an indefinite hang inside
    backend init — no exception ever surfaces — so only an external
    timeout can distinguish "unreachable" from "slow"."""
    jax = _setup_jax()
    d = jax.devices()[0]
    print(
        json.dumps(
            {
                "platform": d.platform,
                "device_kind": d.device_kind,
                "n_devices": len(jax.devices()),
                "error": None,
            }
        ),
        flush=True,
    )


def main():
    jax = _setup_jax()

    from rl_tpu.collectors import Collector
    from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
    from rl_tpu.modules import (
        MLP,
        Categorical,
        ProbabilisticActor,
        TDModule,
        ValueOperator,
    )
    from rl_tpu.objectives import ClipPPOLoss
    from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram

    env = TransformedEnv(VmapEnv(CartPoleEnv(), NUM_ENVS), RewardSum())
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2, num_cells=(64, 64)), ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(64, 64)))
    loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=FRAMES_PER_BATCH
    )
    program = OnPolicyProgram(
        coll, loss, OnPolicyConfig(num_epochs=NUM_EPOCHS, minibatch_size=MINIBATCH)
    )

    ts = program.init(jax.random.key(0))
    # NOTE: no donate_argnums — the axon TPU backend rejects donated inputs on
    # a freshly-compiled executable (INVALID_ARGUMENT); donation gains little
    # at this model size.
    step = jax.jit(program.train_step)

    # warmup/compile — timed separately so the steady-state number and the
    # one-off compile cost are never conflated (compile_s vs wall_s)
    tc0 = time.perf_counter()
    ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    compile_s = time.perf_counter() - tc0

    t0 = time.perf_counter()
    for _ in range(TRAIN_STEPS):
        ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0

    steps_per_sec = TRAIN_STEPS * FRAMES_PER_BATCH / dt

    mfu = _model_flops_per_train_step() * TRAIN_STEPS / dt / _peak_flops(jax)
    _headline.update(_headline_dict(steps_per_sec, mfu))
    _report_extras.update(_platform_tag(jax))
    _report_extras["compile_s"] = round(compile_s, 2)
    _report(steps_per_sec, mfu)


def bench_pixel(report: bool = True) -> dict:
    """BENCH_MODE=pixel: pixel-observation PPO — Nature-CNN (32/64/64 convs
    + 512 dense) over device-rendered 84x84x4 CartPole frames
    (:class:`rl_tpu.envs.PixelRender`), the whole
    render→conv-rollout→GAE→ClipPPO cycle as ONE jitted program. This is
    the MFU-meaningful on-policy bench (round-4 VERDICT weak #7: the
    64-wide-MLP headline cannot demonstrate MXU utilization; a conv stack
    can). ``vs_baseline`` is vs the same per-chip env-steps north-star
    share; ``mfu`` counts conv+dense matmul FLOPs analytically."""
    jax = _setup_jax()

    from rl_tpu.collectors import Collector
    from rl_tpu.envs import (
        CartPoleEnv,
        PixelRender,
        TransformedEnv,
        VmapEnv,
        cartpole_pixels,
    )
    from rl_tpu.modules import (
        MLP,
        Categorical,
        ConvNet,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        ValueOperator,
    )
    from rl_tpu.objectives import ClipPPOLoss
    from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram

    n_envs = _T(smoke=4, cpu=16, full=256)
    rollout = _T(smoke=4, cpu=8, full=16)
    train_steps = _T(smoke=1, cpu=2, full=4)
    frames = n_envs * rollout
    epochs = 4

    env = TransformedEnv(
        VmapEnv(CartPoleEnv(), n_envs),
        PixelRender(cartpole_pixels, shape=(84, 84, 4), keep_obs=False),
    )

    actor = ProbabilisticActor(
        TDSequential(
            TDModule(ConvNet(), ["pixels"], ["feat"]),
            TDModule(MLP(out_features=2, num_cells=(512,)), ["feat"], ["logits"]),
        ),
        Categorical,
        dist_keys=("logits",),
    )
    critic = TDSequential(
        TDModule(ConvNet(), ["pixels"], ["vfeat"]),
        ValueOperator(MLP(out_features=1, num_cells=(512,)), in_keys=["vfeat"]),
    )
    loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OnPolicyProgram(
        coll,
        loss,
        OnPolicyConfig(num_epochs=epochs, minibatch_size=min(frames, max(32, frames // 4))),
    )
    ts = program.init(jax.random.key(0))
    step = jax.jit(program.train_step)
    tc0 = time.perf_counter()
    ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    compile_s = time.perf_counter() - tc0

    t0 = time.perf_counter()
    for _ in range(train_steps):
        ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    sps = train_steps * frames / dt

    # Analytic conv+dense MACs per frame, Nature CNN on 84x84x4:
    # conv(32,8,8,s4)->20x20, conv(64,4,4,s2)->9x9, conv(64,3,3,s1)->7x7,
    # dense 3136->512, head 512->2 (+1 critic). fwd = 2*MACs.
    conv_macs = (
        20 * 20 * 32 * 8 * 8 * 4
        + 9 * 9 * 64 * 4 * 4 * 32
        + 7 * 7 * 64 * 3 * 3 * 64
        + 3136 * 512
    )
    actor_macs = conv_macs + 512 * 2
    critic_macs = conv_macs + 512 * 1
    per_frame = (
        2 * actor_macs  # rollout fwd
        + 2 * critic_macs  # GAE fwd
        + 3 * 2 * (actor_macs + critic_macs) * epochs  # train fwd+bwd
    )
    mfu = per_frame * frames * train_steps / dt / _peak_flops(jax)
    out = {
        "metric": "pixel_ppo_env_steps_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "env_steps/s",
        "vs_baseline": round(sps / PER_CHIP_TARGET, 3),
        "mfu": round(mfu, 4),
        "n_envs": n_envs,
        "compile_s": round(compile_s, 2),
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_hopper(report: bool = True) -> dict:
    """BENCH_MODE=hopper: PPO env-steps/sec on the native planar Hopper
    (round-4 VERDICT next-step #8 — BASELINE.md config #1 is *MuJoCo*
    steps/s; this is the physics-shaped workload, not CartPole's 4-float
    toy). The Lagrangian dynamics (autodiff mass matrix + contact) run
    INSIDE the fused collect+GAE+ClipPPO program: 5 physics substeps per
    env step, all on device."""
    jax = _setup_jax()

    from rl_tpu.collectors import Collector
    from rl_tpu.envs import HopperEnv, RewardSum, TransformedEnv, VmapEnv
    from rl_tpu.modules import (
        MLP,
        NormalParamExtractor,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        TanhNormal,
        ValueOperator,
    )
    from rl_tpu.objectives import ClipPPOLoss
    from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram

    n_envs = _T(smoke=8, cpu=64, full=512)
    rollout = _T(smoke=4, cpu=16, full=32)
    train_steps = _T(smoke=1, cpu=2, full=6)
    frames = n_envs * rollout

    env = TransformedEnv(VmapEnv(HopperEnv(), n_envs), RewardSum())
    actor = ProbabilisticActor(
        TDSequential(
            TDModule(MLP(out_features=6, num_cells=(256, 256)), ["observation"], ["raw"]),
            TDModule(NormalParamExtractor(), ["raw"], ["loc", "scale"]),
        ),
        TanhNormal,
        dist_keys=("loc", "scale"),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(256, 256)))
    loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OnPolicyProgram(
        coll,
        loss,
        OnPolicyConfig(num_epochs=4, minibatch_size=min(frames, 4096)),
    )
    ts = program.init(jax.random.key(0))
    step = jax.jit(program.train_step)
    tc0 = time.perf_counter()
    ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    compile_s = time.perf_counter() - tc0

    t0 = time.perf_counter()
    for _ in range(train_steps):
        ts, metrics = step(ts)
    jax.block_until_ready(metrics)
    dt = time.perf_counter() - t0
    sps = train_steps * frames / dt
    out = {
        "metric": "hopper_ppo_env_steps_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "env_steps/s",
        "vs_baseline": round(sps / PER_CHIP_TARGET, 3),
        "n_envs": n_envs,
        "physics_substeps_per_sec": round(sps * HopperEnv.FRAME_SKIP, 1),
        "compile_s": round(compile_s, 2),
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_serve(report: bool = True) -> dict:
    """BENCH_MODE=serve: continuous-batching + paged-KV serving throughput
    vs fixed-batch generate at mixed response lengths (the vLLM scenario
    the reference delegates; round-4 VERDICT next-step #6). Reports the
    engine's useful tokens/sec and the speedup over fixed batching on the
    SAME model and request set; >1 means slot admission + paged KV win
    wall-clock, not just work accounting."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.models import ContinuousBatchingEngine, TransformerConfig, TransformerLM, generate

    on_tpu = jax.devices()[0].platform != "cpu"
    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, lengths = 4, [4, 4, 6, 24] * 2
        pmax, bucket = 12, 16
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=2048, d_model=256, n_layers=4,
                                n_heads=4, d_ff=1024, max_seq_len=256,
                                dtype=jnp.float32)
        S, lengths = 4, [8, 8, 12, 96] * 3
        pmax, bucket = 24, 32
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=1024,
                                dtype=jnp.bfloat16,
                                flash_decode=on_tpu)
        S, lengths = 16, [32, 32, 48, 384] * 8
        pmax, bucket = 96, 128
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))), n)
            for n in lengths]
    useful = sum(n for _, n in reqs)

    # decode_chunk="auto": the engine's tuner sizes the chunk from measured
    # chunk wall-time vs host/sync overhead — no per-tier constants. The
    # SAME engine instance runs warm-up and the timed pass so the timed pass
    # reuses compiled decode programs AND an already-converged tuner.
    eng = ContinuousBatchingEngine(
        model, params, n_slots=S, block_size=16,
        n_blocks=S * (cfg.max_seq_len // 16) + 1,
        prompt_buckets=(bucket,), greedy=True,
        decode_chunk="auto",
    )

    def run_engine():
        for p, n in reqs:
            eng.submit(p, n)
        t0 = time.perf_counter()
        out = eng.run()
        return time.perf_counter() - t0, len(out)

    # compile prefill buckets + decode ladder (one traffic round; first-round
    # host-glue ops compile here too, so the timed round is steady state)
    t_warm, _ = bench_warmup(run_engine, calls=1)
    steps0 = eng.decode_steps
    from rl_tpu.compile import CompileDelta

    with CompileDelta() as steady:
        t_engine, n_done = run_engine()
    assert n_done == len(reqs)
    # token-slot work accounting: every decode step computes n_slots rows
    engine_token_slots = (eng.decode_steps - steps0) * S

    def run_fixed():
        t0 = time.perf_counter()
        slots = 0
        for i in range(0, len(reqs), S):
            chunk = reqs[i : i + S]
            maxp = max(len(p) for p, _ in chunk)
            maxn = max(n for _, n in chunk)
            toks = np.zeros((len(chunk), maxp), np.int32)
            mask = np.zeros((len(chunk), maxp), np.float32)
            for j, (p, _) in enumerate(chunk):
                toks[j, maxp - len(p):] = p
                mask[j, maxp - len(p):] = 1.0
            out = generate(model, params, jnp.asarray(toks), jnp.asarray(mask),
                           jax.random.key(i), max_new_tokens=maxn, greedy=True,
                           eos_id=None)
            jax.block_until_ready(out.tokens)
            slots += len(chunk) * maxn
        return time.perf_counter() - t0, slots

    t_fixed_warm, _ = run_fixed()  # compile
    t_fixed, fixed_token_slots = run_fixed()

    out = {
        "metric": "serve_continuous_batching_tokens_per_sec",
        "value": round(useful / t_engine, 1),
        "unit": "tokens/s",
        "vs_baseline": round(t_fixed / t_engine, 3),
        "speedup_vs_fixed_batch": round(t_fixed / t_engine, 3),
        "work_efficiency_token_slots": round(
            fixed_token_slots / max(1, engine_token_slots), 3
        ),
        "decode_chunk": eng.decode_chunk_last,
        # 0 == no silent recompile inside the timed pass; the auto decode
        # chunk tuner MAY legitimately re-chunk here, which this field makes
        # visible instead of reading as latency noise
        "steady_state_compile_delta": steady.delta if steady.supported else None,
        "engine_decode_steps": int(eng.decode_steps - steps0),
        "fixed_tokens_per_sec": round(useful / t_fixed, 1),
        "compile_s": round(t_warm + t_fixed_warm, 2),
        "n_requests": len(reqs),
        "n_slots": S,
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def _compile_worker(report: bool = True) -> dict:
    """One process lifetime of the serving cold-start path (COMPILE_ROLE
    names it ``cold`` or ``warm``): build a 2-engine serving set, run the
    registry AOT warm-up over the full program ladder, then prove fleet
    steady state. The orchestrator runs this twice against ONE sandboxed
    executable store + compilation cache — run 1 populates them (cold),
    run 2 is the supervised-restart scenario where ``lower()`` is skipped
    and executables deserialize from the store (warm)."""
    jax = _setup_jax()
    # the orchestrator sandboxes the jax compilation cache alongside the
    # executable store: the repo-level .jax_cache would otherwise leak
    # warmth from earlier bench invocations into the "cold" run
    cache = os.environ.get("COMPILE_BENCH_CACHE")
    if cache:
        try:
            jax.config.update("jax_compilation_cache_dir", cache)
        except Exception:
            pass
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.compile import CompileDelta
    from rl_tpu.models import (
        ContinuousBatchingEngine,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )

    role = os.environ.get("COMPILE_ROLE", "cold")
    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, pmax = 8, 32, 24
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def mk_engine(i):
        return ContinuousBatchingEngine(
            model, params, n_slots=S, block_size=16,
            n_blocks=S * (cfg.max_seq_len // 16) + 1,
            prompt_buckets=(bucket,), greedy=True, decode_chunk=4, seed=i,
        )

    engines = [mk_engine(i) for i in range(2)]
    t0 = time.perf_counter()
    programs: dict = {}
    for e in engines:
        for name, runs in e.aot_warmup().items():
            rec = programs.setdefault(name, {"s": 0.0, "sources": {}})
            for src, s in runs:
                rec["s"] += s
                rec["sources"][src] = rec["sources"].get(src, 0) + 1
    warmup_s = time.perf_counter() - t0
    for rec in programs.values():
        rec["s"] = round(rec["s"], 4)
    compiles = sum(r["sources"].get("compile", 0) for r in programs.values())
    loads = sum(r["sources"].get("store", 0) for r in programs.values())

    # fleet traffic: warm-up rounds absorb one-time host-glue compiles
    # (tiny unattributed ops on first dispatch). The fleet groups
    # admissions by arrival timing, so a single warm-up round can miss an
    # admit-size-shaped glue op a later round then hits — loop until one
    # full round is compile-free, then the measured round must be too
    # (the ISSUE-10 steady-state acceptance gate).
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))),
             int(rng.integers(4, 10))) for _ in range(3 * S)]
    wait_s = _T(smoke=120, cpu=300, full=300)
    warmup_rounds = 0
    fleet = ServingFleet(engines, max_queue=4 * len(reqs)).start()
    try:
        for _ in range(4):
            warmup_rounds += 1
            with CompileDelta() as glue:
                ids = [fleet.submit(p, n) for p, n in reqs]
                fleet.wait(ids, timeout=wait_s)
            if not glue.supported or glue.delta == 0:
                break
        with CompileDelta() as steady:
            ids = [fleet.submit(p, n) for p, n in reqs]
            done = fleet.wait(ids, timeout=wait_s)
    finally:
        fleet.shutdown()

    steady_ok = (steady.delta == 0) if steady.supported else None
    err = None
    if len(done) != len(ids):
        err = f"fleet completed {len(done)}/{len(ids)} requests"
    elif steady_ok is False:
        err = "steady-state recompile: " + steady.explain()
    out = {
        "metric": "compile_warmup_seconds",
        "value": round(warmup_s, 3),
        "unit": "s",
        "role": role,
        "warmup_s": round(warmup_s, 3),
        "n_programs": len(programs),
        "compiles": compiles,
        "store_loads": loads,
        "programs": programs,
        "steady_state_compile_delta": steady.delta if steady.supported else None,
        "steady_state_ok": steady_ok,
        "traffic_warmup_rounds": warmup_rounds,
        "n_requests": len(reqs),
        "error": err,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_compile(report: bool = True) -> dict:
    """BENCH_MODE=compile: cold vs warm process startup over one sandboxed
    executable store — the ISSUE-10 cold-start headline.

    Two ``_compile_worker`` subprocesses share a fresh store + compilation
    cache: the ``cold`` run pays ``lower().compile()`` for every serving
    program and serializes the executables; the ``warm`` run models a
    supervised restart, deserializing the same programs instead of
    recompiling. Distills ``cold_s`` / ``warm_s`` / the warm speedup
    (acceptance: >= 3x on the cpu tier) and the warm run's fleet
    steady-state compile delta (acceptance: 0)."""
    if os.environ.get("COMPILE_ROLE"):
        return _compile_worker(report)
    import shutil
    import tempfile

    sandbox = tempfile.mkdtemp(prefix="rl_tpu_compile_bench_")
    deadline = _START + _TIMEOUT - 20.0
    roles = ("cold", "warm")
    results: dict = {}
    try:
        for i, role in enumerate(roles):
            remaining = deadline - time.monotonic()
            if remaining <= 10.0:
                results[role] = {"error": "skipped: BENCH_TIMEOUT budget exhausted"}
                continue
            results[role] = _run_sub_bench(
                "compile", remaining / (len(roles) - i), {
                    "COMPILE_ROLE": role,
                    "RL_TPU_EXEC_STORE_DIR": os.path.join(sandbox, "exec_store"),
                    "COMPILE_BENCH_CACHE": os.path.join(sandbox, "jax_cache"),
                },
            )
    finally:
        shutil.rmtree(sandbox, ignore_errors=True)

    cold, warm = results.get("cold", {}), results.get("warm", {})
    cold_s, warm_s = cold.get("warmup_s"), warm.get("warmup_s")
    speedup = round(cold_s / warm_s, 2) if cold_s and warm_s else None
    errors = [f"{k}: {v['error']}" for k, v in results.items() if v.get("error")]
    metrics = {
        "cold_warmup_s": cold_s,
        "warm_warmup_s": warm_s,
        "warm_speedup": speedup,
        "compiles_cold": cold.get("compiles"),
        "store_loads_warm": warm.get("store_loads"),
        "steady_state_compile_delta": warm.get("steady_state_compile_delta"),
    }
    out = {
        "metric": "compile_warm_vs_cold_speedup",
        "value": speedup or 0.0,
        "unit": "x",
        "vs_baseline": speedup or 0.0,
        "cold_s": cold_s,
        "warm_s": warm_s,
        # acceptance gates: warm restart >= 3x and ZERO lower() calls on
        # the warm path (every program deserializes or memory-hits)
        "warm_ok": bool(speedup is not None and speedup >= 3.0),
        "warm_skipped_lowering": (warm.get("compiles") == 0
                                  if "compiles" in warm else None),
        "steady_state_ok": warm.get("steady_state_ok"),
        "steady_state_compile_delta": warm.get("steady_state_compile_delta"),
        "n_programs": warm.get("n_programs") or cold.get("n_programs"),
        "cold": cold,
        "warm": warm,
        "metrics": metrics,
        "platform": warm.get("platform") or cold.get("platform"),
        "shapes": _TIER,
        "error": "; ".join(errors) or None,
    }
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_attention():
    """BENCH_MODE=attention: Pallas flash attention vs plain XLA attention,
    forward + full backward (the training path; flash bwd kernels), on the
    real chip (VERDICT round-1 weak #4 — the kernel had never been timed
    on TPU). Reports the flash/XLA speedup; > 1 means the Pallas kernels
    win at this shape."""
    jax = _setup_jax()
    import jax.numpy as jnp

    from rl_tpu.ops.attention import flash_attention

    B, T, H, D = (2, 256, 4, 64) if _SMOKE else (4, 4096, 16, 128)
    dtype = jnp.bfloat16
    interpret = jax.devices()[0].platform == "cpu"  # Mosaic needs a TPU
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), dtype)
    k = jax.random.normal(kk, (B, T, H, D), dtype)
    v = jax.random.normal(kv, (B, T, H, D), dtype)

    def xla_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        causal = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(causal[None, None], s, -1e9)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def run(fn, reps=2 if _SMOKE else 20):
        # forward + FULL backward (dq, dk, dv) — the training path. Time N
        # chained iterations INSIDE one jit call: the axon relay adds tens
        # of ms of per-dispatch latency (and its async block_until_ready is
        # unreliable), so per-call host timing is garbage either way.
        from jax import lax

        def chain(carry0):
            def body(_, carry):
                g = jax.grad(
                    lambda t: fn(*t).astype(jnp.float32).sum()
                )(carry)
                eps = jnp.asarray(1e-8, dtype)
                return tuple(c + gi.astype(dtype) * eps for c, gi in zip(carry, g))
            out = lax.fori_loop(0, reps, body, carry0)
            return sum(o.astype(jnp.float32).sum() for o in out)

        jit_chain = jax.jit(chain)
        tc0 = time.perf_counter()
        float(jit_chain((q, k, v)))  # compile + warm
        compile_s = time.perf_counter() - tc0
        t0 = time.perf_counter()
        float(jit_chain((q, k, v)))
        return (time.perf_counter() - t0) / reps, compile_s

    t_flash, c_flash = run(
        lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=interpret)
    )
    t_xla, c_xla = run(xla_attn)
    # causal attention fwd+bwd: (2 fwd + 5 bwd) matmuls x 2*B*H*T^2*D FLOPs
    # each, halved by the causal mask (ideal algorithm FLOPs, recompute not
    # counted — standard MFU accounting)
    flops = 7 * 2 * B * H * T * T * D / 2
    kind = jax.devices()[0].device_kind
    peak = next((v for kk_, v in _PEAK_FLOPS.items() if kk_.lower() in kind.lower()), 100e12)
    print(
        json.dumps(
            {
                "metric": "flash_attention_speedup_vs_xla",
                "value": round(t_xla / t_flash, 3),
                "unit": "x",
                "vs_baseline": round(t_xla / t_flash, 3),
                "flash_ms": round(t_flash * 1e3, 3),
                "xla_ms": round(t_xla * 1e3, 3),
                "flash_mfu": round(flops / t_flash / peak, 4),
                "shape": [B, T, H, D],
                "compile_s": round(c_flash + c_xla, 2),
                "error": None,
            }
        ),
        flush=True,
    )


def bench_hostenv():
    """BENCH_MODE=hostenv: host-env collection throughput (gymnasium
    CartPole through ThreadedEnvPool + HostCollector with a jitted batched
    MLP policy served per step — the ParallelEnv-analog path; reference
    benchmarks/test_collectors_benchmark.py). vs_baseline compares against
    the reference's async collector throughput band (~4.4k fps, BASELINE.md
    config #6)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.collectors import HostCollector, ThreadedEnvPool
    from rl_tpu.envs.libs import GymEnv
    from rl_tpu.modules import MLP

    n_envs = 4 if _SMOKE else 16
    frames = 256 if _SMOKE else 8192
    pool = ThreadedEnvPool([lambda: GymEnv("CartPole-v1") for _ in range(n_envs)])
    net = MLP(out_features=2, num_cells=(64, 64))
    params = net.init(jax.random.key(1), jnp.zeros((1, 4)))["params"]

    def policy(p, td, key):
        logits = net.apply({"params": p}, td["observation"])
        return td.set("action", jax.random.categorical(key, logits))

    coll = HostCollector(pool, policy, frames_per_batch=frames)
    key = jax.random.key(0)
    tc0 = time.perf_counter()
    coll.collect(params, key)  # warm (compile the policy, prime envs)
    compile_s = time.perf_counter() - tc0
    t0 = time.perf_counter()
    batch = coll.collect(params, key)
    dt = time.perf_counter() - t0
    pool.close()
    fps = frames / dt
    print(
        json.dumps(
            {
                "metric": "host_env_steps_per_sec",
                "value": round(fps, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(fps / 4400.0, 3),
                "n_envs": n_envs,
                "compile_s": round(compile_s, 2),
                "error": None,
            }
        ),
        flush=True,
    )
    assert np.isfinite(float(batch["next"]["reward"].sum()))


def _peak_flops(jax) -> float:
    kind = jax.devices()[0].device_kind
    return next(
        (v for k, v in _PEAK_FLOPS.items() if k.lower() in kind.lower()), 100e12
    )


def _peak_bw(jax) -> float:
    env = float(os.environ.get("RL_TPU_PEAK_BYTES_PER_S", "0") or 0.0)
    if env > 0:
        return env
    kind = jax.devices()[0].device_kind
    return next((v for k, v in _PEAK_BW.items() if k.lower() in kind.lower()), 0.0)


def _ir_audit_section(jax, prefix: str = "") -> dict:
    """PR-15 deep-tier roll-up for a bench's output: every program the
    default ProgramRegistry compiled during this bench was audited
    (R101-R105) at lowering time; here the static roofline prediction is
    paired with the PR-12 sampled device-time attribution so the
    committed AUDIT artifact shows predicted vs measured MFU side by
    side. ``findings`` must come out 0 — a real finding fails the tier-1
    gate long before a bench runs; the section records that proof next
    to the perf numbers it certifies. ``prefix`` scopes to one program
    family (bench-mode ``all`` runs every sub-bench in one artifact)."""
    from rl_tpu.analysis.ir import get_ir_auditor, roofline
    from rl_tpu.compile import get_program_registry

    section: dict = {"programs_audited": 0, "findings": 0, "by_program": {}}
    aud = get_ir_auditor(create=False)
    if aud is None:
        return section
    peak, bw = _peak_flops(jax), _peak_bw(jax)
    stats = get_program_registry().stats()
    reps: dict = {}
    for rep in aud._snapshot():
        if prefix and not rep.name.startswith(prefix):
            continue
        # one row per (program, fingerprint): last signature wins, but
        # distinct lowerings sharing a name (e.g. the f32 and int8-cache
        # engines' decode) each keep their row instead of shadowing
        reps[(rep.name, getattr(rep, "fingerprint", ""))] = rep
    rows: dict = {}
    for (name, _fp), rep in sorted(reps.items()):
        key, n = name, 2
        while key in rows:
            key, n = f"{name}#{n}", n + 1
        rows[key] = rep
    by_kernel: dict = {}
    for name, rep in rows.items():
        rec: dict = {"findings": len(rep.findings)}
        cost = rep.cost
        if cost is not None:
            rl = roofline(cost, peak, bw)
            rec["flops"] = cost.flops
            rec["bytes"] = cost.bytes
            rec["intensity"] = round(rl.get("intensity", 0.0), 3)
            if bw > 0:
                # the roofline MFU ceiling is trivially 1.0 without a byte
                # term, so it only rides when the bandwidth is known
                rec["predicted_mfu"] = round(rl.get("predicted_mfu", 0.0), 6)
                rec["bound"] = rl.get("bound")
                rec["transfer_bound"] = bool(rl.get("transfer_bound"))
        # stats are keyed by bare program name (shared across the
        # lowerings a #-suffixed row disambiguates)
        s = stats.get(name.split("#")[0]) or {}
        dev_s = float(s.get("device_s") or 0.0)
        dev_fl = float(s.get("device_flops") or 0.0)
        if dev_s > 0 and dev_fl > 0:
            rec["measured_mfu"] = round(dev_fl / dev_s / peak, 6)
        # programs lowered with registered Pallas kernels carry the kernel
        # names, and each kernel gets a predicted-vs-measured roll-up row
        # (the cost above already prices the kernel's custom-calls via
        # rl_tpu.kernels.registry.price_call)
        sites = getattr(getattr(rep, "facts", None), "kernel_sites", None)
        if sites:
            kernels = sorted({k for _t, k, _p in sites if k})
            if kernels:
                rec["kernels"] = kernels
                for kname in kernels:
                    row = by_kernel.setdefault(kname, {"programs": {}})
                    row["programs"][name] = {
                        k: rec[k]
                        for k in ("predicted_mfu", "measured_mfu", "intensity")
                        if k in rec
                    }
        section["by_program"][name] = rec
        section["findings"] += rec["findings"]
    if by_kernel:
        section["by_kernel"] = by_kernel
    section["programs_audited"] = len(reps)
    return section


def bench_rlhf(report: bool = True) -> dict:
    """BENCH_MODE=rlhf: the CO-HEADLINE metric (BASELINE.md config #5,
    reference examples/rlhf/train_rlhf.py + benchmarks/test_llm.py).

    One full RLHF cycle on a GPT-2-small-scale TransformerLM (~110M params,
    bf16, flash attention): KV-cache rollout of 512 response tokens from a
    512-token prompt, then one GRPO update over the full [B, 1024] batch.
    Reports end-to-end tokens/sec/chip; ``train_mfu`` is the GRPO train
    step's model-FLOPs utilization (the VERDICT round-2 target: >= 0.30);
    ``vs_baseline`` = train_mfu / 0.30. The ``cpu`` shape tier runs a ~19M
    model at T=256 (a 110M at T=1024 does not fit a single-core-CPU slice)
    — the ``n_params``/``shape`` fields plus ``platform``/``shapes`` label
    it unambiguously."""
    jax = _setup_jax()
    import jax.numpy as jnp

    import numpy as np
    import optax

    from rl_tpu.data import ArrayDict
    from rl_tpu.models import (
        TransformerConfig,
        TransformerLM,
        generate,
        token_log_probs,
    )
    from rl_tpu.models.generate import generate_flops, train_step_flops
    from rl_tpu.models.serving import ContinuousBatchingEngine
    from rl_tpu.obs import DeviceMetrics
    from rl_tpu.objectives.llm.grpo import GRPOLoss, mc_advantage
    from rl_tpu.trainers.grpo import RolloutPipeline
    from rl_tpu.weight_update.schemes import DevicePutScheme

    on_tpu = jax.devices()[0].platform != "cpu"
    if _TIER == "smoke":
        B, Tp, Tn = 2, 32, 32
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=2, d_ff=512,
            max_seq_len=Tp + Tn, dtype=jnp.bfloat16,
            attention_impl="flash" if on_tpu else "local",
        )
    elif _TIER == "cpu":
        B, Tp, Tn = 4, 128, 128
        cfg = TransformerConfig(
            vocab_size=8192, d_model=384, n_layers=6, n_heads=6, d_ff=1536,
            max_seq_len=Tp + Tn, dtype=jnp.bfloat16,
            attention_impl="flash" if on_tpu else "local",
        )
    else:
        B, Tp, Tn = 16, 512, 512
        # flash_decode=False: at S=1024 the cache fits 2 pallas blocks and
        # grid overhead beats the bandwidth saving (measured 4.1k vs 4.9k
        # tok/s); the decode kernel pays off on long caches, not here
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12, d_ff=3072,
            max_seq_len=Tp + Tn, dtype=jnp.bfloat16,
            attention_impl="flash" if on_tpu else "local",
        )
    T = Tp + Tn
    model = TransformerLM(cfg)
    key = jax.random.key(0)
    params = model.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))

    opt = optax.adamw(3e-5)
    opt_state = opt.init(params)
    loss = GRPOLoss(
        lambda p, b: token_log_probs(model, p, b["tokens"]), clip_epsilon=0.2
    )

    prompts = jax.random.randint(key, (B, Tp), 0, cfg.vocab_size)
    pmask = jnp.ones((B, Tp), jnp.float32)

    eos_id = 0  # a real stop id: rows that sample it stop accruing mask

    @jax.jit
    def rollout(params, key):
        out = generate(
            model, params, prompts, pmask, key, max_new_tokens=Tn, eos_id=eos_id
        )
        lp = jnp.concatenate(
            [jnp.zeros((B, Tp)), out.response_log_probs], axis=1
        )
        amask = jnp.concatenate(
            [jnp.zeros((B, Tp), bool), out.response_mask], axis=1
        )
        return out.tokens, lp, amask

    @jax.jit
    def train_step(params, opt_state, tokens, sample_lp, amask, key):
        reward = jax.random.normal(key, (B,))
        adv = mc_advantage(reward, jnp.arange(B) // 4, max(1, (B + 3) // 4))
        batch = ArrayDict(
            tokens=tokens, sample_log_prob=sample_lp,
            assistant_mask=amask, advantage=adv,
        )
        (v, m), g = jax.value_and_grad(
            lambda p: loss(p, batch), has_aux=True
        )(params)
        upd, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, upd), opt_state, v

    # the framework's actual update path (GRPOTrainer._update_impl shape):
    # ONE donated dispatch, gradient-accumulation scan over microbatches
    # with token-count weighting, step metrics accumulated on device
    mbs = max(1, B // 2)
    n_mb = B // mbs
    dm_spec = DeviceMetrics(counters=("updates", "tokens"), gauges=("loss",))

    def _mb_train(params, opt_state, dm, tokens, sample_lp, amask, key):
        reward = jax.random.normal(key, (B,))
        adv = mc_advantage(reward, jnp.arange(B) // 4, max(1, (B + 3) // 4))
        full = dict(
            tokens=tokens, sample_log_prob=sample_lp,
            assistant_mask=amask, advantage=adv,
        )
        xs = jax.tree.map(
            lambda x: x.reshape((n_mb, mbs) + x.shape[1:]), full
        )

        def body(carry, mb):
            gsum, vsum, wsum = carry
            w = loss.microbatch_weight(mb)
            (v, _), g = jax.value_and_grad(
                lambda p: loss(p, mb), has_aux=True
            )(params)
            gsum = jax.tree.map(lambda a, b: a + w * b, gsum, g)
            return (gsum, vsum + w * v, wsum + w), None

        zero = jnp.zeros((), jnp.float32)
        (gsum, vsum, wsum), _ = jax.lax.scan(
            body, (jax.tree.map(jnp.zeros_like, params), zero, zero), xs
        )
        wsum = jnp.maximum(wsum, 1e-8)
        g = jax.tree.map(lambda a: a / wsum, gsum)
        upd, opt_state = opt.update(g, opt_state, params)
        dm = dm_spec.inc(dm, "updates", 1.0)
        dm = dm_spec.inc(dm, "tokens", jnp.sum(amask.astype(jnp.float32)))
        dm = dm_spec.set_gauge(dm, "loss", vsum / wsum)
        return optax.apply_updates(params, upd), opt_state, dm

    mb_train = jax.jit(_mb_train, donate_argnums=(1,))

    # warm/compile the three programs
    k1, k2 = jax.random.split(key)
    tc0 = time.perf_counter()
    tokens, lp, amask = rollout(params, k1)
    params2, opt_state2, v = train_step(params, opt_state, tokens, lp, amask, k2)
    dm = dm_spec.init()
    os_live = jax.tree.map(jnp.copy, opt_state)  # mb_train donates its opt state
    p_live, os_live, dm = mb_train(params, os_live, dm, tokens, lp, amask, k2)
    jax.block_until_ready(v)
    jax.block_until_ready(jax.tree.leaves(p_live)[0])
    compile_s = time.perf_counter() - tc0

    reps = 1 if _TIER != "full" else 3
    # time generation and training separately (different bound regimes),
    # then report the fused cycle
    t0 = time.perf_counter()
    for i in range(reps):
        tokens, lp, amask = rollout(params, jax.random.key(10 + i))
    jax.block_until_ready(tokens)
    t_gen = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for i in range(reps):
        params2, opt_state2, v = train_step(
            params, opt_state, tokens, lp, amask, jax.random.key(20 + i)
        )
    jax.block_until_ready(v)
    t_train_single = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for i in range(reps):
        p_live, os_live, dm = mb_train(
            params, os_live, dm, tokens, lp, amask, jax.random.key(20 + i)
        )
    jax.block_until_ready(jax.tree.leaves(p_live)[0])
    t_train = (time.perf_counter() - t0) / reps  # headline: microbatched

    train_flops = train_step_flops(cfg, n_params, B, T)
    peak = _peak_flops(jax)
    train_mfu = train_flops / t_train / peak
    gen_mfu = generate_flops(cfg, n_params, B, Tp, Tn) / t_gen / peak

    # -- pipelined leg: engine rollout (per-request budgets stop decode at
    # max(budget) steps, not Tn) overlapping the donated update via
    # RolloutPipeline + DevicePutScheme. On a 1-core CPU slice the XLA
    # programs serialize (overlap_frac ~ 0) and the win is structural —
    # fewer decode steps + no blocking host syncs; on TPU generation and
    # update overlap and overlap_frac reports how much.
    # per-request response budgets: realistic rollouts stop at eos well
    # before the cap, with varied lengths across the batch. The engine's
    # on-device budget/eos stop means decode ends at max(budget) steps;
    # the fixed-batch leg's static scan always pays Tn. max = 0.625*Tn.
    budgets = [max(1, int(Tn * f)) for f in (0.625, 0.375, 0.5, 0.4375)]
    chunk = max(1, Tn // 8)
    slots = min(B, 8)
    eng = ContinuousBatchingEngine(
        model, params,
        n_slots=slots, block_size=16,
        n_blocks=slots * (-(-T // 16)) + 1,
        prompt_buckets=(Tp,), eos_id=eos_id,
        temperature=1.0, seed=0, decode_chunk=chunk,
    )
    scheme = DevicePutScheme(jax.devices()[0])
    scheme.push(params)
    prompts_np = np.asarray(prompts)
    gen_times: list = []

    def collect_fn(p, k):
        tg0 = time.perf_counter()
        eng.params = p
        eng._key = jax.random.fold_in(k, 0)
        rids = [
            eng.submit(prompts_np[i], budgets[i % len(budgets)])
            for i in range(B)
        ]
        rid_row = {r: i for i, r in enumerate(rids)}
        resp = np.zeros((B, Tn), np.int32)
        rlp = np.zeros((B, Tn), np.float32)
        rm = np.zeros((B, Tn), bool)

        def absorb(done):
            for rid, f in done.items():
                i = rid_row.pop(rid)
                n = len(f.tokens)
                resp[i, :n] = f.tokens
                rlp[i, :n] = f.log_probs
                rm[i, :n] = True

        while eng.step():
            absorb(eng.harvest())
        absorb(eng.harvest())
        toks = jnp.concatenate([prompts, jnp.asarray(resp)], axis=1)
        slp = jnp.concatenate(
            [jnp.zeros((B, Tp)), jnp.asarray(rlp)], axis=1
        )
        am = jnp.concatenate(
            [jnp.zeros((B, Tp), bool), jnp.asarray(rm)], axis=1
        )
        gen_times.append(time.perf_counter() - tg0)
        return toks, slp, am

    pipe = RolloutPipeline(scheme, collect_fn, jax.random.key(7)).start()
    p_live = params
    # warm TWO pipelined cycles: the engine compiles on the first collect
    # and again on the second (first collect against re-placed weights)
    for j in range(2):
        (ptok, plp, pam), _ = pipe.get()
        p_live, os_live, dm = mb_train(
            p_live, os_live, dm, ptok, plp, pam, jax.random.key(30 + j)
        )
        scheme.push(p_live)
        jax.block_until_ready(jax.tree.leaves(p_live)[0])

    reps_p = 2 if _TIER == "smoke" else 3
    stale_max = 0
    t0 = time.perf_counter()
    for i in range(reps_p):
        (ptok, plp, pam), ver = pipe.get()
        stale_max = max(stale_max, scheme.version - ver)
        p_live, os_live, dm = mb_train(
            p_live, os_live, dm, ptok, plp, pam, jax.random.key(40 + i)
        )
        scheme.push(p_live)
        DeviceMetrics.drain_async(dm)  # lagged drain: never blocks the update
    jax.block_until_ready(jax.tree.leaves(p_live)[0])
    cycle_p = (time.perf_counter() - t0) / reps_p
    pipe.stop()
    gen_p = sum(gen_times[-reps_p:]) / reps_p
    overlap_frac = max(
        0.0, (gen_p + t_train - cycle_p) / max(1e-9, min(gen_p, t_train))
    )
    dm_flat = dm_spec.to_flat(DeviceMetrics.drain(dm))

    cycle = t_gen + t_train
    toks_per_sec = B * T / cycle  # full-batch tokens through one RLHF cycle
    out = {
        "metric": "rlhf_tokens_per_sec_per_chip",
        "value": round(toks_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(train_mfu / 0.30, 3),
        "train_mfu": round(train_mfu, 4),
        "train_mfu_single": round(train_flops / t_train_single / peak, 4),
        "gen_mfu": round(gen_mfu, 4),
        "gen_tokens_per_sec": round(B * Tn / t_gen, 1),
        "train_tokens_per_sec": round(B * T / t_train, 1),
        "microbatch": [n_mb, mbs],
        "n_params": n_params,
        "shape": [B, Tp, Tn],
        "compile_s": round(compile_s, 2),
        "pipeline": {
            "value": round(B * T / cycle_p, 1),
            "unit": "tokens/s",
            "cycle_s": round(cycle_p, 4),
            "gen_s": round(gen_p, 4),
            "train_s": round(t_train, 4),
            "overlap_frac": round(overlap_frac, 3),
            "budgets": budgets,
            "staleness_max": int(stale_max),
        },
        "metrics": {"train": dm_flat, "engine": eng.metrics_snapshot()},
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_sac(report: bool = True) -> dict:
    """BENCH_MODE=sac: SAC with on-device replay (BASELINE.md config #2,
    reference sota-implementations/sac/): the fused collect -> extend ->
    sample -> update train step as ONE jitted program on a native
    continuous-control env. Reports env-steps/sec/chip; ``vs_baseline``
    relative to the same per-chip north-star share as the ppo mode."""
    jax = _setup_jax()

    import jax.numpy as jnp

    from rl_tpu.collectors import Collector
    from rl_tpu.data.replay import DeviceStorage, ReplayBuffer
    from rl_tpu.envs import PendulumEnv, VmapEnv
    from rl_tpu.modules import (
        MLP,
        ConcatMLP,
        NormalParamExtractor,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        TanhNormal,
    )
    from rl_tpu.objectives import SACLoss
    from rl_tpu.trainers import OffPolicyConfig, OffPolicyProgram

    n_envs = _T(smoke=8, cpu=64, full=256)
    frames = _T(smoke=64, cpu=512, full=2048)
    cells = _T(smoke=(64,), cpu=(128, 128), full=(256, 256))
    act_dim = 1
    actor = ProbabilisticActor(
        TDSequential(
            TDModule(MLP(out_features=2 * act_dim, num_cells=cells),
                     ["observation"], ["raw"]),
            TDModule(NormalParamExtractor(), ["raw"], ["loc", "scale"]),
        ),
        TanhNormal,
        dist_keys=("loc", "scale"),
    )
    sac = SACLoss(actor, ConcatMLP(out_features=1, num_cells=cells))
    env = VmapEnv(PendulumEnv(), n_envs)

    def policy(params, td, key):
        return sac.actor(params["actor"], td, key)

    coll = Collector(env, policy, frames_per_batch=frames)
    buffer = ReplayBuffer(DeviceStorage(100_000))
    program = OffPolicyProgram(
        coll, sac, buffer,
        OffPolicyConfig(batch_size=256, utd_ratio=4, learning_rate=3e-4),
    )
    ts = program.init(jax.random.key(0))
    step = jax.jit(program.train_step)
    tc0 = time.perf_counter()
    ts, m = step(ts)
    jax.block_until_ready(m)
    compile_s = time.perf_counter() - tc0
    reps = _T(smoke=2, cpu=4, full=8)
    t0 = time.perf_counter()
    for _ in range(reps):
        ts, m = step(ts)
    jax.block_until_ready(m)
    dt = time.perf_counter() - t0
    sps = reps * frames / dt
    out = {
        "metric": "sac_device_replay_env_steps_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "env_steps/s",
        "vs_baseline": round(sps / PER_CHIP_TARGET, 3),
        "grad_updates_per_sec": round(reps * 4 / dt, 2),
        "loss": float(jnp.asarray(m["loss"])),
        "compile_s": round(compile_s, 2),
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def _per_end_to_end(jax) -> tuple[dict, float]:
    """End-to-end PER: the SAME fused SAC train step (collect -> extend ->
    UTD x (sample -> grad -> polyak)) run two ways — the jit-resident
    PrioritizedSampler in-program vs the host C++ segment tree driving
    sampling and priority write-back from outside the program (one
    device->host td_error sync + one index/weight upload per update, the
    reference's architecture). The micro cycle above isolates the sampler;
    this measures what the sampler placement does to a whole train step.
    Returns (report fields, compile seconds)."""
    import numpy as np
    import jax.numpy as jnp
    import optax

    from rl_tpu.collectors import Collector
    from rl_tpu.csrc import SumSegmentTree
    from rl_tpu.data.replay import DeviceStorage, ReplayBuffer
    from rl_tpu.data.replay.samplers import PrioritizedSampler
    from rl_tpu.envs import PendulumEnv, VmapEnv
    from rl_tpu.modules import (
        MLP,
        ConcatMLP,
        NormalParamExtractor,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        TanhNormal,
    )
    from rl_tpu.objectives import SACLoss
    from rl_tpu.trainers import OffPolicyConfig, OffPolicyProgram

    n_envs = _T(smoke=4, cpu=16, full=64)
    frames = _T(smoke=16, cpu=64, full=256)
    bs = _T(smoke=32, cpu=128, full=256)
    utd = 4
    cap = _T(smoke=2048, cpu=8192, full=1 << 15)
    reps = _T(smoke=1, cpu=3, full=6)
    cells = (64, 64)

    actor = ProbabilisticActor(
        TDSequential(
            TDModule(MLP(out_features=2, num_cells=cells), ["observation"], ["raw"]),
            TDModule(NormalParamExtractor(), ["raw"], ["loc", "scale"]),
        ),
        TanhNormal,
        dist_keys=("loc", "scale"),
    )
    sac = SACLoss(actor, ConcatMLP(out_features=1, num_cells=cells))
    env = VmapEnv(PendulumEnv(), n_envs)
    coll = Collector(
        env, lambda p, td, k: sac.actor(p["actor"], td, k), frames_per_batch=frames
    )
    cfg_op = OffPolicyConfig(batch_size=bs, utd_ratio=utd, learning_rate=3e-4)
    sampler = PrioritizedSampler()

    # -- device: PER lives inside the one jitted program -----------------------
    dev_prog = OffPolicyProgram(
        coll,
        sac,
        ReplayBuffer(DeviceStorage(cap), sampler=sampler),
        cfg_op,
        priority_key="td_error",
    )
    ts = dev_prog.init(jax.random.key(1))
    dstep = jax.jit(dev_prog.train_step)
    tc0 = time.perf_counter()
    ts, m = dstep(ts)
    jax.block_until_ready(m)
    compile_s = time.perf_counter() - tc0
    t0 = time.perf_counter()
    for _ in range(reps):
        ts, m = dstep(ts)
    jax.block_until_ready(m)
    t_dev = (time.perf_counter() - t0) / reps

    # -- host: same update math, sampling + priorities through the C++ tree ----
    host_buf = ReplayBuffer(DeviceStorage(cap))
    hprog = OffPolicyProgram(coll, sac, host_buf, cfg_op)
    hts = hprog.init(jax.random.key(1))

    @jax.jit
    def h_collect_extend(params, cstate, bstate):
        batch, cstate = coll.collect(params, cstate)
        bstate = host_buf.extend(bstate, hprog._flatten(batch), n=frames)
        return cstate, bstate

    @jax.jit
    def h_update(params, opt_state, storage, idx, weight, key):
        mb = host_buf.storage.get(storage, idx)
        mb = mb.set("index", idx).set("_weight", weight)
        _, grads, metrics = sac.grad(params, mb, key)
        updates, opt_state = hprog.optimizer.update(
            grads, opt_state, sac.trainable(params)
        )
        params = sac.merge(
            optax.apply_updates(sac.trainable(params), updates), params
        )
        params = hprog.target_update(params)
        return params, opt_state, metrics["td_error"]

    tree = SumSegmentTree(cap)
    prios = np.zeros(cap, np.float64)  # host mirror of p^alpha (tree has no read)
    rng = np.random.default_rng(1)
    alpha, beta, eps_p = sampler.alpha, sampler.beta0, sampler.eps

    state = {
        "params": hts["params"], "opt": hts["opt"],
        "collector": hts["collector"], "buffer": hts["buffer"],
        "wpos": 0, "size": 0, "key": jax.random.key(2),
    }

    def host_step(st):
        cstate, bstate = h_collect_extend(st["params"], st["collector"], st["buffer"])
        new_idx = (st["wpos"] + np.arange(frames)) % cap
        pa = (1.0 + eps_p) ** alpha  # new items at max priority (PER convention)
        prios[new_idx] = pa
        tree[new_idx] = pa
        wpos, size = st["wpos"] + frames, min(st["size"] + frames, cap)
        params, opt_state, key = st["params"], st["opt"], st["key"]
        for _ in range(utd):
            key, k = jax.random.split(key)
            us = rng.uniform(0.0, tree.reduce(), bs)
            idx = tree.scan(us)
            p = np.maximum(prios[idx], 1e-12)
            w = (size * p / tree.reduce()) ** (-beta)
            w = (w / w.max()).astype(np.float32)
            params, opt_state, td = h_update(
                params, opt_state, bstate["storage"],
                jnp.asarray(idx, jnp.int32), jnp.asarray(w), k,
            )
            td_np = np.asarray(td)  # the per-update device->host sync
            pa_new = (np.abs(td_np) + eps_p) ** alpha
            prios[idx] = pa_new
            tree[idx] = pa_new
        return {
            "params": params, "opt": opt_state, "collector": cstate,
            "buffer": bstate, "wpos": wpos, "size": size, "key": key,
        }

    tc0 = time.perf_counter()
    state = host_step(state)  # compile collect_extend + update
    compile_s += time.perf_counter() - tc0
    t0 = time.perf_counter()
    for _ in range(reps):
        state = host_step(state)
    jax.block_until_ready(state["params"])
    t_host = (time.perf_counter() - t0) / reps

    return (
        {
            "e2e_device_ms_per_step": round(t_dev * 1e3, 2),
            "e2e_host_tree_ms_per_step": round(t_host * 1e3, 2),
            "e2e_step_time_ratio": round(t_host / t_dev, 3),
            "e2e_frames_per_batch": frames,
            "e2e_utd": utd,
        },
        compile_s,
    )


def bench_per(report: bool = True) -> dict:
    """BENCH_MODE=per: on-device prioritized replay vs the host C++ segment
    tree (BASELINE.md config #3's target: on-device PER >= host tree),
    measured three ways:

    - **device**: the flat level-array PrioritizedSampler fully in-program —
      the fused ``sample_and_update`` cycle (sample → gather the batch →
      td-error → priority write-back, all inside one ``fori_loop``), plus
      sample-only and update-only splits;
    - **host pure loop**: the native SumSegmentTree driven entirely
      host-side, device never involved — the sampler microcosm (this is
      what the old bench measured, kept for transparency);
    - **host in-program**: the tree serving a DEVICE learner, which is what
      a real trainer pays — indices upload, the device gathers the batch
      and produces td-errors, those download (blocking) to update the tree.

    The headline ``per_on_device_speedup_vs_host_tree`` is
    host_in-program / device_fused: both sides do the same work (sample by
    priority, gather, derive new priorities, write back); only the sampler
    placement differs. ``e2e_*`` fields compare whole fused SAC train
    steps both ways (``_per_end_to_end``)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.csrc import SumSegmentTree
    from rl_tpu.data.replay.samplers import PrioritizedSampler

    capacity = _T(smoke=4096, cpu=1 << 16, full=1 << 20)
    batch = 256
    inner = _T(smoke=5, cpu=20, full=50)  # cycles per timed call
    reps = _T(smoke=2, cpu=5, full=5)  # timed calls; best-of taken
    sampler = PrioritizedSampler()
    key = jax.random.key(0)
    prio0 = jax.random.uniform(key, (capacity,)) + 0.01
    # initialize through the public API so both levels of the sum-tree are
    # consistent (writing raw "priorities" into the state would desync the
    # block sums — the old bench's init bug)
    sstate = sampler.init(capacity)
    sstate = sampler.update_priority(
        sstate, jnp.arange(capacity), prio0, indices_sorted=True
    )
    size = jnp.asarray(capacity, jnp.int32)
    # stand-in stored transitions: the rows a learner gathers per sample
    data = jax.random.normal(jax.random.key(1), (capacity, 8), jnp.float32)

    def fake_td(idx):
        return jnp.abs(data[idx].sum(axis=-1)) + 0.01

    @jax.jit
    def fused_cycles(sstate, key):
        def body(_, carry):
            sstate, key = carry
            key, k1 = jax.random.split(key)
            _idx, _info, sstate = sampler.sample_and_update(
                sstate, k1, batch, size, capacity, lambda i, _info: fake_td(i)
            )
            return sstate, key

        return jax.lax.fori_loop(0, inner, body, (sstate, key))

    # same fused cycle with a DeviceMetrics pytree threaded through the
    # carry — the exact instrumentation AsyncOffPolicyTrainer pays per
    # update. Its cost relative to fused_cycles is the observability
    # overhead the PR-3 acceptance bound (<5%) is about.
    from rl_tpu.obs.device import DeviceMetrics

    obs_spec = DeviceMetrics(
        counters=("updates",),
        gauges=("mean_td",),
        histograms={"td_error": (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0)},
    )

    @jax.jit
    def fused_cycles_obs(sstate, key, dm):
        def body(_, carry):
            sstate, key, dm = carry
            key, k1 = jax.random.split(key)
            box = []  # captures the td tracer the cycle already computes

            def prio_fn(i, _info):
                td = fake_td(i)
                box.append(td)
                return td

            _idx, _info, sstate = sampler.sample_and_update(
                sstate, k1, batch, size, capacity, prio_fn
            )
            td = box[0]
            dm = obs_spec.inc(dm, "updates")
            dm = obs_spec.set_gauge(dm, "mean_td", td.mean())
            dm = obs_spec.observe(dm, "td_error", td)
            return sstate, key, dm

        return jax.lax.fori_loop(0, inner, body, (sstate, key, dm))

    @jax.jit
    def sample_cycles(sstate, key):
        def body(_, carry):
            sstate, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            idx, _info, sstate = sampler.sample(sstate, k1, batch, size, capacity)
            # poke: XLA hoists loop-invariant work (the level cumsum, the
            # row gather) out of fori_loop when the state never changes —
            # touching one idx-dependent leaf keeps every iteration live
            tiny = jax.random.uniform(k2, ()) * 1e-30
            sstate = sstate.replace(
                priorities=sstate["priorities"].at[idx[0]].add(tiny),
                esum=sstate["esum"].at[idx[0] // sampler.fanout].add(tiny),
            )
            return sstate, key

        return jax.lax.fori_loop(0, inner, body, (sstate, key))

    @jax.jit
    def update_cycles(sstate, key):
        def body(_, carry):
            sstate, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            idx = jax.random.randint(k1, (batch,), 0, capacity)
            newp = jax.random.uniform(k2, (batch,)) + 0.01
            sstate = sampler.update_priority(sstate, idx, newp)
            return sstate, key

        return jax.lax.fori_loop(0, inner, body, (sstate, key))

    compile_s = 0.0

    def time_device(fn, *extra, n=None):
        nonlocal compile_s
        t0 = time.perf_counter()
        out = fn(sstate, key, *extra)[0]
        jax.block_until_ready(out["priorities"])
        compile_s += time.perf_counter() - t0
        best = float("inf")
        for _ in range(n or reps):
            t0 = time.perf_counter()
            out = fn(sstate, key, *extra)[0]
            jax.block_until_ready(out["priorities"])
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    # the obs-overhead ratio divides two near-equal numbers, so wall-clock
    # jitter that the other metrics shrug off shows up as ±10% here: take
    # best-of-3x reps for the pair being compared (cost: milliseconds)
    t_fused = time_device(fused_cycles, n=3 * reps)
    t_fused_obs = time_device(fused_cycles_obs, obs_spec.init(), n=3 * reps)
    t_sample = time_device(sample_cycles)
    t_update = time_device(update_cycles)

    # one more instrumented run to drain real accumulated values into the
    # artifact (and prove the drain path end-to-end on this backend)
    *_, dm_final = fused_cycles_obs(sstate, key, obs_spec.init())
    obs_snapshot = obs_spec.to_flat(DeviceMetrics.drain(dm_final))

    # -- host comparators -----------------------------------------------------
    alpha, beta, eps_p = sampler.alpha, sampler.beta0, sampler.eps
    tree = SumSegmentTree(capacity)
    pa0 = (np.asarray(prio0, np.float64) + eps_p) ** alpha
    tree[np.arange(capacity)] = pa0
    prios = pa0.copy()  # host mirror of p^alpha (the tree has no read)
    rng = np.random.default_rng(0)
    consume = jax.jit(fake_td)
    tc0 = time.perf_counter()
    jax.block_until_ready(consume(jnp.arange(batch)))
    compile_s += time.perf_counter() - tc0

    def host_cycle(in_program: bool):
        us = rng.uniform(0, tree.reduce(), batch)
        idx = tree.scan(us)
        p = np.maximum(prios[idx], 1e-12)
        w = (capacity * p / tree.reduce()) ** (-beta)
        w = w / w.max()  # IS weights, same normalization as the device side
        if in_program:
            # upload indices, device gathers the batch + computes td-errors,
            # download them — the two boundary crossings a device learner
            # with a host-side tree cannot avoid
            td = np.asarray(consume(jnp.asarray(idx, jnp.int32)))
        else:
            td = rng.uniform(0.01, 1.01, batch)
        pa = (np.abs(td) + eps_p) ** alpha
        prios[idx] = pa
        tree[idx] = pa

    def time_host(in_program: bool):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                host_cycle(in_program)
            best = min(best, (time.perf_counter() - t0) / inner)
        return best

    t_host_pure = time_host(False)
    t_host_inprog = time_host(True)

    e2e, e2e_compile = _per_end_to_end(jax)
    compile_s += e2e_compile
    out = {
        "metric": "per_on_device_speedup_vs_host_tree",
        "value": round(t_host_inprog / t_fused, 3),
        "unit": "x",
        "vs_baseline": round(t_host_inprog / t_fused, 3),
        "device_fused_us_per_cycle": round(t_fused * 1e6, 1),
        "device_fused_obs_us_per_cycle": round(t_fused_obs * 1e6, 1),
        "device_sample_us_per_cycle": round(t_sample * 1e6, 1),
        "device_update_us_per_cycle": round(t_update * 1e6, 1),
        "host_inprogram_us_per_cycle": round(t_host_inprog * 1e6, 1),
        "host_pure_loop_us_per_cycle": round(t_host_pure * 1e6, 1),
        "host_pure_loop_ratio": round(t_host_pure / t_fused, 3),
        "native_tree": bool(getattr(tree, "IS_NATIVE", False)),
        "capacity": capacity,
        "batch": batch,
        "fanout": sampler.fanout,
        "compile_s": round(compile_s, 2),
        "error": None,
    }
    out["metrics"] = {
        # observability cost of the fused cycle (PR-3 acceptance: < 0.05)
        "overhead_frac": round(t_fused_obs / t_fused - 1.0, 4),
        "device_fused_obs_us_per_cycle": round(t_fused_obs * 1e6, 1),
        "device": obs_snapshot,
    }
    out.update(e2e)
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_async_collect(report: bool = True) -> dict:
    """BENCH_MODE=async_collect: overlapped vs serialized off-policy SAC on
    host envs. Async = AsyncHostCollector + AsyncOffPolicyTrainer
    (background env threads feeding a bounded queue, donated K-update
    programs on the device side); sync = the SAME envs, policy, loss, and
    K-update program driven serially through HostCollector (collect blocks,
    then update blocks — nothing overlaps). Reports env-steps/s and
    grad-updates/s for both paths, their ratios (>1 = async wins), and a
    device-utilization estimate: fraction of wall spent inside the K-update
    program, derived from a warm standalone timing of that same program.
    ``compile_s`` covers both paths' warmup; timed windows are
    compile-free."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.collectors import AsyncHostCollector, HostCollector, ThreadedEnvPool
    from rl_tpu.data import ArrayDict
    from rl_tpu.data.replay import DeviceStorage, ReplayBuffer
    from rl_tpu.data.replay.samplers import PrioritizedSampler
    from rl_tpu.envs.libs import GymEnv
    from rl_tpu.modules import (
        MLP,
        ConcatMLP,
        NormalParamExtractor,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        TanhNormal,
    )
    from rl_tpu.objectives import SACLoss
    from rl_tpu.trainers import AsyncOffPolicyTrainer, OffPolicyConfig

    n_envs = _T(smoke=2, cpu=8, full=16)
    fpb = _T(smoke=32, cpu=128, full=256)
    total = _T(smoke=96, cpu=1536, full=4096)
    utd = _T(smoke=1, cpu=2, full=4)
    bs = _T(smoke=32, cpu=128, full=256)
    cap = 1 << 14
    cells = (64, 64)
    act_dim = 1

    def env_fn():
        return GymEnv("Pendulum-v1")

    actor = ProbabilisticActor(
        TDSequential(
            TDModule(MLP(out_features=2 * act_dim, num_cells=cells),
                     ["observation"], ["raw"]),
            TDModule(NormalParamExtractor(), ["raw"], ["loc", "scale"]),
        ),
        TanhNormal,
        dist_keys=("loc", "scale"),
    )
    sac = SACLoss(actor, ConcatMLP(out_features=1, num_cells=cells))

    def policy(p, td, k):
        return sac.actor(p["actor"], td, k)

    cfg = OffPolicyConfig(batch_size=bs, utd_ratio=utd, learning_rate=3e-4)
    compile_s = 0.0

    # -- async path ------------------------------------------------------------
    pool_a = ThreadedEnvPool([env_fn for _ in range(n_envs)])
    coll_a = AsyncHostCollector(pool_a, policy, frames_per_batch=fpb, seed=0)
    tr = AsyncOffPolicyTrainer(
        coll_a, sac, ReplayBuffer(DeviceStorage(cap), PrioritizedSampler()),
        cfg, priority_key="td_error",
    )
    ts = tr.init(jax.random.key(0))
    tc0 = time.perf_counter()
    for ts, _m in tr.train(ts, total_frames=2 * fpb):  # compile pass
        pass
    jax.block_until_ready(ts["params"])
    compile_s += time.perf_counter() - tc0

    steps0 = coll_a.stats()["env_steps"]
    updates0 = int(ts["update_count"])
    t0 = time.perf_counter()
    for ts, _m in tr.train(ts, total_frames=total):
        pass
    jax.block_until_ready(ts["params"])
    wall_async = time.perf_counter() - t0
    frames_async = coll_a.stats()["env_steps"] - steps0
    updates_async = int(ts["update_count"]) - updates0
    stats_a = coll_a.stats()

    # warm standalone timing of the K-update program (donates + consumes the
    # final async state, which is no longer needed)
    t0 = time.perf_counter()
    out, m = tr._k_updates(
        ts["params"], ts["opt"], ts["buffer"], ts["rng"], ts["update_count"]
    )
    jax.block_until_ready(m)
    t_kupd = time.perf_counter() - t0
    pool_a.close()

    # -- sync path -------------------------------------------------------------
    pool_s = ThreadedEnvPool([env_fn for _ in range(n_envs)])
    hc = HostCollector(pool_s, policy, frames_per_batch=fpb, seed=0)
    # separate AsyncOffPolicyTrainer instance purely as the update/extend
    # program factory — its collector is never started; the sync loop
    # drives the SAME jitted K-update program serially
    coll_dummy = AsyncHostCollector(pool_s, policy, frames_per_batch=fpb)
    tr_s = AsyncOffPolicyTrainer(
        coll_dummy, sac, ReplayBuffer(DeviceStorage(cap), PrioritizedSampler()),
        cfg, priority_key="td_error",
    )
    ts_s = tr_s.init(jax.random.key(0))
    scan_len = fpb // n_envs

    def flatten_with_stamps(batch, version, step0):
        # [T, N] -> [T*N] plus the stamp columns the async writer records,
        # so both paths share one buffer schema. The actor writes dist
        # intermediates (loc/scale/raw/sample_log_prob) into the td; the
        # buffer schema has no slots for them, so keep transition keys only.
        batch = batch.select("observation", "action", "next")
        flat = batch.apply(lambda x: x.reshape((-1,) + x.shape[2:]))
        stamps = ArrayDict(
            policy_version=jnp.full((fpb,), version, jnp.int32),
            env_ids=jnp.tile(jnp.arange(n_envs, dtype=jnp.int32), scan_len),
            step=step0 + jnp.arange(fpb, dtype=jnp.int32),
        )
        return flat.set("collector", stamps)

    key = jax.random.key(7)

    def sync_iteration(ts_s, key, version, step0):
        key, k = jax.random.split(key)
        batch = hc.collect(ts_s["params"], k)  # serial: envs block the loop
        flat = flatten_with_stamps(batch, version, step0)
        bstate = tr_s._extend(ts_s["buffer"], flat)
        out, _m = tr_s._k_updates(
            ts_s["params"], ts_s["opt"], bstate, ts_s["rng"], ts_s["update_count"]
        )
        params, opt_state, bstate, rng, uc, _dm = out
        return {
            "params": params, "opt": opt_state, "buffer": bstate,
            "rng": rng, "update_count": uc,
        }, key

    tc0 = time.perf_counter()
    ts_s, key = sync_iteration(ts_s, key, 0, 0)  # compile pass
    jax.block_until_ready(ts_s["params"])
    compile_s += time.perf_counter() - tc0
    n_iters = total // fpb
    t0 = time.perf_counter()
    for i in range(n_iters):
        ts_s, key = sync_iteration(ts_s, key, i + 1, (i + 1) * fpb)
    jax.block_until_ready(ts_s["params"])
    wall_sync = time.perf_counter() - t0
    frames_sync = n_iters * fpb
    updates_sync = n_iters * utd
    pool_s.close()

    fps_async = frames_async / wall_async
    fps_sync = frames_sync / wall_sync
    ups_async = updates_async / wall_async
    ups_sync = updates_sync / wall_sync
    out = {
        "metric": "async_collect_env_steps_per_sec",
        "value": round(fps_async, 1),
        "unit": "env_steps/s",
        "vs_baseline": round(fps_async / max(fps_sync, 1e-9), 3),
        "env_steps_per_sec_async": round(fps_async, 1),
        "env_steps_per_sec_sync": round(fps_sync, 1),
        "grad_updates_per_sec_async": round(ups_async, 2),
        "grad_updates_per_sec_sync": round(ups_sync, 2),
        "async_over_sync_env_steps": round(fps_async / max(fps_sync, 1e-9), 3),
        "async_over_sync_grad_updates": round(ups_async / max(ups_sync, 1e-9), 3),
        "device_utilization_async": round(
            min(1.0, (updates_async / utd) * t_kupd / wall_async), 3
        ),
        "device_utilization_sync": round(
            min(1.0, (updates_sync / utd) * t_kupd / wall_sync), 3
        ),
        "straggler_cutoffs": stats_a["straggler_cutoffs"],
        "harvests": stats_a["harvests"],
        "n_envs": n_envs,
        "frames_per_batch": fpb,
        "utd": utd,
        "compile_s": round(compile_s, 2),
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_chaos(report: bool = True) -> dict:
    """BENCH_MODE=chaos: resilience-subsystem cost model — the two numbers
    that decide whether the subsystem is allowed near production loops.

    1. ``injector_overhead_frac``: steady-state cost of an ENABLED but idle
       FaultInjector. The same off-policy SAC workload (host envs, async
       collector, donated K-update program) is timed in alternating windows
       with injection disabled and under an injector whose only fault can
       never fire — every hook is then live and the update dispatch carries
       the poison operand. Best-of-R per config; bound <2% (``overhead_ok``).
    2. ``recovery_latency_s``: wall-clock cost of one supervised recovery.
       The collector actor thread is crashed deterministically mid-run; the
       latency is the excess wall of the batch that spans the crash
       (supervisor backoff + env pool re-reset + queue refill) over the
       clean-batch median.
    """
    jax = _setup_jax()
    import numpy as np

    from rl_tpu.collectors import AsyncHostCollector, ThreadedEnvPool
    from rl_tpu.data import DeviceStorage, PrioritizedSampler, ReplayBuffer
    from rl_tpu.data.specs import Bounded, Composite, Unbounded
    from rl_tpu.modules import (
        MLP,
        ConcatMLP,
        NormalParamExtractor,
        ProbabilisticActor,
        TDModule,
        TDSequential,
        TanhNormal,
    )
    from rl_tpu.objectives import SACLoss
    from rl_tpu.obs import MetricsRegistry
    from rl_tpu.resilience import Fault, FaultInjector, Supervisor, injection
    from rl_tpu.trainers import AsyncOffPolicyTrainer, OffPolicyConfig

    n_envs = _T(smoke=2, cpu=4, full=8)
    fpb = _T(smoke=32, cpu=64, full=128)
    window = _T(smoke=2 * 32, cpu=4 * 64, full=8 * 128)  # frames per window
    reps = _T(smoke=2, cpu=3, full=4)
    n_batches = _T(smoke=6, cpu=8, full=10)  # recovery run length

    class _ChaosEnv:
        """Pure-host toy env: no gymnasium, deterministic, microsecond
        steps — the timing signal is the resilience machinery, not env
        physics."""

        def __init__(self, seed=0, horizon=64):
            self._rng = np.random.default_rng(seed)
            self._t = 0
            self.horizon = horizon
            self.observation_spec = Composite(observation=Unbounded((2,)))
            self.action_spec = Bounded(shape=(1,), low=-1.0, high=1.0)

        def _obs(self):
            return {"observation": self._rng.normal(size=2).astype(np.float32)}

        def reset(self, seed=None):
            if seed is not None:
                self._rng = np.random.default_rng(seed)
            self._t = 0
            return self._obs()

        def step(self, action):
            self._t += 1
            a = float(np.asarray(action).reshape(-1)[0])
            return (self._obs(), np.float32(1.0 - (a - 0.3) ** 2), False,
                    self._t >= self.horizon)

        def close(self):
            pass

    net = TDSequential(
        TDModule(MLP(out_features=2, num_cells=(64, 64)),
                 ["observation"], ["raw"]),
        TDModule(NormalParamExtractor(), ["raw"], ["loc", "scale"]),
    )
    sac = SACLoss(ProbabilisticActor(net, TanhNormal),
                  ConcatMLP(out_features=1, num_cells=(64, 64)))

    def policy(p, td, k):
        return sac.actor(p["actor"], td, k)

    # a plan whose single fault can never fire: hooks live, zero chaos
    idle_plan = {"offpolicy.update": Fault("nan", at=(10**9,))}

    # -- 1. armed-but-idle injector overhead -----------------------------
    pool = ThreadedEnvPool([lambda i=i: _ChaosEnv(seed=i)
                            for i in range(n_envs)])
    coll = AsyncHostCollector(pool, policy, frames_per_batch=fpb, seed=0)
    cfg = OffPolicyConfig(batch_size=32, utd_ratio=1, learning_rate=3e-4,
                          init_random_frames=fpb)
    tr = AsyncOffPolicyTrainer(
        coll, sac, ReplayBuffer(DeviceStorage(1 << 13), PrioritizedSampler()),
        cfg, priority_key="td_error",
        device_metrics=True, metrics_registry=MetricsRegistry(),
    )
    ts = tr.init(jax.random.key(0))
    idle_reg = MetricsRegistry()
    idle_inj = FaultInjector(idle_plan, registry=idle_reg)

    def run(frames, armed):
        nonlocal ts
        if armed:
            with injection(idle_inj):
                for ts, _m in tr.train(ts, total_frames=frames):
                    pass
        else:
            for ts, _m in tr.train(ts, total_frames=frames):
                pass
        jax.block_until_ready(ts["params"])

    t0 = time.perf_counter()
    run(2 * fpb, armed=False)  # compile the plain trace
    run(2 * fpb, armed=True)  # compile the poison-carrying trace
    compile_s = time.perf_counter() - t0

    walls: dict = {False: [], True: []}
    for _ in range(reps):
        for armed in (False, True):  # interleave to decorrelate drift
            t0 = time.perf_counter()
            run(window, armed)
            walls[armed].append(time.perf_counter() - t0)
    pool.close()
    wall_off = min(walls[False])
    wall_armed = min(walls[True])
    overhead_frac = wall_armed / wall_off - 1.0

    # -- 2. supervised recovery latency ----------------------------------
    reg = MetricsRegistry()
    sup = Supervisor(max_restarts=3, backoff_base_s=0.01, backoff_max_s=0.05,
                     registry=reg)
    pool_r = ThreadedEnvPool([lambda i=i: _ChaosEnv(seed=i)
                              for i in range(n_envs)])
    coll_r = AsyncHostCollector(pool_r, None, frames_per_batch=fpb, seed=0,
                                supervisor=sup)
    crash_inj = FaultInjector(
        {"collector.actor_loop": Fault("crash", at=(n_batches // 2,))},
        registry=reg,
    )
    batch_walls = []
    try:
        with injection(crash_inj):
            coll_r.start()
            for _ in range(n_batches):
                t0 = time.perf_counter()
                coll_r.get_batch(timeout=120)
                batch_walls.append(time.perf_counter() - t0)
    finally:
        coll_r.stop()
        sup.stop()
        pool_r.close()
    clean_batch_s = float(np.median(batch_walls))
    recovery_latency_s = max(0.0, max(batch_walls) - clean_batch_s)
    restarts = sup.restarts("async-collector")

    out = {
        "metric": "chaos_recovery_latency_s",
        "value": round(recovery_latency_s, 4),
        "unit": "s",
        # <1.0 = the idle injector is inside its 2% budget
        "vs_baseline": round(overhead_frac / 0.02, 3),
        "injector_overhead_frac": round(overhead_frac, 4),
        "overhead_ok": bool(overhead_frac < 0.02),
        "recovery_latency_s": round(recovery_latency_s, 4),
        "clean_batch_s": round(clean_batch_s, 4),
        "restarts": restarts,
        "idle_faults_fired": len(idle_inj.fired),  # must be 0
        "wall_off_s": round(wall_off, 3),
        "wall_armed_s": round(wall_armed, 3),
        "n_envs": n_envs,
        "frames_per_batch": fpb,
        "window_frames": window,
        "reps": reps,
        "compile_s": round(compile_s, 2),
        "metrics": {
            "injector_overhead_frac": round(overhead_frac, 4),
            "overhead_ok": bool(overhead_frac < 0.02),
            "recovery_latency_s": round(recovery_latency_s, 4),
            "clean_batch_s": round(clean_batch_s, 4),
            "restarts": restarts,
            "idle_faults_fired": len(idle_inj.fired),
        },
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_fleet(report: bool = True) -> dict:
    """BENCH_MODE=fleet: open-loop chaos traffic against a 3-engine
    :class:`ServingFleet` — the ISSUE-6 robustness proof.

    Seeded Poisson arrivals (plus a 3x burst window) are replayed open-loop
    against the fleet, 70/30 interactive/batch lanes; halfway through, a
    seeded ``fleet.engine_crash.1`` fault kills member 1 mid-decode. The
    invariant under test: ZERO admitted requests are lost — the
    completed-or-shed accounting balances exactly across the crash,
    failover re-dispatch, and re-admission. Reports fleet tokens/s plus
    p50/p99 TTFT (submit -> first-token admission) split pre/post-crash;
    ``vs_baseline`` is the p99-TTFT recovery ratio post/pre (~1 = failover
    is invisible at the tail, large = the crash bled into latency)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.models import (
        ContinuousBatchingEngine,
        ServiceSaturated,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )
    from rl_tpu.obs import (
        FlightRecorder,
        MetricsRegistry,
        TraceRecorder,
        set_tracer,
    )
    from rl_tpu.resilience import Fault, FaultInjector, injection

    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
        horizon_s, n_lo, n_hi = 4.0, 4, 10
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
        horizon_s, n_lo, n_hi = 12.0, 6, 16
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, pmax = 8, 32, 24
        horizon_s, n_lo, n_hi = 20.0, 16, 48
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)

    def mk_engine(i):
        # fixed decode_chunk: the auto-tuner's chunk ladder would recompile
        # mid-traffic and read as latency noise in the TTFT percentiles
        return ContinuousBatchingEngine(
            model, params, n_slots=S, block_size=16,
            n_blocks=S * (cfg.max_seq_len // 16) + 1,
            prompt_buckets=(bucket,), greedy=True, decode_chunk=4, seed=i,
        )

    engines = [mk_engine(i) for i in range(3)]
    t0 = time.perf_counter()
    for e in engines:
        # warm the FULL program ladder (every admit count x prompt bucket),
        # not just what two probe requests happen to hit — a mid-traffic
        # admit-shape compile would bleed straight into the TTFT tail
        e.aot_warmup()
    for e in engines:  # one traffic round: first-round host-glue ops compile
        for _ in range(2):
            e.submit(rng.integers(0, cfg.vocab_size, 8), 4)
        e.run()
    compile_s = time.perf_counter() - t0

    # calibrate the offered load to this host: one warm replica's request
    # rate x3 replicas x0.9 — just under fleet saturation, so the burst and
    # the crash are what push it over
    n_cal = 3 * S
    cal = [(rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))),
            int(rng.integers(n_lo, n_hi))) for _ in range(n_cal)]
    for p, n in cal:
        engines[0].submit(p, n)
    t0 = time.perf_counter()
    engines[0].run()
    lam = 0.9 * 3.0 * n_cal / (time.perf_counter() - t0)  # requests/s

    # seeded open-loop arrival plan: Poisson(lam) over the horizon plus a
    # 3x burst window at [0.4T, 0.55T]; crash lands mid-burst at 0.5T
    arrivals = []
    t = 0.0
    while t < horizon_s:
        t += rng.exponential(1.0 / lam)
        arrivals.append(t)
    b0, b1 = 0.4 * horizon_s, 0.55 * horizon_s
    t = b0
    while t < b1:
        t += rng.exponential(1.0 / (2.0 * lam))  # +2x on top of base = 3x
        arrivals.append(t)
    arrivals = sorted(a for a in arrivals if a < horizon_s)
    plan = [(a,
             "interactive" if rng.random() < 0.7 else "batch",
             rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))),
             int(rng.integers(n_lo, n_hi)))
            for a in arrivals]
    crash_at = 0.5 * horizon_s

    reg = MetricsRegistry()
    # PR-12: arm a fresh recorder so the chaos traffic itself is the
    # trace-tree sample — fleet.submit roots a trace per request, and the
    # crash/failover re-dispatch spans link into those trees
    tracer = TraceRecorder()
    prev_tracer = set_tracer(tracer)

    # PR-18: arm the triggered profiler + drift detector for the chaos
    # window. The bench exercises the trigger plumbing end-to-end (the
    # fleet monitor polls; the attribution worker feeds both) and bounds
    # the armed feed cost (< 2% of wall) in the distilled artifact below.
    import shutil
    import tempfile

    from rl_tpu.obs import (
        DriftDetector,
        TriggeredProfiler,
        set_drift_detector,
        set_profiler,
    )

    pdir = tempfile.mkdtemp(prefix="rl_tpu_prof_bench_")
    # trace_s=0: host-only bundles — a device-trace window would stall
    # the monitor thread on the profiler backend's lazy import mid-traffic
    # and bleed into the TTFT tail it's supposed to explain
    prof = TriggeredProfiler(pdir, registry=reg, tracer=tracer, trace_s=0.0)
    prof.arm_compile_delta()  # armed post-warmup: a hit = silent recompile
    prof.arm_p99_spike()
    det = DriftDetector(registry=reg, tracer=tracer, profiler=prof)
    prev_prof = set_profiler(prof)
    prev_det = set_drift_detector(det)

    fleet = ServingFleet(
        engines, registry=reg, probe_interval_s=0.02,
        max_queue=len(plan),  # shed path exercised by the watermark, not cap
    ).start()
    inj = FaultInjector(
        {"fleet.engine_crash.1": Fault("crash", at=(1,))}, registry=reg)

    from rl_tpu.compile import CompileDelta

    admitted, rejected = [], 0
    crash_wall = None
    steady = CompileDelta()
    t_start = time.monotonic()
    try:
        with steady, injection(inj):
            for a, lane, prompt, n_new in plan:
                now = time.monotonic() - t_start
                if crash_wall is None and now >= crash_at:
                    crash_wall = time.monotonic()  # injector armed from the
                    # start, but at=(1,) only counts once member 1 is BUSY —
                    # record the moment the plan says the crash window opens
                if a > now:
                    time.sleep(a - now)
                try:
                    admitted.append(fleet.submit(prompt, n_new, lane=lane))
                except ServiceSaturated:
                    rejected += 1
            results = fleet.wait(admitted, timeout=_T(smoke=120, cpu=300,
                                                      full=300))
    finally:
        wall = time.monotonic() - t_start
        acc = fleet.accounting()
        snap = fleet.metrics_snapshot()
        stats = fleet.request_stats()
        slo_snap = fleet.slo.snapshot()
        fleet.shutdown()
        set_profiler(prev_prof)
        set_drift_detector(prev_det)
        set_tracer(prev_tracer)
    if crash_wall is None:
        crash_wall = t_start + crash_at  # all arrivals landed pre-0.5T

    from rl_tpu.models import FinishedRequest

    tokens = sum(len(r.tokens) for r in results.values()
                 if isinstance(r, FinishedRequest))

    def ttfts(pred):
        return [s["first_token_at"] - s["submitted_at"] for s in stats
                if s["first_token_at"] is not None and pred(s)]

    pre = ttfts(lambda s: s["submitted_at"] < crash_wall)
    post = ttfts(lambda s: s["submitted_at"] >= crash_wall)

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 4) if xs else None

    p99_pre, p99_post = pct(pre, 99), pct(post, 99)
    shed_total = acc["shed_admission"] + acc["shed_post_admission"]
    metrics = {
        "fleet_tokens_per_sec": round(tokens / wall, 1),
        "p50_ttft_pre_s": pct(pre, 50), "p99_ttft_pre_s": p99_pre,
        "p50_ttft_post_s": pct(post, 50), "p99_ttft_post_s": p99_post,
        "admitted": acc["admitted"], "completed": acc["completed"],
        "shed": shed_total, "redispatched": acc["redispatched"],
        "duplicates_suppressed": acc["duplicates_suppressed"],
        "lost": acc["lost"],
        "invariant_ok": bool(acc["lost"] == 0
                             and acc["completed"] + acc["shed_post_admission"]
                             == len(admitted)),
        "crashes": snap["crashes"], "quarantines": snap["quarantines"],
        "readmissions": snap["readmissions"],
        # 0 == the whole chaos window (crash, failover re-dispatch,
        # re-admission included) ran on warmed executables
        "steady_state_compile_delta": steady.delta if steady.supported else None,
    }

    # PR-12 observability distillation: trace-tree shape from the Perfetto
    # export, SLO attainment/burn from the fleet's engine, and the size of
    # a flight-record bundle cut from this very run
    import shutil
    import tempfile

    events = tracer.export()["traceEvents"]
    traced = [e for e in events
              if e.get("ph") in ("X", "i")
              and isinstance(e.get("args"), dict)
              and "trace_id" in e["args"]]
    spans = [e for e in traced if e["ph"] == "X"]
    by_id = {e["args"]["span_id"]: e for e in spans if "span_id" in e["args"]}

    def span_depth(e):
        d = 1
        while d < 64:
            pid = e["args"].get("parent_id")
            parent = by_id.get(pid)
            if parent is None:
                # a dangling parent_id is the request's root *context*
                # (fleet.submit opens a trace, not a span) — still a level
                return d + (1 if pid is not None else 0)
            e, d = parent, d + 1
        return d

    trace_ids = {e["args"]["trace_id"] for e in traced}
    fdir = tempfile.mkdtemp(prefix="rl_tpu_flight_bench_")
    flight = {"files": 0, "bytes": 0}
    try:
        bundle = FlightRecorder(fdir, tracer=tracer, registry=reg).dump("bench_fleet")
        if bundle:
            names = sorted(os.listdir(bundle))
            flight = {
                "files": len(names),
                "bytes": sum(os.path.getsize(os.path.join(bundle, f))
                             for f in names),
            }
    finally:
        shutil.rmtree(fdir, ignore_errors=True)
    obs_section = {
        "trace_spans": len(spans),
        "trace_instants": len(traced) - len(spans),
        "trace_trees": len(trace_ids),
        "trace_depth": max((span_depth(e) for e in by_id.values()), default=0),
        "trace_threads": len({e["tid"] for e in traced}),
        "slo": slo_snap,
        "flight_record": flight,
    }
    # PR-18 profiling distillation: what the armed profiler/drift pair
    # saw over the chaos window, plus a measured bound on the feed cost.
    # The feed runs on the attribution daemon (every 8th dispatch), never
    # a dispatch thread, so the *hot-path* cost is zero by construction;
    # what the artifact bounds is the total ring+compare cost as a
    # fraction of the bench wall-clock, had it all landed on one thread.
    drift_snap = det.snapshot()
    prof_snap = prof.snapshot()
    fed = sum(r["samples"] for r in prof.ring_snapshot().values())
    t0 = time.perf_counter()
    probe_n = 2000
    for _ in range(probe_n):
        prof.record_dispatch("overhead_probe", 1e-3)
        det.observe("overhead_probe", 1e-3)
    feed_cost_s = (time.perf_counter() - t0) / probe_n
    armed_overhead_frac = fed * feed_cost_s / wall if wall > 0 else 0.0
    assert armed_overhead_frac < 0.02, (
        f"armed profiler feed cost {armed_overhead_frac:.4f} of wall "
        "exceeds the 2% bound")
    shutil.rmtree(pdir, ignore_errors=True)
    profiling_section = {
        "armed_overhead_frac": round(armed_overhead_frac, 6),
        "feed_cost_us": round(feed_cost_s * 1e6, 3),
        "fed_dispatches": fed,
        "captures": len(prof_snap["captures"]),
        "capture_triggers": prof_snap["fired"],
        "suppressed": prof_snap["suppressed"],
        "triggers_armed": prof_snap["triggers_armed"],
        "programs_ringed": prof_snap["programs_ringed"],
        "drift": {
            "tolerance": drift_snap["tolerance"],
            "events_total": drift_snap["events_total"],
            "programs": len(drift_snap["programs"]),
            "fired": drift_snap["fired"][-8:],
        },
    }
    metrics["profiler_armed_overhead_frac"] = round(armed_overhead_frac, 6)
    metrics["drift_events_total"] = drift_snap["events_total"]

    # headline scalars also ride the flat metrics section so the generic
    # METRICS distillation picks them up without knowing about "obs"
    att = slo_snap.get("fleet_ttft", {}).get("attainment")
    metrics["slo_ttft_attainment"] = round(att, 4) if att is not None else None
    metrics["slo_availability_burn_60s"] = (
        slo_snap.get("fleet_availability", {}).get("burn_rate_60s"))

    out = {
        "metric": "fleet_tokens_per_sec",
        "value": metrics["fleet_tokens_per_sec"],
        "unit": "tokens/s",
        # p99 TTFT recovery: post-crash tail over pre-crash tail
        "vs_baseline": (round(p99_post / p99_pre, 3)
                        if p99_pre and p99_post else 0.0),
        **metrics,
        "rejected_at_admission": rejected,
        "offered_rps": round(lam, 2),
        "n_arrivals": len(plan),
        "horizon_s": horizon_s,
        "wall_s": round(wall, 2),
        "faults_fired": len(inj.fired),
        "compile_s": round(compile_s, 2),
        "n_slots": S,
        "n_engines": 3,
        "obs": obs_section,
        "profiling": profiling_section,
        "ir_audit": _ir_audit_section(jax, prefix="serving."),
        "metrics": metrics,
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_autoscale(report: bool = True) -> dict:
    """BENCH_MODE=autoscale: elastic fleet vs fixed fleet on ONE seeded
    diurnal+bursty replay (the ISSUE-19 tentpole proof).

    The same open-loop arrival plan — a diurnal rate envelope (lull ->
    peak -> lull) with a 2.5x burst riding the peak and a seeded member
    crash mid-burst — is replayed against two arms:

    - **fixed**: the fleet stays at its initial size;
    - **autoscale**: an :class:`~rl_tpu.models.Autoscaler` grows the
      member set when fleet_ttft burn crosses its threshold (the warm
      must be COMPILE-FREE: per-event CompileDelta is asserted in the
      artifact) and drains one back through the failover path when the
      free_adjusted KV slack is sustained (``lost == 0`` across the
      scale-down AND the crash).

    Both arms carry the same batch-lane rollout tenant harvesting
    whatever capacity the interactive SLO lane leaves idle (with a
    periodic fleet-wide weight push), so the artifact reports: SLO
    attainment through the burst window per arm (the autoscale arm must
    win), rollout tokens/s from slack, and idle-capacity waste (idle
    slot-seconds over PROVISIONED slot-seconds — shrinking in the lulls
    is where elasticity pays). A flight-recorder bundle is cut at every
    scale-down carrying the autoscaler decision trail. Stretch sub-result
    (RL_TPU_BENCH_DISAGG=0 to skip): a prefill/decode disaggregated pair
    serving the same prompts via paged-KV handoff."""
    jax = _setup_jax()
    import contextlib
    import shutil
    import tempfile
    import threading

    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.compile import CompileDelta
    from rl_tpu.models import (
        Autoscaler,
        AutoscalerConfig,
        ContinuousBatchingEngine,
        FinishedRequest,
        ServiceSaturated,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )
    from rl_tpu.obs import FlightRecorder, MetricsRegistry
    from rl_tpu.resilience import Fault, FaultInjector, injection

    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
        horizon_s, n_lo, n_hi = 5.0, 4, 10
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, pmax = 4, 16, 12
        horizon_s, n_lo, n_hi = 14.0, 6, 16
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, pmax = 8, 32, 24
        horizon_s, n_lo, n_hi = 20.0, 16, 48
    slo_ttft_s = 0.2 if _TIER != "full" else 0.15
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)

    def mk_engine(i):
        # fixed decode_chunk for the same reason as bench_fleet: the
        # auto-tuner's chunk ladder would recompile mid-traffic
        return ContinuousBatchingEngine(
            model, params, n_slots=S, block_size=16,
            n_blocks=S * (cfg.max_seq_len // 16) + 1,
            prompt_buckets=(bucket,), greedy=True, decode_chunk=4, seed=i,
        )

    # warm the FULL ladder once: every later engine build (both arms AND
    # every autoscaler scale-up) loads from the in-process registry/store
    t0 = time.perf_counter()
    warm0 = mk_engine(0)
    warm0.aot_warmup()
    for _ in range(2):
        warm0.submit(rng.integers(0, cfg.vocab_size, 8), 4)
    warm0.run()
    compile_s = time.perf_counter() - t0

    # calibrate offered load: one warm replica's rate x2 members x0.95 —
    # the diurnal peak + burst is what pushes the FIXED arm over
    n_cal = 2 * S
    cal = [(rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))),
            int(rng.integers(n_lo, n_hi))) for _ in range(n_cal)]
    for p, n in cal:
        warm0.submit(p, n)
    t0 = time.perf_counter()
    warm0.run()
    lam = 0.95 * 2.0 * n_cal / (time.perf_counter() - t0)  # requests/s

    # seeded diurnal plan by thinning: rate(t) = lam*(0.3 + 0.9*sin^2) is
    # a lull->peak->lull day in miniature; a 1.5*lam Poisson burst rides
    # the peak at [0.45T, 0.6T]; the crash lands mid-burst at 0.5T
    T = horizon_s
    rate_max = 1.2 * lam
    arrivals = []
    t = 0.0
    while t < T:
        t += rng.exponential(1.0 / rate_max)
        rate = lam * (0.3 + 0.9 * float(np.sin(np.pi * t / T)) ** 2)
        if rng.random() < rate / rate_max:
            arrivals.append(t)
    b0, b1 = 0.4 * T, 0.65 * T
    t = b0
    while t < b1:
        t += rng.exponential(1.0 / (3.4 * lam))
        arrivals.append(t)
    arrivals = sorted(a for a in arrivals if a < T)
    # the plan is ALL interactive: the batch lane belongs to the rollout
    # tenant, which is how lane tenancy is exercised
    plan = [(a, rng.integers(0, cfg.vocab_size, int(rng.integers(4, pmax))),
             int(rng.integers(n_lo, n_hi))) for a in arrivals]
    crash_at = 0.5 * T

    def rollout_tenant(fleet, stop_ev, out, rng_seed):
        """Batch-lane slack harvester: modest depth so the SLO lane always
        wins admission, sheds simply yield; a fleet-wide weight push every
        ~2 s proves a publish never stalls serving."""
        trng = np.random.default_rng(rng_seed)
        outstanding: set = set()
        last_push = time.monotonic()
        while not stop_ev.is_set():
            now = time.monotonic()
            if now - last_push >= 2.0:
                out["pushes"] += 1
                out["pushed_members"] += fleet.push_params(params)
                last_push = now
            while len(outstanding) < S:
                try:
                    outstanding.add(fleet.submit(
                        trng.integers(0, cfg.vocab_size,
                                      int(trng.integers(4, pmax))),
                        int(trng.integers(n_lo, n_hi)), lane="batch"))
                except (ServiceSaturated, RuntimeError):
                    break
            for frid, res in fleet.poll(list(outstanding)).items():
                outstanding.discard(frid)
                if isinstance(res, FinishedRequest):
                    out["tokens"] += len(res.tokens)
                    out["completed"] += 1
                else:
                    out["shed"] += 1
            stop_ev.wait(0.02)
        # drain what is still in flight (bounded): the tenant's rows are
        # real tokens the slack produced
        deadline = time.monotonic() + 30.0
        while outstanding and time.monotonic() < deadline:
            for frid, res in fleet.poll(list(outstanding)).items():
                outstanding.discard(frid)
                if isinstance(res, FinishedRequest):
                    out["tokens"] += len(res.tokens)
                    out["completed"] += 1
                else:
                    out["shed"] += 1
            time.sleep(0.02)

    def waste_sampler(fleet, stop_ev, samples):
        """(provisioned_slots, busy_slots) every 50 ms: waste is idle
        slot-seconds over provisioned slot-seconds."""
        while not stop_ev.is_set():
            snap = fleet.metrics_snapshot()
            alive = [m for m in snap["members"]
                     if m["state"] not in ("dead", "retired")]
            slots = S * len(alive)
            busy = sum(min(m["pending"], S) for m in alive)
            samples.append((slots, busy))
            stop_ev.wait(0.05)

    def run_arm(elastic: bool) -> dict:
        reg = MetricsRegistry()
        engines = [mk_engine(i) for i in range(2)]
        with CompileDelta() as arm_warm:
            for e in engines:
                e.aot_warmup()  # loads — warm0 already built the ladder
        for e in engines:  # first-round host-glue ops
            for _ in range(2):
                e.submit(rng.integers(0, cfg.vocab_size, 8), 4)
            e.run()
        fleet = ServingFleet(
            engines, registry=reg, probe_interval_s=0.02,
            slo_ttft_s=slo_ttft_s, max_queue=len(plan) + 4 * S,
            max_members=3,
        ).start()
        fleet.push_params(params)  # warm the weight-push path pre-traffic
        fdir = tempfile.mkdtemp(prefix="rl_tpu_autoscale_flight_")
        flight = FlightRecorder(fdir, registry=reg)
        flight.add_source("fleet_scale_events", lambda: fleet.scale_events)
        scaler = None
        if elastic:
            scaler = Autoscaler(
                fleet, engine_factory=lambda: mk_engine(
                    10 + fleet.n_routable()),
                config=AutoscalerConfig(
                    min_members=2, max_members=3,
                    burn_window_s=1.5, scale_up_burn=0.3,
                    scale_down_free_frac=0.8, scale_down_sustain_s=2.0,
                    cooldown_s=0.5, poll_interval_s=0.05,
                ),
                registry=reg, flight=flight,
            ).start()
        inj = FaultInjector(
            {"fleet.engine_crash": Fault("crash", at=(1,))}, registry=reg)
        stop_ev = threading.Event()
        tenant = {"tokens": 0, "completed": 0, "shed": 0,
                  "pushes": 0, "pushed_members": 0}
        samples: list = []
        threads = [
            threading.Thread(target=rollout_tenant, name="bench-tenant",
                             args=(fleet, stop_ev, tenant, 999), daemon=True),
            threading.Thread(target=waste_sampler, name="bench-waste",
                             args=(fleet, stop_ev, samples), daemon=True),
        ]
        admitted, rejected = [], 0
        steady = CompileDelta()
        t_start = time.monotonic()
        crash_wall = None
        try:
            with steady, contextlib.ExitStack() as stack:
                for th in threads:
                    th.start()
                for a, prompt, n_new in plan:
                    now = time.monotonic() - t_start
                    if crash_wall is None and now >= crash_at:
                        # arm the injector ONLY now: the generic site fires
                        # on the next busy stepper iteration — mid-burst
                        stack.enter_context(injection(inj))
                        crash_wall = time.monotonic()
                    if a > now:
                        time.sleep(a - now)
                    try:
                        admitted.append(
                            fleet.submit(prompt, n_new, lane="interactive"))
                    except ServiceSaturated:
                        rejected += 1
                fleet.wait(admitted, timeout=_T(smoke=120, cpu=300, full=300))
        finally:
            wall = time.monotonic() - t_start
            stop_ev.set()
            for th in threads:
                th.join(timeout=45)
            if scaler is not None:
                scaler.stop()
            acc = fleet.accounting()
            snap = fleet.metrics_snapshot()
            stats = fleet.request_stats()
            slo_snap = fleet.slo.snapshot()
            scale_events = list(fleet.scale_events)
            counter_slack, recount = fleet.kv_slack(), fleet.kv_recount()
            fleet.shutdown()
        if crash_wall is None:
            crash_wall = t_start + crash_at
        bundle = flight.dump("bench_autoscale_end")
        names = sorted(os.listdir(bundle)) if bundle else []
        flight_section = {
            "dumps": 1 + sum(1 for e in (scaler.snapshot()["decisions"]
                                         if scaler else [])
                             if e["action"] == "scale_down"),
            "files": len(names),
            "bytes": sum(os.path.getsize(os.path.join(bundle, f))
                         for f in names) if bundle else 0,
        }
        shutil.rmtree(fdir, ignore_errors=True)

        inter = [s for s in stats if s["lane"] == "interactive"]

        def attainment(lo, hi):
            win = [s for s in inter
                   if lo <= s["submitted_at"] - t_start < hi]
            met = [s for s in win
                   if s["first_token_at"] is not None
                   and s["first_token_at"] - s["submitted_at"] <= slo_ttft_s]
            return round(len(met) / len(win), 4) if win else None

        ttfts = [s["first_token_at"] - s["submitted_at"] for s in inter
                 if s["first_token_at"] is not None]
        slots_s = sum(s for s, _ in samples)
        busy_s = sum(b for _, b in samples)
        up_deltas = [e.get("compile_delta") for e in scale_events
                     if e["event"] == "scale_up"]
        return {
            "arm": "autoscale" if elastic else "fixed",
            "slo_ttft_attainment": attainment(0.0, wall),
            "slo_ttft_attainment_burst": attainment(b0, b1 + 1.0),
            "p50_ttft_s": (round(float(np.percentile(ttfts, 50)), 4)
                           if ttfts else None),
            "p99_ttft_s": (round(float(np.percentile(ttfts, 99)), 4)
                           if ttfts else None),
            "interactive_tokens_per_sec": round(
                sum(s["tokens"] for s in inter) / wall, 1),
            "rollout_tokens_per_sec": round(tenant["tokens"] / wall, 1),
            "rollout_completed": tenant["completed"],
            "rollout_shed": tenant["shed"],
            "weight_pushes": tenant["pushes"],
            "weight_pushed_members": tenant["pushed_members"],
            "waste_frac": (round(1.0 - busy_s / slots_s, 4)
                           if slots_s else None),
            "admitted": acc["admitted"], "completed": acc["completed"],
            "rejected_at_admission": rejected,
            "shed": acc["shed_admission"] + acc["shed_post_admission"],
            "redispatched": acc["redispatched"],
            "lost": acc["lost"],
            "invariant_ok": bool(acc["lost"] == 0),
            "crashes": snap["crashes"],
            "scale_ups": snap["scale_ups"],
            "scale_downs": snap["scale_downs"],
            "scale_up_compile_deltas": up_deltas,
            "scale_events": scale_events,
            "autoscaler": scaler.snapshot() if scaler else None,
            "kv_counter_exact": bool(counter_slack == recount),
            "members_final": snap["members_routable"],
            "arm_warm_compile_delta": (arm_warm.delta
                                       if arm_warm.supported else None),
            "steady_state_compile_delta": (steady.delta
                                           if steady.supported else None),
            "flight_record": flight_section,
            "slo": slo_snap.get("fleet_ttft"),
            "wall_s": round(wall, 2),
        }

    fixed = run_arm(elastic=False)
    auto = run_arm(elastic=True)

    # stretch (flag-gated): prefill/decode disaggregation — a kv_handoff
    # pair serving the same prompt distribution through the paged-KV
    # block-table handoff, reported as its own sub-result
    disagg = None
    if os.environ.get("RL_TPU_BENCH_DISAGG", "1") != "0":
        def mk_handoff(i):
            return ContinuousBatchingEngine(
                model, params, n_slots=S, block_size=16,
                n_blocks=S * (cfg.max_seq_len // 16) + 1,
                prompt_buckets=(bucket,), greedy=True, decode_chunk=4,
                seed=i, kv_handoff=True,
            )

        dreg = MetricsRegistry()
        dengines = [mk_handoff(20), mk_handoff(21)]
        for e in dengines:
            e.aot_warmup()
        dfleet = ServingFleet(
            dengines, registry=dreg, probe_interval_s=0.02,
            disaggregate=True, roles=("prefill", "decode"),
        ).start()
        n_d = min(len(plan), 8 * S)
        t0 = time.monotonic()
        try:
            frids = [dfleet.submit(p, n) for _, p, n in plan[:n_d]]
            dres = dfleet.wait(frids, timeout=_T(smoke=120, cpu=300,
                                                 full=300))
            dwall = time.monotonic() - t0
            dacc = dfleet.accounting()
            dtok = sum(len(r.tokens) for r in dres.values()
                       if isinstance(r, FinishedRequest))
            disagg = {
                "requests": n_d,
                "completed": dacc["completed"],
                "lost": dacc["lost"],
                "tokens_per_sec": round(dtok / dwall, 1),
                "kv_counter_exact": bool(
                    dfleet.kv_slack() == dfleet.kv_recount()),
            }
        finally:
            dfleet.shutdown()

    up_deltas = [d for d in auto["scale_up_compile_deltas"] if d is not None]
    att_fixed = fixed["slo_ttft_attainment_burst"]
    att_auto = auto["slo_ttft_attainment_burst"]
    out = {
        "metric": "slo_ttft_attainment_burst",
        "value": att_auto if att_auto is not None else 0.0,
        "unit": "fraction",
        # >1 = the elastic arm held the SLO better through the burst
        "vs_baseline": (round(att_auto / att_fixed, 3)
                        if att_auto and att_fixed else 0.0),
        "slo_ttft_attainment": auto["slo_ttft_attainment"],
        "attainment_delta_burst": (round(att_auto - att_fixed, 4)
                                   if att_auto is not None
                                   and att_fixed is not None else None),
        "rollout_tokens_per_sec": auto["rollout_tokens_per_sec"],
        "waste_frac": auto["waste_frac"],
        "waste_frac_fixed": fixed["waste_frac"],
        "lost": auto["lost"] + fixed["lost"],
        "scale_ups": auto["scale_ups"],
        "scale_downs": auto["scale_downs"],
        "scale_up_compile_delta_max": max(up_deltas, default=0),
        "steady_state_compile_delta": auto["steady_state_compile_delta"],
        "crashes": auto["crashes"] + fixed["crashes"],
        "kv_counter_exact": bool(auto["kv_counter_exact"]
                                 and fixed["kv_counter_exact"]),
        "offered_rps": round(lam, 2),
        "n_arrivals": len(plan),
        "horizon_s": horizon_s,
        "slo_ttft_threshold_s": slo_ttft_s,
        "compile_s": round(compile_s, 2),
        "n_slots": S,
        "arms": {"fixed": fixed, "autoscale": auto},
        "disagg": disagg,
        "ir_audit": _ir_audit_section(jax, prefix="serving."),
        "metrics": {
            "slo_ttft_attainment_burst_autoscale": att_auto,
            "slo_ttft_attainment_burst_fixed": att_fixed,
            "rollout_tokens_per_sec": auto["rollout_tokens_per_sec"],
            "waste_frac_autoscale": auto["waste_frac"],
            "waste_frac_fixed": fixed["waste_frac"],
            "lost": auto["lost"] + fixed["lost"],
            "scale_up_compile_delta_max": max(up_deltas, default=0),
        },
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_prefix(report: bool = True) -> dict:
    """BENCH_MODE=prefix: prefix-aware KV reuse (the ISSUE-11 tentpole).

    The workload is the shape prefix caching exists for: a few long
    shared system prompts with short per-request suffixes, replayed
    open-loop (seeded Poisson arrivals) against a 2-engine
    :class:`ServingFleet` twice — once with the legacy allocator, once
    with ``prefix_cache=True`` — on the SAME seeded plan.  Headline is
    the measured per-request prefill-compute reduction (prefix-off
    prefill token positions / prefix-on), the ISSUE-11 acceptance bar
    being >= 2x; also reported: KV blocks charged per request, hit rate,
    CoW copies, evictions, and p50/p99 TTFT for both arms.

    Mid-run chaos: a seeded ``kvmem.evict`` crash fires on the first LRU
    eviction step of the prefix arm — the member quarantines, work fails
    over, and the accounting must still balance (``lost == 0``).  The
    prefix arm's traffic window runs under :class:`CompileDelta` after
    engine-level glue rounds (two consecutive compile-free rounds), so
    ``steady_state_compile_delta == 0`` proves partial prefill + CoW
    copies + table flushes all run on warmed shapes.  TTFT tails of the
    two arms are not directly comparable (only the prefix arm absorbs a
    crash); the reduction ratio is the headline, the tails are context.
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.compile import CompileDelta, ShapeBuckets
    from rl_tpu.models import (
        ContinuousBatchingEngine,
        FinishedRequest,
        ServiceSaturated,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )
    from rl_tpu.obs import MetricsRegistry
    from rl_tpu.resilience import Fault, FaultInjector, injection

    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 22
        horizon_s, n_lo, n_hi = 3.0, 4, 8
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 24
        horizon_s, n_lo, n_hi = 8.0, 6, 12
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, sys_len = 8, 128, 96
        horizon_s, n_lo, n_hi = 15.0, 16, 32
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    sysps = [rng.integers(0, cfg.vocab_size, sys_len) for _ in range(3)]

    def mk_prompt():
        sp = sysps[int(rng.integers(len(sysps)))]
        return np.concatenate(
            [sp, rng.integers(0, cfg.vocab_size, int(rng.integers(2, 8)))]
        )

    buckets = ShapeBuckets(prompt=(bucket,), suffix=(8, 16))
    n_blocks = S * (cfg.max_seq_len // 16) + 1

    def mk_engines(prefix: bool):
        return [
            ContinuousBatchingEngine(
                model, params, n_slots=S, block_size=16, n_blocks=n_blocks,
                prompt_buckets=None, buckets=buckets, greedy=True,
                decode_chunk=4, seed=i, prefix_cache=prefix,
            )
            for i in range(2)
        ]

    def glue(engines):
        """aot_warmup + engine-level traffic rounds until two CONSECUTIVE
        rounds are compile-free: the eager host-glue shape set (pending
        table-write flushes, CoW pad counts, admit pads) is finite but
        only fully visited once tree growth and eviction reach their
        steady pattern."""
        t0 = time.perf_counter()
        for e in engines:
            e.aot_warmup()
        clean = 0
        for _ in range(12):
            with CompileDelta() as d:
                for e in engines:
                    for _ in range(2 * S):
                        e.submit(mk_prompt(), int(rng.integers(n_lo, n_hi)))
                    e.run()
            clean = clean + 1 if (not d.supported or d.delta == 0) else 0
            if clean >= 2:
                break
        return time.perf_counter() - t0

    def run_arm(engines, faults: bool):
        # calibrate offered load off this arm's engine 0 (post-glue, warm)
        cal = [(mk_prompt(), int(rng.integers(n_lo, n_hi)))
               for _ in range(2 * S)]
        for p, n in cal:
            engines[0].submit(p, n)
        t0 = time.perf_counter()
        engines[0].run()
        lam = 0.9 * 2.0 * len(cal) / (time.perf_counter() - t0)
        arrivals, t = [], 0.0
        while t < horizon_s:
            t += rng.exponential(1.0 / lam)
            if t < horizon_s:
                arrivals.append(t)
        plan = [(a, mk_prompt(), int(rng.integers(n_lo, n_hi)))
                for a in arrivals]
        pre_computed = sum(e.prefill_tokens_computed for e in engines)
        pre_cached = sum(e.prefill_tokens_cached for e in engines)
        pre_charged = sum(e._kvmem.blocks_charged for e in engines
                          if e._kvmem is not None)
        reg = MetricsRegistry()
        fleet = ServingFleet(engines, registry=reg, probe_interval_s=0.02,
                             max_queue=len(plan)).start()
        inj = FaultInjector(
            {"kvmem.evict": Fault("crash", at=(1,))} if faults else {},
            registry=reg)
        admitted, rejected = [], 0
        steady = CompileDelta()
        t_start = time.monotonic()
        try:
            with steady, injection(inj):
                for a, prompt, n_new in plan:
                    now = time.monotonic() - t_start
                    if a > now:
                        time.sleep(a - now)
                    try:
                        admitted.append(fleet.submit(prompt, n_new))
                    except ServiceSaturated:
                        rejected += 1
                results = fleet.wait(
                    admitted, timeout=_T(smoke=120, cpu=300, full=300))
        finally:
            wall = time.monotonic() - t_start
            acc = fleet.accounting()
            stats = fleet.request_stats()
            fleet.shutdown()
        done = sum(1 for r in results.values()
                   if isinstance(r, FinishedRequest))
        ttft = [s["first_token_at"] - s["submitted_at"] for s in stats
                if s["first_token_at"] is not None]

        def pct(q):
            return round(float(np.percentile(ttft, q)), 4) if ttft else None

        kv = {}
        if engines[0]._kvmem is not None:
            snaps = [e.metrics_snapshot() for e in engines]
            kv = {
                "kv_prefix_hit_rate": round(
                    sum(s["kv_prefill_tokens_cached"] for s in snaps)
                    / max(1, sum(s["kv_prefill_tokens_cached"]
                                 + s["kv_prefill_tokens_computed"]
                                 for s in snaps)), 4),
                "kv_shared_blocks": sum(s["kv_shared_blocks"] for s in snaps),
                "kv_cow_copies_total": sum(s["kv_cow_copies_total"] for s in snaps),
                "kv_evictions_total": sum(s["kv_evictions_total"] for s in snaps),
                "kv_blocks_per_request": round(
                    (sum(e._kvmem.blocks_charged for e in engines)
                     - pre_charged) / max(1, done), 3),
            }
        else:
            # legacy arm: every admission charges the full table row; the
            # engine pops free_blocks without a counter, but with greedy
            # decode and no eos the final coverage is exactly
            # ceil((P + G) / block) per completed request
            rid_plan = {rid: (p, n) for rid, (_, p, n)
                        in zip(admitted, plan[:len(admitted)])}
            kv = {"kv_blocks_per_request": round(sum(
                -(-(len(rid_plan[rid][0]) + rid_plan[rid][1]) // 16)
                for rid, r in results.items()
                if isinstance(r, FinishedRequest) and rid in rid_plan
            ) / max(1, done), 3)}
        return {
            "computed": sum(e.prefill_tokens_computed for e in engines) - pre_computed,
            "cached": sum(e.prefill_tokens_cached for e in engines) - pre_cached,
            "done": done, "rejected": rejected, "wall_s": round(wall, 2),
            "p50_ttft_s": pct(50), "p99_ttft_s": pct(99),
            "lost": acc["lost"],
            "invariant_ok": bool(
                acc["lost"] == 0
                and acc["completed"] + acc["shed_post_admission"] == len(admitted)),
            "steady_state_compile_delta": steady.delta if steady.supported else None,
            "faults_fired": len(inj.fired),
            **kv,
        }

    base_eng = mk_engines(False)
    compile_s = glue(base_eng)
    base = run_arm(base_eng, faults=False)
    pfx_eng = mk_engines(True)
    compile_s += glue(pfx_eng)
    pfx = run_arm(pfx_eng, faults=True)

    base_per = base["computed"] / max(1, base["done"])
    pfx_per = pfx["computed"] / max(1, pfx["done"])
    reduction = round(base_per / max(1e-9, pfx_per), 3)
    metrics = {
        "prefill_reduction_x": reduction,
        "reduction_ok": bool(reduction >= 2.0),
        "prefill_tokens_per_request_baseline": round(base_per, 2),
        "prefill_tokens_per_request_prefix": round(pfx_per, 2),
        "kv_blocks_per_request_baseline": base["kv_blocks_per_request"],
        "kv_blocks_per_request_prefix": pfx["kv_blocks_per_request"],
        "kv_prefix_hit_rate": pfx["kv_prefix_hit_rate"],
        "kv_shared_blocks": pfx["kv_shared_blocks"],
        "kv_cow_copies_total": pfx["kv_cow_copies_total"],
        "kv_evictions_total": pfx["kv_evictions_total"],
        "steady_state_compile_delta": pfx["steady_state_compile_delta"],
        "lost": pfx["lost"],
        "invariant_ok": bool(pfx["invariant_ok"] and base["invariant_ok"]),
        "faults_fired": pfx["faults_fired"],
    }
    out = {
        "metric": "prefix_prefill_reduction_x",
        "value": reduction,
        "unit": "x",
        "vs_baseline": reduction,
        **metrics,
        "baseline": base,
        "prefix": pfx,
        "compile_s": round(compile_s, 2),
        "n_slots": S, "n_engines": 2, "horizon_s": horizon_s,
        "metrics": metrics,
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_spec(report: bool = True) -> dict:
    """BENCH_MODE=spec: speculative decoding A/B (the ISSUE-16 tentpole).

    The workload is the shape self-speculation exists for: a small pool
    of prompts REPLAYED open-loop (seeded Poisson arrivals) against a
    2-engine ``prefix_cache=True`` fleet — every replay's continuation
    is already donated into the radix tree, so the draft source proposes
    the exact tokens greedy decode will accept.  Two arms on the SAME
    seeded plan and the same decode chunk: ``speculative=False`` vs
    ``speculative=True`` (PrefixTreeDraft).  Headline is the tokens/s
    speedup (ISSUE-16 bar: >= 1.3x); also reported: accepted tokens per
    verify dispatch (bar: > 1.0), draft hit rate, p50/p99 TTFT and
    end-to-end latency for both arms, and ``steady_state_compile_delta``
    for both arms (the verify family must ride the warmed decode
    ladder — the bar is 0).

    Mid-run chaos: a seeded ``fleet.engine_crash.0`` fires on the spec
    arm while verifies are in flight — the member quarantines, work
    fails over, and the accounting must still balance (``lost == 0``).
    """
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.compile import CompileDelta, ShapeBuckets
    from rl_tpu.models import (
        ContinuousBatchingEngine,
        FinishedRequest,
        ServiceSaturated,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )
    from rl_tpu.obs import MetricsRegistry
    from rl_tpu.resilience import Fault, FaultInjector, injection

    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 22
        horizon_s, n_new, n_pool = 3.0, 64, 4
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 24
        horizon_s, n_new, n_pool = 8.0, 80, 6
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, sys_len = 8, 128, 96
        horizon_s, n_new, n_pool = 15.0, 128, 8
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    sysp = rng.integers(0, cfg.vocab_size, sys_len)
    # the replay pool: shared system prompt + short distinct suffixes;
    # the SAME prompts recur, so every continuation is a resident donor
    pool = [np.concatenate([sysp, rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(2, 8)))])
            for _ in range(n_pool)]

    def mk_prompt():
        return pool[int(rng.integers(len(pool)))]

    buckets = ShapeBuckets(prompt=(bucket,), suffix=(8, 16))
    # 8x the live-slot footprint: headroom for the replay pool's donors
    # (draft hits keep them LRU-hot; see PrefixTree.lookahead) plus the
    # per-completion partial-tail churn of the oversaturated backlog
    n_blocks = 8 * S * (cfg.max_seq_len // 16) + 1

    def mk_engines(spec: bool):
        return [
            ContinuousBatchingEngine(
                model, params, n_slots=S, block_size=16, n_blocks=n_blocks,
                prompt_buckets=None, buckets=buckets, greedy=True,
                decode_chunk=4, seed=i, prefix_cache=True,
                speculative=spec, spec_lookahead=15,
            )
            for i in range(2)
        ]

    def glue(engines):
        """aot_warmup + replayed traffic rounds until two CONSECUTIVE
        rounds are compile-free (see bench_prefix.glue); the replays
        also seed the radix tree so the measured window drafts hot."""
        t0 = time.perf_counter()
        for e in engines:
            e.aot_warmup()
        clean = 0
        for _ in range(12):
            with CompileDelta() as d:
                for e in engines:
                    for p in pool:
                        e.submit(p, n_new)
                    e.run()
            clean = clean + 1 if (not d.supported or d.delta == 0) else 0
            if clean >= 2:
                break
        return time.perf_counter() - t0

    def run_arm(engines, plan, faults: bool):
        pre_acc = sum(e.spec_accepted_tokens for e in engines)
        pre_disp = sum(e.spec_dispatches for e in engines)
        reg = MetricsRegistry()
        fleet = ServingFleet(engines, registry=reg, probe_interval_s=0.02,
                             max_queue=len(plan)).start()
        inj = FaultInjector(
            {"fleet.engine_crash.0": Fault("crash", at=(3,))} if faults
            else {},
            registry=reg)
        admitted, rejected = [], 0
        steady = CompileDelta()
        t_start = time.monotonic()
        try:
            with steady, injection(inj):
                for a, prompt, n_new in plan:
                    now = time.monotonic() - t_start
                    if a > now:
                        time.sleep(a - now)
                    try:
                        admitted.append(fleet.submit(prompt, n_new))
                    except ServiceSaturated:
                        rejected += 1
                results = fleet.wait(
                    admitted, timeout=_T(smoke=120, cpu=300, full=300))
        finally:
            wall = time.monotonic() - t_start
            acc = fleet.accounting()
            stats = fleet.request_stats()
            fleet.shutdown()
        done = sum(1 for r in results.values()
                   if isinstance(r, FinishedRequest))
        tokens = sum(s["tokens"] for s in stats)
        ttft = [s["first_token_at"] - s["submitted_at"] for s in stats
                if s["first_token_at"] is not None]
        lat = [s["done_at"] - s["submitted_at"] for s in stats
               if s["done_at"] is not None]

        def pct(xs, q):
            return round(float(np.percentile(xs, q)), 4) if xs else None

        disp = sum(e.spec_dispatches for e in engines) - pre_disp
        accepted = sum(e.spec_accepted_tokens for e in engines) - pre_acc
        snaps = [e.metrics_snapshot() for e in engines]
        hits = sum(s.get("spec_draft_hits", 0) for s in snaps)
        misses = sum(s.get("spec_draft_misses", 0) for s in snaps)
        return {
            "done": done, "rejected": rejected, "tokens": tokens,
            "wall_s": round(wall, 2),
            "tokens_per_s": round(tokens / max(1e-9, wall), 2),
            "p50_ttft_s": pct(ttft, 50), "p99_ttft_s": pct(ttft, 99),
            "p50_latency_s": pct(lat, 50), "p99_latency_s": pct(lat, 99),
            "spec_dispatches": disp,
            "accepted_tokens_per_dispatch": round(accepted / disp, 3)
            if disp else None,
            "spec_draft_hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "lost": acc["lost"],
            "invariant_ok": bool(
                acc["lost"] == 0
                and acc["completed"] + acc["shed_post_admission"]
                == len(admitted)),
            "steady_state_compile_delta": steady.delta if steady.supported
            else None,
            "faults_fired": len(inj.fired),
        }

    off_eng = mk_engines(False)
    compile_s = glue(off_eng)
    # calibrate offered load off the vanilla arm (post-glue, warm), then
    # OVERSATURATE it: both arms see the same backlogged plan, so each
    # arm's tokens/s measures its service rate, not the arrival process
    cal = [(mk_prompt(), n_new) for _ in range(2 * S)]
    for p, n in cal:
        off_eng[0].submit(p, n)
    t0 = time.perf_counter()
    off_eng[0].run()
    lam = 2.0 * 2.0 * len(cal) / (time.perf_counter() - t0)
    arrivals, t = [], 0.0
    while t < horizon_s:
        t += rng.exponential(1.0 / lam)
        if t < horizon_s:
            arrivals.append(t)
    plan = [(a, mk_prompt(), n_new) for a in arrivals]
    off = run_arm(off_eng, plan, faults=False)
    spec_eng = mk_engines(True)
    compile_s += glue(spec_eng)
    spec = run_arm(spec_eng, plan, faults=True)

    speedup = round(spec["tokens_per_s"] / max(1e-9, off["tokens_per_s"]), 3)
    metrics = {
        "spec_speedup_x": speedup,
        "speedup_ok": bool(speedup >= 1.3),
        "accepted_tokens_per_dispatch": spec["accepted_tokens_per_dispatch"],
        "accept_ok": bool((spec["accepted_tokens_per_dispatch"] or 0) > 1.0),
        "spec_draft_hit_rate": spec["spec_draft_hit_rate"],
        "tokens_per_s_off": off["tokens_per_s"],
        "tokens_per_s_spec": spec["tokens_per_s"],
        "steady_state_compile_delta_off": off["steady_state_compile_delta"],
        "steady_state_compile_delta_spec": spec["steady_state_compile_delta"],
        "lost": spec["lost"],
        "invariant_ok": bool(spec["invariant_ok"] and off["invariant_ok"]),
        "faults_fired": spec["faults_fired"],
    }
    out = {
        "metric": "spec_decode_speedup_x",
        "value": speedup,
        "unit": "x",
        "vs_baseline": speedup,
        **metrics,
        "baseline": off,
        "spec": spec,
        "compile_s": round(compile_s, 2),
        "n_slots": S, "n_engines": 2, "horizon_s": horizon_s,
        "metrics": metrics,
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_kernels(report: bool = True) -> dict:
    """BENCH_MODE=kernels: Pallas kernel tier A/B (the ISSUE-17 tentpole).

    Each registered kernel against its stock-XLA fallback on the SAME
    seeded workload:

    - **serving** (paged_attention + sampling): the seeded fleet replay
      plan (bench_spec's workload minus speculation) — a prompt pool
      replayed open-loop against a 2-engine prefix-cache fleet. The
      fallback arm pins ``RL_TPU_NO_KERNELS=1``; the kernel arm runs
      native Mosaic on a supporting backend and Pallas interpret mode
      elsewhere (on CPU the kernel arm measures correctness-at-speed —
      parity under load — not a win; the win is a chip-only number).
      Reported per arm: tokens/s, p50/p99 TTFT + latency, per-dispatch
      decode device time, and steady-state CompileDelta (bar: 0 BOTH
      arms — kernels ride the same warmed ladder). Greedy decoding makes
      the arms' total token count a cross-arm parity probe.
    - **per** (sumtree): the fused PER sample→update cycle (bench_per's
      ``fused_cycles``) A/B'd the same way, plus a bit-exact priorities
      parity check between the arms after identical update streams.
    - **kv_int8 capacity**: the effective-KV-blocks-per-chip multiplier
      of the int8 pool layout (ISSUE gate: >= 1.8x) and its accuracy
      delta — greedy tokens + log-probs from a ``kv_int8=True`` engine
      vs the f32 engine on identical traffic.

    The ``ir_audit`` section carries the per-kernel predicted-vs-
    measured MFU rows (``by_kernel``) priced by the kernel registry's
    cost formulas, and ``kernel_status`` records the feature-detection
    matrix each arm resolved.
    """
    jax = _setup_jax()
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.compile import CompileDelta, ShapeBuckets, get_program_registry
    from rl_tpu.data.replay.samplers import PrioritizedSampler
    from rl_tpu.kernels.kvcache import effective_blocks_ratio
    from rl_tpu.kernels.registry import registered_kernels
    from rl_tpu.kernels.registry import status as kernel_status
    from rl_tpu.models import (
        ContinuousBatchingEngine,
        FinishedRequest,
        ServingFleet,
        TransformerConfig,
        TransformerLM,
    )
    from rl_tpu.obs import MetricsRegistry

    if _TIER == "smoke":
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, d_ff=128, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 22
        horizon_s, n_new, n_pool = 2.0, 48, 4
    elif _TIER == "cpu":
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_layers=2,
                                n_heads=4, d_ff=512, max_seq_len=128,
                                dtype=jnp.float32)
        S, bucket, sys_len = 4, 32, 24
        horizon_s, n_new, n_pool = 6.0, 64, 6
    else:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_layers=12,
                                n_heads=12, d_ff=3072, max_seq_len=256,
                                dtype=jnp.bfloat16)
        S, bucket, sys_len = 8, 128, 96
        horizon_s, n_new, n_pool = 12.0, 128, 8
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    sysp = rng.integers(0, cfg.vocab_size, sys_len)
    pool = [np.concatenate([sysp, rng.integers(0, cfg.vocab_size,
                                               int(rng.integers(2, 8)))])
            for _ in range(n_pool)]

    def mk_prompt():
        return pool[int(rng.integers(len(pool)))]

    buckets = ShapeBuckets(prompt=(bucket,), suffix=(8, 16))
    n_blocks = 8 * S * (cfg.max_seq_len // 16) + 1

    # arm env control: restore-then-set keeps the two knobs from leaking
    # between arms (and out of the bench). Selection is re-read at trace
    # time, and kernels_fingerprint() rides every program fingerprint, so
    # each arm's engines compile their own executables.
    prev_env = {k: os.environ.get(k)
                for k in ("RL_TPU_NO_KERNELS", "RL_TPU_KERNELS_INTERPRET")}

    def set_arm(active: bool) -> None:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if not active:
            os.environ["RL_TPU_NO_KERNELS"] = "1"
        elif jax.default_backend() not in ("tpu",):
            os.environ["RL_TPU_KERNELS_INTERPRET"] = "1"

    def mk_engines(cfg=cfg, model=model):
        return [
            ContinuousBatchingEngine(
                model, params, n_slots=S, block_size=16, n_blocks=n_blocks,
                prompt_buckets=None, buckets=buckets, greedy=True,
                decode_chunk=4, seed=i, prefix_cache=True,
            )
            for i in range(2)
        ]

    def glue(engines):
        t0 = time.perf_counter()
        for e in engines:
            e.aot_warmup()
        clean = 0
        for _ in range(12):
            with CompileDelta() as d:
                for e in engines:
                    for p in pool:
                        e.submit(p, n_new)
                    e.run()
            clean = clean + 1 if (not d.supported or d.delta == 0) else 0
            if clean >= 2:
                break
        return time.perf_counter() - t0

    def decode_stats():
        out = {}
        for name, s in get_program_registry().stats().items():
            if name.startswith(("serving.decode.", "serving.sdecode.")):
                out[name] = (float(s.get("device_s") or 0.0),
                             int(s.get("device_samples") or 0))
        return out

    def run_arm(engines, plan):
        reg = MetricsRegistry()
        fleet = ServingFleet(engines, registry=reg, probe_interval_s=0.02,
                             max_queue=len(plan)).start()
        admitted = []
        steady = CompileDelta()
        pre = decode_stats()
        t_start = time.monotonic()
        try:
            with steady:
                for a, prompt, n in plan:
                    now = time.monotonic() - t_start
                    if a > now:
                        time.sleep(a - now)
                    admitted.append(fleet.submit(prompt, n))
                results = fleet.wait(
                    admitted, timeout=_T(smoke=240, cpu=420, full=300))
        finally:
            wall = time.monotonic() - t_start
            stats = fleet.request_stats()
            fleet.shutdown()
        post = decode_stats()
        done = sum(1 for r in results.values()
                   if isinstance(r, FinishedRequest))
        tokens = sum(s["tokens"] for s in stats)
        ttft = [s["first_token_at"] - s["submitted_at"] for s in stats
                if s["first_token_at"] is not None]
        lat = [s["done_at"] - s["submitted_at"] for s in stats
               if s["done_at"] is not None]

        def pct(xs, q):
            return round(float(np.percentile(xs, q)), 4) if xs else None

        d_dev = sum(b[0] - pre.get(n, (0.0, 0))[0] for n, b in post.items())
        d_n = sum(b[1] - pre.get(n, (0.0, 0))[1] for n, b in post.items())
        return {
            "done": done, "tokens": tokens, "wall_s": round(wall, 2),
            "tokens_per_s": round(tokens / max(1e-9, wall), 2),
            "p50_ttft_s": pct(ttft, 50), "p99_ttft_s": pct(ttft, 99),
            "p50_latency_s": pct(lat, 50), "p99_latency_s": pct(lat, 99),
            "decode_dispatch_us": round(1e6 * d_dev / d_n, 1) if d_n else None,
            "steady_state_compile_delta": steady.delta if steady.supported
            else None,
        }

    try:
        # -- serving A/B -------------------------------------------------
        def calibrate(eng):
            cal = [(mk_prompt(), n_new) for _ in range(2 * S)]
            for p, n in cal:
                eng.submit(p, n)
            t0 = time.perf_counter()
            eng.run()
            return len(cal) / (time.perf_counter() - t0)

        set_arm(False)
        status_off = kernel_status()
        off_eng = mk_engines()
        compile_s = glue(off_eng)
        rate_off = calibrate(off_eng[0])
        set_arm(True)
        status_on = kernel_status()
        on_eng = mk_engines()
        compile_s += glue(on_eng)
        rate_on = calibrate(on_eng[0])
        # calibrate offered load off the SLOWER warmed arm (on CPU the
        # interpret-mode kernel arm is the slow one — interpret measures
        # parity, not speed), then oversaturate: both arms see the same
        # backlogged seeded plan, so tokens/s measures each arm's
        # service rate, not the arrival process
        lam = 2.0 * 2.0 * min(rate_off, rate_on)
        arrivals, t = [], 0.0
        while t < horizon_s:
            t += rng.exponential(1.0 / lam)
            if t < horizon_s:
                arrivals.append(t)
        plan = [(a, mk_prompt(), n_new) for a in arrivals]
        set_arm(False)
        off = run_arm(off_eng, plan)
        del off_eng
        set_arm(True)
        on = run_arm(on_eng, plan)
        del on_eng

        # -- PER sum-tree A/B --------------------------------------------
        capacity = _T(smoke=4096, cpu=1 << 14, full=1 << 18)
        batch, inner = 256, _T(smoke=3, cpu=8, full=30)
        reps = _T(smoke=2, cpu=3, full=5)
        sampler = PrioritizedSampler()
        prio0 = jax.random.uniform(jax.random.key(0), (capacity,)) + 0.01
        data = jax.random.normal(jax.random.key(1), (capacity, 8), jnp.float32)
        size = jnp.asarray(capacity, jnp.int32)

        def fake_td(idx):
            return jnp.abs(data[idx].sum(axis=-1)) + 0.01

        def mk_state():
            st = sampler.init(capacity)
            return sampler.update_priority(
                st, jnp.arange(capacity), prio0, indices_sorted=True)

        def run_per_arm(active: bool):
            set_arm(active)

            @jax.jit
            def fused(sstate, key):
                def body(_, carry):
                    sstate, key = carry
                    key, k1 = jax.random.split(key)
                    _i, _f, sstate = sampler.sample_and_update(
                        sstate, k1, batch, size, capacity,
                        lambda i, _info: fake_td(i))
                    return sstate, key

                return jax.lax.fori_loop(0, inner, body, (sstate, key))

            st = mk_state()
            st, _k = fused(st, jax.random.key(2))  # compile + warm
            jax.block_until_ready(st["priorities"])
            best = float("inf")
            for r in range(reps):
                t0 = time.perf_counter()
                out, _k = fused(st, jax.random.key(3))
                jax.block_until_ready(out["priorities"])
                best = min(best, time.perf_counter() - t0)
            # one dispatch through the REGISTERED fused-PER program so the
            # sumtree kernel shows up in the ir_audit roll-up (R106 +
            # priced roofline); the fori_loop above stays the timing path
            prog = sampler.jit_sample_and_update(
                lambda i, _info: fake_td(i), batch, capacity,
                donate=False, fingerprint="bench.kernels",
            )
            jax.block_until_ready(
                prog(mk_state(), jax.random.key(4), size)[2]["priorities"]
            )
            return round(inner * batch / best, 1), out

        per_off_rate, per_off_state = run_per_arm(False)
        per_on_rate, per_on_state = run_per_arm(True)
        per_parity = bool(
            np.array_equal(np.asarray(per_off_state["priorities"]),
                           np.asarray(per_on_state["priorities"]))
            and np.array_equal(np.asarray(per_off_state["esum"]),
                               np.asarray(per_on_state["esum"])))

        # -- int8 KV capacity + accuracy ---------------------------------
        head_dim = cfg.d_model // cfg.n_heads
        kvh = cfg.n_kv_heads or cfg.n_heads
        capacity_ratio = round(effective_blocks_ratio(16, kvh, head_dim), 3)
        acc_prompts = pool[: min(4, len(pool))]

        def serve_once(use_int8: bool):
            set_arm(use_int8)  # int8 engine exercises the int8 read kernel
            c = dataclasses.replace(cfg, kv_int8=True) if use_int8 else cfg
            m = TransformerLM(c)
            eng = ContinuousBatchingEngine(
                m, params, n_slots=S, block_size=16, n_blocks=n_blocks,
                prompt_buckets=None, buckets=buckets, greedy=True,
                decode_chunk=4, seed=0,
            )
            rids = [eng.submit(p, 8) for p in acc_prompts]
            res = eng.run()
            return [res[r] for r in rids]

        ref = serve_once(False)
        q = serve_once(True)
        agree = [float(np.mean(a.tokens[: len(b.tokens)]
                               == b.tokens[: len(a.tokens)]))
                 for a, b in zip(ref, q)]
        lp_delta = [float(np.mean(np.abs(
            a.log_probs[: min(len(a.log_probs), len(b.log_probs))]
            - b.log_probs[: min(len(a.log_probs), len(b.log_probs))])))
            for a, b in zip(ref, q)]
        int8 = {
            "capacity_ratio_x": capacity_ratio,
            "capacity_ok": bool(capacity_ratio >= 1.8),
            "token_agreement": round(float(np.mean(agree)), 4),
            "mean_abs_lp_delta": round(float(np.mean(lp_delta)), 5),
        }
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    speedup = round(on["tokens_per_s"] / max(1e-9, off["tokens_per_s"]), 3)
    per_speedup = round(per_on_rate / max(1e-9, per_off_rate), 3)
    metrics = {
        "kernel_speedup_x": speedup,
        "per_kernel_speedup_x": per_speedup,
        "tokens_per_s_fallback": off["tokens_per_s"],
        "tokens_per_s_kernel": on["tokens_per_s"],
        "arms_token_parity": bool(off["tokens"] == on["tokens"]),
        "per_updates_per_s_fallback": per_off_rate,
        "per_updates_per_s_kernel": per_on_rate,
        "per_state_bit_parity": per_parity,
        "steady_state_compile_delta_fallback": off["steady_state_compile_delta"],
        "steady_state_compile_delta_kernel": on["steady_state_compile_delta"],
        "int8_capacity_ratio_x": int8["capacity_ratio_x"],
        "int8_capacity_ok": int8["capacity_ok"],
    }
    out = {
        "metric": "kernel_serving_speedup_x",
        "value": speedup,
        "unit": "x",
        **metrics,
        "fallback": off,
        "kernel": on,
        "int8_kv": int8,
        "kernel_status": {"fallback_arm": status_off, "kernel_arm": status_on},
        "registered": sorted(registered_kernels()),
        "compile_s": round(compile_s, 2),
        "n_slots": S, "n_engines": 2, "horizon_s": horizon_s,
        "ir_audit": _ir_audit_section(jax, prefix=""),
        "metrics": metrics,
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def _force_host_devices_flags(n: int) -> str:
    """XLA_FLAGS with the host-platform device count forced to ``n`` (any
    pre-existing force dropped). Only affects the cpu backend — on real
    chips the flag is inert and the worker uses the hardware topology."""
    base = os.environ.get("XLA_FLAGS", "")
    parts = [p for p in base.split() if "xla_force_host_platform_device_count" not in p]
    parts.append(f"--xla_force_host_platform_device_count={n}")
    return " ".join(parts)


def _multichip_worker(report: bool = True) -> dict:
    """One topology point of BENCH_MODE=multichip: MULTICHIP_DEVICES names
    the device count; the process builds the ``(batch, fsdp)`` mesh, times
    the donated gradient-accumulation GRPO update under (a) fully
    replicated params (the pre-sharding baseline) and (b) per-leaf FSDP
    placements with explicit in/out shardings, plus a sharded-params
    KV-cache rollout, and reports train MFU + tokens/s for each."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import optax

    from rl_tpu.models import TransformerConfig, TransformerLM, generate, token_log_probs
    from rl_tpu.models.generate import generate_flops, train_step_flops
    from rl_tpu.objectives.llm.grpo import GRPOLoss, mc_advantage
    from rl_tpu.parallel import data_sharding, fsdp_sharding, make_fsdp_mesh, replicated

    n = int(os.environ["MULTICHIP_DEVICES"])
    avail = len(jax.devices())
    if avail < n:
        out = {"metric": "multichip_worker", "n_devices": n, "value": 0.0,
               "error": f"only {avail} devices available (wanted {n})"}
        out.update(_platform_tag(jax))
        if report:
            print(json.dumps(out), flush=True)
        return out
    batch_ax, fsdp_ax = {1: (1, 1), 2: (1, 2), 4: (2, 2), 8: (2, 4)}.get(n, (1, n))
    mesh = make_fsdp_mesh(fsdp=fsdp_ax, batch=batch_ax)

    if _TIER == "smoke":
        B, Tp, Tn = 8, 16, 16
        cfg = TransformerConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=2,
                                d_ff=256, max_seq_len=Tp + Tn, dtype=jnp.float32)
    elif _TIER == "cpu":
        B, Tp, Tn = 16, 32, 32
        cfg = TransformerConfig(vocab_size=512, d_model=128, n_layers=4, n_heads=4,
                                d_ff=512, max_seq_len=Tp + Tn, dtype=jnp.float32)
    else:
        B, Tp, Tn = 32, 128, 128
        cfg = TransformerConfig(vocab_size=8192, d_model=512, n_layers=8, n_heads=8,
                                d_ff=2048, max_seq_len=Tp + Tn, dtype=jnp.bfloat16)
    T = Tp + Tn
    model = TransformerLM(cfg)
    key = jax.random.key(0)
    params = model.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    opt = optax.adam(3e-5)
    loss = GRPOLoss(
        lambda p, b: token_log_probs(model, p, b["tokens"]), clip_epsilon=0.2
    )
    mbs = max(1, B // 2)
    n_mb = B // mbs

    def _update_impl(params, opt_state, tokens, slp, amask, adv):
        full = dict(tokens=tokens, sample_log_prob=slp,
                    assistant_mask=amask, advantage=adv)
        xs = jax.tree.map(lambda x: x.reshape((n_mb, mbs) + x.shape[1:]), full)

        def body(carry, mb):
            gsum, vsum, wsum = carry
            w = loss.microbatch_weight(mb)
            (v, _), g = jax.value_and_grad(
                lambda p: loss(p, mb), has_aux=True
            )(params)
            gsum = jax.tree.map(lambda a, b: a + w * b, gsum, g)
            return (gsum, vsum + w * v, wsum + w), None

        zero = jnp.zeros((), jnp.float32)
        (gsum, vsum, wsum), _ = jax.lax.scan(
            body, (jax.tree.map(jnp.zeros_like, params), zero, zero), xs
        )
        wsum = jnp.maximum(wsum, 1e-8)
        g = jax.tree.map(lambda a: a / wsum, gsum)
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, upd), opt_state, vsum / wsum

    # fixed rollout-shaped inputs (one batch reused across reps: this bench
    # times the UPDATE dispatch, not collection)
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (B, T), 0, cfg.vocab_size)
    slp = -jnp.abs(jax.random.normal(k2, (B, T))) * 0.1
    amask = jnp.concatenate(
        [jnp.zeros((B, Tp), bool), jnp.ones((B, Tn), bool)], axis=1
    )
    reward = jax.random.normal(k2, (B,))
    adv = mc_advantage(reward, jnp.arange(B) // 4, max(1, (B + 3) // 4))
    reps = 2 if _TIER == "smoke" else 3
    train_flops = train_step_flops(cfg, n_params, B, T)
    peak = _peak_flops(jax) * n

    def _time_update(upd_fn, p0, o0):
        p, o = p0, o0

        def upd_step():  # raw jit + donation: one layout warmup after compile
            nonlocal p, o
            p, o, v = upd_fn(p, o, tokens, slp, amask, adv)
            return v

        compile_s, v = bench_warmup(upd_step, calls=2)
        # loss after TWO identical updates on both layouts: still an exact
        # replicated-vs-sharded parity probe
        v0 = float(v)
        t0 = time.perf_counter()
        for _ in range(reps):
            p, o, v = upd_fn(p, o, tokens, slp, amask, adv)
        jax.block_until_ready(v)
        dt = (time.perf_counter() - t0) / reps
        return {
            "train_s": round(dt, 4),
            "train_tokens_per_sec": round(B * T / dt, 1),
            "train_mfu": round(train_flops / dt / peak, 6),
            "compile_s": round(compile_s, 2),
        }, v0

    # (a) replicated baseline: the pre-sharding layout (every device holds
    # a full replica; grads all-reduce)
    repl = replicated(mesh)
    p_r = jax.device_put(params, repl)
    o_r = jax.device_put(opt.init(params), repl)
    upd_r = jax.jit(_update_impl, donate_argnums=(1,))
    res_r, v_r = _time_update(upd_r, p_r, o_r)

    # (b) FSDP-sharded: per-leaf placements, batch split over every data
    # axis, explicit in/out shardings on the donated dispatch
    psh = fsdp_sharding(params, mesh, min_size_mbytes=0.0)
    p_s = jax.tree.map(jax.device_put, params, psh)
    opt_state = opt.init(p_s)
    osh = fsdp_sharding(opt_state, mesh, min_size_mbytes=0.0)
    o_s = jax.tree.map(jax.device_put, opt_state, osh)
    bsh = data_sharding(mesh)
    upd_s = jax.jit(
        _update_impl,
        donate_argnums=(1,),
        in_shardings=(psh, osh, bsh, bsh, bsh, bsh),
        out_shardings=(psh, osh, repl),
    )
    res_s, v_s = _time_update(
        upd_s,
        p_s,
        o_s,
    )
    parity = abs(v_r - v_s)

    # sharded-params rollout: GSPMD derives the generation collectives
    # from the param placements alone
    prompts = jax.random.randint(k1, (B, Tp), 0, cfg.vocab_size)
    pmask = jnp.ones((B, Tp), jnp.float32)
    rollout = jax.jit(
        lambda p, k: generate(
            model, p, prompts, pmask, k, max_new_tokens=Tn, eos_id=None
        ).tokens
    )
    out_toks = rollout(p_s, jax.random.key(3))
    jax.block_until_ready(out_toks)
    gen_reps = max(1, reps - 1)
    t0 = time.perf_counter()
    for i in range(gen_reps):
        out_toks = rollout(p_s, jax.random.key(4 + i))
    jax.block_until_ready(out_toks)
    t_gen = (time.perf_counter() - t0) / gen_reps
    res_s["gen_tokens_per_sec"] = round(B * Tn / t_gen, 1)
    res_s["gen_mfu"] = round(
        generate_flops(cfg, n_params, B, Tp, Tn) / t_gen / peak, 6
    )

    out = {
        "metric": "multichip_worker",
        "value": res_s["train_tokens_per_sec"],
        "unit": "tokens/s",
        "n_devices": n,
        "mesh": [batch_ax, fsdp_ax],
        "replicated": res_r,
        "sharded": res_s,
        "loss_parity_absdiff": round(parity, 6),
        "n_params": n_params,
        "shape": [B, Tp, Tn],
        "error": None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_multichip(report: bool = True) -> dict:
    """BENCH_MODE=multichip: scaling-efficiency sweep over device counts.

    The default multichip tier forces the 8-device host topology
    (``--xla_force_host_platform_device_count=8``) and runs one worker
    subprocess per point (1, 4, 8 devices; the count must be pinned
    before JAX initializes, so each point owns a process). Each worker
    times the donated FSDP-sharded GRPO update against the replicated
    baseline; this orchestrator (which never imports jax) distills train
    MFU + tokens/s per point, scaling efficiency vs 1 device, and the
    sharded-vs-replicated ratio at 1 device (the no-regression gate)."""
    if os.environ.get("MULTICHIP_DEVICES"):
        return _multichip_worker(report)
    points = (1, 8) if _TIER == "smoke" else (1, 4, 8)
    deadline = _START + _TIMEOUT - 20.0
    results: dict = {}
    for i, n in enumerate(points):
        remaining = deadline - time.monotonic()
        if remaining <= 10.0:
            results[str(n)] = {"error": "skipped: BENCH_TIMEOUT budget exhausted"}
            continue
        extra = {
            "MULTICHIP_DEVICES": str(n),
            "XLA_FLAGS": _force_host_devices_flags(n),
        }
        if not os.environ.get("BENCH_PLATFORM") and _TIER != "full":
            extra["BENCH_PLATFORM"] = "cpu"  # forced topology is a cpu-tier run
        results[str(n)] = _run_sub_bench(
            "multichip", remaining / (len(points) - i), extra
        )

    def _tps(n, layout="sharded"):
        return (results.get(str(n), {}).get(layout) or {}).get("train_tokens_per_sec")

    metrics: dict = {}
    scaling: dict = {}
    base = _tps(1)
    for n in points:
        r = results.get(str(n), {})
        sh = r.get("sharded") or {}
        if not sh:
            continue
        metrics[f"train_tokens_per_sec_{n}dev"] = sh.get("train_tokens_per_sec")
        metrics[f"train_mfu_{n}dev"] = sh.get("train_mfu")
        metrics[f"gen_tokens_per_sec_{n}dev"] = sh.get("gen_tokens_per_sec")
        if base and sh.get("train_tokens_per_sec") is not None:
            scaling[str(n)] = round(sh["train_tokens_per_sec"] / base / n, 3)
    r1 = results.get("1", {})
    ratio = None
    if _tps(1) and _tps(1, "replicated"):
        ratio = round(_tps(1) / _tps(1, "replicated"), 3)
        metrics["sharded_vs_replicated_1dev"] = ratio
    metrics["scaling_efficiency"] = scaling
    top = max((n for n in points if _tps(n)), default=None)
    errors = [f"{k}: {v['error']}" for k, v in results.items() if v.get("error")]
    out = {
        "metric": "multichip_train_tokens_per_sec",
        "value": _tps(top) if top else 0.0,
        "unit": "tokens/s",
        "top_devices": top,
        "devices": results,
        "scaling_efficiency": scaling,
        "sharded_vs_replicated_1dev": ratio,
        # same-program-different-annotations at fsdp=1: anything beyond
        # timer noise is a real regression in the sharded dispatch
        "sharded_ok_1dev": (ratio is not None and ratio >= 0.9),
        "metrics": metrics,
        "platform": r1.get("platform"),
        "shapes": _TIER,
        "error": "; ".join(errors) or None,
    }
    if report:
        print(json.dumps(out), flush=True)
    return out


def _anakin_flops_per_train_step(frames: int, num_epochs: int = 4) -> float:
    """Analytic matmul FLOPs of one fused Anakin train step — the same
    actor/critic MLPs as the ppo headline (``_model_flops_per_train_step``)
    parameterized by batch size."""
    actor_macs = 4 * 64 + 64 * 64 + 64 * 2
    critic_macs = 4 * 64 + 64 * 64 + 64 * 1
    fwd = 2 * (actor_macs + critic_macs)
    rollout = 2 * actor_macs * frames
    gae = 2 * critic_macs * frames
    train = 3 * fwd * frames * num_epochs
    return float(rollout + gae + train)


def _anakin_worker(report: bool = True) -> dict:
    """One device-count point of BENCH_MODE=anakin: ANAKIN_DEVICES names
    the device count; the process builds a pure batch-parallel
    ``(batch=n, fsdp=1)`` mesh and sweeps num_envs, timing the fully
    fused env+policy+learner dispatch (AnakinProgram). At the smallest
    num_envs it also times the same math dispatched the host way —
    (a) Collector dispatch + update dispatch (two programs per step) and
    (b) one jitted env-step dispatched per frame from Python (the
    AsyncHostCollector pattern Anakin exists to kill) — so the committed
    artifact carries the fused-vs-host ratio the ISSUE-9 acceptance asks
    for."""
    jax = _setup_jax()
    import jax.numpy as jnp

    from rl_tpu.modules import (
        MLP,
        Categorical,
        ProbabilisticActor,
        TDModule,
        ValueOperator,
    )
    from rl_tpu.objectives import ClipPPOLoss
    from rl_tpu.parallel import make_fsdp_mesh
    from rl_tpu.trainers import AnakinConfig, AnakinProgram

    n = int(os.environ["ANAKIN_DEVICES"])
    avail = len(jax.devices())
    if avail < n:
        out = {"metric": "anakin_worker", "n_devices": n, "value": 0.0,
               "error": f"only {avail} devices available (wanted {n})"}
        out.update(_platform_tag(jax))
        if report:
            print(json.dumps(out), flush=True)
        return out
    mesh = make_fsdp_mesh(fsdp=1, batch=n)
    on_cpu = jax.devices()[0].platform == "cpu"

    sweep_envs = _T(smoke=[64], cpu=[256, 1024, 4096], full=[4096, 16384, 65536])
    unroll = _T(smoke=4, cpu=16, full=32)
    spd = _T(smoke=1, cpu=2, full=4)  # train steps fused per dispatch
    dispatches = _T(smoke=10, cpu=10, full=8)
    deadline = _START + _TIMEOUT - 15.0

    def build(num_envs):
        actor = ProbabilisticActor(
            TDModule(MLP(out_features=2, num_cells=(64, 64)),
                     ["observation"], ["logits"]),
            Categorical,
            dist_keys=("logits",),
        )
        critic = ValueOperator(MLP(out_features=1, num_cells=(64, 64)))
        loss = ClipPPOLoss(actor, critic, normalize_advantage=True)
        loss.make_value_estimator(gamma=0.99, lmbda=0.95)
        frames = num_envs * unroll
        cfg = AnakinConfig(
            num_envs=num_envs,
            unroll_length=unroll,
            steps_per_dispatch=spd,
            num_epochs=NUM_EPOCHS,
            minibatch_size=min(8192, frames // 2),
            # the axon TPU backend rejects donated inputs (see main());
            # donation is the steady-state win, so keep it where accepted
            donate=on_cpu,
        )
        return AnakinProgram(
            "cartpole", lambda p, td, k: actor(p["actor"], td, k), loss, cfg,
            mesh=mesh,
        )

    peak = _peak_flops(jax) * n
    sweep: list = []
    host_baselines: dict = {}
    for i, num_envs in enumerate(sweep_envs):
        if deadline - time.monotonic() <= 10.0:
            sweep.append({"num_envs": num_envs,
                          "error": "skipped: BENCH_TIMEOUT budget exhausted"})
            continue
        prog = build(num_envs)
        frames = prog.frames_per_step
        ts = prog.init(jax.random.key(0))
        dm = prog.init_metrics()

        def fused_step():
            nonlocal ts, dm
            ts, dm, m = prog.dispatch(ts, dm)
            return m

        # the fused dispatch is registry-backed (anakin.dispatch), so its
        # AOT layouts are committed at compile time: call 2 recompiling
        # would be a silent cold-start regression, and bench_warmup asserts
        # it does not happen
        compile_s, m = bench_warmup(fused_step, assert_no_recompile=True)
        t0 = time.perf_counter()
        for _ in range(dispatches):
            ts, dm, m = prog.dispatch(ts, dm)
        jax.block_until_ready(m)
        dt = time.perf_counter() - t0
        fused_sps = dispatches * prog.env_steps_per_dispatch / dt
        point = {
            "num_envs": num_envs,
            "frames_per_step": frames,
            "env_steps_per_sec": round(fused_sps, 1),
            "env_steps_per_sec_per_chip": round(fused_sps / n, 1),
            "mfu": round(
                _anakin_flops_per_train_step(frames, NUM_EPOCHS)
                * dispatches * spd / dt / peak, 6,
            ),
            "compile_s": round(compile_s, 2),
        }

        if i == 0 and deadline - time.monotonic() > 10.0:
            # host path (a): Collector dispatch + update dispatch per step
            inner = prog.inner
            collect = jax.jit(inner.collector.collect)
            update = jax.jit(inner.update_from_batch)
            hts = prog.init(jax.random.key(0))
            params, opt, cstate, rng = (
                hts["params"], hts["opt"], hts["collector"], hts["rng"],
            )

            def host_collector_step(params, opt, cstate, rng):
                batch, cstate = collect(params, cstate)
                params, opt, rng, hm = update(params, opt, rng, batch)
                return params, opt, cstate, rng, hm

            steps = dispatches * spd

            def host_warm():  # raw jit: layout-change recompile on call 2
                nonlocal params, opt, cstate, rng
                params, opt, cstate, rng, hm = host_collector_step(
                    params, opt, cstate, rng
                )
                return hm

            bench_warmup(host_warm, calls=2)
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt, cstate, rng, hm = host_collector_step(params, opt, cstate, rng)
            jax.block_until_ready(hm)
            host_sps = steps * frames / (time.perf_counter() - t0)

            # host path (b): one jitted env-step dispatch PER FRAME
            env = prog.env
            policy = inner.collector.policy

            def one_step(params, state, td, key):
                td = policy(params, td, key)
                state, full_td, carry_td = env.step_and_reset(state, td)
                return state, full_td, carry_td

            one = jax.jit(one_step)
            upd = jax.jit(inner.update_from_batch)
            state, td = env.reset(jax.random.key(1))
            params2, opt2, rng2 = hts["params"], hts["opt"], hts["rng"]

            def per_step_train(params, opt, state, td, rng, seed):
                fulls = []
                for t in range(unroll):
                    state, full_td, td = one(
                        params, state, td, jax.random.fold_in(jax.random.key(seed), t)
                    )
                    fulls.append(full_td)
                batch = jax.tree.map(lambda *xs: jnp.stack(xs), *fulls)
                params, opt, rng, hm = upd(params, opt, rng, batch)
                return params, opt, state, td, rng, hm

            warm_seed = iter((10_000, 10_001))

            def per_step_warm():  # raw jit: layout-change recompile on call 2
                nonlocal params2, opt2, state, td, rng2
                params2, opt2, state, td, rng2, hm = per_step_train(
                    params2, opt2, state, td, rng2, next(warm_seed)
                )
                return hm

            bench_warmup(per_step_warm, calls=2)
            ps_steps = max(1, steps // 2)
            t0 = time.perf_counter()
            for s in range(ps_steps):
                params2, opt2, state, td, rng2, hm = per_step_train(
                    params2, opt2, state, td, rng2, s + 1
                )
            jax.block_until_ready(hm)
            per_step_sps = ps_steps * frames / (time.perf_counter() - t0)

            point["host_collector_env_steps_per_sec"] = round(host_sps, 1)
            point["host_per_step_env_steps_per_sec"] = round(per_step_sps, 1)
            point["fused_vs_host_collector"] = round(fused_sps / host_sps, 3)
            point["fused_vs_per_step"] = round(fused_sps / per_step_sps, 3)
            host_baselines = {
                "num_envs": num_envs,
                "fused_vs_host_collector": point["fused_vs_host_collector"],
                "fused_vs_per_step": point["fused_vs_per_step"],
            }
        sweep.append(point)

    per_chip = [p.get("env_steps_per_sec_per_chip") for p in sweep
                if p.get("env_steps_per_sec_per_chip")]
    best = max(per_chip, default=0.0)
    out = {
        "metric": "anakin_worker",
        "value": best,
        "unit": "env_steps/s/chip",
        "n_devices": n,
        "mesh": [n, 1],
        "unroll_length": unroll,
        "steps_per_dispatch": spd,
        "sweep": sweep,
        "host_baseline": host_baselines or None,
        "ir_audit": _ir_audit_section(jax, prefix="anakin."),
        "error": "; ".join(p["error"] for p in sweep if p.get("error")) or None,
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps(out), flush=True)
    return out


def bench_anakin(report: bool = True) -> dict:
    """BENCH_MODE=anakin: the fused env+policy+learner program (ISSUE 9,
    Podracer "Anakin") swept over num_envs x {1,4,8} forced-host devices.

    Mirrors the multichip orchestration: the device count must be pinned
    before JAX initializes, so each point owns a worker subprocess
    (``ANAKIN_DEVICES``). Distills env-steps/s/chip + MFU per point, the
    per-chip scaling across num_envs (flat-to-rising = no host sync in
    the fused step), and the fused-vs-host-Collector ratio from the
    1-device worker."""
    if os.environ.get("ANAKIN_DEVICES"):
        return _anakin_worker(report)
    points = (1, 8) if _TIER == "smoke" else (1, 4, 8)
    deadline = _START + _TIMEOUT - 20.0
    results: dict = {}
    for i, n in enumerate(points):
        remaining = deadline - time.monotonic()
        if remaining <= 10.0:
            results[str(n)] = {"error": "skipped: BENCH_TIMEOUT budget exhausted"}
            continue
        extra = {
            "ANAKIN_DEVICES": str(n),
            "XLA_FLAGS": _force_host_devices_flags(n),
        }
        if not os.environ.get("BENCH_PLATFORM") and _TIER != "full":
            extra["BENCH_PLATFORM"] = "cpu"  # forced topology is a cpu-tier run
        results[str(n)] = _run_sub_bench(
            "anakin", remaining / (len(points) - i), extra
        )

    metrics: dict = {}
    num_envs_scaling: dict = {}
    top = None
    best = 0.0
    for n in points:
        r = results.get(str(n), {})
        v = r.get("value") or 0.0
        if v:
            metrics[f"env_steps_per_sec_per_chip_{n}dev"] = v
            if v >= best:
                best, top = v, n
    top_sweep = (results.get(str(top), {}) or {}).get("sweep") or []
    for p in top_sweep:
        if p.get("env_steps_per_sec_per_chip"):
            num_envs_scaling[str(p["num_envs"])] = p["env_steps_per_sec_per_chip"]
    r1 = results.get("1", {})
    hb = r1.get("host_baseline") or {}
    if hb.get("fused_vs_host_collector"):
        metrics["fused_vs_host_collector"] = hb["fused_vs_host_collector"]
        metrics["fused_vs_per_step"] = hb.get("fused_vs_per_step")
    metrics["num_envs_scaling_per_chip"] = num_envs_scaling
    errors = [f"{k}: {v['error']}" for k, v in results.items() if v.get("error")]
    # lift the deep-tier audit from whichever worker carried it (the audit
    # runs in the subprocess that owns the chip; the parent never compiles)
    ir_audit = next(
        (r["ir_audit"] for r in (results.get(str(n), {}) for n in points)
         if isinstance(r.get("ir_audit"), dict) and r["ir_audit"].get("programs_audited")),
        None,
    )
    out = {
        "metric": "anakin_env_steps_per_sec_per_chip",
        "value": best,
        "unit": "env_steps/s/chip",
        "vs_target": round(best / PER_CHIP_TARGET, 3),
        "top_devices": top,
        "devices": results,
        "num_envs_scaling": num_envs_scaling,
        "fused_vs_host_collector": hb.get("fused_vs_host_collector"),
        "fused_beats_host": (
            hb.get("fused_vs_host_collector") is not None
            and hb["fused_vs_host_collector"] > 1.0
        ),
        "ir_audit": ir_audit,
        "metrics": metrics,
        "platform": r1.get("platform"),
        "shapes": _TIER,
        "error": "; ".join(errors) or None,
    }
    if report:
        print(json.dumps(out), flush=True)
    return out


def _parse_last_json(text: str) -> dict | None:
    for ln in reversed((text or "").strip().splitlines()):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    return None


def _run_sub_bench(name: str, budget: float, extra_env: dict | None = None) -> dict:
    """Run BENCH_MODE=<name> in a fresh subprocess, killed at ``budget``
    seconds. The PARENT process of mode=all never initializes JAX — the
    TPU is exclusive per process, so each mode must own the chip alone —
    and a crashed/wedged sub-bench costs only its own slice."""
    env = dict(os.environ)
    env["BENCH_MODE"] = name
    # the parent aggregates child "metrics" sections itself; a child writing
    # the same BENCH_METRICS_OUT file would race/overwrite it
    env.pop("BENCH_METRICS_OUT", None)
    env.update(extra_env or {})
    # the child manages only its own slice; disable its outer watchdog so a
    # timeout is OUR kill (clean error field), not a nested 0.0 line
    env["BENCH_TIMEOUT"] = str(max(5.0, budget * 4))
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=budget,
        )
    except subprocess.TimeoutExpired as e:
        # a child may have printed its result and then wedged in teardown —
        # never drop a measured value
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout
        got = _parse_last_json(out or "")
        if got is not None:
            got.setdefault("error", None)
            got["note"] = f"result recovered; teardown exceeded {budget:.0f}s slice"
            return got
        return {"error": f"sub-bench '{name}' exceeded its {budget:.0f}s slice"}
    got = _parse_last_json(proc.stdout)
    if got is not None:
        # wall time incl. process start + compile: the slice-budget evidence
        got["wall_s"] = round(time.monotonic() - t0, 1)
        return got
    return {
        "error": f"sub-bench '{name}' emitted no JSON (rc={proc.returncode}): "
        + (proc.stderr or "")[-400:]
    }


PROBE_BUDGET = float(os.environ.get("BENCH_PROBE_TIMEOUT", "45"))
UNREACHABLE = "tpu backend unreachable (init hang)"


def bench_replay_shard(report: bool = True) -> dict:
    """BENCH_MODE=replay_shard: sharded experience tier A/B (ISSUE-20).

    Arm A: ONE ``ReplayService`` endpoint owning a device PER sum-tree at
    capacity C. Arm B: N=4 ``ReplayShard`` endpoints at C/N each behind
    the ``ShardedReplayBuffer`` mixture coordinator. Same total capacity,
    same offered write stream (4 writer threads), a sampling thread per
    arm measuring end-to-end sample latency. The PER write path's exact
    esum rebuild is O(capacity) per extend, so partitioning buys a real
    single-core win — the >=2x acceptance bound holds even on a 1-core
    host; process parallelism across shard servers is upside on top.

    Phase 2 replays the acceptance chaos scenario: a seeded
    ``replay.shard_crash.1`` kills a shard mid-traffic under supervised
    keepers — reported: learner-visible errors (must be 0), faults fired,
    and seconds from the crash to supervisor re-admission."""
    jax = _setup_jax()
    import threading

    import jax.numpy as jnp
    import numpy as np

    from rl_tpu.data import (
        ArrayDict,
        DeviceStorage,
        PrioritizedSampler,
        ReplayBuffer,
    )
    from rl_tpu.data.replay import (
        RemoteReplayBuffer,
        ReplayService,
        ReplayShard,
        ShardedReplayBuffer,
    )
    from rl_tpu.resilience import Fault, FaultInjector, injection

    N_SHARDS = 4
    # capacity picks the regime the subsystem targets (GEAR-scale
    # buffers): the PER write program carries O(capacity) full-array
    # work per extend (measured ~33ms/extend at 2^20 vs ~10ms at the
    # 2^18 shard size on cpu), so the partitioning win is algorithmic,
    # not core-count-dependent
    CAP = _T(smoke=1 << 12, cpu=1 << 20, full=1 << 21)
    ITEMS = _T(smoke=128, cpu=256, full=512)  # items per extend
    ARM_S = _T(smoke=2.0, cpu=6.0, full=8.0)  # timed window per arm
    SAMPLE_B = 64
    N_WRITERS = 4

    example = ArrayDict(
        observation=jnp.zeros((8,), jnp.float32),
        action=jnp.zeros((2,), jnp.float32),
        next=ArrayDict(
            reward=jnp.asarray(0.0, jnp.float32),
            done=jnp.asarray(False),
        ),
        collector=ArrayDict(policy_version=jnp.asarray(0, jnp.int32)),
    )

    def mk_batch(n, version=0):
        return ArrayDict(
            observation=jnp.zeros((n, 8), jnp.float32),
            action=jnp.zeros((n, 2), jnp.float32),
            next=ArrayDict(
                reward=jnp.zeros((n,), jnp.float32),
                done=jnp.zeros((n,), bool),
            ),
            collector=ArrayDict(
                policy_version=jnp.full((n,), version, jnp.int32)
            ),
        )

    def mk_buffer(cap):
        return ReplayBuffer(
            DeviceStorage(cap), PrioritizedSampler(), batch_size=SAMPLE_B
        )

    batch = jax.block_until_ready(mk_batch(ITEMS))

    def drive_arm(extend_fn, sample_fn, update_fn, warm_fn=None):
        """4 writers + 1 sampler against one arm for ARM_S seconds.
        Returns (items_written, sample_latencies_s)."""
        for _ in range(N_SHARDS):  # prefill + compile the write path
            extend_fn(batch)  # (round-robin: one batch lands per shard)
        if warm_fn is not None:
            warm_fn()  # pre-compile every in-shard draw bucket
        mb = sample_fn(SAMPLE_B)  # compile the sample path
        update_fn(
            np.asarray(mb["index"]).reshape(-1),
            np.full((SAMPLE_B,), 1.0, np.float32),
        )
        stop = time.monotonic() + ARM_S
        counts = [0] * N_WRITERS
        lat: list = []
        errs: list = []

        def writer(i):
            try:
                while time.monotonic() < stop:
                    extend_fn(batch)
                    counts[i] += ITEMS
            except Exception as e:  # noqa: BLE001 - surfaced in the result
                errs.append(repr(e))

        def sampler():
            # paced like a real learner (fixed consumption rate), not a
            # spin loop — an unpaced sampler on a small host just steals
            # writer CPU and the arm with the cheaper sample path wins
            # the WRITE benchmark for the wrong reason
            try:
                while time.monotonic() < stop:
                    t0 = time.perf_counter()
                    mb = sample_fn(SAMPLE_B)
                    lat.append(time.perf_counter() - t0)
                    update_fn(
                        np.asarray(mb["index"]).reshape(-1),
                        np.full((SAMPLE_B,), 1.0, np.float32),
                    )
                    time.sleep(max(0.0, 0.1 - (time.perf_counter() - t0)))
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [
            threading.Thread(target=writer, args=(i,)) for i in range(N_WRITERS)
        ] + [threading.Thread(target=sampler)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(f"arm errors: {errs[:3]}")
        return sum(counts), lat

    # -- arm A: one endpoint at full capacity ---------------------------------
    svc = ReplayService(mk_buffer(CAP), example, seed=0).start()
    clients = [RemoteReplayBuffer(*svc.address) for _ in range(N_WRITERS + 1)]
    rr = iter(range(1 << 30))
    try:
        n_single, lat_single = drive_arm(
            lambda b: clients[next(rr) % N_WRITERS].extend(b),
            clients[-1].sample,
            clients[-1].update_priority,
        )
    finally:
        svc.shutdown()

    # -- arm B: N shards at CAP/N behind the mixture coordinator ---------------
    shards = [
        ReplayShard(i, lambda: mk_buffer(CAP // N_SHARDS), example, seed=i).start()
        for i in range(N_SHARDS)
    ]
    coord = ShardedReplayBuffer(
        [s.address for s in shards], CAP // N_SHARDS,
        batch_size=SAMPLE_B, seed=0,
    )
    try:
        n_sharded, lat_sharded = drive_arm(
            coord.extend, coord.sample, coord.update_priority,
            warm_fn=coord.warm_sample,
        )
    finally:
        coord.close()
        for s in shards:
            s.shutdown()

    single_ips = n_single / ARM_S
    sharded_ips = n_sharded / ARM_S
    speedup = sharded_ips / max(single_ips, 1e-9)

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)) * 1e3, 2) if xs else None

    # -- phase 2: seeded shard crash under supervised keepers ------------------
    cap_c = _T(smoke=1 << 10, cpu=1 << 12, full=1 << 12)
    cshards = [
        ReplayShard(i, lambda: mk_buffer(cap_c), example, seed=i).start()
        for i in range(3)
    ]
    ccoord = ShardedReplayBuffer(
        [s.address for s in cshards], cap_c,
        batch_size=SAMPLE_B, seed=0,
        mass_refresh_s=0.05, probe_interval_s=0.05,
        restart_fn=lambda i: cshards[i].restart(),
    )
    inj = FaultInjector(
        {"replay.shard_crash.1": Fault(kind="crash", at=(20,))}, seed=0
    )
    learner_errors = 0
    recovery_s = None
    try:
        ccoord.start_keepers()
        with injection(inj):
            for step in range(_T(smoke=80, cpu=200, full=200)):
                try:
                    ccoord.extend(mk_batch(SAMPLE_B, version=step))
                    if step > 2:
                        mb = ccoord.sample(SAMPLE_B)
                        ccoord.update_priority(
                            np.asarray(mb["index"]).reshape(-1),
                            np.full((SAMPLE_B,), 1.0, np.float32),
                        )
                except Exception:  # noqa: BLE001 - the count IS the metric
                    learner_errors += 1
                # stamp recovery the moment the keeper re-admits — waiting
                # until after the loop would fold the remaining traffic
                # time into the number and overstate it by ~10x
                if (
                    recovery_s is None
                    and inj.last_fire_monotonic is not None
                    and ccoord._c_readmit.value({"shard": "1"}) >= 1
                ):
                    recovery_s = round(
                        time.monotonic() - inj.last_fire_monotonic, 3
                    )
                time.sleep(0.002)
        if recovery_s is None:
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if ccoord._c_readmit.value({"shard": "1"}) >= 1:
                    recovery_s = round(
                        time.monotonic() - (inj.last_fire_monotonic or time.monotonic()), 3
                    )
                    break
                time.sleep(0.01)
    finally:
        ccoord.close()
        for s in cshards:
            try:
                s.shutdown()
            except Exception:
                pass

    out = {
        "metric": "replay_shard_extend_items_per_sec",
        "value": round(sharded_ips, 1),
        "unit": "items/s",
        # vs the >=2x acceptance bound over the single endpoint
        "vs_baseline": round(speedup / 2.0, 3),
        "shard_speedup_x": round(speedup, 2),
        "single_items_per_sec": round(single_ips, 1),
        "n_shards": N_SHARDS,
        "capacity_single": CAP,
        "capacity_per_shard": CAP // N_SHARDS,
        "items_per_extend": ITEMS,
        "sample_p50_ms": pct(lat_sharded, 50),
        "sample_p99_ms": pct(lat_sharded, 99),
        "single_sample_p50_ms": pct(lat_single, 50),
        "single_sample_p99_ms": pct(lat_single, 99),
        "chaos": {
            "faults_fired": len(inj.fired),
            "learner_errors": learner_errors,
            "readmitted": 1 if recovery_s is not None else 0,
            "recovery_s": recovery_s,
        },
    }
    out.update(_platform_tag(jax))
    if report:
        print(json.dumps({"replay_shard": out}), flush=True)
    return out


def bench_all():
    """Default mode: a pure orchestrator — it never imports jax, because
    the TPU is process-exclusive. Order:

    0. BENCH_MODE=probe under a hard ~45s kill decides reachability. A
       hang is reported as ``tpu backend unreachable (init hang)`` —
       distinct from any overrun — and ALL sub-benches then run with
       BENCH_PLATFORM=cpu BENCH_SHAPES=cpu, labeled as such, so the round
       still yields measured numbers (round-4 VERDICT next-step #1a).
    1. BENCH_MODE=ppo runs in its own subprocess under the ppo slice of
       BENCH_TIMEOUT and its headline line is re-printed IMMEDIATELY —
       whatever happens later, the driver has a real number on stdout;
    2. rlhf (co-headline) / pixel / sac / per each run in a subprocess
       under a weighted slice of the remaining budget, so an overrun
       kills that sub-bench alone; each result line is re-printed as it
       completes;
    3. the headline line is printed again with the sub-bench dicts
       nested — the LAST stdout line also carries the headline value and
       the co-headline ``rlhf_train_mfu``.
    """
    child_env: dict = {}
    probe: dict
    if os.environ.get("BENCH_PLATFORM"):
        # caller pinned a platform (e.g. deliberate CPU run): trust it
        probe = {"platform": os.environ["BENCH_PLATFORM"], "pinned": True,
                 "error": None}
    else:
        probe = _run_sub_bench("probe", PROBE_BUDGET)
        err = probe.get("error")
        if err is not None:
            # only a slice timeout is the relay's hang signature; a fast
            # crash (rc!=0, no JSON) is a code/install failure and must not
            # be misdiagnosed as an outage — but both fall back to CPU so
            # the round still yields labeled numbers
            if "exceeded its" in err:
                probe = {"error": UNREACHABLE, "probe_timeout_s": PROBE_BUDGET}
            else:
                probe = {"error": "tpu probe failed (not a hang): " + err}
            child_env = {"BENCH_PLATFORM": "cpu", "BENCH_SHAPES": "cpu"}
    unreachable = probe.get("error") == UNREACHABLE
    _report_extras["probe"] = probe
    print(json.dumps({"probe": probe}), flush=True)

    weights = {"ppo": 2.0, "rlhf": 1.4, "pixel": 1.2, "hopper": 1.0,
               "sac": 1.0, "per": 1.0, "async_collect": 0.8, "serve": 0.8,
               "fleet": 0.8, "autoscale": 0.8, "replay_shard": 0.8, "prefix": 0.8,
               "spec": 0.8, "kernels": 0.8,
               "multichip": 0.8,
               "anakin": 0.8, "compile": 0.8, "chaos": 0.6}
    deadline = _START + _TIMEOUT - 30.0  # safety margin for the final print
    pending = list(weights)
    results: dict = {}
    for i, name in enumerate(pending):
        remaining = deadline - time.monotonic()
        if remaining <= 10.0:
            results[name] = {"error": "skipped: BENCH_TIMEOUT budget exhausted"}
        else:
            w_left = sum(weights[n] for n in pending[i:])
            slice_s = remaining * weights[name] / w_left  # surplus rolls fwd
            results[name] = _run_sub_bench(name, slice_s, child_env)
        if name == "ppo":
            # headline handling covers the skip path too: a skipped or
            # failed headline must carry its error, never a clean 0.0
            head = results[name]
            err = head.get("error")
            if unreachable:
                err = (
                    UNREACHABLE + "; value is a BENCH_PLATFORM=cpu "
                    "BENCH_SHAPES=cpu fallback"
                    + (f" ({err})" if err else "")
                )
            _headline.update(
                {
                    "value": float(head.get("value") or 0.0),
                    "mfu": float(head.get("mfu") or 0.0),
                    "error": err,
                }
            )
            # always the FULL metric schema, even when the child only
            # produced an error dict (a schema-less first line would read
            # as garbage to a driver parsing the first JSON line)
            first = _headline_dict(
                _headline["value"], _headline["mfu"], _headline["error"]
            )
            first["platform"] = head.get("platform") or probe.get("platform")
            first["shapes"] = head.get("shapes")
            print(json.dumps(first), flush=True)  # headline FIRST
        else:
            print(json.dumps({name: results[name]}), flush=True)
    _report_extras.update({k: v for k, v in results.items() if k != "ppo"})
    # co-headline: surface the rlhf train MFU at the top level of the final
    # line (round-4 VERDICT next-step #4 — rlhf is promoted, not nested-only)
    mfu = results.get("rlhf", {}).get("train_mfu")
    if mfu is not None:
        _report_extras["rlhf_train_mfu"] = mfu
    _report_extras.setdefault("platform", results["ppo"].get("platform") or probe.get("platform"))
    _report_extras.setdefault("shapes", results["ppo"].get("shapes"))
    _report(
        _headline.get("value", 0.0),
        _headline.get("mfu", 0.0),
        _headline.get("error"),
    )
    return {"probe": probe, **results}


_report_extras: dict = {}


def _maybe_write_metrics(result) -> None:
    """``--metrics-out PATH`` / ``BENCH_METRICS_OUT``: after the mode
    function returns, dump this process's metrics-registry snapshot plus
    any ``"metrics"`` sections the benches attached (the per bench's
    device-metrics drain; nested sub-bench sections under mode=all) as one
    JSON document. No-op when neither the flag nor the env var is set."""
    path = os.environ.get("BENCH_METRICS_OUT")
    if "--metrics-out" in sys.argv:
        i = sys.argv.index("--metrics-out")
        if i + 1 < len(sys.argv):
            path = sys.argv[i + 1]
    if not path:
        return
    payload: dict = {"mode": os.environ.get("BENCH_MODE", "all")}
    try:
        # pure-python import (numpy only) — safe even in the mode=all
        # orchestrator, which must never initialize jax
        from rl_tpu.obs import get_registry

        payload["registry"] = get_registry().snapshot()
    except Exception as e:  # never let telemetry sink a finished bench
        payload["registry_error"] = repr(e)
    if isinstance(result, dict):
        sections = {}
        if isinstance(result.get("metrics"), dict):
            sections[payload["mode"]] = result["metrics"]
        for k, v in result.items():
            if isinstance(v, dict) and isinstance(v.get("metrics"), dict):
                sections[k] = v["metrics"]
        if sections:
            payload["bench_metrics"] = sections
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def _watchdog(seconds: float):
    """Emit the failure JSON and hard-exit if the run wedges (e.g. the TPU
    relay hangs inside backend init, where no exception ever surfaces).
    If the headline was already measured, report THAT value with an
    overrun note instead of a 0.0 (round-3 regression: never again).
    Gates on key presence, not truthiness — a measured 0.0 is still a
    measurement (round-4 ADVICE bench.py:699)."""
    import threading

    def fire():
        if "value" in _headline:
            _report_extras.setdefault(
                "overrun", f"watchdog fired after {seconds}s; extras partial"
            )
            _report(
                _headline["value"], _headline.get("mfu", 0.0), _headline.get("error")
            )
            os._exit(0)
        _report(error=f"bench timed out after {seconds}s (backend hang?)")
        os._exit(1)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


if __name__ == "__main__":
    timer = _watchdog(float(os.environ.get("BENCH_TIMEOUT", "900")))
    mode = os.environ.get("BENCH_MODE", "all")
    try:
        _result = {
            "all": bench_all,
            "probe": bench_probe,
            "ppo": main,
            "pixel": bench_pixel,
            "hopper": bench_hopper,
            "serve": bench_serve,
            "attention": bench_attention,
            "hostenv": bench_hostenv,
            "rlhf": bench_rlhf,
            "sac": bench_sac,
            "per": bench_per,
            "async_collect": bench_async_collect,
            "chaos": bench_chaos,
            "fleet": bench_fleet,
            "autoscale": bench_autoscale,
            "replay_shard": bench_replay_shard,
            "prefix": bench_prefix,
            "spec": bench_spec,
            "kernels": bench_kernels,
            "multichip": bench_multichip,
            "anakin": bench_anakin,
            "compile": bench_compile,
        }[mode]()
        timer.cancel()
        _maybe_write_metrics(_result)
    except BaseException:  # always emit the JSON line, whatever happened
        _report(error=traceback.format_exc(limit=5))
        raise SystemExit(1)
