"""A2C on vectorized CartPole (reference analog: sota-implementations/a2c/).
Run: python examples/a2c_cartpole.py"""

from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers.algorithms import make_a2c_trainer


def main(total_steps: int = 100, n_envs: int = 32, frames: int = 1024):
    env = TransformedEnv(VmapEnv(CartPoleEnv(), n_envs), RewardSum())
    trainer = make_a2c_trainer(
        env,
        total_steps=total_steps,
        frames_per_batch=frames,
        learning_rate=7e-4,
        logger=CSVLogger("a2c_cartpole"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
