"""Contextual bandits over an OpenML-style tabular dataset (reference
analog: sota-implementations/bandits/dqn.py): a Q-network over
(context, arm) trained on logged one-step data; greedy accuracy tracks
how often the argmax arm equals the true label.
Run: python examples/bandit_openml.py"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.data import OpenMLDataset
from rl_tpu.modules import MLP


def synth_tabular(n=4096, d=16, classes=5, seed=0):
    """Separable synthetic stand-in for the sklearn-fetched datasets
    (network access is gated exactly like the reference)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, d)) * 2.0
    y = rng.integers(0, classes, n)
    X = centers[y] + rng.normal(size=(n, d))
    return X.astype(np.float32), y


def main(steps: int = 300, batch_size: int = 256, log_interval: int = 50):
    X, y = synth_tabular()
    ds = OpenMLDataset(X, y, batch_size=batch_size)
    n_arms = ds.max_outcome_val + 1
    qnet = MLP(out_features=n_arms, num_cells=(128, 128))
    params = qnet.init(jax.random.key(0), X[:1])["params"]
    opt = optax.adam(1e-3)
    ost = opt.init(params)

    @jax.jit
    def step(params, ost, batch):
        def loss(p):
            q = qnet.apply({"params": p}, batch["X"])  # [B, arms]
            # logged bandit feedback: reward 1 for the true arm
            chosen = jnp.take_along_axis(q, batch["y"][:, None], axis=1)[:, 0]
            others = (q.sum(axis=1) - chosen) / (n_arms - 1)
            return jnp.mean((chosen - 1.0) ** 2) + jnp.mean(others**2)

        v, g = jax.value_and_grad(loss)(params)
        upd, ost = opt.update(g, ost)
        return optax.apply_updates(params, upd), ost, v

    for i in range(steps):
        batch = ds.sample(jax.random.key(i))
        params, ost, v = step(params, ost, batch)
        if i % log_interval == 0:
            q = qnet.apply({"params": params}, X[:1024])
            acc = float((jnp.argmax(q, axis=1) == jnp.asarray(y[:1024])).mean())
            print(f"step {i}: loss {float(v):.4f} greedy-acc {acc:.3f}")
    return params


if __name__ == "__main__":
    main()
