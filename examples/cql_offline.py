"""Offline CQL over a D4RL-format dataset (reference analog:
sota-implementations/cql/): conservative Q regularization on top of SAC.
Run: python examples/cql_offline.py"""

import os
import tempfile

from rl_tpu.data import D4RLH5Dataset
from rl_tpu.trainers.algorithms import train_cql


def main(steps: int = 200, workdir=None):
    workdir = workdir or tempfile.mkdtemp()
    from iql_offline_to_online import synthesize_d4rl

    h5 = synthesize_d4rl(os.path.join(workdir, "pendulum_random.hdf5"))
    ds = D4RLH5Dataset(h5, scratch_dir=os.path.join(workdir, "mm"), batch_size=256)
    params = train_cql(ds.buffer, ds.state, total_steps=steps, batch_size=128,
                       log_interval=50)
    return params


if __name__ == "__main__":
    main()
