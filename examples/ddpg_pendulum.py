"""DDPG on Pendulum (reference analog: sota-implementations/ddpg/).
Run: python examples/ddpg_pendulum.py"""

from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_ddpg_trainer


def main(total_steps: int = 100, n_envs: int = 16, frames: int = 1024):
    trainer = make_ddpg_trainer(
        VmapEnv(PendulumEnv(), n_envs),
        total_steps=total_steps,
        frames_per_batch=frames,
        config=OffPolicyConfig(init_random_frames=2048, batch_size=256),
        logger=CSVLogger("ddpg_pendulum"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
