"""Discrete SAC on CartPole (reference analog:
sota-implementations/discrete_sac/): categorical policy + twin discrete
critics, entropy-regularized off-policy updates.
Run: python examples/discrete_sac_cartpole.py"""

import jax

from rl_tpu.collectors import Collector
from rl_tpu.data.replay import DeviceStorage, ReplayBuffer
from rl_tpu.envs import CartPoleEnv, VmapEnv
from rl_tpu.modules import MLP, Categorical, ProbabilisticActor, TDModule
from rl_tpu.objectives import DiscreteSACLoss
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig, OffPolicyProgram, Trainer
from rl_tpu.trainers.trainer import CountFramesLog, LogScalar


def main(total_steps: int = 100, n_envs: int = 16, frames: int = 512):
    env = VmapEnv(CartPoleEnv(), n_envs)
    n_actions = env.action_spec.n
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=n_actions, num_cells=(256, 256)),
                 ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    loss = DiscreteSACLoss(
        actor, MLP(out_features=n_actions, num_cells=(256, 256)),
        num_actions=n_actions,
    )
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OffPolicyProgram(
        coll, loss, ReplayBuffer(DeviceStorage(100_000)),
        OffPolicyConfig(init_random_frames=1024, batch_size=256),
    )
    trainer = Trainer(program, total_steps, logger=CSVLogger("discrete_sac"))
    trainer.register_op("post_step", LogScalar(interval=5))
    trainer.register_op("post_step", CountFramesLog(interval=5))
    trainer.train(0)


if __name__ == "__main__":
    main()
