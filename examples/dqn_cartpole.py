"""Double-DQN + n-step + PER on CartPole (reference analog:
sota-implementations/dqn/)."""

from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_dqn_trainer


def main():
    env = TransformedEnv(VmapEnv(CartPoleEnv(), 16), RewardSum())
    trainer = make_dqn_trainer(
        env,
        total_steps=300,
        frames_per_batch=512,
        config=OffPolicyConfig(batch_size=256, utd_ratio=4, learning_rate=1e-3, tau=0.01,
                               init_random_frames=2000),
        logger=CSVLogger("dqn_cartpole"),
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
