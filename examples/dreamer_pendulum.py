"""Dreamer (v1) on Pendulum (reference analog: sota-implementations/
dreamer/): Gaussian-latent RSSM world model + imagination actor-critic
with lambda-returns. The v3 twin (examples/dreamerv3_pendulum.py) uses
the discrete-latent stack; this is the original recipe.
Run: python examples/dreamer_pendulum.py"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.data import ArrayDict
from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.models import RSSM, RSSMConfig
from rl_tpu.models.rssm import DreamerModelLoss
from rl_tpu.modules import MLP, TanhNormal
from rl_tpu.objectives import DreamerActorLoss, DreamerValueLoss
from rl_tpu.record import CSVLogger

N_ENVS, T, HORIZON = 16, 32, 15


class LatentActor:
    def __init__(self, action_dim):
        self.mlp = MLP(out_features=2 * action_dim, num_cells=(128, 128))

    def _dist(self, params, td):
        feat = jnp.concatenate([td["h"], td["z"]], axis=-1)
        loc, raw = jnp.split(self.mlp.apply(params, feat), 2, axis=-1)
        return TanhNormal(loc, jax.nn.softplus(raw + 0.5413) + 1e-4)

    def init(self, key, td):
        feat = jnp.concatenate([td["h"], td["z"]], axis=-1)
        return self.mlp.init(key, feat)

    def __call__(self, params, td, key=None):
        dist = self._dist(params, td)
        a = dist.mode if key is None else dist.sample(key)
        return td.set("action", a)


def main(num_steps: int = 60, log_interval: int = 10):
    env = VmapEnv(PendulumEnv(), N_ENVS)
    obs_dim = env.observation_spec["observation"].shape[-1]
    act_dim = env.action_spec.shape[-1]
    cfg = RSSMConfig(obs_dim=obs_dim, action_dim=act_dim,
                     deter_dim=128, stoch_dim=32, hidden=128)
    rssm = RSSM(cfg)
    actor = LatentActor(act_dim)
    value_mlp = MLP(out_features=1, num_cells=(128, 128))

    def value_fn(vp, feat):
        return value_mlp.apply(vp, feat)[..., 0]

    model_loss = DreamerModelLoss(rssm)
    actor_loss = DreamerActorLoss(
        rssm, lambda p, td, k: actor(p, td, k), value_fn, horizon=HORIZON
    )
    value_loss = DreamerValueLoss(
        rssm, lambda p, td, k: actor(p, td, k), value_fn, horizon=HORIZON
    )

    key = jax.random.key(0)
    feat_dim = cfg.deter_dim + cfg.stoch_dim
    td0 = ArrayDict(h=jnp.zeros((1, cfg.deter_dim)), z=jnp.zeros((1, cfg.stoch_dim)))
    params = {
        "rssm": rssm.init(key),
        "actor": actor.init(key, td0),
        "value": value_mlp.init(key, jnp.zeros((1, feat_dim))),
    }
    opts = {
        "rssm": optax.adam(3e-4),
        "actor": optax.adam(8e-5),
        "value": optax.adam(8e-5),
    }
    ostates = {k: opts[k].init(params[k]) for k in opts}

    @jax.jit
    def collect(params, key):
        """Latent-actor collection: online belief filtering
        (rssm.filter_step) + act on (h, z) — the Dreamer deployment loop."""
        k0, k1, kroll = jax.random.split(key, 3)
        env_state, td = env.reset(k0)
        h = jnp.zeros((N_ENVS, cfg.deter_dim))
        z = jnp.zeros((N_ENVS, cfg.stoch_dim))
        h, z = rssm.filter_step(
            params["rssm"], h, z, jnp.zeros((N_ENVS, act_dim)),
            td["observation"], jnp.ones((N_ENVS,), bool), k1,
        )

        def body(carry, k):
            env_state, td, h, z, was_done = carry
            ka, kf = jax.random.split(k)
            a = actor(params["actor"], ArrayDict(h=h, z=z), ka)["action"]
            env_state, out, carry_td = env.step_and_reset(
                env_state, td.set("action", a)
            )
            nxt = out["next"]
            step = ArrayDict(
                observation=td["observation"], action=a,
                reward=nxt["reward"], terminated=nxt["terminated"],
                is_first=was_done,
            )
            h, z = rssm.filter_step(
                params["rssm"], h, z, a, carry_td["observation"],
                nxt["done"], kf,
            )
            return (env_state, carry_td, h, z, nxt["done"]), step

        keys = jax.random.split(kroll, T)
        _, steps = jax.lax.scan(
            body,
            (env_state, td, h, z, jnp.zeros((N_ENVS,), bool)),
            keys,
        )
        return jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), steps)  # [B, T]

    @jax.jit
    def update(params, ostates, batch, key):
        km, ka, kv = jax.random.split(key, 3)
        # DreamerModelLoss takes the rssm params directly
        (lm, mm), gm = jax.value_and_grad(
            lambda rp: model_loss(rp, batch, km), has_aux=True
        )(params["rssm"])
        upd, ostates["rssm"] = opts["rssm"].update(gm, ostates["rssm"])
        params = {**params, "rssm": optax.apply_updates(params["rssm"], upd)}

        out = rssm.observe(
            params["rssm"], batch["observation"], batch["action"],
            batch["is_first"], km,
        )
        latents = ArrayDict(h=out["h"], z=out["z"])
        (la, ma), ga = jax.value_and_grad(
            lambda p: actor_loss({**params, "actor": p}, latents, ka), has_aux=True
        )(params["actor"])
        upd, ostates["actor"] = opts["actor"].update(ga, ostates["actor"])
        params = {**params, "actor": optax.apply_updates(params["actor"], upd)}

        (lv, mv), gv = jax.value_and_grad(
            lambda p: value_loss({**params, "value": p}, latents, kv), has_aux=True
        )(params["value"])
        upd, ostates["value"] = opts["value"].update(gv, ostates["value"])
        params = {**params, "value": optax.apply_updates(params["value"], upd)}
        return params, ostates, ArrayDict(loss_model=lm, loss_actor=la, loss_value=lv)

    logger = CSVLogger("dreamer_pendulum")
    for step in range(num_steps):
        key, kc, ku = jax.random.split(key, 3)
        batch = collect(params, kc)
        params, ostates, metrics = update(params, ostates, batch, ku)
        if step % log_interval == 0:
            vals = {k: float(v) for k, v in metrics.items()}
            logger.log_scalars(vals, step=step)
            print(step, vals)
    return params


if __name__ == "__main__":
    main()
