"""DreamerV3 on Pendulum: world-model learning + imagination-based
actor-critic (reference analog: sota-implementations/dreamer_v3/).

The end-to-end loop the losses are built for:
  1. collect real trajectories with the current latent-space actor
     (online belief filtering via rssm.filter_step inside one scan);
  2. model update — symlog recon + two-hot reward CE + balanced KL
     (DreamerV3ModelLoss);
  3. posterior states from ``rssm.observe`` seed imagination;
  4. actor/value updates on imagined λ-returns (DreamerV3Actor/ValueLoss).
Everything device-side; one jitted program per phase.
Run: python examples/dreamerv3_pendulum.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.data import ArrayDict
from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.models import RSSMv3, RSSMv3Config
from rl_tpu.modules import MLP, TanhNormal
from rl_tpu.objectives import (
    DreamerV3ActorLoss,
    DreamerV3ModelLoss,
    DreamerV3ValueLoss,
)
from rl_tpu.record import CSVLogger

N_ENVS, T, HORIZON = 16, 32, 15


class LatentActor:
    """TanhNormal policy over the latent feature [h, z]."""

    in_keys = [("h",), ("z",)]
    out_keys = [("action",)]

    def __init__(self, action_dim):
        self.mlp = MLP(out_features=2 * action_dim, num_cells=(128, 128))

    def _dist(self, params, td):
        feat = jnp.concatenate([td["h"], td["z"]], axis=-1)
        loc, raw = jnp.split(self.mlp.apply(params, feat), 2, axis=-1)
        return TanhNormal(loc, jax.nn.softplus(raw + 0.5413) + 1e-4)

    def init(self, key, td):
        feat = jnp.concatenate([td["h"], td["z"]], axis=-1)
        return self.mlp.init(key, feat)

    def __call__(self, params, td, key=None):
        dist = self._dist(params, td)
        a = dist.mode if key is None else dist.sample(key)
        return td.set("action", a)


def main(num_steps: int = 100, log_interval: int = 10):
    env = VmapEnv(PendulumEnv(), N_ENVS)
    obs_dim = env.observation_spec["observation"].shape[-1]
    act_dim = env.action_spec.shape[-1]
    cfg = RSSMv3Config(
        obs_dim=obs_dim, action_dim=act_dim,
        deter_dim=128, groups=8, classes=8, hidden=128,
    )
    rssm = RSSMv3(cfg)
    actor = LatentActor(act_dim)
    value_mlp = MLP(out_features=1, num_cells=(128, 128))

    def value_fn(vp, feat):
        return value_mlp.apply(vp, feat)

    model_loss = DreamerV3ModelLoss(rssm)
    actor_loss = DreamerV3ActorLoss(rssm, actor, value_fn, horizon=HORIZON)
    value_loss = DreamerV3ValueLoss(rssm, actor, value_fn, horizon=HORIZON)

    key = jax.random.key(0)
    dummy = ArrayDict(
        observation=jnp.zeros((1, 2, obs_dim)),
        action=jnp.zeros((1, 2, act_dim)),
        reward=jnp.zeros((1, 2)),
        terminated=jnp.zeros((1, 2), bool),
        is_first=jnp.zeros((1, 2), bool),
    )
    params = model_loss.init_params(key, dummy)
    feat_dim = cfg.deter_dim + cfg.stoch_dim
    td0 = ArrayDict(h=jnp.zeros((1, cfg.deter_dim)), z=jnp.zeros((1, cfg.stoch_dim)))
    params["actor"] = actor.init(key, td0)
    params["value"] = value_mlp.init(key, jnp.zeros((1, feat_dim)))
    params["slow_value"] = jax.tree.map(jnp.copy, params["value"])
    params["return_scale"] = jnp.asarray(1.0)

    opts = {
        "model": optax.adam(3e-4),
        "actor": optax.adam(8e-5),
        "value": optax.adam(8e-5),
    }
    ostates = {
        "model": opts["model"].init({"rssm": params["rssm"]}),
        "actor": opts["actor"].init(params["actor"]),
        "value": opts["value"].init(params["value"]),
    }

    # latent-space collection with the CURRENT actor: the (h, z) belief is
    # filtered online (rssm.filter_step) and the actor acts on it — the
    # Dreamer deployment loop, one fused scan
    @jax.jit
    def collect(params, key):
        k0, k1, kroll = jax.random.split(key, 3)
        env_state, td = env.reset(k0)
        h = jnp.zeros((N_ENVS, cfg.deter_dim))
        z = jnp.zeros((N_ENVS, cfg.stoch_dim))
        h, z = rssm.filter_step(
            params["rssm"], h, z, jnp.zeros((N_ENVS, act_dim)),
            td["observation"], jnp.ones((N_ENVS,), bool), k1,
        )

        def body(carry, k):
            env_state, td, h, z, was_done = carry
            ka, kf = jax.random.split(k)
            a = actor(params["actor"], ArrayDict(h=h, z=z), ka)["action"]
            # auto-reset: finished sub-envs restart and the NEXT stored
            # step is flagged is_first (the model loss cuts sequences
            # there; filter_step zeroes the belief the same way)
            env_state, out, carry_td = env.step_and_reset(
                env_state, td.set("action", a)
            )
            nxt = out["next"]
            step = ArrayDict(
                observation=td["observation"], action=a,
                reward=nxt["reward"], terminated=nxt["terminated"],
                is_first=was_done,
            )
            h, z = rssm.filter_step(
                params["rssm"], h, z, a, carry_td["observation"],
                nxt["done"], kf,
            )
            return (env_state, carry_td, h, z, nxt["done"]), step

        _, steps = jax.lax.scan(
            body,
            (env_state, td, h, z, jnp.ones((N_ENVS,), bool)),
            jax.random.split(kroll, T),
        )
        return jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), steps)  # [B, T]

    @jax.jit
    def update(params, ostates, batch, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        # 1. world model
        mp = {"rssm": params["rssm"]}
        (ml, mm), mg = jax.value_and_grad(
            lambda p: model_loss(p, batch, k1), has_aux=True
        )(mp)
        upd, ostates["model"] = opts["model"].update(mg, ostates["model"], mp)
        params["rssm"] = optax.apply_updates(mp, upd)["rssm"]
        # 2. posterior states seed imagination
        out = rssm.observe(
            params["rssm"], batch["observation"], batch["action"],
            batch["is_first"], k2,
        )
        ab = ArrayDict(h=out["h"], z=out["z"])
        # 3. actor on imagined lambda-returns
        (al, am), ag = jax.value_and_grad(
            lambda p: actor_loss({**params, "actor": p}, ab, k3), has_aux=True
        )(params["actor"])
        upd, ostates["actor"] = opts["actor"].update(ag, ostates["actor"], params["actor"])
        params["actor"] = optax.apply_updates(params["actor"], upd)
        params["return_scale"] = am["return_scale"]
        # 4. value on the same imagination
        (vl, vm), vg = jax.value_and_grad(
            lambda p: value_loss({**params, "value": p}, ab, k4), has_aux=True
        )(params["value"])
        upd, ostates["value"] = opts["value"].update(vg, ostates["value"], params["value"])
        params["value"] = optax.apply_updates(params["value"], upd)
        # slow critic EMA
        params["slow_value"] = jax.tree.map(
            lambda s, v: 0.98 * s + 0.02 * v, params["slow_value"], params["value"]
        )
        metrics = ArrayDict(model_loss=ml, actor_loss=al, value_loss=vl,
                            reward_mean=batch["reward"].mean())
        return params, ostates, metrics

    logger = CSVLogger("dreamerv3_pendulum")
    for step in range(num_steps):
        key, k1, k2 = jax.random.split(key, 3)
        batch = collect(params, k1)
        params, ostates, m = update(params, ostates, batch, k2)
        if step % log_interval == 0:
            vals = {k: float(v) for k, v in m.items()}
            logger.log_scalars(vals, step)
            print(step, vals)


if __name__ == "__main__":
    main()
