"""Decision Transformer on synthesized trajectories (reference analog:
sota-implementations/decision_transformer/): return-conditioned action
prediction over (RTG, obs, action, timestep) sequences.
Run: python examples/dt_offline.py"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.data import ArrayDict
from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.envs.utils import rollout
from rl_tpu.models.decision_transformer import DTConfig, DTLoss


def build_sequences(T=20, n_envs=16, ctx=8, seed=0):
    """Random-policy trajectories -> fixed-length DT training windows."""
    env = VmapEnv(PendulumEnv(), n_envs)
    steps = rollout(env, jax.random.key(seed), None, max_steps=T)
    obs = np.moveaxis(np.asarray(steps["observation"]), 0, 1)   # [B, T, D]
    act = np.moveaxis(np.asarray(steps["action"]), 0, 1)
    rew = np.moveaxis(np.asarray(steps["next", "reward"]), 0, 1)
    rtg = np.flip(np.cumsum(np.flip(rew, 1), 1), 1)[..., None]  # returns-to-go
    t = np.broadcast_to(np.arange(T), (n_envs, T))
    wins = []
    for s in range(0, T - ctx + 1, ctx // 2):
        wins.append(ArrayDict(
            returns_to_go=jnp.asarray(rtg[:, s:s + ctx], jnp.float32),
            observation=jnp.asarray(obs[:, s:s + ctx]),
            action=jnp.asarray(act[:, s:s + ctx]),
            timesteps=jnp.asarray(t[:, s:s + ctx], jnp.int32),
        ))
    import jax as _j

    return _j.tree.map(lambda *xs: jnp.concatenate(xs, 0), *wins)


def main(steps: int = 200, ctx: int = 8, log_interval: int = 50):
    data = build_sequences(ctx=ctx)
    cfg = DTConfig(state_dim=3, action_dim=1, context_len=ctx,
                   d_model=64, n_layers=2, n_heads=2, max_ep_len=64)
    loss = DTLoss(cfg)
    params = loss.init_params(jax.random.key(0), data)
    opt = optax.adam(1e-3)
    ost = opt.init(params)

    @jax.jit
    def step(params, ost, batch):
        (v, m), g = jax.value_and_grad(
            lambda p: loss(p, batch), has_aux=True
        )(params)
        upd, ost = opt.update(g, ost)
        return optax.apply_updates(params, upd), ost, v

    n = data["observation"].shape[0]
    first = None
    for i in range(steps):
        idx = jax.random.randint(jax.random.key(i), (64,), 0, n)
        batch = jax.tree.map(lambda x: x[idx], data)
        params, ost, v = step(params, ost, batch)
        first = first if first is not None else float(v)
        if i % log_interval == 0:
            print(f"step {i}: action-mse {float(v):.5f}")
    print(f"improved: {first:.5f} -> {float(v):.5f}")
    return params


if __name__ == "__main__":
    main()
