"""GAIL on Pendulum (reference analog: sota-implementations/gail/):
a discriminator learns expert vs policy transitions and its confusion
becomes the reward shaping a PPO update — imitation without rewards.
The "expert" here is a scripted energy controller's (obs, action) set.
Run: python examples/gail_pendulum.py"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.collectors import Collector
from rl_tpu.data import ArrayDict
from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.objectives import ClipPPOLoss, GAILLoss
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram
from rl_tpu.trainers.algorithms import default_continuous_actor



def expert_demos(n: int = 2048, seed: int = 7):
    """Scripted pendulum 'expert': torque opposing angular velocity."""
    rng = np.random.default_rng(seed)
    theta = rng.uniform(-np.pi, np.pi, n)
    thdot = rng.uniform(-8, 8, n)
    obs = np.stack([np.cos(theta), np.sin(theta), thdot], axis=1).astype(np.float32)
    act = np.clip(-0.5 * thdot - 2.0 * np.sin(theta), -2, 2)[:, None].astype(np.float32)
    return ArrayDict(observation=jnp.asarray(obs), action=jnp.asarray(act))


def main(total_steps: int = 40, n_envs: int = 16, frames: int = 512):
    env = VmapEnv(PendulumEnv(), n_envs)
    actor = default_continuous_actor(env, num_cells=(64, 64))
    from rl_tpu.modules import MLP, ValueOperator

    critic = ValueOperator(MLP(out_features=1, num_cells=(64, 64)))
    ppo = ClipPPOLoss(actor, critic, normalize_advantage=True)
    ppo.make_value_estimator(gamma=0.99, lmbda=0.95)
    gail = GAILLoss(gp_coeff=0.1)
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OnPolicyProgram(
        coll, ppo, OnPolicyConfig(num_epochs=2, minibatch_size=frames // 2)
    )

    key = jax.random.key(0)
    ts = program.init(key)
    popt = optax.adam(3e-4)
    pstate = popt.init(ppo.trainable(ts["params"]))
    demos = expert_demos()
    dparams = gail.init_params(
        key, ArrayDict(observation=demos["observation"][:4],
                       action=demos["action"][:4], expert=demos[:4])
    )
    dopt = optax.adam(3e-4)
    dstate = dopt.init(dparams)

    @jax.jit
    def disc_step(dparams, dstate, batch, demos, k):
        kd, ks = jax.random.split(k)
        idx = jax.random.randint(ks, (batch["observation"].shape[0],), 0,
                                 demos["observation"].shape[0])
        db = ArrayDict(
            observation=batch["observation"], action=batch["action"],
            expert=ArrayDict(observation=demos["observation"][idx],
                             action=demos["action"][idx]),
        )
        (v, m), g = jax.value_and_grad(
            lambda p: gail(p, db, kd), has_aux=True
        )(dparams)
        upd, dstate = dopt.update(g, dstate)
        return optax.apply_updates(dparams, upd), dstate, m

    @jax.jit
    def shaped_train_step(ts, pstate, dparams, k):
        # collect, relabel rewards with the discriminator, GAE + PPO update
        params = ts["params"]
        batch, cstate = coll.collect(params, ts["collector"])
        r = gail.reward(dparams, batch["observation"], batch["action"])
        shaped = batch.set("next", batch["next"].set("reward", r))
        shaped = program.advantage(params, shaped)
        flat = shaped.flatten_batch()
        v, grads, metrics = ppo.grad(params, flat)
        upd, pstate = popt.update(grads, pstate, ppo.trainable(params))
        params = ppo.merge(
            optax.apply_updates(ppo.trainable(params), upd), params
        )
        new_ts = dict(ts)
        new_ts["params"] = params
        new_ts["collector"] = cstate
        return new_ts, pstate, flat, metrics.set("loss", v)

    logger = CSVLogger("gail_pendulum")
    for step in range(total_steps):
        key, k1, k2 = jax.random.split(key, 3)
        ts, pstate, flat, metrics = shaped_train_step(ts, pstate, dparams, k1)
        dparams, dstate, dm = disc_step(dparams, dstate, flat, demos, k2)
        if step % 5 == 0:
            vals = dict(loss=float(metrics["loss"]),
                        expert_acc=float(dm["expert_acc"]),
                        policy_acc=float(dm["policy_acc"]))
            logger.log_scalars(vals, step=step)
            print(step, vals)
    return ts, dparams


if __name__ == "__main__":
    main()
