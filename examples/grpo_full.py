"""Full GRPO/RLHF recipe: local tokenizer + arithmetic task dataset →
DatasetChatEnv → KV-cache generation → KL-shaped rewards → GRPO updates →
DevicePut weight push → greedy eval (reference analog:
sota-implementations/grpo/grpo-sync.py, engine-free and hub-free).

Run:  python examples/grpo_full.py [steps]
With >1 devices (e.g. the 8-dev CPU mesh:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/grpo_full.py)
the training forward runs ring attention over a "context" mesh axis.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from rl_tpu.envs.llm import arithmetic_dataset  # noqa: E402
from rl_tpu.trainers.grpo import GRPOTrainer  # noqa: E402


def main(steps: int = 60):
    mesh = None
    if len(jax.devices()) > 1:
        from rl_tpu.parallel import make_mesh

        n = len(jax.devices())
        mesh = make_mesh(data=1, context=n)
        print(f"ring attention over {n}-way context axis")

    ds = arithmetic_dataset(n=256, max_operand=4)
    trainer = GRPOTrainer(ds, mesh=mesh, num_prompts=8, group_repeats=8,
                          kl_coeff=0.02)
    print(f"vocab={trainer.tokenizer.vocab_size} "
          f"eval@init={trainer.evaluate():.3f}")
    for i in range(steps):
        m = trainer.step()
        if i % 10 == 0:
            print(f"step {i:4d} reward {m['reward']:.3f} loss {m['loss']:.4f}")
    print(f"eval@end={trainer.evaluate():.3f} "
          f"(policy v{trainer.policy_version.version})")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 60)
