"""GRPO on GSM8K-format math word problems (reference analog:
sota-implementations/grpo/ + the GSM8KEnv recipe).

The full RLHF cycle against locally generated, verifiable ground truth:
gsm8k_dataset produces multi-step word problems with exact GSM8K gold
formatting (<<a+b=c>> calculator annotations + '#### N'), GSM8KScorer
applies the standard GRPO reward levels (1.0 correct / 0.1 parseable /
0.0 none), and GRPOTrainer assembles tokenizer -> DatasetChatEnv ->
KV-cache generation -> group advantages -> clipped update.
Run: python examples/grpo_gsm8k.py
"""

from rl_tpu.envs.llm import GSM8KScorer, gsm8k_dataset
from rl_tpu.trainers.grpo import GRPOTrainer


def main(steps: int = 40, max_prompt_len: int = 96, max_new_tokens: int = 32):
    ds = gsm8k_dataset(n=256, seed=0)
    trainer = GRPOTrainer(
        ds,
        scorer=GSM8KScorer(ds.answers, think_bonus=0.0),
        num_prompts=4,
        group_repeats=8,
        max_prompt_len=max_prompt_len,
        max_new_tokens=max_new_tokens,
        learning_rate=1e-3,
        kl_coeff=0.01,
    )
    for step in range(steps):
        m = trainer.step()
        if step % 5 == 0:
            print(step, {k: round(v, 4) for k, v in m.items()})
    print("eval accuracy:", trainer.evaluate(num_prompts=16))


if __name__ == "__main__":
    main()
