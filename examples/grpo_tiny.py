"""GRPO RLHF loop on a tiny native transformer with a rule-based reward
(reference analog: sota-implementations/grpo/grpo-sync.py, engine-free)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.collectors import LLMCollector
from rl_tpu.data.llm import History
from rl_tpu.envs.llm import DatasetChatEnv
from rl_tpu.models import TransformerConfig, TransformerLM, token_log_probs
from rl_tpu.objectives.llm import GRPOLoss
from rl_tpu.weight_update import SharedProgramScheme


class ByteTokenizer:
    def encode(self, s):
        return [ord(c) % 120 + 1 for c in s]

    def decode(self, ids):
        return "".join(chr(i) for i in ids)


def main():
    cfg = TransformerConfig(vocab_size=128, d_model=128, n_layers=4, n_heads=8,
                            d_ff=256, max_seq_len=128, dtype=jnp.float32)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]

    prompts = History.from_chats(
        [[{"role": "user", "content": c}] for c in ["count", "list", "sing"]]
    )
    env = DatasetChatEnv(
        prompts,
        ByteTokenizer(),
        reward_fn=lambda h, t: float((np.asarray(t) % 2 == 0).mean()) if len(t) else 0.0,
        group_repeats=8,
        max_prompt_len=16,
    )
    scheme = SharedProgramScheme()
    scheme.push(params)
    coll = LLMCollector(env, model, num_prompts=4, max_new_tokens=16,
                        weight_scheme=scheme, ref_params=params)
    loss = GRPOLoss(
        lambda p, b: token_log_probs(model, p, b["tokens"], b["attention_mask"]),
        kl_coeff=0.02,
    )
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def update(params, opt_state, batch):
        (v, m), g = jax.value_and_grad(lambda p: loss(p, batch), has_aux=True)(params)
        upd, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, upd), opt_state, m

    key = jax.random.key(1)
    for i in range(60):
        key, k = jax.random.split(key)
        batch = coll.collect(params, k)
        params, opt_state, m = update(params, opt_state, batch)
        scheme.push(params)
        if i % 10 == 0:
            print(f"step {i} reward {float(batch['reward'].mean()):.3f} "
                  f"kl {float(m['kl_approx']):.4f}")


if __name__ == "__main__":
    main()
