"""IMPALA-style actor-learner on vectorized CartPole (reference analog:
sota-implementations/impala/).

The IMPALA recipe = policy-gradient learning from STALE behavior data with
V-trace off-policy correction (Espeholt et al. 2018), the correction
recomputed against the CURRENT policy at every learner epoch. This script
is the thin twin of ``make_impala_trainer`` (and of
examples/configs/impala_cartpole.yaml). Run: python examples/impala_cartpole.py
"""

from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import make_impala_trainer


def main(total_steps: int = 50, n_envs: int = 32, frames: int = 2048):
    env = TransformedEnv(VmapEnv(CartPoleEnv(), n_envs), RewardSum())
    trainer = make_impala_trainer(
        env,
        total_steps=total_steps,
        frames_per_batch=frames,
        logger=CSVLogger("impala_cartpole"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
