"""IMPALA-style actor-learner on vectorized CartPole (reference analog:
sota-implementations/impala/).

The IMPALA recipe = policy-gradient learning from STALE behavior data with
V-trace off-policy correction (Espeholt et al. 2018). The TPU-native shape:
collection and learning are two jitted programs sharing one param tree;
each collected batch is reused for several learner epochs, so later epochs
train on data from an older policy — exactly the actor-lag V-trace absorbs
(importance ratios between the stored ``sample_log_prob`` and the current
policy). Run: python examples/impala_cartpole.py
"""

import jax

from rl_tpu.collectors import Collector
from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.modules import MLP, Categorical, ProbabilisticActor, TDModule, ValueOperator
from rl_tpu.objectives import A2CLoss
from rl_tpu.objectives.value import VTrace
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram, Trainer


def main(total_steps: int = 50, n_envs: int = 32, frames: int = 2048):
    env = TransformedEnv(VmapEnv(CartPoleEnv(), n_envs), RewardSum())
    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2, num_cells=(128, 128)), ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(128, 128)))
    loss = A2CLoss(actor, critic, entropy_coeff=0.01)
    # V-trace instead of GAE: rho/c-clipped importance weighting makes the
    # multi-epoch reuse below sound (each epoch after the first is
    # off-policy w.r.t. the behavior policy that collected the batch)
    loss.value_estimator = VTrace(
        lambda p, td: critic(p, td),
        lambda ap, td: actor.log_prob(ap, td),
        gamma=0.99,
        rho_clip=1.0,
        c_clip=1.0,
    )
    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OnPolicyProgram(
        coll,
        loss,
        OnPolicyConfig(num_epochs=4, minibatch_size=max(64, frames // 2), learning_rate=5e-4),
        # the point of V-trace: recompute the importance-corrected
        # advantage against the CURRENT policy at every epoch
        recompute_advantage=True,
    )
    trainer = Trainer(program, total_steps=total_steps, logger=CSVLogger("impala_cartpole"))
    trainer.train(0)


if __name__ == "__main__":
    main()
