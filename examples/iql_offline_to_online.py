"""IQL offline -> online on Pendulum (reference analog:
sota-implementations/iql/ with a D4RL dataset): synthesize a dataset by
rolling a random policy, write it in the exact D4RL HDF5 layout, load it
back through D4RLH5Dataset, pretrain with train_iql, then fine-tune the
SAME params online on freshly collected transitions.
Run: python examples/iql_offline_to_online.py"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rl_tpu.collectors import Collector
from rl_tpu.data import D4RLH5Dataset
from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.modules import MLP, ConcatMLP
from rl_tpu.objectives import IQLLoss, SoftUpdate
from rl_tpu.trainers.algorithms import (
    _offline_continuous_actor,
    _offline_example,
    train_iql,
)


def synthesize_d4rl(path, n_envs=8, steps=64, seed=0):
    """Random-policy Pendulum transitions in the D4RL on-disk layout."""
    import h5py

    from rl_tpu.envs.utils import rollout

    env = VmapEnv(PendulumEnv(), n_envs)
    steps_td = rollout(env, jax.random.key(seed), None, max_steps=steps)

    # rollout() is TIME-major [T, B, ...]; D4RL's on-disk layout is one
    # flat stream whose next-obs is the global [1:] shift, so rows must be
    # ENV-major (each env's trajectory contiguous) and each env's last row
    # must be flagged timeout — otherwise the shift would pair a
    # transition with another trajectory's observation
    def env_major(x):
        return np.moveaxis(np.asarray(x), 0, 1).reshape((-1,) + x.shape[2:])

    obs = env_major(steps_td["observation"])
    act = env_major(steps_td["action"])
    rew = env_major(steps_td["next", "reward"])
    term = env_major(steps_td["next", "terminated"])
    trunc = env_major(steps_td["next", "truncated"]).copy()
    trunc[steps - 1 :: steps] = True  # episode boundary at each env's tail
    with h5py.File(path, "w") as f:
        f.create_dataset("observations", data=obs)
        f.create_dataset("actions", data=act)
        f.create_dataset("rewards", data=rew)
        f.create_dataset("terminals", data=term)
        f.create_dataset("timeouts", data=trunc)
    return path


def main(offline_steps: int = 200, online_steps: int = 20, workdir=None):
    workdir = workdir or tempfile.mkdtemp()
    h5 = synthesize_d4rl(os.path.join(workdir, "pendulum_random.hdf5"))
    ds = D4RLH5Dataset(h5, scratch_dir=os.path.join(workdir, "mm"), batch_size=256)

    # -- offline phase (reference IQLTrainer path) ---------------------------
    params = train_iql(ds.buffer, ds.state, total_steps=offline_steps,
                       batch_size=128, log_interval=50)

    # -- online fine-tune: SAME params, fresh env data -----------------------
    actor = _offline_continuous_actor(_offline_example(ds.buffer, ds.state))
    # architectures must match the offline phase (train_iql defaults)
    loss = IQLLoss(
        actor,
        ConcatMLP(out_features=1, num_cells=(256, 256)),
        MLP(out_features=1, num_cells=(256, 256)),
    )
    env = VmapEnv(PendulumEnv(), 8)
    coll = Collector(env, lambda p, td, k: actor(p["actor"], td, k),
                     frames_per_batch=256)
    cstate = coll.init(jax.random.key(1))
    opt = optax.adam(3e-4)
    ost = opt.init(loss.trainable(params))
    updater = SoftUpdate(loss, tau=0.005)

    @jax.jit
    def online_step(params, ost, cstate, key):
        batch, cstate = coll.collect(params, cstate)
        flat = batch.flatten_batch()
        v, grads, m = loss.grad(params, flat, key)
        upd, ost = opt.update(grads, ost, loss.trainable(params))
        params = updater(
            loss.merge(optax.apply_updates(loss.trainable(params), upd), params)
        )
        return params, ost, cstate, v, m

    for i in range(online_steps):
        params, ost, cstate, v, m = online_step(
            params, ost, cstate, jax.random.key(100 + i)
        )
        if i % 5 == 0:
            print(f"online step {i}: loss {float(v):.4f}")
    return params


if __name__ == "__main__":
    main()
