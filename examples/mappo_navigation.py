"""MAPPO on the native multi-agent NavigationEnv (reference analog:
sota-implementations/multiagent/mappo_ippo.py).

Centralized-critic PPO over an agent group: per-agent observations under
("agents", ...), a shared-parameter agent MLP for the policy, a
centralized state critic, team reward. Thin twin of
``make_mappo_trainer`` (and of examples/configs/mappo_navigation.yaml).
Run: python examples/mappo_navigation.py
"""

from rl_tpu.envs import NavigationEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import make_mappo_trainer

N_AGENTS = 4


def main(total_steps: int = 60, n_envs: int = 16, frames: int = 1024):
    env = TransformedEnv(
        VmapEnv(NavigationEnv(n_agents=N_AGENTS), n_envs), RewardSum()
    )
    trainer = make_mappo_trainer(
        env,
        total_steps=total_steps,
        n_agents=N_AGENTS,
        frames_per_batch=frames,
        logger=CSVLogger("mappo_navigation"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
