"""MAPPO on the native multi-agent NavigationEnv (reference analog:
sota-implementations/multiagent/mappo_ippo.py).

Centralized-critic PPO over an agent group: per-agent observations under
("agents", ...), a shared-parameter agent MLP for the policy, a
centralized state critic, team reward. The whole collect+GAE+update cycle
is one jitted program on device.
Run: python examples/mappo_navigation.py
"""

import jax
import jax.numpy as jnp

from rl_tpu.collectors import Collector
from rl_tpu.envs import NavigationEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.modules import (
    MLP,
    MultiAgentMLP,
    ProbabilisticActor,
    TanhNormal,
    ValueOperator,
)
from rl_tpu.objectives import MAPPOLoss
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OnPolicyConfig, OnPolicyProgram, Trainer

N_AGENTS = 4


def main(total_steps: int = 60, n_envs: int = 16, frames: int = 1024):
    env = TransformedEnv(
        VmapEnv(NavigationEnv(n_agents=N_AGENTS), n_envs), RewardSum()
    )
    act_dim = env.action_spec.shape[-1]
    manet = MultiAgentMLP(N_AGENTS, out_features=2 * act_dim, num_cells=(128, 128))

    class GroupActorNet:
        in_keys = [("agents", "observation")]
        out_keys = [("loc",), ("scale",)]

        def init(self, key, td):
            return manet.init(key, td["agents", "observation"])

        def __call__(self, params, td, key=None):
            loc, raw = jnp.split(
                manet(params, td["agents", "observation"]), 2, axis=-1
            )
            return td.set("loc", loc).set(
                "scale", jax.nn.softplus(raw + 0.5413) + 1e-4
            )

    actor = ProbabilisticActor(GroupActorNet(), TanhNormal, dist_keys=("loc", "scale"))
    critic = ValueOperator(MLP(out_features=1, num_cells=(256, 256)), in_keys=["state"])
    loss = MAPPOLoss(actor, critic, normalize_advantage=True, entropy_coeff=0.01)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)

    coll = Collector(
        env, lambda p, td, k: actor(p["actor"], td, k), frames_per_batch=frames
    )
    program = OnPolicyProgram(
        coll,
        loss,
        OnPolicyConfig(num_epochs=4, minibatch_size=max(64, frames // 4), learning_rate=3e-4),
    )
    trainer = Trainer(program, total_steps=total_steps, logger=CSVLogger("mappo_navigation"))
    trainer.train(0)


if __name__ == "__main__":
    main()
