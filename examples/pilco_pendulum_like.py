"""PILCO: analytic model-based policy search (reference analog:
sota-implementations/pilco/).

The data-efficient loop: collect a few real transitions, fit one RBF-ARD
GP per state dim (NLML by autodiff — no GP library), then IMPROVE THE
POLICY WITHOUT THE ENV by differentiating the expected saturating cost of
a moment-matched belief rollout (Deisenroth & Rasmussen 2011, Eqs. 10-25)
straight through lax.scan. Run: python examples/pilco_pendulum_like.py
"""

import jax
import jax.numpy as jnp

from rl_tpu.data import ArrayDict
from rl_tpu.modules import GPWorldModel
from rl_tpu.objectives import pilco_cost


def main(n_data: int = 100, horizon: int = 10, iters: int = 60):
    key = jax.random.key(0)
    # toy nonlinear plant: x' = x + 0.1 sin(x) + 0.2 u   (2-dim state)
    x = jax.random.uniform(key, (n_data, 2), minval=-2, maxval=2)
    u = jax.random.uniform(jax.random.key(1), (n_data, 1), minval=-1, maxval=1)
    nx = x + 0.1 * jnp.sin(x) + 0.2 * u
    gp = GPWorldModel(obs_dim=2, action_dim=1)
    gp_state = gp.fit(
        ArrayDict(observation=x, action=u, next=ArrayDict(observation=nx)),
        num_steps=200,
    )
    print("GP fitted; NLML:", float(gp_state["nlml"]))

    mu0 = jnp.asarray([1.2, 0.8])
    S0 = 0.01 * jnp.eye(2)
    W = 0.25 * jnp.eye(2)  # wide saturating cost: drive the state to 0

    def rollout_cost(theta):
        def body(carry, _):
            mu_x, S_x = carry
            a = jnp.tanh(theta @ mu_x)[None]
            mu = jnp.concatenate([mu_x, a])
            S = jnp.zeros((3, 3)).at[:2, :2].set(S_x).at[2, 2].set(1e-6)
            mu_t, S_t = gp.propagate(gp_state, mu, S)
            return (mu_t, S_t), pilco_cost(mu_t, S_t, weights=W)

        _, costs = jax.lax.scan(body, (mu0, S0), None, length=horizon)
        return costs.sum()

    theta = jnp.zeros((2,))
    step = jax.jit(jax.value_and_grad(rollout_cost))
    for i in range(iters):
        c, g = step(theta)
        theta = theta - 0.5 * g
        if i % 10 == 0:
            print(i, "expected cost:", float(c))
    print("final expected cost:", float(step(theta)[0]))


if __name__ == "__main__":
    main()
