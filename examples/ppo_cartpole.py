"""PPO on vectorized CartPole — the minimal end-to-end recipe
(reference analog: sota-implementations/ppo/). Run: python examples/ppo_cartpole.py"""

from rl_tpu.envs import CartPoleEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OnPolicyConfig
from rl_tpu.trainers.algorithms import make_ppo_trainer


def main():
    env = TransformedEnv(VmapEnv(CartPoleEnv(), 32), RewardSum())
    trainer = make_ppo_trainer(
        env,
        total_steps=50,
        frames_per_batch=2048,
        config=OnPolicyConfig(num_epochs=4, minibatch_size=512, learning_rate=3e-4),
        logger=CSVLogger("ppo_cartpole"),
        log_interval=5,
    )
    trainer.train(seed_or_key := 0)


if __name__ == "__main__":
    main()
