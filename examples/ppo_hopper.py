"""PPO on the pure-JAX planar Hopper — the physics-shaped on-policy recipe
(reference analog: sota-implementations/ppo/ on MuJoCo Hopper-v4; here the
dynamics are the native Lagrangian simulator, so the entire
collect+GAE+ClipPPO cycle is ONE XLA program with the physics inside it).
Run: python examples/ppo_hopper.py"""

from rl_tpu.envs import HopperEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OnPolicyConfig
from rl_tpu.trainers.algorithms import make_ppo_trainer


def main(total_steps: int = 100, num_envs: int = 64):
    env = TransformedEnv(VmapEnv(HopperEnv(), num_envs), RewardSum())
    trainer = make_ppo_trainer(
        env,
        total_steps=total_steps,
        frames_per_batch=num_envs * 32,
        config=OnPolicyConfig(
            num_epochs=4,
            minibatch_size=min(512, num_envs * 32 // 2),
            learning_rate=3e-4,
        ),
        logger=CSVLogger("ppo_hopper"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
