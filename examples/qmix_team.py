"""QMIX on the cooperative team-counting env (reference analog:
sota-implementations/multiagent/qmix_vdn.py; the reference trains on VMAS,
which is not in this image — the cooperative mock exercises the identical
per-agent-Q + monotonic-mixer machinery).
Run: python examples/qmix_team.py"""

from rl_tpu.record import CSVLogger
from rl_tpu.envs import VmapEnv
from rl_tpu.testing import MultiAgentCountingEnv
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_qmix_trainer


def main(total_steps: int = 60, n_envs: int = 8, frames: int = 256):
    trainer = make_qmix_trainer(
        VmapEnv(MultiAgentCountingEnv(3), n_envs),
        total_steps=total_steps,
        frames_per_batch=frames,
        config=OffPolicyConfig(init_random_frames=512, batch_size=128),
        logger=CSVLogger("qmix_team"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
