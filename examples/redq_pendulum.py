"""REDQ on Pendulum (reference analog: sota-implementations/redq/):
10-critic ensemble, random 2-subset targets, UTD 8.
Run: python examples/redq_pendulum.py"""

from rl_tpu.envs import PendulumEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_redq_trainer


def main(total_steps: int = 100, n_envs: int = 16, frames: int = 1024):
    trainer = make_redq_trainer(
        VmapEnv(PendulumEnv(), n_envs),
        total_steps=total_steps,
        frames_per_batch=frames,
        config=OffPolicyConfig(init_random_frames=2048, batch_size=256, utd_ratio=8),
        logger=CSVLogger("redq_pendulum"),
        log_interval=5,
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
