"""SAC on Pendulum with device-resident replay (reference analog:
sota-implementations/sac/)."""

from rl_tpu.envs import PendulumEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_sac_trainer


def main():
    env = TransformedEnv(VmapEnv(PendulumEnv(), 16), RewardSum())
    trainer = make_sac_trainer(
        env,
        total_steps=200,
        frames_per_batch=1024,
        buffer_capacity=200_000,
        config=OffPolicyConfig(batch_size=256, utd_ratio=4, init_random_frames=4096),
        logger=CSVLogger("sac_pendulum"),
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
