"""TD3 (twin critics, target smoothing, delayed policy) on Pendulum
(reference analog: sota-implementations/td3/)."""

from rl_tpu.envs import PendulumEnv, RewardSum, TransformedEnv, VmapEnv
from rl_tpu.record import CSVLogger
from rl_tpu.trainers import OffPolicyConfig
from rl_tpu.trainers.algorithms import make_td3_trainer


def main():
    env = TransformedEnv(VmapEnv(PendulumEnv(), 16), RewardSum())
    trainer = make_td3_trainer(
        env,
        total_steps=200,
        frames_per_batch=1024,
        config=OffPolicyConfig(
            batch_size=256, utd_ratio=4, init_random_frames=4096, policy_delay=2
        ),
        logger=CSVLogger("td3_pendulum"),
    )
    trainer.train(0)


if __name__ == "__main__":
    main()
