"""TD3+BC on a D4RL-format dataset (reference analog:
sota-implementations/td3_bc/): the one-line offline regularization —
-lambda Q(s, pi(s)) + ||pi(s) - a||^2 — over a dataset loaded through the
format-exact D4RL HDF5 loader.
Run: python examples/td3bc_d4rl.py"""

import os
import tempfile

import jax
import numpy as np
import optax

from rl_tpu.data import D4RLH5Dataset
from rl_tpu.modules import ConcatMLP, TanhPolicy, TDModule
from rl_tpu.objectives import SoftUpdate, TD3BCLoss


def main(steps: int = 300, workdir=None, log_interval: int = 50):
    workdir = workdir or tempfile.mkdtemp()
    from iql_offline_to_online import synthesize_d4rl

    h5 = synthesize_d4rl(os.path.join(workdir, "pendulum_random.hdf5"))
    ds = D4RLH5Dataset(h5, scratch_dir=os.path.join(workdir, "mm"), batch_size=256)

    act_dim = int(np.asarray(ds.sample(jax.random.key(0))["action"]).shape[-1])
    actor = TDModule(
        TanhPolicy(action_dim=act_dim, low=-2.0, high=2.0),
        ["observation"], ["action"],
    )
    loss = TD3BCLoss(
        actor, ConcatMLP(out_features=1, num_cells=(256, 256)),
        action_low=-2.0, action_high=2.0, alpha=2.5,
    )
    params = loss.init_params(jax.random.key(0), ds.sample(jax.random.key(1)))
    opt = optax.adam(3e-4)
    ost = opt.init(loss.trainable(params))
    updater = SoftUpdate(loss, tau=0.005)

    @jax.jit
    def step(params, ost, batch, key):
        v, grads, m = loss.grad(params, batch, key)
        upd, ost = opt.update(grads, ost, loss.trainable(params))
        params = updater(
            loss.merge(optax.apply_updates(loss.trainable(params), upd), params)
        )
        return params, ost, v, m

    for i in range(steps):
        k = jax.random.key(10 + i)
        params, ost, v, m = step(params, ost, ds.sample(k), k)
        if i % log_interval == 0:
            print(f"step {i}: loss {float(v):.4f} bc {float(m['bc_loss']):.4f}")
    return params


if __name__ == "__main__":
    main()
