"""Train any algorithm from a YAML recipe alone — the config-driven driver
(reference analog: sota-implementations/*/`python xxx.py --config-name=...`
via hydra; here: `python examples/train_from_yaml.py <recipe.yaml> [steps]`).

Every component in the recipe resolves through the rl_tpu.config registry,
so the YAML is the full specification of the run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rl_tpu.configs import load_recipe  # noqa: E402


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    trainer = load_recipe(argv[0])
    if len(argv) > 1:  # optional step-count override for smoke runs
        trainer.total_steps = int(argv[1])
    trainer.train(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
