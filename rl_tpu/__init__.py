"""rl_tpu: a TPU-native reinforcement-learning framework.

Brand-new design with the capabilities of TorchRL (pytorch/rl), built
idiomatically for JAX/XLA on TPU: named-pytree data model (ArrayDict), spec
trees, pure-functional environments vectorized with ``vmap``, single-program
``lax.scan`` collectors, device-resident replay, a full loss library with
``associative_scan`` value estimators, mesh/pjit parallelism over ICI/DCN,
and an LLM/RLHF stack with ring attention.

Blueprint: SURVEY.md (structural analysis of the reference with file:line
citations). Performance targets: BASELINE.md.
"""

__version__ = "0.1.0"

from .data import ArrayDict, Composite

__all__ = ["ArrayDict", "Composite", "__version__"]
