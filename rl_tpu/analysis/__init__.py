"""rlint: JAX/thread-discipline static analysis + runtime lock sanitizer.

Static rules (see :mod:`.rules` and :mod:`.lockorder`):

=====  =======================================================================
R001   host sync (``.item()``/``float()``/``np.asarray``/``jax.device_get``/
       ``.block_until_ready()``) reachable from a jit/lax body or ``@hot_path``
R002   buffer referenced after passing through a ``donate_argnums`` dispatch
R003   PRNG key consumed twice without an intervening split/rebind
R004   recompile hazards: tracer-dependent Python branches, jit-in-loop
R005   lock-order cycles over the package-wide lock-acquisition graph
R006   raw ``jax.jit``/``jax.pjit`` in rl_tpu/models/ or rl_tpu/trainers/
       bypassing the ProgramRegistry (not AOT-warmable, invisible to the
       executable store and compile metrics)
R007   cross-thread shared-state hazard: a field mutated inside a
       ``Supervisor.spawn``/``threading.Thread`` worker target and read
       from another method, neither side holding a lock
=====  =======================================================================

IR rules (R101–R105, see :mod:`.ir` / :mod:`.irrules`) audit the
*lowered* program — jaxpr + compiled HLO — at ProgramRegistry compile
time: host callbacks, unhonored donation, shard-local collectives, f64
creep, dead computation, plus a static FLOPs/bytes cost model feeding a
roofline-predicted MFU.

CLI: ``python tools/rlint.py rl_tpu/`` — findings are gated by the
checked-in ``.rlint-baseline.json`` (every suppression carries a reason)
and ``tests/test_rlint.py`` holds the package at zero unsuppressed
findings as part of tier-1.

Runtime: :class:`LockWitness` patches ``threading.Lock``/``RLock``
construction to record the observed lock-order graph and flag
inversions; armed under the chaos/fleet suites via the ``lock_witness``
conftest fixture.
"""

from __future__ import annotations

import os

from .baseline import Baseline, DEFAULT_BASELINE
from .core import ModuleIndex, PackageIndex, hot_path
from .findings import Finding
from .ir import IRAuditor, IRCost, get_ir_auditor, roofline, set_ir_auditor
from .irrules import IR_RULES
from .lockorder import lock_edges, run_lockorder
from .rules import run_rules
from .witness import LockWitness, WitnessedLock

__all__ = [
    "ALL_RULES",
    "Baseline",
    "DEFAULT_BASELINE",
    "Finding",
    "IRAuditor",
    "IRCost",
    "IR_RULES",
    "LockWitness",
    "WitnessedLock",
    "analyze_paths",
    "analyze_sources",
    "build_index",
    "get_ir_auditor",
    "hot_path",
    "lock_edges",
    "roofline",
    "set_ir_auditor",
]

ALL_RULES = ("R001", "R002", "R003", "R004", "R005", "R006", "R007")


def _module_name(path: str, root: str) -> str:
    """Dotted module name for a file: relative to the directory *containing*
    the package root, so ``rl_tpu/obs/trace.py`` → ``rl_tpu.obs.trace``."""
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p not in ("", "."))


def _iter_py_files(path: str):
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        )
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def build_index(paths, root: str | None = None) -> PackageIndex:
    """Index every .py under ``paths``. ``root`` is the directory module
    names are computed relative to (default: parent of the first path)."""
    if isinstance(paths, str):
        paths = [paths]
    if root is None:
        first = os.path.abspath(paths[0])
        root = os.path.dirname(first if os.path.isdir(first) else os.path.dirname(first))
    modules = []
    for p in paths:
        for f in _iter_py_files(p):
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            rel = os.path.relpath(os.path.abspath(f), os.path.abspath(root))
            modules.append(ModuleIndex(_module_name(f, root), rel.replace(os.sep, "/"), src))
    return PackageIndex(modules)


def analyze_paths(paths, rules=None, root: str | None = None) -> list[Finding]:
    """Run the selected rules (default: all) over files/directories."""
    index = build_index(paths, root=root)
    return _run(index, rules)


def analyze_sources(sources: dict, rules=None) -> list[Finding]:
    """Analyze in-memory sources: ``{module_name: source}`` (tests use
    this for fixture snippets; file = ``<module>.py``)."""
    modules = [
        ModuleIndex(name, f"{name.replace('.', '/')}.py", src)
        for name, src in sources.items()
    ]
    return _run(PackageIndex(modules), rules)


def _run(index: PackageIndex, rules) -> list[Finding]:
    ruleset = set(rules) if rules is not None else None
    out = run_rules(index, ruleset)
    if ruleset is None or "R005" in ruleset:
        out.extend(run_lockorder(index))
    # two syncs in one expression (``int(x) / float(y)``) produce one
    # finding each with the same line+snippet — collapse exact repeats
    seen: set = set()
    uniq = []
    for f in out:
        k = (f.rule, f.file, f.line, f.message, f.fingerprint)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return sorted(uniq, key=lambda f: (f.file, f.line, f.rule))
