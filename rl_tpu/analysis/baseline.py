"""Checked-in suppression baseline for rlint.

The baseline is the triage ledger: every *intentional* violation lives
here with a one-line reason, every genuine one gets fixed instead. The
gate (tests/test_rlint.py) holds the analyzer at zero unsuppressed
findings, so a new finding either gets a fix or a reviewed reason — it
cannot land silently.

Matching is by :attr:`Finding.fingerprint` (rule + file + qualname +
snippet — line-number independent). Entries that no longer match any
finding are *stale*: reported as warnings so the file shrinks as code
improves, but never a failure (deleting code must not break the gate).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .findings import Finding

__all__ = ["Baseline", "DEFAULT_BASELINE"]

DEFAULT_BASELINE = ".rlint-baseline.json"


@dataclass
class Baseline:
    suppressions: list = field(default_factory=list)  # dicts with fingerprint+reason
    fixed: list = field(default_factory=list)         # ledger of violations fixed in PRs
    path: str | None = None

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path) as f:
            data = json.load(f)
        bl = cls(
            suppressions=list(data.get("suppressions", [])),
            fixed=list(data.get("fixed", [])),
            path=path,
        )
        missing = [s for s in bl.suppressions if not s.get("reason")]
        if missing:
            fps = ", ".join(s.get("fingerprint", "?") for s in missing)
            raise ValueError(
                f"baseline {path}: every suppression needs a non-empty 'reason' "
                f"(missing on: {fps})"
            )
        return bl

    def save(self, path: str | None = None) -> None:
        path = path or self.path or DEFAULT_BASELINE
        data = {
            "version": 1,
            "tool": "rlint",
            "suppressions": sorted(
                self.suppressions, key=lambda s: (s.get("rule", ""), s.get("file", ""),
                                                  s.get("fingerprint", "")),
            ),
            "fixed": self.fixed,
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    @property
    def fingerprints(self) -> set:
        return {s["fingerprint"] for s in self.suppressions if "fingerprint" in s}

    def split(self, findings: list[Finding]):
        """(unsuppressed, suppressed, stale_entries)."""
        fps = self.fingerprints
        hit: set = set()
        unsup, sup = [], []
        for f in findings:
            if f.fingerprint in fps:
                hit.add(f.fingerprint)
                sup.append(f)
            else:
                unsup.append(f)
        stale = [s for s in self.suppressions if s.get("fingerprint") not in hit]
        return unsup, sup, stale

    def add(self, finding: Finding, reason: str) -> None:
        if not reason:
            raise ValueError("a suppression reason is required")
        if finding.fingerprint in self.fingerprints:
            return
        self.suppressions.append({
            "fingerprint": finding.fingerprint,
            "rule": finding.rule,
            "file": finding.file,
            "qualname": finding.qualname,
            "snippet": finding.snippet,
            "reason": reason,
        })
