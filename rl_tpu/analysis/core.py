"""AST indexing for rlint: functions, aliases, call graph, hot-path reachability.

The analyzer never imports the code under analysis — everything is
derived from the AST, so a module with heavyweight import side effects
(or one that would initialize a JAX backend) costs nothing to lint.

Identity model
--------------
Every function/method (including nested defs) becomes a
:class:`FunctionInfo` keyed by a dotted qualname
``<module>.<Class>.<method>`` / ``<module>.<func>.<locals>.<inner>``.
Import statements are folded into a per-module alias map so call
expressions canonicalize to full dotted paths (``jnp.asarray`` →
``jax.numpy.asarray``, ``fault_point`` → ``rl_tpu.resilience.faults
.fault_point``) — cross-module edges fall out of ordinary name lookup.

Hot roots
---------
A function is a *hot root* when it is (a) decorated ``@jax.jit`` /
``@partial(jax.jit, ...)``, (b) passed into ``jax.jit``/``pjit`` or a
``lax`` control-flow combinator (``scan``/``while_loop``/``fori_loop``/
``cond``/``switch``/``map``/``associative_scan``) anywhere in its module,
or (c) decorated :func:`hot_path` — the explicit marker for *host-side*
dispatch loops (serving decode, collector actor loops) where a stray
``.item()``/``float()`` stalls the device pipeline even though no tracer
is in sight. Reachability is the transitive closure over resolved call
edges; function references passed to other ``jax.*`` transforms
(``vmap``, ``grad``, ``remat``, …) count as call edges.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["hot_path", "FunctionInfo", "ModuleIndex", "PackageIndex"]


def hot_path(fn=None, *, reason: str = ""):
    """Mark a host-side function as a hot path for rlint.

    No-op at runtime (returns ``fn`` unchanged); the static analyzer
    treats decorated functions as R001 roots — anything reachable from
    them must not host-sync. Usable bare (``@hot_path``) or with a
    reason (``@hot_path(reason="decode loop")``).
    """
    def mark(f):
        f.__rl_tpu_hot_path__ = reason or True
        return f
    return mark(fn) if fn is not None else mark


# jax/lax combinators whose function-valued args are traced (arg positions)
_TRACED_ARG_POSITIONS = {
    "jax.jit": (0,),
    "jax.pjit": (0,),
    "jax.experimental.pjit.pjit": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
}
# transforms where a function arg becomes callable from the enclosing scope
_TRANSFORM_PREFIXES = ("jax.",)

_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_HOT_PATH_NAMES = {
    "hot_path",
    "rl_tpu.analysis.hot_path",
    "rl_tpu.analysis.core.hot_path",
    "analysis.hot_path",
}


def canon(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonical dotted name of a Name/Attribute chain, folding import
    aliases (``jnp`` → ``jax.numpy``). None for non-name expressions."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(aliases.get(node.id, node.id))
        return ".".join(reversed(parts))
    return None


def _target_names(target: ast.AST) -> list[str]:
    """Flat trackable names assigned by a target: ``x``, ``self.x``."""
    out: list[str] = []
    if isinstance(target, ast.Name):
        out.append(target.id)
    elif isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
        out.append(f"{target.value.id}.{target.attr}")
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out.extend(_target_names(elt))
    elif isinstance(target, ast.Starred):
        out.extend(_target_names(target.value))
    return out


@dataclass
class FunctionInfo:
    qualname: str                     # dotted: module.Class.method
    display: str                      # Class.method (module-relative)
    file: str
    node: ast.AST                     # FunctionDef / AsyncFunctionDef / Lambda
    module: str
    class_name: str | None = None
    calls: set = field(default_factory=set)        # resolved callee qualnames
    hot_root: bool = False
    hot_kind: str = ""                # "jit" | "scan" | "hot_path" | ...
    hot_detail: str = ""
    static_params: set = field(default_factory=set)
    params: list = field(default_factory=list)

    @property
    def is_traced_root(self) -> bool:
        """True for roots whose body runs under a tracer (jit/lax bodies),
        as opposed to host-side @hot_path loops."""
        return self.hot_root and self.hot_kind != "hot_path"


class ModuleIndex:
    """Single-file index: aliases, function defs (incl. nested/methods)."""

    def __init__(self, modname: str, path: str, source: str):
        self.modname = modname
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases: dict[str, str] = {}
        self.functions: dict[str, FunctionInfo] = {}      # qualname -> info
        self.toplevel: dict[str, str] = {}                # simple name -> qualname
        self.methods: dict[str, dict[str, str]] = {}      # class -> {method: qualname}
        self._collect_imports()
        self._collect_functions()

    def snippet(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return self.lines[ln - 1].strip() if 0 < ln <= len(self.lines) else ""

    # -- imports ---------------------------------------------------------------

    def _resolve_relative(self, level: int, module: str | None) -> str:
        parts = self.modname.split(".")
        # level=1 → current package (strip the module leaf), 2 → parent, ...
        base = parts[: max(0, len(parts) - level)]
        if module:
            base = base + module.split(".")
        return ".".join(base)

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.aliases.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                mod = (
                    self._resolve_relative(node.level, node.module)
                    if node.level
                    else (node.module or "")
                )
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name

    # -- function defs ---------------------------------------------------------

    def _collect_functions(self) -> None:
        mod = self.modname

        def visit(node: ast.AST, scope: list[str], cls: str | None):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual_parts = scope + [child.name]
                    qualname = ".".join([mod] + qual_parts)
                    info = FunctionInfo(
                        qualname=qualname,
                        display=".".join(qual_parts),
                        file=self.path,
                        node=child,
                        module=mod,
                        class_name=cls,
                        params=[a.arg for a in (
                            child.args.posonlyargs + child.args.args + child.args.kwonlyargs
                        )],
                    )
                    self.functions[qualname] = info
                    if not scope:
                        self.toplevel[child.name] = qualname
                    if cls is not None and len(scope) == 1:
                        self.methods.setdefault(cls, {})[child.name] = qualname
                    self._mark_decorator_roots(info)
                    visit(child, qual_parts, cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, scope + [child.name], child.name)
                else:
                    visit(child, scope, cls)

        visit(self.tree, [], None)

    def _mark_decorator_roots(self, info: FunctionInfo) -> None:
        for dec in getattr(info.node, "decorator_list", []):
            name = canon(dec, self.aliases)
            if name in _JIT_NAMES:
                info.hot_root, info.hot_kind = True, "jit"
                info.hot_detail = "@jax.jit"
            elif name in _HOT_PATH_NAMES:
                info.hot_root, info.hot_kind = True, "hot_path"
                info.hot_detail = "@hot_path"
            elif isinstance(dec, ast.Call):
                cname = canon(dec.func, self.aliases)
                if cname in _HOT_PATH_NAMES:
                    info.hot_root, info.hot_kind = True, "hot_path"
                    info.hot_detail = "@hot_path(...)"
                elif cname in _JIT_NAMES:
                    info.hot_root, info.hot_kind = True, "jit"
                    info.hot_detail = "@jax.jit(...)"
                    info.static_params |= self._static_names(dec, info)
                elif cname in _PARTIAL_NAMES and dec.args:
                    inner = canon(dec.args[0], self.aliases)
                    if inner in _JIT_NAMES:
                        info.hot_root, info.hot_kind = True, "jit"
                        info.hot_detail = "@partial(jax.jit, ...)"
                        info.static_params |= self._static_names(dec, info)

    def _static_names(self, call: ast.Call, info: FunctionInfo) -> set:
        out: set = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        out.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        if 0 <= n.value < len(info.params):
                            out.add(info.params[n.value])
        return out


class PackageIndex:
    """Whole-package index + call graph + hot-path reachability."""

    def __init__(self, modules: list[ModuleIndex]):
        self.modules = modules
        self.functions: dict[str, FunctionInfo] = {}
        self.methods_by_name: dict[str, list[str]] = {}
        for m in modules:
            self.functions.update(m.functions)
        for m in modules:
            for cls, meths in m.methods.items():
                for name, qual in meths.items():
                    self.methods_by_name.setdefault(name, []).append(qual)
        for m in modules:
            self._link_module(m)
        self.hot_from: dict[str, str] = {}
        self._compute_reachability()

    # -- resolution ------------------------------------------------------------

    def resolve_call(self, m: ModuleIndex, fn: FunctionInfo | None,
                     func_node: ast.AST) -> str | None:
        """Resolve a call expression to a known function qualname."""
        name = canon(func_node, m.aliases)
        if name is not None:
            if name in self.functions:
                return name
            # module-local bare name (possibly nested sibling)
            if "." not in name and name in m.toplevel:
                return m.toplevel[name]
            if fn is not None and "." not in name:
                # nested def inside the same enclosing function
                nested = f"{fn.qualname}.{name}"
                if nested in self.functions:
                    return nested
        if isinstance(func_node, ast.Attribute):
            attr = func_node.attr
            if isinstance(func_node.value, ast.Name) and func_node.value.id == "self":
                if fn is not None and fn.class_name and fn.class_name in m.methods:
                    q = m.methods[fn.class_name].get(attr)
                    if q:
                        return q
            # unique-method heuristic: exactly one definition package-wide
            cands = self.methods_by_name.get(attr, [])
            if len(cands) == 1 and not attr.startswith("__"):
                return cands[0]
        return None

    def resolve_func_ref(self, m: ModuleIndex, fn: FunctionInfo | None,
                         node: ast.AST) -> str | None:
        """Resolve a *function reference* (not a call): Name / self.attr.
        ``self._f`` also tries the ``_f_impl``-style method directly."""
        if isinstance(node, ast.Lambda):
            return None
        return self.resolve_call(m, fn, node)

    # -- linking ---------------------------------------------------------------

    def _enclosing_fn(self, m: ModuleIndex, stack: list[FunctionInfo]) -> FunctionInfo | None:
        return stack[-1] if stack else None

    def _link_module(self, m: ModuleIndex) -> None:
        """Populate call edges and usage-site hot roots for one module."""

        index = self

        class Linker(ast.NodeVisitor):
            def __init__(self):
                self.stack: list[FunctionInfo] = []

            def _info_for(self, node):
                for info in m.functions.values():
                    if info.node is node:
                        return info
                return None

            def visit_FunctionDef(self, node):
                info = self._info_for(node)
                if info is None:
                    return
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                fn = self.stack[-1] if self.stack else None
                callee = index.resolve_call(m, fn, node.func)
                if callee is not None and fn is not None:
                    fn.calls.add(callee)
                cname = canon(node.func, m.aliases)
                if cname is not None:
                    positions = _TRACED_ARG_POSITIONS.get(cname)
                    if positions is not None:
                        for pos in positions:
                            if pos < len(node.args):
                                index._mark_usage_root(m, fn, node.args[pos], cname, node)
                        if cname in _JIT_NAMES and node.args:
                            index._attach_static(m, fn, node)
                    elif cname.startswith(_TRANSFORM_PREFIXES):
                        # other jax transforms: function args become call edges
                        for a in node.args:
                            ref = index.resolve_func_ref(m, fn, a)
                            if ref is not None and fn is not None:
                                fn.calls.add(ref)
                    elif cname in _PARTIAL_NAMES and node.args:
                        inner = canon(node.args[0], m.aliases)
                        if inner in _JIT_NAMES and len(node.args) > 1:
                            index._mark_usage_root(m, fn, node.args[1], inner, node)
                self.generic_visit(node)

        Linker().visit(m.tree)

    def _mark_usage_root(self, m: ModuleIndex, fn: FunctionInfo | None,
                         arg: ast.AST, via: str, call: ast.Call) -> None:
        if isinstance(arg, (ast.List, ast.Tuple)):        # lax.switch branches
            for elt in arg.elts:
                self._mark_usage_root(m, fn, elt, via, call)
            return
        ref = self.resolve_func_ref(m, fn, arg)
        if ref is None:
            # a lambda or unresolvable expression; treat lambda body as an
            # extension of the enclosing function (already visited)
            return
        info = self.functions[ref]
        if not info.hot_root:
            info.hot_root = True
            info.hot_kind = "jit" if via in _JIT_NAMES else "scan"
            info.hot_detail = f"passed to {via} at {m.path}:{call.lineno}"
        if via in _JIT_NAMES:
            info.static_params |= m._static_names(call, info)

    def _attach_static(self, m: ModuleIndex, fn: FunctionInfo | None,
                       call: ast.Call) -> None:
        ref = self.resolve_func_ref(m, fn, call.args[0])
        if ref is not None:
            info = self.functions[ref]
            info.static_params |= m._static_names(call, info)

    # -- reachability ----------------------------------------------------------

    def _compute_reachability(self) -> None:
        frontier = [q for q, f in self.functions.items() if f.hot_root]
        for q in frontier:
            self.hot_from[q] = self.functions[q].hot_detail or self.functions[q].hot_kind
        while frontier:
            q = frontier.pop()
            for callee in self.functions[q].calls:
                if callee not in self.hot_from:
                    src = self.functions[q]
                    self.hot_from[callee] = f"called from hot {src.display} ({src.module})"
                    frontier.append(callee)

    def is_hot(self, qualname: str) -> bool:
        return qualname in self.hot_from
