"""Finding record + stable fingerprints for baseline suppression.

A finding's fingerprint deliberately ignores the line number: baselines
must survive unrelated edits above the flagged site. Identity is the
(rule, file, enclosing qualname, normalized source snippet) tuple — the
same violation moving a few lines keeps its suppression; a *new* call
site with identical text inside the same function is (correctly) treated
as already-triaged, because the reviewer's reason applies to it verbatim.
"""

from __future__ import annotations

import hashlib
from dataclasses import asdict, dataclass, field

__all__ = ["Finding"]


@dataclass(frozen=True)
class Finding:
    rule: str          # "R001".."R005"
    file: str          # repo-relative path ("rl_tpu/x/y.py")
    line: int
    qualname: str      # enclosing function ("Class.method", "func.<locals>.g") or lock-cycle id
    message: str
    snippet: str = ""  # stripped source line of the flagged node
    col: int = 0
    extra: dict = field(default_factory=dict, compare=False, hash=False)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            "|".join((self.rule, self.file, self.qualname, self.snippet)).encode()
        )
        return h.hexdigest()[:12]

    def to_dict(self) -> dict:
        d = asdict(self)
        d.pop("extra", None)
        d["fingerprint"] = self.fingerprint
        return d

    def format(self) -> str:
        return (
            f"{self.file}:{self.line}: {self.rule} [{self.qualname}] "
            f"{self.message}  [{self.fingerprint}]"
        )
