"""IR-level program audit: jaxpr walker, static cost model, IRAuditor.

rlint's R001–R007 read Python source; this module reads what actually
ships to the accelerator. :func:`summarize_jaxpr` walks a (closed)
jaxpr — duck-typed, so this module never imports jax and the analysis
package stays importable in milliseconds — collecting the facts the
R100-series rules (:mod:`.irrules`) judge: host-callback primitives,
collectives, f64 creep, dead computation, plus a static FLOPs /
bytes-moved cost model. The compiled executable's HLO text contributes
the facts tracing cannot see: honored input-output aliasing (did XLA
actually take the donation?) and partitioner-inserted collectives.

The auditor piggybacks on :meth:`rl_tpu.compile.CachedProgram._compile`
— the one place every registered program already pays a trace+lower —
so the audit adds **zero dispatch-path cost** and every executable the
ProgramRegistry materializes is checked exactly once per signature.
Findings reuse the :class:`~.findings.Finding` record (the program name
stands in for the file path as ``program:<name>``), so baseline
suppression, fingerprints, and the reason-required triage flow are the
same machinery R001–R007 already use.

Cost model: ``dot_general`` counts ``2·B·M·N·K``, convolutions
``2·|out|·(kernel taps × in-features / groups)``, reductions one flop
per input element, everything else one per output element; ``scan``
bodies multiply by trip count. Bytes are the sum of operand+result
sizes per equation — an un-fused upper bound, which is exactly what a
roofline wants (:func:`roofline` flags transfer-bound programs by
comparing ``flops/peak`` against ``bytes/bandwidth``).
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any

from .baseline import Baseline, DEFAULT_BASELINE
from .findings import Finding

__all__ = [
    "IRAuditor",
    "IRCost",
    "IRFacts",
    "ProgramAudit",
    "get_ir_auditor",
    "roofline",
    "summarize_jaxpr",
]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# primitives that re-enter Python from inside the program (R101)
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "outside_call", "host_callback_call",
})
# cross-device primitives (R103); psum lowered as psum2 on current jax
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "reduce_scatter", "psum_scatter",
})
# HLO op names the SPMD partitioner may insert post-trace (R103)
_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter|collective-broadcast)\b"
)
_ALIAS_ENTRY_RE = re.compile(r"(?:may|must)-alias")


def _alias_block(hlo_text: str) -> str:
    """The brace-balanced body of ``input_output_alias={...}`` in an HLO
    module header (nested braces — regex can't scope it reliably)."""
    marker = "input_output_alias={"
    start = hlo_text.find(marker)
    if start < 0:
        return ""
    i = start + len(marker)
    depth = 1
    for j in range(i, min(len(hlo_text), i + 65536)):
        ch = hlo_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return hlo_text[i:j]
    return ""

_WIDE_DTYPES = ("float64", "complex128")


# -- cost model ---------------------------------------------------------------

@dataclass
class IRCost:
    """Static per-call cost of one program signature."""

    flops: float = 0.0       # total FLOPs per call
    bytes: float = 0.0       # operand+result bytes summed per equation
    io_bytes: float = 0.0    # program inputs + outputs only
    eqns: int = 0            # equation count (scan bodies counted once)
    by_prim: dict = field(default_factory=dict)  # prim name -> eqn count

    def to_dict(self) -> dict:
        return {
            "flops": self.flops, "bytes": self.bytes,
            "io_bytes": self.io_bytes, "eqns": self.eqns,
        }


def _aval(v: Any):
    return getattr(v, "aval", None)


def _nbytes(aval: Any) -> float:
    if aval is None:
        return 0.0
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None:
        return 0.0
    n = 1.0
    for d in shape:
        n *= float(d)
    return n * float(getattr(dtype, "itemsize", 4) or 4)


def _nelems(aval: Any) -> float:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0.0
    n = 1.0
    for d in shape:
        n *= float(d)
    return n


def _dtype_name(aval: Any) -> str:
    return str(getattr(aval, "dtype", ""))


def _inner_jaxprs(params: dict):
    """(closed) jaxprs hiding in an eqn's params: scan/while/cond/pjit
    bodies, shard_map, custom_* — anything with .eqns (or .jaxpr.eqns)."""
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            if hasattr(x, "eqns") or hasattr(getattr(x, "jaxpr", None), "eqns"):
                yield x


def _open(jaxpr: Any):
    """Raw jaxpr for either a ClosedJaxpr or an already-open one."""
    inner = getattr(jaxpr, "jaxpr", None)
    return inner if hasattr(inner, "eqns") else jaxpr


# prims that wrap an opaque device kernel whose body the generic
# per-equation rules can't price (the inner jaxpr runs once PER GRID
# STEP, so recursing into it undercounts; a bare custom_call has no body
# at all) — the kernels.registry formulas price these by call target
_KERNEL_CALL_PRIMS = ("pallas_call", "custom_call", "tpu_custom_call")


def _call_target(params: dict) -> str:
    """Best-effort call-target name of a kernel-call eqn (pallas names
    the kernel body function via ``name_and_src_info``)."""
    nsi = params.get("name_and_src_info")
    name = (
        getattr(nsi, "name", None)
        or params.get("name")
        or params.get("call_target_name")
    )
    return str(name) if name else ""


def _price_kernel_call(target: str, eqn: Any) -> dict | None:
    """Registered-kernel cost for one call eqn, or None (lazy import —
    kernels.registry never imports jax, so this keeps the millisecond
    import budget)."""
    try:
        from ..kernels.registry import price_call

        return price_call(
            target,
            [_aval(v) for v in eqn.invars],
            [_aval(v) for v in eqn.outvars],
        )
    except Exception:
        return None


def _eqn_flops(prim: str, eqn: Any) -> float:
    try:
        if prim == "dot_general":
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            lhs = _aval(eqn.invars[0])
            rhs = _aval(eqn.invars[1])
            lsh, rsh = lhs.shape, rhs.shape
            batch = 1.0
            for d in lb:
                batch *= float(lsh[d])
            contract = 1.0
            for d in lc:
                contract *= float(lsh[d])
            m = 1.0
            for i, d in enumerate(lsh):
                if i not in lb and i not in lc:
                    m *= float(d)
            n = 1.0
            for i, d in enumerate(rsh):
                if i not in rb and i not in rc:
                    n *= float(d)
            return 2.0 * batch * m * n * contract
        if prim == "conv_general_dilated":
            out = _aval(eqn.outvars[0])
            rhs = _aval(eqn.invars[1])
            dn = eqn.params.get("dimension_numbers")
            out_feat = float(rhs.shape[dn.rhs_spec[0]]) if dn is not None else 1.0
            taps = _nelems(rhs) / max(out_feat, 1.0)
            groups = float(eqn.params.get("feature_group_count", 1) or 1)
            return 2.0 * _nelems(out) * taps / groups
        if prim.startswith(("reduce_", "arg")) or prim in ("reduce_sum", "cumsum",
                                                           "cumlogsumexp", "cummax"):
            return sum(_nelems(_aval(v)) for v in eqn.invars)
    except Exception:
        pass
    return sum(_nelems(_aval(v)) for v in eqn.outvars)


def summarize_jaxpr(jaxpr: Any, *, dead_bytes_threshold: float = 8192.0) -> "IRFacts":
    """One recursive walk → everything the R100 rules + cost model need.

    ``jaxpr`` is a jax ClosedJaxpr (or raw jaxpr) but is only touched
    through ``.eqns`` / ``.invars`` / ``.outvars`` / ``.aval`` duck
    typing, so callers in tests can also hand in lightweight fakes.
    """
    facts = IRFacts()
    top = _open(jaxpr)
    if top is None or not hasattr(top, "eqns"):
        return facts

    for v in getattr(top, "invars", ()):
        facts.input_dtypes.append(_dtype_name(_aval(v)))

    cost = facts.cost
    try:
        cost.io_bytes = sum(_nbytes(_aval(v)) for v in top.invars) + sum(
            _nbytes(_aval(v)) for v in top.outvars
        )
    except Exception:
        pass

    def walk(jx: Any, mult: float, path: str) -> None:
        jx = _open(jx)
        if jx is None or not hasattr(jx, "eqns"):
            return
        for eqn in jx.eqns:
            prim = getattr(getattr(eqn, "primitive", None), "name", "?")
            cost.eqns += 1
            cost.by_prim[prim] = cost.by_prim.get(prim, 0) + 1
            priced = None
            if prim in _KERNEL_CALL_PRIMS:
                target = _call_target(getattr(eqn, "params", None) or {})
                if target:
                    priced = _price_kernel_call(target, eqn)
                    facts.kernel_sites.append(
                        (target, (priced or {}).get("kernel", ""), path)
                    )
            if priced is not None:
                cost.flops += mult * float(priced.get("flops", 0.0))
                cost.bytes += mult * float(priced.get("bytes", 0.0))
            else:
                cost.flops += mult * _eqn_flops(prim, eqn)
                try:
                    cost.bytes += mult * (
                        sum(_nbytes(_aval(v)) for v in eqn.invars)
                        + sum(_nbytes(_aval(v)) for v in eqn.outvars)
                    )
                except Exception:
                    pass
            if prim in CALLBACK_PRIMS or prim.startswith("debug_"):
                facts.callback_sites.append((prim, path))
            if prim in COLLECTIVE_PRIMS:
                facts.collective_sites.append((prim, path))
            for v in getattr(eqn, "outvars", ()):
                dt = _dtype_name(_aval(v))
                if dt in _WIDE_DTYPES:
                    facts.wide_sites.append((prim, dt, path))
                    break
            params = getattr(eqn, "params", None) or {}
            inner_mult = mult
            if prim == "scan":
                try:
                    inner_mult = mult * float(params.get("length", 1) or 1)
                except Exception:
                    inner_mult = mult
            if priced is None:
                # a priced kernel's formula already covers its body;
                # recursing would double-count (and at 1x, not grid-x)
                for sub in _inner_jaxprs(params):
                    walk(sub, inner_mult, f"{path}/{prim}")

    walk(top, 1.0, "")

    # dead computation (top level only): backward liveness from outputs.
    # Effectful primitives (callbacks, collectives) are always live.
    try:
        # any-consumer map: a dead eqn feeding only other dead eqns is part
        # of a dead *chain* — report just the chain's root, not every link
        consumed = {
            id(iv)
            for eqn in top.eqns
            for iv in eqn.invars
            if not hasattr(iv, "val")
        }
        needed = {id(v) for v in top.outvars}
        for eqn in reversed(top.eqns):
            prim = getattr(getattr(eqn, "primitive", None), "name", "?")
            live = (
                prim in CALLBACK_PRIMS
                or prim in COLLECTIVE_PRIMS
                or bool(getattr(eqn, "effects", None))
                or any(id(v) in needed for v in eqn.outvars)
            )
            if live:
                for v in eqn.invars:
                    if _aval(v) is not None and not hasattr(v, "val"):
                        needed.add(id(v))
            elif not any(id(v) in consumed for v in eqn.outvars):
                dead_b = sum(_nbytes(_aval(v)) for v in eqn.outvars)
                if dead_b >= dead_bytes_threshold:
                    shape = tuple(getattr(_aval(eqn.outvars[0]), "shape", ()))
                    facts.dead_sites.append((prim, dead_b, str(shape)))
        for i, v in enumerate(top.invars):
            if id(v) not in needed and _nbytes(_aval(v)) >= dead_bytes_threshold:
                used = any(
                    any(id(iv) == id(v) for iv in eqn.invars) for eqn in top.eqns
                )
                if not used:
                    facts.dead_inputs.append((i, _nbytes(_aval(v))))
    except Exception:
        pass
    return facts


def honored_alias_count(hlo_text: str) -> int:
    """Entries in the executable's ``input_output_alias`` map — how many
    donated buffers XLA actually reused for outputs."""
    return len(_ALIAS_ENTRY_RE.findall(_alias_block(hlo_text or "")))


def hlo_collectives(hlo_text: str) -> list[str]:
    return sorted(set(_HLO_COLLECTIVE_RE.findall(hlo_text or "")))


def roofline(cost: IRCost, peak_flops: float, peak_bytes_per_s: float = 0.0) -> dict:
    """Predicted step time / MFU from the static cost model.

    ``predicted_s = max(flops/peak, bytes/bw)``; a program is
    *transfer-bound* when the byte term dominates — on such a program
    measured MFU can never reach peak no matter how good the kernels
    are, which is the actionable signal for the bench `ir_audit`
    section."""
    out: dict[str, Any] = {
        "flops": cost.flops, "bytes": cost.bytes,
        "intensity": cost.flops / cost.bytes if cost.bytes else 0.0,
    }
    if peak_flops <= 0.0:
        return out
    compute_s = cost.flops / peak_flops
    transfer_s = cost.bytes / peak_bytes_per_s if peak_bytes_per_s > 0.0 else 0.0
    predicted_s = max(compute_s, transfer_s)
    out["predicted_s"] = predicted_s
    out["bound"] = "transfer" if transfer_s > compute_s else "compute"
    out["transfer_bound"] = transfer_s > compute_s
    out["predicted_mfu"] = (compute_s / predicted_s) if predicted_s > 0.0 else 0.0
    return out


# -- facts + audit records ----------------------------------------------------

@dataclass
class IRFacts:
    """What one walk of a lowered program established (rule input)."""

    callback_sites: list = field(default_factory=list)   # (prim, path)
    collective_sites: list = field(default_factory=list)  # (prim, path)
    wide_sites: list = field(default_factory=list)       # (prim, dtype, path)
    dead_sites: list = field(default_factory=list)       # (prim, bytes, shape)
    dead_inputs: list = field(default_factory=list)      # (argpos, bytes)
    input_dtypes: list = field(default_factory=list)
    kernel_sites: list = field(default_factory=list)     # (target, kernel, path)
    cost: IRCost = field(default_factory=IRCost)


@dataclass
class ProgramAudit:
    """One audited (program, signature) with its verdicts."""

    name: str
    fingerprint: str = ""
    facts: IRFacts | None = None
    findings: list = field(default_factory=list)      # all Findings
    unsuppressed: list = field(default_factory=list)
    donated_declared: int = 0
    donated_honored: int = 0
    hlo_collectives: list = field(default_factory=list)

    @property
    def cost(self) -> IRCost | None:
        return self.facts.cost if self.facts is not None else None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "findings": [f.to_dict() for f in self.findings],
            "unsuppressed": len(self.unsuppressed),
            "donated": {"declared": self.donated_declared,
                        "honored": self.donated_honored},
            "cost": self.cost.to_dict() if self.cost else None,
        }


class IRAuditor:
    """Collects per-program audits across a process (or a test fixture).

    One process-default instance (:func:`get_ir_auditor`) receives every
    audit the default ProgramRegistry triggers — the tier-1 gate and the
    ``/metrics`` counter read it. Tests that *deliberately* compile
    poisoned programs pass their own instance to
    ``ProgramRegistry(auditor=...)`` so the gate stays clean.
    """

    def __init__(self, baseline_path: str | None = None,
                 dead_bytes_threshold: float = 8192.0):
        self.baseline_path = (
            baseline_path
            if baseline_path is not None
            else os.path.join(_REPO, DEFAULT_BASELINE)
        )
        self.dead_bytes_threshold = dead_bytes_threshold
        self._lock = threading.Lock()
        self._baseline: Baseline | None = None
        self.reports: dict[tuple, ProgramAudit] = {}  # (name, sig_key) -> audit

    def _load_baseline(self) -> Baseline:
        with self._lock:
            if self._baseline is None:
                try:
                    self._baseline = Baseline.load(self.baseline_path)
                except Exception:
                    self._baseline = Baseline(path=self.baseline_path)
            return self._baseline

    def audit(
        self,
        *,
        name: str,
        fingerprint: str = "",
        jaxpr: Any = None,
        compiled_text: str = "",
        donated_leaves: int = 0,
        donation_declared: bool = False,
        contract: dict | None = None,
        sig_key: Any = None,
    ) -> ProgramAudit:
        from .irrules import run_ir_rules

        facts = (
            summarize_jaxpr(jaxpr, dead_bytes_threshold=self.dead_bytes_threshold)
            if jaxpr is not None
            else None
        )
        honored = honored_alias_count(compiled_text)
        hlo_colls = hlo_collectives(compiled_text)
        report = ProgramAudit(
            name=name,
            fingerprint=fingerprint,
            facts=facts,
            donated_declared=donated_leaves,
            donated_honored=honored,
            hlo_collectives=hlo_colls,
        )
        report.findings = run_ir_rules(
            name=name,
            facts=facts,
            donated_leaves=donated_leaves,
            donation_declared=donation_declared,
            honored_aliases=honored,
            hlo_collectives=hlo_colls,
            contract=contract or {},
        )
        unsup, _sup, _stale = self._load_baseline().split(report.findings)
        report.unsuppressed = unsup
        with self._lock:
            self.reports[(name, sig_key)] = report
        return report

    # -- introspection ---------------------------------------------------

    def _snapshot(self) -> list[ProgramAudit]:
        with self._lock:
            return list(self.reports.values())

    def findings(self) -> list:
        return [f for r in self._snapshot() for f in r.findings]

    def unsuppressed(self) -> list:
        return [f for r in self._snapshot() for f in r.unsuppressed]

    def counts_by_rule(self) -> dict:
        from .irrules import IR_RULES

        out = {rid: 0 for rid in IR_RULES}
        for f in self.findings():
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def programs_audited(self) -> int:
        return len(self.reports)

    def report_for(self, name: str) -> ProgramAudit | None:
        """Most recent audit for a program name (any signature)."""
        best = None
        for (n, _), r in sorted(self.reports.items(), key=lambda kv: str(kv[0])):
            if n == name:
                best = r
        return best


_default_auditor: IRAuditor | None = None
_default_lock = threading.Lock()


def get_ir_auditor(create: bool = True) -> IRAuditor | None:
    """Process-default auditor (created on first use)."""
    global _default_auditor
    with _default_lock:
        if _default_auditor is None and create:
            _default_auditor = IRAuditor()
        return _default_auditor


def set_ir_auditor(aud: IRAuditor | None) -> IRAuditor | None:
    global _default_auditor
    with _default_lock:
        prev = _default_auditor
        _default_auditor = aud
        return prev
