"""R100-series rules: semantic checks over lowered programs.

=====  =======================================================================
R101   host callback primitive (``pure_callback``/``io_callback``/
       ``debug.*``) inside a registered program — every dispatch re-enters
       Python, serializing the device pipeline the registry exists to keep
       full
R102   donation not honored: the program declares ``donate_argnums`` but the
       compiled executable's input-output alias map is empty — XLA copied
       every "donated" buffer, so the program silently pays 2× memory
R103   unexpected collective: a cross-device primitive (or a partitioner-
       inserted HLO collective) in a program whose registration declares a
       ``shard_local`` contract — the gate the cross-shard replay client
       (ROADMAP item 3) dispatches under
R104   dtype promotion: f64/c128 values materialize in a program whose
       inputs are all ≤ 32-bit — a weak-type or accidental upcast that
       doubles bytes moved (and is unsupported on TPU hardware)
R105   dead computation: an equation whose outputs feed nothing (or an
       input buffer nothing reads) above a size threshold — transferred
       and/or computed, then thrown away
R106   hot path on fallback: the registration declares a
       ``kernel_hot_path`` contract (serving decode/sampling, PER
       sum-tree), the kernels registry says the backend supports that
       Pallas kernel, but the lowered jaxpr contains no matching kernel
       call target — the hot path silently regressed to the stock-XLA
       fallback (``RL_TPU_NO_KERNELS`` is the sanctioned opt-out: it
       turns the registry answer off, so no finding)
=====  =======================================================================

Findings carry ``file="program:<name>"`` and a stable snippet (primitive
/ detail, never a line number), so the sha1 fingerprint survives re-
registration and the ordinary ``.rlint-baseline.json`` triage flow
applies unchanged.
"""

from __future__ import annotations

from typing import Any

from .findings import Finding
from .ir import IRFacts

__all__ = ["IR_RULES", "run_ir_rules"]

IR_RULES = ("R101", "R102", "R103", "R104", "R105", "R106")

_NARROW_BITS = 32


def _prog_finding(rule: str, name: str, snippet: str, message: str,
                  extra: dict | None = None) -> Finding:
    return Finding(
        rule=rule, file=f"program:{name}", line=0, qualname=name,
        snippet=snippet, message=message, extra=extra or {},
    )


def _input_is_wide(input_dtypes: list) -> bool:
    return any(dt in ("float64", "complex128", "int64", "uint64")
               for dt in input_dtypes)


def run_ir_rules(
    *,
    name: str,
    facts: IRFacts | None,
    donated_leaves: int = 0,
    donation_declared: bool = False,
    honored_aliases: int = 0,
    hlo_collectives: list | None = None,
    contract: dict | None = None,
) -> list[Finding]:
    contract = contract or {}
    hlo_collectives = hlo_collectives or []
    out: list[Finding] = []

    # R101 — host callback in a registered (hence hot) program
    if facts is not None:
        seen: set = set()
        for prim, path in facts.callback_sites:
            if prim in seen:
                continue
            seen.add(prim)
            where = f" (at {path.lstrip('/')})" if path else ""
            out.append(_prog_finding(
                "R101", name, f"callback:{prim}",
                f"host callback primitive '{prim}' in program '{name}'{where} — "
                "every dispatch re-enters Python and stalls the device queue",
            ))

    # R102 — declared donation, zero honored aliases
    if donation_declared and donated_leaves > 0 and honored_aliases == 0:
        out.append(_prog_finding(
            "R102", name, "donation:none-honored",
            f"program '{name}' declares donate_argnums ({donated_leaves} "
            "donated buffer(s)) but the executable aliases none of them to "
            "an output — XLA copied every donated buffer (2x memory, "
            "usually a shape/dtype mismatch between input and output)",
            extra={"declared": donated_leaves, "honored": honored_aliases},
        ))

    # R103 — collective in a shard-local program
    if contract.get("shard_local"):
        prims = sorted({p for p, _ in facts.collective_sites}) if facts else []
        for prim in prims:
            out.append(_prog_finding(
                "R103", name, f"collective:{prim}",
                f"collective primitive '{prim}' in program '{name}', whose "
                "registration declares a shard-local contract — the program "
                "must never synchronize across shards",
            ))
        # HLO-level scan only adds partitioner-inserted collectives that
        # have no jaxpr primitive to point at; with an explicit primitive
        # the jaxpr finding above is the precise one
        for op in hlo_collectives if not prims else []:
            out.append(_prog_finding(
                "R103", name, f"collective:{op}",
                f"partitioner-inserted HLO collective '{op}' in shard-local "
                f"program '{name}' — an in/out sharding mismatch is forcing "
                "a resharding exchange",
            ))

    # R104 — f64/c128 creep with ≤32-bit inputs
    if facts is not None and facts.wide_sites and not _input_is_wide(facts.input_dtypes):
        seen = set()
        for prim, dtype, path in facts.wide_sites:
            key = (prim, dtype)
            if key in seen:
                continue
            seen.add(key)
            out.append(_prog_finding(
                "R104", name, f"promote:{prim}:{dtype}",
                f"'{prim}' produces {dtype} in program '{name}' whose inputs "
                f"are all <= {_NARROW_BITS}-bit — a weak-type/accidental "
                "upcast (2x bytes; unsupported on TPU)",
            ))

    # R105 — dead computation / dead inputs above threshold
    if facts is not None:
        for prim, dead_b, shape in facts.dead_sites:
            out.append(_prog_finding(
                "R105", name, f"dead:{prim}:{shape}",
                f"dead computation in program '{name}': '{prim}' result "
                f"{shape} ({int(dead_b)} bytes) feeds no output",
                extra={"bytes": dead_b},
            ))
        for pos, dead_b in facts.dead_inputs:
            out.append(_prog_finding(
                "R105", name, f"dead-input:{pos}",
                f"program '{name}' input #{pos} ({int(dead_b)} bytes) is "
                "never read — transferred to the device for nothing",
                extra={"bytes": dead_b},
            ))

    # R106 — declared kernel hot path lowered on the stock-XLA fallback
    wanted = contract.get("kernel_hot_path") or ()
    if facts is not None and wanted:
        lowered = {t for t, _k, _p in getattr(facts, "kernel_sites", ())}
        for kname in wanted:
            if not _kernel_expected_active(kname):
                continue
            targets = _kernel_targets(kname)
            if targets and not any(
                any(t in lt for lt in lowered) for t in targets
            ):
                out.append(_prog_finding(
                    "R106", name, f"fallback:{kname}",
                    f"program '{name}' declares the '{kname}' Pallas kernel "
                    "hot path and the backend supports it, but the lowered "
                    "jaxpr contains no matching kernel call — the hot path "
                    "silently regressed to the stock-XLA fallback "
                    "(set RL_TPU_NO_KERNELS to opt out deliberately)",
                    extra={"kernel": kname, "targets": list(targets)},
                ))
    return out


def _kernel_expected_active(kname: str) -> bool:
    """Lazy registry query (keeps :mod:`rl_tpu.analysis` import-light);
    an unimportable registry means no expectation, hence no finding."""
    try:
        from ..kernels.registry import expected_active

        return bool(expected_active(kname))
    except Exception:
        return False


def _kernel_targets(kname: str) -> tuple:
    try:
        from ..kernels.registry import kernel_targets

        return tuple(kernel_targets(kname))
    except Exception:
        return ()
