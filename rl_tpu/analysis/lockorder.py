"""R005: static lock-order analysis.

Builds the package-wide lock-acquisition graph and fails on cycles.

Lock identity is the *creation site class/module attribute*
(``Supervisor._lock``, ``MetricsRegistry._lock``) — every instance of a
class shares one node, which is exactly the granularity a lock-order
discipline is stated at ("never take the registry lock while holding a
scheme lock"). Edges come from two sources:

- **lexical nesting**: a ``with self._b:`` inside a ``with self._a:``
  block adds a→b;
- **one-hop-closed calls**: a call inside a ``with a:`` block to a
  function whose *transitive* lock summary contains b adds a→b (the
  summary is a fixpoint over the resolved call graph, so chains through
  helpers are caught).

Self-edges are only reported for *lexically* nested acquisitions of a
non-reentrant ``threading.Lock`` (same attribute under itself is a
guaranteed deadlock); call-derived self-edges are ignored because two
*instances* of the same class may legitimately nest (e.g. a fleet
iterating its members).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import FunctionInfo, ModuleIndex, PackageIndex, canon
from .findings import Finding

__all__ = ["run_lockorder", "collect_locks"]

_LOCK_CTORS = {"threading.Lock": "Lock", "threading.RLock": "RLock"}


@dataclass
class LockDef:
    lock_id: str       # "Class.attr" or "module.NAME"
    kind: str          # "Lock" | "RLock"
    file: str
    line: int


@dataclass
class _Graph:
    edges: dict = field(default_factory=dict)  # a -> {b: (file, line, snippet)}

    def add(self, a: str, b: str, site: tuple) -> None:
        self.edges.setdefault(a, {}).setdefault(b, site)


def collect_locks(modules: list[ModuleIndex]) -> dict[str, LockDef]:
    """All threading.Lock/RLock creation sites, keyed by lock id."""
    locks: dict[str, LockDef] = {}

    def ctor_kind(value: ast.AST, m: ModuleIndex) -> str | None:
        if isinstance(value, ast.Call):
            return _LOCK_CTORS.get(canon(value.func, m.aliases))
        return None

    for m in modules:
        # class attributes + self.<attr> = Lock() inside methods
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ClassDef):
                cls = node.name
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    kind = ctor_kind(sub.value, m)
                    if kind is None:
                        continue
                    for t in sub.targets:
                        attr = None
                        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            attr = t.attr
                        elif isinstance(t, ast.Name):
                            attr = t.id
                        if attr is not None:
                            lid = f"{cls}.{attr}"
                            locks.setdefault(
                                lid, LockDef(lid, kind, m.path, sub.lineno)
                            )
        # module-level locks
        for node in m.tree.body:
            if isinstance(node, ast.Assign):
                kind = ctor_kind(node.value, m)
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lid = f"{m.modname}.{t.id}"
                        locks.setdefault(lid, LockDef(lid, kind, m.path, node.lineno))
    return locks


class _LockPass:
    def __init__(self, index: PackageIndex):
        self.index = index
        self.locks = collect_locks(index.modules)
        # attr name -> lock ids defining it (for cross-class binding)
        self.by_attr: dict[str, list[str]] = {}
        for lid in self.locks:
            attr = lid.rsplit(".", 1)[-1]
            self.by_attr.setdefault(attr, []).append(lid)
        self.direct: dict[str, set] = {}       # fn qualname -> lock ids acquired directly
        self.summary: dict[str, set] = {}      # transitive (fixpoint)
        self.graph = _Graph()
        self.sites: dict[str, tuple] = {}      # lock id -> example acquisition site

    # -- binding ---------------------------------------------------------------

    def bind(self, expr: ast.AST, m: ModuleIndex, fn: FunctionInfo) -> str | None:
        """Lock id for an acquisition expression (with-context or
        .acquire() receiver)."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" and fn.class_name:
                lid = f"{fn.class_name}.{attr}"
                if lid in self.locks:
                    return lid
            name = canon(expr, m.aliases)
            if name is not None:
                # Class.ATTR or module.NAME reference
                tail2 = ".".join(name.split(".")[-2:])
                if tail2 in self.locks:
                    return tail2
                if name in self.locks:
                    return name
            cands = self.by_attr.get(attr, [])
            if len(cands) == 1:
                return cands[0]
        elif isinstance(expr, ast.Name):
            cands = self.by_attr.get(expr.id, [])
            if len(cands) == 1:
                return cands[0]
        return None

    # -- per-function direct info ---------------------------------------------

    def _acquisitions(self, m: ModuleIndex, fn: FunctionInfo):
        """Yield (lock_id, with_node | call_node, kind) for every acquisition
        in fn: kind 'with' (scoped) or 'acquire' (unscoped)."""
        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        lid = self.bind(item.context_expr, m, fn)
                        if lid is not None:
                            yield lid, child, "with"
                elif isinstance(child, ast.Call) and isinstance(child.func, ast.Attribute) \
                        and child.func.attr == "acquire":
                    lid = self.bind(child.func.value, m, fn)
                    if lid is not None:
                        yield lid, child, "acquire"
                yield from walk(child)
        yield from walk(fn.node)

    def compute_direct(self) -> None:
        for m in self.index.modules:
            for fn in m.functions.values():
                acq = set()
                for lid, node, _kind in self._acquisitions(m, fn):
                    acq.add(lid)
                    self.sites.setdefault(lid, (m.path, node.lineno, m.snippet(node)))
                if acq:
                    self.direct[fn.qualname] = acq

    def compute_summaries(self) -> None:
        self.summary = {q: set(v) for q, v in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for q, fn in self.index.functions.items():
                cur = self.summary.get(q, set())
                new = set(cur)
                for callee in fn.calls:
                    new |= self.summary.get(callee, set())
                if new != cur:
                    self.summary[q] = new
                    changed = True

    # -- edges -----------------------------------------------------------------

    def compute_edges(self) -> list[Finding]:
        lexical_self: list[Finding] = []
        for m in self.index.modules:
            for fn in m.functions.values():
                self._edges_in(m, fn, fn.node, held=[], out=lexical_self)
        return lexical_self

    def _edges_in(self, m: ModuleIndex, fn: FunctionInfo, node: ast.AST,
                  held: list[str], out: list[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in child.items:
                    lid = self.bind(item.context_expr, m, fn)
                    if lid is None:
                        continue
                    site = (m.path, child.lineno, m.snippet(child))
                    for h in held:
                        if h == lid:
                            if self.locks[lid].kind == "Lock":
                                out.append(Finding(
                                    rule="R005", file=m.path, line=child.lineno,
                                    qualname=fn.display, snippet=m.snippet(child),
                                    message=(
                                        f"non-reentrant lock {lid} re-acquired while "
                                        "held (self-deadlock)"
                                    ),
                                ))
                        else:
                            self.graph.add(h, lid, site)
                    acquired.append(lid)
                self._edges_in(m, fn, child, held + acquired, out)
                continue
            if held and isinstance(child, ast.Call):
                callee = self.index.resolve_call(m, fn, child.func)
                if callee is not None:
                    for lid in self.summary.get(callee, ()):  # transitive
                        for h in held:
                            if h != lid:
                                self.graph.add(
                                    h, lid,
                                    (m.path, child.lineno, m.snippet(child)),
                                )
            self._edges_in(m, fn, child, held, out)

    # -- cycles ----------------------------------------------------------------

    def find_cycles(self) -> list[list[str]]:
        """Elementary cycles via DFS over SCCs (graph is tiny)."""
        edges = {a: set(bs) for a, bs in self.graph.edges.items()}
        cycles: list[list[str]] = []
        seen_keys: set = set()

        def dfs(start, node, path, visited):
            for nxt in sorted(edges.get(node, ())):
                if nxt == start and len(path) > 0:
                    cyc = path + [start]
                    lo = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                    norm = tuple(cyc[lo:-1] + cyc[:lo])
                    if norm not in seen_keys:
                        seen_keys.add(norm)
                        cycles.append(list(norm) + [norm[0]])
                elif nxt not in visited and nxt > start:
                    dfs(start, nxt, path + [nxt], visited | {nxt})

        for a in sorted(edges):
            dfs(a, a, [a], {a})
        return cycles


def run_lockorder(index: PackageIndex) -> list[Finding]:
    p = _LockPass(index)
    p.compute_direct()
    p.compute_summaries()
    findings = p.compute_edges()       # lexical self-deadlocks
    for cyc in p.find_cycles():
        pairs = list(zip(cyc, cyc[1:]))
        sites = [p.graph.edges[a][b] for a, b in pairs]
        where = "; ".join(f"{a}->{b} at {s[0]}:{s[1]}" for (a, b), s in zip(pairs, sites))
        first = sites[0]
        findings.append(Finding(
            rule="R005", file=first[0], line=first[1],
            qualname="lock-order", snippet=" -> ".join(cyc),
            message=f"lock-order cycle: {where}",
        ))
    return findings


def lock_edges(index: PackageIndex) -> dict:
    """Debug/introspection: the full lock graph ({a: {b: site}})."""
    p = _LockPass(index)
    p.compute_direct()
    p.compute_summaries()
    p.compute_edges()
    return p.graph.edges
