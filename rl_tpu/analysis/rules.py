"""rlint rules R001–R004 (R005 lives in lockorder.py).

R001 host-sync-in-hot-path — ``.item()``, ``float()/int()/bool()`` on a
    non-literal, ``np.asarray``/``np.array``, ``jax.device_get``,
    ``.block_until_ready()`` inside any function reachable from a hot
    root (jit/lax body or ``@hot_path`` host loop). Each of these forces
    the host to wait on the device (or copies device→host), which stalls
    the dispatch pipeline — the exact regression PR 1 and PR 4 each
    removed by hand.

R002 donation-after-use — an argument passed through a
    ``donate_argnums``/``donate_argnames`` dispatch is dead: XLA may
    reuse its buffer for the outputs. Referencing it afterwards in the
    same scope (or re-passing it on the next loop iteration without
    rebinding) reads freed memory — PR 5 fixed a real heap corruption
    from exactly this.

R003 PRNG key reuse — the same key consumed by two randomness calls
    (or split twice) without an intervening rebind silently correlates
    samples.

R004 recompile hazards — tracer-dependent Python branches inside traced
    roots (``if`` on a non-static parameter retraces or crashes), and
    ``jax.jit`` calls constructed inside a loop (a fresh jit wrapper per
    iteration defeats the compile cache).

R006 registry bypass — a literal ``jax.jit``/``jax.pjit`` (call or
    decorator) inside ``rl_tpu/models/`` or ``rl_tpu/trainers/``. Hot
    programs in those packages are expected to go through
    :class:`rl_tpu.compile.ProgramRegistry`: a raw jit wrapper is
    invisible to ``aot_warmup()``, the persistent executable store, and
    the per-program compile metrics, so it silently re-pays the
    cold-start tax this subsystem exists to kill. Intentional raw sites
    (docstring examples, cold-path eval helpers) live in the baseline
    with a reason.

R007 cross-thread shared-state hazard — a ``self.X`` field rebound inside
    a function reachable from a ``Supervisor.spawn``/``threading.Thread``
    target and read from a method running on other threads, with neither
    side inside a ``with <lock>`` (lock identity reuses the R005
    lock-site index). GIL-atomic flag reads that are *intentionally*
    lock-free live in the baseline with a reason.
"""

from __future__ import annotations

import ast

from .core import FunctionInfo, ModuleIndex, PackageIndex, canon, _target_names
from .findings import Finding

__all__ = ["run_rules"]

_HOST_SYNC_CASTS = {"float", "int", "bool"}
_HOST_SYNC_CALLS = {
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.device_get": "jax.device_get",
}
_RANDOM_SAFE = {
    "PRNGKey", "key", "key_data", "wrap_key_data", "fold_in", "clone",
    "key_impl", "default_prng_impl",
}
_JIT_NAMES = {"jax.jit", "jax.pjit"}


def _iter_functions(m: ModuleIndex):
    return m.functions.values()


def _body_nodes(fn: FunctionInfo):
    """Walk a function body without descending into nested defs/lambdas
    (those are separate FunctionInfos / out of scope)."""
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


# -- R001 ---------------------------------------------------------------------

def _r001(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    out: list[Finding] = []
    for fn in _iter_functions(m):
        if not index.is_hot(fn.qualname):
            continue
        why = index.hot_from.get(fn.qualname, "hot")
        for node in _body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            label = None
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    label = ".item()"
                elif node.func.attr == "block_until_ready":
                    label = ".block_until_ready()"
            name = canon(node.func, m.aliases)
            if label is None and name in _HOST_SYNC_CASTS:
                if node.args and not isinstance(node.args[0], ast.Constant):
                    label = f"{name}()"
            if label is None and name in _HOST_SYNC_CALLS:
                label = _HOST_SYNC_CALLS[name]
            if label is not None:
                out.append(Finding(
                    rule="R001", file=m.path, line=node.lineno,
                    qualname=fn.display, snippet=m.snippet(node),
                    message=f"host sync {label} in hot path ({why})",
                ))
    return out


# -- R002 ---------------------------------------------------------------------

def _donated_positions(call: ast.Call) -> tuple[tuple, tuple] | None:
    """(argnums, argnames) literally present in a jit call's donate kwargs;
    None when the call donates nothing."""
    nums: list[int] = []
    names: list[str] = []
    seen = False
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            seen = True
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.append(n.value)
        elif kw.arg == "donate_argnames":
            seen = True
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.append(n.value)
    if not seen or not (nums or names):
        return None
    return tuple(sorted(set(nums))), tuple(names)


def _collect_donating_callables(m: ModuleIndex) -> dict[str, tuple[tuple, tuple]]:
    """Map trackable callee names ('f', 'self._update') to donated
    (argnums, argnames). Module-local: assignments of jit(...) results and
    @partial(jax.jit, donate_*) decorators."""
    donors: dict[str, tuple] = {}
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = canon(node.value.func, m.aliases)
            if name in _JIT_NAMES:
                pos = _donated_positions(node.value)
                if pos is not None:
                    for t in node.targets:
                        for tn in _target_names(t):
                            donors[tn] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    cname = canon(dec.func, m.aliases)
                    is_jit = cname in _JIT_NAMES
                    is_partial_jit = (
                        cname in {"functools.partial", "partial"}
                        and dec.args
                        and canon(dec.args[0], m.aliases) in _JIT_NAMES
                    )
                    if is_jit or is_partial_jit:
                        pos = _donated_positions(dec)
                        if pos is not None:
                            donors[node.name] = pos
                            donors[f"self.{node.name}"] = pos
    return donors


def _expr_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _callee_key(node: ast.Call) -> str | None:
    return _expr_name(node.func)


def _assign_lines(fn: FunctionInfo, name: str) -> list[int]:
    lines = []
    for node in _body_nodes(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For, ast.AsyncFor)):
            targets = [node.target]
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            targets = [node.optional_vars]
        for t in targets:
            if name in _target_names(t):
                lines.append(t.lineno)
    return lines


def _loads_after(fn: FunctionInfo, name: str, after_line: int) -> list[ast.AST]:
    out = []
    for node in _body_nodes(fn):
        if isinstance(node, (ast.Name, ast.Attribute)) and _expr_name(node) == name:
            if isinstance(getattr(node, "ctx", None), ast.Load) and node.lineno > after_line:
                out.append(node)
    return out


def _enclosing_loops(fn: FunctionInfo, line: int) -> list[ast.AST]:
    loops = []
    for node in ast.walk(fn.node):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            if node.lineno <= line <= (node.end_lineno or node.lineno):
                loops.append(node)
    return loops


def _r002(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    donors = _collect_donating_callables(m)
    if not donors:
        return []
    out: list[Finding] = []
    for fn in _iter_functions(m):
        for node in _body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            key = _callee_key(node)
            if key is None or key not in donors:
                continue
            nums, names = donors[key]
            donated_args: list[tuple[str, ast.AST]] = []
            for p in nums:
                if p < len(node.args):
                    nm = _expr_name(node.args[p])
                    if nm is not None:
                        donated_args.append((nm, node.args[p]))
            for kw in node.keywords:
                if kw.arg in names:
                    nm = _expr_name(kw.value)
                    if nm is not None:
                        donated_args.append((nm, kw.value))
            call_end = node.end_lineno or node.lineno
            for nm, _arg in donated_args:
                assigns = _assign_lines(fn, nm)
                # straight-line use after the donating call
                for use in _loads_after(fn, nm, call_end):
                    killed = any(node.lineno <= a <= use.lineno for a in assigns)
                    if not killed:
                        out.append(Finding(
                            rule="R002", file=m.path, line=use.lineno,
                            qualname=fn.display, snippet=m.snippet(use),
                            message=(
                                f"'{nm}' used after being donated to {key} "
                                f"(donate_argnums={nums or names}) at line {node.lineno}"
                            ),
                        ))
                        break  # one finding per (call, arg)
                else:
                    # loop-carried: donated every iteration, never rebound
                    for loop in _enclosing_loops(fn, node.lineno):
                        lo, hi = loop.lineno, loop.end_lineno or loop.lineno
                        if not any(lo <= a <= hi for a in assigns):
                            out.append(Finding(
                                rule="R002", file=m.path, line=node.lineno,
                                qualname=fn.display, snippet=m.snippet(node),
                                message=(
                                    f"'{nm}' donated to {key} inside a loop without "
                                    "rebinding — second iteration passes a freed buffer"
                                ),
                            ))
                            break
    return out


# -- R003 ---------------------------------------------------------------------

def _terminates(stmts: list) -> bool:
    """True when a statement list cannot fall through to the next one."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _KeyFlow:
    """Sequential consumed-key tracking over one function body."""

    def __init__(self, m: ModuleIndex, fn: FunctionInfo):
        self.m = m
        self.fn = fn
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        self._block(self.fn.node.body, {})
        return self.findings

    # consumed: name -> (line, callname)
    def _block(self, stmts, consumed: dict) -> dict:
        for st in stmts:
            consumed = self._stmt(st, consumed)
        return consumed

    def _stmt(self, st, consumed: dict) -> dict:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return consumed
        if isinstance(st, ast.If):
            self._expr(st.test, consumed)
            a = self._block(st.body, dict(consumed))
            b = self._block(st.orelse, dict(consumed))
            # a branch that cannot fall through (return/raise/...) does not
            # contribute its consumed-set to the merge — `if p: return rand(k)`
            # leaves k fresh on the fall-through path
            if _terminates(st.body):
                a = dict(consumed)
            if st.orelse and _terminates(st.orelse):
                b = dict(consumed)
            return {**a, **b}
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._expr(st.iter, consumed)
                pre = dict(consumed)
                for nm in _target_names(st.target):
                    pre.pop(nm, None)
            else:
                self._expr(st.test, consumed)
                pre = dict(consumed)
            body_out = self._block(st.body, dict(pre))
            self._check_loop_carry(st, pre, body_out)
            merged = {**consumed, **body_out}
            return self._block(st.orelse, merged)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, consumed)
            return self._block(st.body, consumed)
        if isinstance(st, ast.Try):
            out = self._block(st.body, consumed)
            for h in st.handlers:
                out = {**out, **self._block(h.body, dict(consumed))}
            out = self._block(st.orelse, out)
            return self._block(st.finalbody, out)
        # plain statement: evaluate value first, then apply target kills
        targets: list[ast.AST] = []
        if isinstance(st, ast.Assign):
            self._expr(st.value, consumed)
            targets = st.targets
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            if getattr(st, "value", None) is not None:
                self._expr(st.value, consumed)
            targets = [st.target]
        else:
            for node in ast.iter_child_nodes(st):
                self._expr(node, consumed)
        for t in targets:
            for nm in _target_names(t):
                consumed.pop(nm, None)
        return consumed

    def _check_loop_carry(self, loop, pre: dict, body_out: dict) -> None:
        assigned: set = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    assigned.update(_target_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For, ast.AsyncFor)):
                assigned.update(_target_names(node.target))
        for nm, (line, callname) in body_out.items():
            if nm not in pre and nm not in assigned:
                self.findings.append(Finding(
                    rule="R003", file=self.m.path, line=line,
                    qualname=self.fn.display,
                    snippet=self.m.lines[line - 1].strip() if line <= len(self.m.lines) else "",
                    message=(
                        f"PRNG key '{nm}' consumed by {callname} every loop "
                        "iteration without an intervening split/rebind"
                    ),
                ))

    def _expr(self, node, consumed: dict) -> None:
        if node is None or isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        for call in [n for n in ast.walk(node)
                     if isinstance(n, ast.Call)
                     and not isinstance(n.func, ast.Lambda)]:
            name = canon(call.func, self.m.aliases)
            if not name or not name.startswith("jax.random."):
                continue
            leaf = name.rsplit(".", 1)[-1]
            if leaf in _RANDOM_SAFE:
                continue
            keyarg = call.args[0] if call.args else None
            if keyarg is None:
                for kw in call.keywords:
                    if kw.arg == "key":
                        keyarg = kw.value
            nm = _expr_name(keyarg) if keyarg is not None else None
            if nm is None:
                continue
            if nm in consumed:
                line0, prev = consumed[nm]
                self.findings.append(Finding(
                    rule="R003", file=self.m.path, line=call.lineno,
                    qualname=self.fn.display, snippet=self.m.snippet(call),
                    message=(
                        f"PRNG key '{nm}' reused by jax.random.{leaf} "
                        f"(already consumed by {prev} at line {line0})"
                    ),
                ))
            else:
                consumed[nm] = (call.lineno, f"jax.random.{leaf}")


def _r003(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    out: list[Finding] = []
    for fn in _iter_functions(m):
        out.extend(_KeyFlow(m, fn).run())
    return out


# -- R004 ---------------------------------------------------------------------

class _DynamicTestVisitor(ast.NodeVisitor):
    """Collect Names in a branch test that read a traced parameter's
    *value* (as opposed to static metadata like .shape/.dtype or
    identity tests like ``x is None``)."""

    _STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

    def __init__(self, params: set):
        self.params = params
        self.hits: list[ast.Name] = []

    def visit_Attribute(self, node):
        if node.attr in self._STATIC_ATTRS:
            return  # x.shape et al. are static under trace
        self.generic_visit(node)

    def visit_Compare(self, node):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return  # `x is None` is a static structure test
        self.generic_visit(node)

    def visit_Call(self, node):
        name = node.func.id if isinstance(node.func, ast.Name) else None
        if name in {"isinstance", "len", "hasattr", "getattr", "callable"}:
            return
        self.generic_visit(node)

    def visit_Name(self, node):
        if node.id in self.params:
            self.hits.append(node)


def _r004(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    out: list[Finding] = []
    for fn in _iter_functions(m):
        info = index.functions.get(fn.qualname)
        # tracer-dependent Python branches: only in traced roots
        if info is not None and info.is_traced_root:
            dyn = set(info.params) - info.static_params - {"self", "cls"}
            for node in _body_nodes(fn):
                if isinstance(node, (ast.If, ast.While)):
                    v = _DynamicTestVisitor(dyn)
                    v.visit(node.test)
                    if v.hits:
                        names = sorted({h.id for h in v.hits})
                        out.append(Finding(
                            rule="R004", file=m.path, line=node.lineno,
                            qualname=fn.display, snippet=m.snippet(node),
                            message=(
                                f"Python branch on traced argument(s) {names} in "
                                f"{info.hot_detail or 'jit'} body — retraces per value "
                                "or raises ConcretizationTypeError"
                            ),
                        ))
        # jit constructed inside a loop: anywhere
        seen_calls: set = set()
        for node in _body_nodes(fn):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call) and id(sub) not in seen_calls
                            and canon(sub.func, m.aliases) in _JIT_NAMES):
                        seen_calls.add(id(sub))
                        out.append(Finding(
                            rule="R004", file=m.path, line=sub.lineno,
                            qualname=fn.display, snippet=m.snippet(sub),
                            message=(
                                "jax.jit constructed inside a loop — a fresh wrapper "
                                "per iteration defeats the trace cache"
                            ),
                        ))
    return out


# -- R006 ---------------------------------------------------------------------

# the packages whose hot programs must dispatch through the ProgramRegistry
# (rl_tpu/compile/); matched against the module's repo-relative path
_R006_SCOPE = ("rl_tpu/models/", "rl_tpu/trainers/")


def _r006(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    if not any(seg in m.path for seg in _R006_SCOPE):
        return []
    out: list[Finding] = []
    seen: set = set()

    def add(node, display: str, label: str) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        out.append(Finding(
            rule="R006", file=m.path, line=node.lineno,
            qualname=display, snippet=m.snippet(node),
            message=(
                f"{label} bypasses the ProgramRegistry — the executable is "
                "invisible to aot_warmup(), the persistent store, and the "
                "compile metrics; register it via "
                "rl_tpu.compile.get_program_registry().register(...)"
            ),
        ))

    for fn in _iter_functions(m):
        for dec in fn.node.decorator_list:
            if isinstance(dec, ast.Call):
                cname = canon(dec.func, m.aliases)
                if cname in _JIT_NAMES:
                    add(dec, fn.display, f"@{cname}(...) decorator")
                elif (cname in {"functools.partial", "partial"} and dec.args
                        and canon(dec.args[0], m.aliases) in _JIT_NAMES):
                    add(dec, fn.display, "@partial(jax.jit, ...) decorator")
            else:
                cname = canon(dec, m.aliases)
                if cname in _JIT_NAMES:
                    add(dec, fn.display, f"@{cname} decorator")
        for node in _body_nodes(fn):
            if (isinstance(node, ast.Call)
                    and canon(node.func, m.aliases) in _JIT_NAMES):
                add(node, fn.display, canon(node.func, m.aliases))
    # module/class-level sites outside any function body
    for node in ast.walk(m.tree):
        if (isinstance(node, ast.Call) and id(node) not in seen
                and canon(node.func, m.aliases) in _JIT_NAMES):
            add(node, "<module>", canon(node.func, m.aliases))
    return out


# -- R007 ---------------------------------------------------------------------

# attrs holding these are synchronization/thread-safe objects, not shared
# mutable state — touching them unlocked is the point of having them
_THREAD_SAFE_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "threading.local", "queue.Queue", "queue.SimpleQueue",
    "queue.LifoQueue", "queue.PriorityQueue", "collections.deque",
}


def _r007_state(index: PackageIndex):
    """Package-level worker-thread reachability, computed once per index:
    every function passed as a ``threading.Thread(target=...)`` /
    ``Supervisor.spawn(name, run)`` target, closed over resolved calls."""
    cached = getattr(index, "_r007_state", None)
    if cached is not None:
        return cached
    from .lockorder import _LockPass

    lp = _LockPass(index)
    roots: dict[str, str] = {}  # fn qualname -> spawn-site description
    for m in index.modules:
        for fn in _iter_functions(m):
            for node in _body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                target = how = None
                cname = canon(node.func, m.aliases)
                if cname is not None and (
                    cname == "threading.Thread" or cname.endswith(".Thread")
                ):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target, how = kw.value, "Thread target"
                elif isinstance(node.func, ast.Attribute) and node.func.attr == "spawn":
                    if len(node.args) >= 2:
                        target, how = node.args[1], "spawn target"
                    else:
                        for kw in node.keywords:
                            if kw.arg in ("run", "target"):
                                target, how = kw.value, "spawn target"
                if target is None:
                    continue
                ref = index.resolve_func_ref(m, fn, target)
                if ref is not None:
                    roots.setdefault(ref, f"{how} at {m.path}:{node.lineno}")
    thread_side = dict(roots)
    frontier = list(roots)
    while frontier:
        q = frontier.pop()
        info = index.functions.get(q)
        if info is None:
            continue
        for callee in info.calls:
            if callee not in thread_side:
                thread_side[callee] = thread_side[q]
                frontier.append(callee)
    state = (lp, thread_side)
    index._r007_state = state
    return state


def _self_accesses(lp, m: ModuleIndex, fn: FunctionInfo):
    """Yield (attr, node, kind, locked) for every ``self.X`` access in fn.
    ``locked`` is True when the access sits inside a ``with`` whose
    context binds to a known lock (the R005 lock-site index)."""
    def walk(node, locked):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            child_locked = locked
            if isinstance(child, (ast.With, ast.AsyncWith)):
                if any(lp.bind(i.context_expr, m, fn) for i in child.items):
                    child_locked = True
            if (isinstance(child, ast.Attribute)
                    and isinstance(child.value, ast.Name)
                    and child.value.id == "self"):
                kind = "write" if isinstance(child.ctx, (ast.Store, ast.Del)) else "read"
                yield child.attr, child, kind, locked
            yield from walk(child, child_locked)
    yield from walk(fn.node, False)


def _r007_safe_attrs(lp, m: ModuleIndex, cls: str) -> set:
    """Attrs of ``cls`` that are locks or thread-safe containers."""
    safe = {lid.rsplit(".", 1)[-1] for lid in lp.locks if lid.startswith(f"{cls}.")}
    for node in ast.walk(m.tree):
        if not (isinstance(node, ast.ClassDef) and node.name == cls):
            continue
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call)):
                continue
            if canon(sub.value.func, m.aliases) in _THREAD_SAFE_CTORS:
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        safe.add(t.attr)
    return safe


def _r007(index: PackageIndex, m: ModuleIndex) -> list[Finding]:
    lp, thread_side = _r007_state(index)
    if not thread_side:
        return []
    out: list[Finding] = []
    for cls in m.methods:
        safe = None  # computed lazily, only for classes with thread-side writes
        writes: dict[str, tuple] = {}   # attr -> (fn, node) unlocked thread-side write
        reads: dict[str, list] = {}     # attr -> [(fn, node)] unlocked foreign reads
        for fn in _iter_functions(m):
            if fn.class_name != cls:
                continue
            on_thread = fn.qualname in thread_side
            if not on_thread and fn.node.name == "__init__":
                continue  # runs before the thread exists
            for attr, node, kind, locked in _self_accesses(lp, m, fn):
                if locked:
                    continue
                if on_thread and kind == "write":
                    if safe is None:
                        safe = _r007_safe_attrs(lp, m, cls)
                    if attr not in safe:
                        writes.setdefault(attr, (fn, node))
                elif not on_thread and kind == "read":
                    reads.setdefault(attr, []).append((fn, node))
        for attr in sorted(set(writes) & set(reads)):
            wfn, wnode = writes[attr]
            rfn, rnode = min(reads[attr], key=lambda t: t[1].lineno)
            out.append(Finding(
                rule="R007", file=m.path, line=rnode.lineno,
                qualname=rfn.display, snippet=m.snippet(rnode),
                message=(
                    f"'{cls}.{attr}' is written by worker thread "
                    f"{wfn.display} (line {wnode.lineno}, "
                    f"{thread_side[wfn.qualname]}) and read here with no "
                    "lock held on either side — torn/stale reads under churn"
                ),
            ))
    return out


_RULES = {"R001": _r001, "R002": _r002, "R003": _r003, "R004": _r004,
          "R006": _r006, "R007": _r007}


def run_rules(index: PackageIndex, rules: set | None = None) -> list[Finding]:
    out: list[Finding] = []
    for m in index.modules:
        for rid, impl in _RULES.items():
            if rules is None or rid in rules:
                out.extend(impl(index, m))
    return out
