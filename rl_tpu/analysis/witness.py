"""LockWitness: runtime lock-order sanitizer.

The static pass (R005) sees what the AST can prove; the witness sees
what the program actually does. While armed, ``threading.Lock`` /
``threading.RLock`` construction returns witnessed wrappers that record,
per creation site, the observed held-while-acquiring graph across every
thread. Acquiring B while holding A adds the edge A→B; the moment both
A→B and B→A have been observed (by any two threads), the pair is flagged
as an *inversion* — a latent deadlock, even if this run got lucky with
the interleaving. This is the ThreadSanitizer lock-order idea scoped to
CPython's threading module.

Identity is the creation *site* (``file:line``), matching the static
pass's class-attribute granularity: every ``self._lock =
threading.Lock()`` in a class maps to one node no matter how many
instances exist. Reentrant re-acquisition of the same site is ignored,
as are sibling *instances* from one site acquired together (fleet
iterating members) — only cross-site order flips are inversions.

Wrappers keep ``threading.Condition`` (and thus ``queue.Queue``)
working: ``_release_save``/``_acquire_restore``/``_is_owned`` are
implemented so a condition wait keeps the per-thread held-stack in sync
with the real lock state.
"""

from __future__ import annotations

import threading
import traceback

__all__ = ["LockWitness", "WitnessedLock"]


class WitnessedLock:
    """Wrapper around a real Lock/RLock recording acquisition order."""

    def __init__(self, witness: "LockWitness", inner, site: str):
        self._witness = witness
        self._inner = inner
        self._site = site

    # -- core protocol ---------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness._note_acquire(self._site)
        return ok

    def release(self):
        self._inner.release()
        self._witness._note_release(self._site)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessedLock {self._site} wrapping {self._inner!r}>"

    # -- threading.Condition integration --------------------------------------
    # Condition lifts these from its lock when present; implementing them
    # keeps the witness's held-stack consistent across cond.wait().

    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        self._witness._note_release(self._site)
        return state

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._witness._note_acquire(self._site)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class LockWitness:
    """Observes lock-acquisition order process-wide while armed.

    >>> w = LockWitness()
    >>> w.arm()
    >>> try: ...        # run the threaded workload
    ... finally: w.disarm()
    >>> assert not w.inversions()
    """

    def __init__(self, capture_stacks: bool = False):
        self._meta = threading.Lock()    # guards graph/inversions (created pre-arm)
        self._tls = threading.local()
        self._graph: dict[str, dict[str, str]] = {}   # a -> {b: example}
        self._inversions: list[dict] = []
        self._inversion_keys: set = set()
        self._armed = False
        self._orig: tuple | None = None
        self._capture_stacks = capture_stacks
        self._n_locks = 0

    # -- arming ----------------------------------------------------------------

    def _creation_site(self) -> str:
        # nearest frame outside this module and the stdlib lock plumbing
        for fr in reversed(traceback.extract_stack()[:-2]):
            fn = fr.filename
            if fn != __file__ and not fn.endswith(("threading.py", "queue.py")):
                return f"{fn}:{fr.lineno}"
        return "unknown:0"

    def make_lock(self):
        self._n_locks += 1
        return WitnessedLock(self, self._orig_lock(), self._creation_site())

    def make_rlock(self):
        self._n_locks += 1
        return WitnessedLock(self, self._orig_rlock(), self._creation_site())

    def _orig_lock(self):
        return (self._orig[0] if self._orig else threading.Lock)()

    def _orig_rlock(self):
        return (self._orig[1] if self._orig else threading.RLock)()

    def arm(self) -> "LockWitness":
        if self._armed:
            return self
        self._orig = (threading.Lock, threading.RLock)
        threading.Lock = self.make_lock        # type: ignore[assignment]
        threading.RLock = self.make_rlock      # type: ignore[assignment]
        self._armed = True
        return self

    def disarm(self) -> None:
        if not self._armed:
            return
        threading.Lock, threading.RLock = self._orig  # type: ignore[assignment]
        self._orig = None
        self._armed = False

    def __enter__(self):
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()
        return False

    # -- recording -------------------------------------------------------------

    def _held(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, site: str) -> None:
        held = self._held()
        new_edges = [h for h in held if h != site]
        if new_edges:
            tname = threading.current_thread().name
            where = (
                "".join(traceback.format_stack(limit=8)[:-2])
                if self._capture_stacks else tname
            )
            with self._meta:
                for h in new_edges:
                    self._graph.setdefault(h, {}).setdefault(site, where)
                    back = self._graph.get(site, {})
                    if h in back:
                        key = frozenset((h, site))
                        if key not in self._inversion_keys:
                            self._inversion_keys.add(key)
                            self._inversions.append({
                                "locks": tuple(sorted((h, site))),
                                "a_then_b": back[h],
                                "b_then_a": where,
                            })
        held.append(site)

    def _note_release(self, site: str) -> None:
        held = self._held()
        # out-of-order release: drop the most recent matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # -- reporting -------------------------------------------------------------

    def inversions(self) -> list[dict]:
        with self._meta:
            return list(self._inversions)

    def edges(self) -> dict:
        with self._meta:
            return {a: dict(bs) for a, bs in self._graph.items()}

    def stats(self) -> dict:
        with self._meta:
            return {
                "locks_witnessed": self._n_locks,
                "edges": sum(len(b) for b in self._graph.values()),
                "inversions": len(self._inversions),
            }
