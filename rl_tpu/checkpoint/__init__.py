from .checkpoint import (
    ArrayTreeAdapter,
    Checkpoint,
    GlobalRNGState,
    JSONAdapter,
    PickleAdapter,
)

__all__ = [
    "Checkpoint",
    "ArrayTreeAdapter",
    "JSONAdapter",
    "PickleAdapter",
    "GlobalRNGState",
]
