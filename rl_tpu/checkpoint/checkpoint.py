"""Checkpoint registry with adapters and schema migrations.

Redesign of the reference checkpoint subsystem (reference:
torchrl/checkpoint/_checkpoint.py — ``Checkpoint``:692 registry with
``register``:760/``save``:800/``load``:895/``register_migration``:1007;
adapters ``StateDictCheckpointAdapter``:423, ``JSONCheckpointAdapter``:541;
``GlobalRNGState``:596), rebuilt on **orbax** for sharding-aware async array
checkpointing (the TPU story: a restore re-shards arrays onto whatever mesh
the restoring program uses).

Components register by name with an adapter:
- :class:`ArrayTreeAdapter` — pytrees of jax arrays (params, opt state,
  buffer states) via orbax; sharding-aware.
- :class:`JSONAdapter` — counters/config scalars.
- :class:`PickleAdapter` — host-side python state (last resort).

``GlobalRNGState`` captures numpy+python RNG (JAX keys are ordinary arrays —
they live inside the train state and need no special capture, unlike the
reference's torch/cuda RNG).
"""

from __future__ import annotations

import json
import os
import pickle
import random
from typing import Any, Callable

import jax
import numpy as np

__all__ = [
    "Checkpoint",
    "ArrayTreeAdapter",
    "JSONAdapter",
    "PickleAdapter",
    "GlobalRNGState",
]

SCHEMA_VERSION = 1


def _is_typed_key(x: Any) -> bool:
    import jax.numpy as jnp

    return isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jax.dtypes.prng_key)


def _unwrap_keys(tree: Any) -> Any:
    """Typed PRNG key leaves -> raw uint32 key data (orbax can't serialize
    the opaque key dtype)."""
    return jax.tree.map(lambda x: jax.random.key_data(x) if _is_typed_key(x) else x, tree)


class ArrayTreeAdapter:
    """Orbax-backed pytree-of-arrays adapter (sharding-aware restore)."""

    def save(self, path: str, obj: Any) -> None:
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), _unwrap_keys(obj), force=True)

    def load(self, path: str, template: Any | None = None) -> Any:
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            if template is not None:
                restored = ckptr.restore(os.path.abspath(path), item=_unwrap_keys(template))
                # rewrap leaves that were typed PRNG keys in the template
                return jax.tree.map(
                    lambda t, r: (
                        jax.random.wrap_key_data(r, impl=jax.random.key_impl(t))
                        if _is_typed_key(t)
                        else r
                    ),
                    template,
                    restored,
                )
            return ckptr.restore(os.path.abspath(path))


class JSONAdapter:
    def save(self, path: str, obj: Any) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "data.json"), "w") as f:
            json.dump(obj, f)

    def load(self, path: str, template: Any | None = None) -> Any:
        with open(os.path.join(path, "data.json")) as f:
            return json.load(f)


class PickleAdapter:
    def save(self, path: str, obj: Any) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "data.pkl"), "wb") as f:
            pickle.dump(obj, f)

    def load(self, path: str, template: Any | None = None) -> Any:
        with open(os.path.join(path, "data.pkl"), "rb") as f:
            return pickle.load(f)


class GlobalRNGState:
    """Host RNG capture (reference GlobalRNGState:596, minus torch/cuda)."""

    @staticmethod
    def get() -> dict:
        np_state = np.random.get_state()
        return {
            "python": list(random.getstate()[1]) + [random.getstate()[0], random.getstate()[2]],
            "numpy": [np_state[0], np_state[1].tolist(), *np_state[2:]],
        }

    @staticmethod
    def set(state: dict) -> None:
        py = state["python"]
        random.setstate((py[-2], tuple(py[:-2]), py[-1]))
        np_s = state["numpy"]
        np.random.set_state((np_s[0], np.asarray(np_s[1], dtype=np.uint32), *np_s[2:]))


class Checkpoint:
    """Named-component checkpoint registry (reference Checkpoint:692).

    >>> ckpt = Checkpoint("ckpts/run1")
    >>> ckpt.register("train_state", lambda: ts, lambda v: restore(v))
    >>> ckpt.save(step=1000)
    >>> ckpt.load(step=1000)
    """

    def __init__(self, root: str, capture_rng: bool = True):
        self.root = root
        self.capture_rng = capture_rng
        self._components: dict[str, tuple[Callable, Callable, Any]] = {}
        self._migrations: dict[int, Callable[[str], None]] = {}

    def register(
        self,
        name: str,
        getter: Callable[[], Any],
        setter: Callable[[Any], None],
        adapter: Any | None = None,
        template: Callable[[], Any] | None = None,
    ) -> None:
        """``getter`` supplies the object at save; ``setter`` receives the
        restored object at load. Adapter defaults to ArrayTreeAdapter."""
        self._components[name] = (getter, setter, adapter or ArrayTreeAdapter(), template)

    def register_migration(self, from_version: int, fn: Callable[[str], None]) -> None:
        """Migrate an on-disk checkpoint written at ``from_version`` forward
        one schema step (reference register_migration:1007)."""
        self._migrations[from_version] = fn

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{step}")

    def save(self, step: int) -> str:
        d = self._dir(step)
        os.makedirs(d, exist_ok=True)
        meta = {"schema_version": SCHEMA_VERSION, "step": step, "components": list(self._components)}
        if self.capture_rng:
            JSONAdapter().save(os.path.join(d, "_rng"), GlobalRNGState.get())
        for name, (getter, _, adapter, _t) in self._components.items():
            adapter.save(os.path.join(d, name), getter())
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f)
        return d

    def load(self, step: int) -> None:
        d = self._dir(step)
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        version = meta.get("schema_version", 0)
        migrated = False
        while version < SCHEMA_VERSION:
            if version not in self._migrations:
                raise RuntimeError(
                    f"checkpoint at schema v{version}, current v{SCHEMA_VERSION}, "
                    f"no migration registered for v{version}"
                )
            self._migrations[version](d)
            version += 1
            migrated = True
        if migrated:
            # persist the new schema version so non-idempotent migrations
            # never re-apply on a later load
            meta["schema_version"] = version
            with open(os.path.join(d, "meta.json"), "w") as f:
                json.dump(meta, f)
        if self.capture_rng and os.path.exists(os.path.join(d, "_rng")):
            GlobalRNGState.set(JSONAdapter().load(os.path.join(d, "_rng")))
        for name, (_, setter, adapter, template) in self._components.items():
            tmpl = template() if template is not None else None
            setter(adapter.load(os.path.join(d, name), tmpl))

    def latest_step(self) -> int | None:
        """Latest COMPLETE checkpoint (meta.json is written last by save(),
        so its presence marks completeness; foreign/partial dirs are skipped)."""
        if not os.path.isdir(self.root):
            return None
        steps = []
        for n in os.listdir(self.root):
            if not n.startswith("step_"):
                continue
            suffix = n.removeprefix("step_")
            if not suffix.isdigit():
                continue
            if os.path.exists(os.path.join(self.root, n, "meta.json")):
                steps.append(int(suffix))
        return max(steps) if steps else None
