from .host import HostCollector, ThreadedEnvPool
from .llm import LLMCollector
from .single import Collector, CollectorState

__all__ = [
    "Collector",
    "CollectorState",
    "HostCollector",
    "ThreadedEnvPool",
    "LLMCollector",
]
