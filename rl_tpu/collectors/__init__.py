"""Collectors: single-program (Anakin-style), host-env, and LLM.

``LLMCollector`` is imported lazily (PEP 562): it pulls in the transformer
model stack (``rl_tpu.models`` → ``rl_tpu.objectives`` → ``rl_tpu.modules``),
and eager chaining of those imports is what broke the round-1 bench when the
backend was unreachable — importing *anything* must not import *everything*.
"""

from .async_host import AsyncHostCollector
from .host import HostCollector, ProcessEnvPool, ThreadedEnvPool, compact_collected
from .distributed import MeshCollector
from .single import Collector, CollectorState

__all__ = [
    "MeshCollector",
    "Collector",
    "CollectorState",
    "AsyncHostCollector",
    "HostCollector",
    "compact_collected",
    "ProcessEnvPool",
    "ThreadedEnvPool",
    "LLMCollector",
]


def __getattr__(name):
    if name == "LLMCollector":
        from .llm import LLMCollector

        return LLMCollector
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
