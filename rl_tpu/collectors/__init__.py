from .single import Collector, CollectorState

__all__ = ["Collector", "CollectorState"]
