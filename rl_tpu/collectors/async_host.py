"""Sebulba-style decoupled host collection (arXiv:2104.06272).

:class:`HostCollector` serializes the pipeline: every env must finish its
step before the batched policy call, and the trainer cannot touch the
device while the host waits on the slowest simulator. This module splits
the two halves onto different threads — a background actor thread steps the
env pool and batches transitions, while the caller's thread keeps the
device busy with (donated, fused) gradient updates:

- **first-come batching**: envs are harvested as their steps complete
  (``pool.step_ready``), not in lockstep; a fast env can contribute many
  transitions to a batch while a slow one contributes none.
- **straggler cutoff**: a harvest fires once ``min_ready_fraction`` of
  in-flight envs are done, or after ``straggler_wait_s`` — slow workers
  keep cooking and join a later batch instead of stalling everyone
  (the Podracer/Sebulba actor-pool trick).
- **bounded write-queue**: completed batches are handed over through a
  ``queue.Queue(max_pending_batches)``; when the trainer falls behind, the
  actor thread blocks on ``put`` — backpressure, not unbounded memory.
- **per-item staleness stamps**: every transition records the
  ``policy_version`` it was acted with (plus env id and a global step
  counter) under ``("collector", ...)``; ``StalenessAwareSampler`` reads
  the stamp on write so replay can down-weight stale experience.

The reference analog is the prefetch thread inside torchrl's
``ReplayBuffer`` plus ``aSyncDataCollector`` (torchrl/collectors/
collectors.py:3013); here the split is at the env/device boundary instead,
because on TPU the expensive half is the XLA program, not the sampler.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import hot_path
from ..data import ArrayDict
from ..obs import get_registry, get_tracer
from ..obs.trace import carry_context
from ..utils.seeding import seed_generator

__all__ = ["AsyncHostCollector"]


class AsyncHostCollector:
    """Background-thread collector over a host env pool.

    ``policy``: ``(params, td, key) -> td`` over the batched observation
    ArrayDict, same contract as :class:`HostCollector`; ``None`` collects
    spec-uniform random actions. Batches are flat ``[frames_per_batch]``
    transition ArrayDicts in the standard ``{..., "next": ...}`` layout —
    ready for ``ReplayBuffer.extend`` without reshaping.

    Usage::

        collector = AsyncHostCollector(pool, policy, frames_per_batch=256)
        collector.start(params)
        for batch in collector.batches(total_frames=10_000):
            bstate = buffer.extend(bstate, batch, n=collector.frames_per_batch)
            ts = k_updates(ts)                      # device runs; envs step
            collector.update_params(ts["params"])   # bump policy_version
        collector.stop()
    """

    def __init__(
        self,
        pool: Any,
        policy: Callable | None = None,
        frames_per_batch: int = 256,
        seed: int = 0,
        max_pending_batches: int = 2,
        min_ready_fraction: float = 0.5,
        straggler_wait_s: float = 0.01,
        poll_interval_s: float = 2e-4,
        registry: Any = None,
        supervisor: Any = None,
    ):
        self.pool = pool
        self.policy = jax.jit(policy) if policy is not None else None
        self.frames_per_batch = frames_per_batch
        self.max_pending_batches = max_pending_batches
        self.min_ready_fraction = min_ready_fraction
        self.straggler_wait_s = straggler_wait_s
        self.poll_interval_s = poll_interval_s
        self._seed = seed
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending_batches)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        # optional rl_tpu.resilience.Supervisor: the actor loop becomes a
        # supervised child — crashes restart it (the loop re-resets the
        # pool) instead of silently landing in self._error
        self._supervisor = supervisor
        self._child: Any = None
        # params handoff: the trainer publishes (params, version) under a
        # lock; the actor thread snapshots the pair at each send phase so a
        # whole policy call uses one consistent version
        self._lock = threading.Lock()
        self._params: Any = None
        self._version = 0
        # stats (actor-thread written, reader tolerates slight races)
        self._env_steps = 0
        self._batches_emitted = 0
        self._harvests = 0
        self._straggler_cutoffs = 0
        # observability: registry series + trace events from the actor
        # thread (registry metric ops are thread-safe; the tracer keeps a
        # ring per thread, so the actor never contends with the trainer)
        self._tracer = get_tracer()
        self.registry = registry if registry is not None else get_registry()
        p = "rl_tpu_collector"
        reg = self.registry
        self._m_env_steps = reg.counter(f"{p}_env_steps_total", "env transitions harvested")
        self._m_batches = reg.counter(f"{p}_batches_total", "batches emitted to the trainer")
        self._m_harvests = reg.counter(f"{p}_harvests_total", "harvest sweeps")
        self._m_cutoffs = reg.counter(
            f"{p}_straggler_cutoffs_total",
            "harvests fired before every in-flight env finished",
        )
        self._m_queue = reg.gauge(f"{p}_queue_depth", "completed batches awaiting the trainer")
        self._m_version = reg.gauge(f"{p}_policy_version", "latest published policy version")
        self._m_staleness = reg.histogram(
            f"{p}_staleness",
            "policy-version lag of emitted transitions",
            buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        )
        self._m_harvest_s = reg.histogram(
            f"{p}_harvest_seconds",
            "time between consecutive harvest sweeps",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
        )

    # -- lifecycle ------------------------------------------------------------

    def start(self, params: Any = None, key: jax.Array | None = None) -> "AsyncHostCollector":
        if self._thread is not None or self._child is not None:
            raise RuntimeError("AsyncHostCollector already started")
        self._params = params
        self._key = key if key is not None else jax.random.PRNGKey(self._seed)
        self._stop.clear()
        if self._supervisor is not None:
            self._child = self._supervisor.spawn(
                "async-collector", self._collect_loop, on_giveup=self._on_giveup
            )
        else:
            # unsupervised path: carry the starter's TraceContext onto the
            # actor thread (the supervised path gets this from spawn())
            self._thread = threading.Thread(
                target=carry_context(self._run), name="rl-tpu-async-collector",
                daemon=True,
            )
            self._thread.start()
        return self

    def _on_giveup(self, exc: BaseException) -> None:
        self._error = exc

    def _alive(self) -> bool:
        if self._child is not None:
            return self._child.is_alive()
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._child is not None:
            self._child.stop(timeout=10)
            self._child = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # drain so a re-start doesn't see stale batches
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break

    def __enter__(self):
        if self._thread is None and self._child is None:
            self.start(self._params)
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- trainer-facing API ---------------------------------------------------

    def update_params(self, params: Any, version: int | None = None) -> None:
        """Publish fresh policy params; subsequent transitions are stamped
        with the bumped ``policy_version``."""
        with self._lock:
            self._params = params
            self._version = self._version + 1 if version is None else int(version)

    @property
    def policy_version(self) -> int:
        return self._version

    def get_batch(self, timeout: float | None = None) -> ArrayDict | None:
        """Pop the next completed batch (first-come order). Returns ``None``
        on timeout. Re-raises any actor-thread failure."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._error is not None:
                raise RuntimeError("AsyncHostCollector actor thread failed") from self._error
            try:
                return self._queue.get(timeout=0.05)
            except queue.Empty:
                if not self._alive():
                    if self._error is not None:
                        continue  # surface the error on the next spin
                    return None
                if deadline is not None and time.monotonic() >= deadline:
                    return None

    def batches(self, total_frames: int):
        """Yield batches until ``total_frames`` transitions were delivered."""
        delivered = 0
        while delivered < total_frames:
            b = self.get_batch()
            if b is None:
                return
            delivered += self.frames_per_batch
            yield b

    def stats(self) -> dict:
        return {
            "env_steps": self._env_steps,
            "batches_emitted": self._batches_emitted,
            "harvests": self._harvests,
            "straggler_cutoffs": self._straggler_cutoffs,
            "policy_version": self._version,
            "queue_depth": self._queue.qsize(),
        }

    # -- actor thread ---------------------------------------------------------

    def _actions_for(self, obs: list[dict]) -> tuple[np.ndarray, int]:
        """One batched policy call over ALL current observations (static
        [n] shape → single jit trace), indexed down to the envs that need
        an action. Rows for mid-step envs hold their last obs and are
        discarded — constant-shape inference beats per-subset recompiles."""
        n = self.pool.num_envs
        keys = obs[0].keys()
        td = ArrayDict({k: jnp.asarray(np.stack([o[k] for o in obs])) for k in keys})
        self._key, k_act = jax.random.split(self._key)
        with self._lock:
            params, version = self._params, self._version
        if self.policy is None:
            actions = self.pool.action_spec.rand(k_act, (n,))
        else:
            actions = self.policy(params, td, k_act)["action"]
        return np.asarray(actions), version

    def _run(self) -> None:
        try:
            self._collect_loop()
        except BaseException as e:  # surfaced to the trainer via get_batch
            self._error = e

    @hot_path(reason="background env-stepping actor thread")
    def _collect_loop(self) -> None:
        from ..resilience.faults import fault_point

        pool = self.pool
        n = pool.num_envs
        min_ready = max(1, math.ceil(self.min_ready_fraction * n))
        obs = pool.reset(seed=self._seed)
        pending = [False] * n
        sent_action = [None] * n
        sent_obs: list[dict | None] = [None] * n
        sent_version = [0] * n
        needs_send = list(range(n))
        records: list[tuple] = []
        last_harvest = time.monotonic()

        while not self._stop.is_set():
            fault_point("collector.actor_loop")  # chaos site (crash/delay)
            # -- send phase: dispatch actions to every env holding fresh obs
            if needs_send:
                actions, version = self._actions_for(obs)
                for i in needs_send:
                    sent_action[i] = actions[i]
                    sent_obs[i] = obs[i]
                    sent_version[i] = version
                    pool.async_step_send(i, actions[i])
                    pending[i] = True
                needs_send = []

            # -- harvest phase: first-come with straggler cutoff
            ready = [i for i in range(n) if pending[i] and pool.step_ready(i)]
            in_flight = sum(pending)
            now = time.monotonic()
            if not ready or (
                len(ready) < min(min_ready, in_flight)
                and now - last_harvest < self.straggler_wait_s
            ):
                time.sleep(self.poll_interval_s)
                continue
            if len(ready) < in_flight:
                self._straggler_cutoffs += 1
                self._m_cutoffs.inc()
                self._tracer.instant(
                    "straggler_cutoff", {"ready": len(ready), "in_flight": in_flight}
                )
            self._harvests += 1
            self._m_harvest_s.observe(now - last_harvest)
            last_harvest = now

            for i in ready:
                next_obs, reward, term, trunc = pool.async_step_recv(i)[:4]
                pending[i] = False
                records.append(
                    (
                        sent_obs[i],
                        sent_action[i],
                        next_obs,
                        np.float32(reward),
                        bool(term),
                        bool(trunc),
                        sent_version[i],
                        i,
                        self._env_steps,
                    )
                )
                self._env_steps += 1
                if term or trunc:
                    self._seed = seed_generator(self._seed)
                    obs[i] = pool.reset_one(i, self._seed)
                else:
                    obs[i] = next_obs
                needs_send.append(i)

            # -- emit phase: hand over full batches through the bounded queue
            while len(records) >= self.frames_per_batch:
                with self._tracer.span("collector/emit_batch"):
                    batch = self._build_batch(records[: self.frames_per_batch])
                records = records[self.frames_per_batch :]
                if not self._put(batch):
                    return

    def _put(self, batch: ArrayDict) -> bool:
        """Blocking put with stop-awareness — this is the backpressure point:
        a full queue parks the actor thread (envs idle) until the trainer
        drains a batch."""
        while not self._stop.is_set():
            try:
                self._queue.put(batch, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _build_batch(self, recs: list[tuple]) -> ArrayDict:
        keys = recs[0][0].keys()
        obs = {k: jnp.asarray(np.stack([r[0][k] for r in recs])) for k in keys}
        nxt = {k: jnp.asarray(np.stack([r[2][k] for r in recs])) for k in keys}
        td = ArrayDict(obs)
        td = td.set("action", jnp.asarray(np.stack([r[1] for r in recs])))
        next_td = ArrayDict(nxt).update(
            ArrayDict(
                reward=jnp.asarray(np.asarray([r[3] for r in recs], np.float32)),
                terminated=jnp.asarray(np.asarray([r[4] for r in recs])),
                truncated=jnp.asarray(np.asarray([r[5] for r in recs])),
                done=jnp.asarray(np.asarray([r[4] or r[5] for r in recs])),
            )
        )
        versions = np.asarray([r[6] for r in recs], np.int32)
        stamps = ArrayDict(
            policy_version=jnp.asarray(versions),
            env_ids=jnp.asarray(np.asarray([r[7] for r in recs], np.int32)),
            step=jnp.asarray(np.asarray([r[8] for r in recs], np.int32)),
        )
        self._batches_emitted += 1
        self._m_staleness.observe_many(self._version - versions)
        self._m_env_steps.set_total(self._env_steps)
        self._m_batches.set_total(self._batches_emitted)
        self._m_harvests.set_total(self._harvests)
        self._m_queue.set(self._queue.qsize())
        self._m_version.set(self._version)
        return td.set("next", next_td).set("collector", stamps)
