"""Multi-process distributed collection over one global mesh.

Redesign of the reference's distributed collectors (reference:
torchrl/collectors/distributed/ — ``DistributedDataCollector`` generic.py,
``RPCDataCollector``, ``DistributedSyncDataCollector``, ``RayCollector``:
worker processes run collectors and ship batches to a trainer over
NCCL/RPC/Ray). The TPU-native inversion: every process runs the SAME
program under ``jax.distributed`` on one global ``Mesh``; each process
collects its own env shard with a local in-jit :class:`Collector`, the
shards are assembled into ONE globally-sharded batch
(``jax.make_array_from_process_local_data``), and the learner's jitted
update consumes it directly — the gradient all-reduce over ICI/DCN is
inserted by XLA, not hand-written NCCL. Verified end-to-end by the
two-process Gloo test (tests/dist_worker.py phase 2).

Control-plane services (weight broadcast to non-SPMD actors, remote
replay) stay on the TCP stack (rl_tpu.comm); THIS module is the SPMD data
plane.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import jax

from ..data import ArrayDict
from .single import Collector

__all__ = ["MeshCollector"]


class MeshCollector:
    """Per-process wrapper: local in-jit collection -> global sharded batch.

    Every process constructs the same MeshCollector (same arguments) after
    ``jax.distributed.initialize`` / ``JaxDistributedRendezvous``. The
    env is the LOCAL shard (its batch size is this process's share);
    :meth:`collect` returns a batch whose leading axis is globally sharded
    over ``axis`` — feed it straight to a jitted/sharded train step.

    Args:
        env: this process's env shard (VmapEnv over local envs).
        policy: ``(params, td, key) -> td`` — same tree on every process
            (replicate params over the mesh).
        frames_per_batch: frames contributed PER PROCESS per collect.
        mesh: the global ``jax.sharding.Mesh`` (built from
            ``jax.devices()``, which spans all processes).
        axis: mesh axis name the batch shards over. Default "dp".
        flatten: flatten [T, N_local] time/env dims into one leading axis
            before assembly (the global batch is then [world*T*N, ...]).
            Set False to keep [T, N] and shard over envs (N must then be
            the per-process size of a mesh-divisible global dim).
    """

    def __init__(
        self,
        env: Any,
        policy: Callable,
        frames_per_batch: int,
        mesh: Any,
        axis: str = "dp",
        flatten: bool = True,
        postproc: Callable | None = None,
    ):
        from jax.sharding import NamedSharding, PartitionSpec

        self.local = Collector(
            env, policy, frames_per_batch=frames_per_batch, postproc=postproc
        )
        self.mesh = mesh
        self.axis = axis
        self.flatten = flatten
        # flattened batches shard their single leading axis; [T, N] batches
        # shard the ENV axis (dim 1) — time must never interleave across
        # processes (n-step/GAE/sequence consumers read dim 0 as time)
        self._shard = NamedSharding(
            mesh, PartitionSpec(axis) if flatten else PartitionSpec(None, axis)
        )
        self.frames_per_batch = frames_per_batch * jax.process_count()
        self._collect = jax.jit(self.local.collect)

    def init(self, key: jax.Array) -> ArrayDict:
        """Local collector state; fold the process index into the key so
        shards explore independently."""
        return self.local.init(jax.random.fold_in(key, jax.process_index()))

    def collect(self, params: Any, cstate: ArrayDict):
        """One global batch. Returns ``(batch, cstate)`` where every leaf
        of ``batch`` is a globally-sharded jax.Array ([world * local_rows,
        ...] when ``flatten``)."""
        batch, cstate = self._collect(params, cstate)
        if self.flatten:
            batch = batch.flatten_batch()

        def assemble(x):
            return jax.make_array_from_process_local_data(
                self._shard, np.asarray(x)
            )

        return jax.tree.map(assemble, batch), cstate
