"""Host-env collection: threaded env pool + jitted batched policy.

The Sebulba-style actor split for sims that cannot run inside XLA
(reference analogs: torchrl/envs/async_envs.py:59 ``AsyncEnvPool`` /
``ThreadingAsyncEnvPool``:841; torchrl/envs/batched_envs.py:1805
``ParallelEnv`` worker processes; torchrl/modules/inference_server/
``InferenceServer``:261 which batches many actors' queries onto one device
policy). On TPU the shape is: N host envs step in a thread pool, their
observations batch into ONE device policy call (the "inference server" is
just the jitted policy over the stacked batch), actions scatter back.

Produces time-major [T, N, ...] ArrayDict batches in the standard
{..., "next": ...} layout — downstream losses/estimators are identical to
the pure-JAX path.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..data import ArrayDict
from ..utils.seeding import seed_generator

__all__ = ["ThreadedEnvPool", "ProcessEnvPool", "HostCollector"]


def _process_env_worker(env_fn, conn):
    """One env per process; command protocol over the pipe (reference:
    torchrl/envs/batched_envs.py:1805 ParallelEnv worker loop)."""
    env = env_fn()
    try:
        while True:
            cmd, arg = conn.recv()
            if cmd == "reset":
                conn.send(env.reset(seed=arg))
            elif cmd == "step":
                conn.send(env.step(arg))
            elif cmd == "specs":
                conn.send((env.observation_spec, env.action_spec))
            elif cmd == "close":
                try:
                    env.close()
                finally:
                    conn.send(None)
                break
    except (EOFError, KeyboardInterrupt):
        pass


class ProcessEnvPool:
    """N host envs in worker processes — the fallback for Python-heavy sims
    that hold the GIL (reference ParallelEnv's mp workers; ThreadedEnvPool
    covers GIL-releasing C sims).

    Same surface as :class:`ThreadedEnvPool` (reset/step_wait/async pair).
    ``ctx="fork"`` by default: workers must not touch JAX (env code only);
    use ``ctx="spawn"`` with picklable top-level ``env_fns`` otherwise.
    """

    def __init__(self, env_fns, ctx: str = "fork"):
        import multiprocessing as mp

        mctx = mp.get_context(ctx)
        self.num_envs = len(env_fns)
        self._conns = []
        self._procs = []
        for fn in env_fns:
            parent, child = mctx.Pipe()
            p = mctx.Process(
                target=_process_env_worker, args=(fn, child), daemon=True
            )
            p.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(p)
        self._conns[0].send(("specs", None))
        self.observation_spec, self.action_spec = self._conns[0].recv()

    def reset(self, seed: int = 0) -> list[dict]:
        s = seed
        for c in self._conns:
            c.send(("reset", s))
            s = seed_generator(s)
        return [c.recv() for c in self._conns]

    def async_step_send(self, i: int, action) -> None:
        self._conns[i].send(("step", action))

    def async_step_recv(self, i: int):
        return self._conns[i].recv()

    def step_ready(self, i: int) -> bool:
        """Non-blocking: has worker ``i``'s in-flight step finished? Lets an
        async collector harvest fast envs first and leave stragglers
        cooking (first-come batching / straggler cutoff)."""
        return self._conns[i].poll()

    def step_wait(self, actions) -> list[tuple]:
        for i in range(self.num_envs):
            self.async_step_send(i, actions[i])
        return [self.async_step_recv(i) for i in range(self.num_envs)]

    def reset_one(self, i: int, seed: int) -> dict:
        self._conns[i].send(("reset", seed))
        return self._conns[i].recv()

    def alive(self) -> list[bool]:
        """Worker liveness (feed a rl_tpu.comm.liveness.Watchdog)."""
        return [p.is_alive() for p in self._procs]

    def close(self) -> None:
        for c, p in zip(self._conns, self._procs):
            try:
                c.send(("close", None))
                c.recv()
            except (BrokenPipeError, EOFError):
                pass
            c.close()
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()


class ThreadedEnvPool:
    """N host envs stepped concurrently (GIL-friendly: gym envs release the
    GIL in C physics; otherwise threads still overlap with device compute).

    ``async_step_send``/``async_step_recv`` expose the out-of-sync API
    (reference AsyncEnvPool:59); ``step_wait`` is the sync barrier form.
    """

    def __init__(self, env_fns: list[Callable[[], Any]], num_threads: int | None = None):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self._pool = ThreadPoolExecutor(max_workers=num_threads or self.num_envs)
        self._futures: list = [None] * self.num_envs

    @property
    def observation_spec(self):
        return self.envs[0].observation_spec

    @property
    def action_spec(self):
        return self.envs[0].action_spec

    def reset(self, seed: int = 0) -> list[dict]:
        seeds = []
        s = seed
        for _ in range(self.num_envs):
            seeds.append(s)
            s = seed_generator(s)
        return list(self._pool.map(lambda ev_s: ev_s[0].reset(seed=ev_s[1]), zip(self.envs, seeds)))

    # -- async protocol -------------------------------------------------------

    def async_step_send(self, i: int, action) -> None:
        self._futures[i] = self._pool.submit(self.envs[i].step, action)

    def async_step_recv(self, i: int):
        out = self._futures[i].result()
        self._futures[i] = None
        return out

    def step_ready(self, i: int) -> bool:
        """Non-blocking readiness probe (see ProcessEnvPool.step_ready)."""
        fut = self._futures[i]
        return fut is not None and fut.done()

    def step_wait(self, actions: np.ndarray) -> list[tuple]:
        for i in range(self.num_envs):
            self.async_step_send(i, actions[i])
        return [self.async_step_recv(i) for i in range(self.num_envs)]

    def reset_one(self, i: int, seed: int) -> dict:
        return self.envs[i].reset(seed=seed)

    def close(self) -> None:
        for e in self.envs:
            e.close()
        self._pool.shutdown(wait=False)


class HostCollector:
    """Collect batches from a host env pool with a jitted device policy.

    ``policy``: ``(params, td, key) -> td`` over the BATCHED observation
    ArrayDict (the inference-server pattern: one device call serves all
    envs). ``None`` collects random actions. Host-side auto-reset matches
    the device collector's semantics ("next" holds terminal content, the
    carry restarts).
    """

    def __init__(
        self,
        pool: ThreadedEnvPool,
        policy: Callable | None = None,
        frames_per_batch: int = 1024,
        seed: int = 0,
        interruptor: Any = None,
    ):
        self.pool = pool
        # preemption (reference _Interruptor, collectors/_constants.py:53):
        # when raised mid-collection, remaining steps are padded and masked
        # out via "collected_mask" so the batch shape stays static for jit
        self.interruptor = interruptor
        self.policy = jax.jit(policy) if policy is not None else None
        n = pool.num_envs
        if frames_per_batch % n:
            raise ValueError(f"frames_per_batch={frames_per_batch} not divisible by {n} envs")
        self.scan_length = frames_per_batch // n
        self.frames_per_batch = frames_per_batch
        self._seed = seed
        self._obs: list[dict] | None = None

    def _stack_obs(self, obs_list: list[dict]) -> ArrayDict:
        keys = obs_list[0].keys()
        return ArrayDict({k: jnp.asarray(np.stack([o[k] for o in obs_list])) for k in keys})

    def collect(self, params: Any, key: jax.Array) -> ArrayDict:
        n = self.pool.num_envs
        if self._obs is None:
            self._obs = self.pool.reset(seed=self._seed)
        if self.interruptor is not None:
            # re-arm per batch (reference semantics): the flag cuts ONE
            # batch short; a persistent trainer stop is request_stop()
            self.interruptor.start_collection()
        steps = []
        for _ in range(self.scan_length):
            if (
                steps
                and self.interruptor is not None
                and self.interruptor.collection_stopped()
            ):
                break
            td = self._stack_obs(self._obs)
            key, k_act = jax.random.split(key)
            if self.policy is None:
                td = td.set("action", self.pool.action_spec.rand(k_act, (n,)))
            else:
                td = self.policy(params, td, k_act)
            actions = np.asarray(td["action"])

            results = self.pool.step_wait(actions)
            next_obs = [r[0] for r in results]
            reward = np.asarray([r[1] for r in results], np.float32)
            term = np.asarray([r[2] for r in results])
            trunc = np.asarray([r[3] for r in results])
            done = term | trunc

            next_td = self._stack_obs(next_obs).update(
                ArrayDict(
                    reward=jnp.asarray(reward),
                    terminated=jnp.asarray(term),
                    truncated=jnp.asarray(trunc),
                    done=jnp.asarray(done),
                )
            )
            steps.append(td.set("next", next_td))

            # host auto-reset: restart finished envs; carry keeps fresh obs
            carry = list(next_obs)
            for i in range(n):
                if done[i]:
                    self._seed = seed_generator(self._seed)
                    carry[i] = self.pool.reset_one(i, self._seed)
            self._obs = carry
        if self.interruptor is None:
            return ArrayDict.stack(steps, axis=0)
        if len(steps) < self.scan_length:
            # preempted: pad to the static [T, N] shape, mask the tail.
            # Mark the cut point truncated+done so value estimators stop the
            # recursion there (GAE's (1-done) gate) — otherwise the padded
            # rows' fake deltas would bootstrap into every REAL step's
            # advantage, which the loss-level mask cannot undo.
            last = steps[-1]
            tru = jnp.ones((n,), bool)
            last = last.set(("next", "truncated"), tru).set(("next", "done"), tru)
            steps = steps[:-1] + [last]
            pad = self.scan_length - len(steps)
            batch = ArrayDict.stack(steps + [steps[-1]] * pad, axis=0)
            mask = np.zeros((self.scan_length, n), bool)
            mask[: len(steps)] = True
            return batch.set("collected_mask", jnp.asarray(mask))
        return ArrayDict.stack(steps, axis=0).set(
            "collected_mask", jnp.ones((self.scan_length, n), bool)
        )

    def iterate(self, params: Any, key: jax.Array, total_frames: int):
        collected = 0
        while collected < total_frames:
            key, k = jax.random.split(key)
            yield self.collect(params, k)
            collected += self.frames_per_batch


def compact_collected(batch: ArrayDict) -> ArrayDict:
    """Drop padded rows from a preempted [T, N] HostCollector batch.

    Interruptor-cut batches duplicate the last step to keep shapes static
    and mark real rows in ``collected_mask``. Losses fold the mask in
    automatically (ActorCriticLossMixin._mask), but replay-buffer insertion
    does not — padded rows would enter storage as fake transitions. Call
    this host-side (dynamic shape is fine off-device) before ``extend``:

    >>> buffer_state = buffer.extend(buffer_state, compact_collected(b).flatten_batch())

    Fully-collected batches pass through with only the mask key removed.
    Only whole time rows are dropped (the mask is constant across envs
    within a row), so the [T', N] layout is preserved.
    """
    if "collected_mask" not in batch:
        return batch
    mask = np.asarray(batch["collected_mask"])
    rest = batch.exclude("collected_mask")
    if mask.all():
        return rest
    rows = mask.any(axis=1)
    return jax.tree.map(lambda x: x[np.flatnonzero(rows)], rest)
