"""LLM collector: chat env x jitted generation -> GRPO training batches.

Redesign of the reference's ``LLMCollector`` (reference:
torchrl/collectors/llm/base.py:26 — rollout = wrapper.generate() batch into a
ChatEnv) without the external engine: generation is the jitted KV-cache scan
(rl_tpu/models/generate.py) over the SAME params the trainer optimizes
(SharedProgramScheme — zero-copy weight "sync"), or over a scheme-provided
snapshot for decoupled rollout.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import hot_path
from ..data import ArrayDict
from ..envs.llm.chat import DatasetChatEnv
from ..models import generate
from ..objectives.llm import mc_advantage

__all__ = ["LLMCollector"]


class LLMCollector:
    """Collect GRPO batches: sample prompt groups, generate G responses per
    prompt, score, compute group-relative advantages."""

    def __init__(
        self,
        env: DatasetChatEnv,
        model: Any,
        num_prompts: int = 8,
        max_new_tokens: int = 64,
        temperature: float = 1.0,
        eos_id: int | None = None,
        ref_params: Any = None,
        weight_scheme: Any = None,
        reward_transform: Callable | None = None,
        continuous_batching: bool = False,
        engine_slots: int | None = None,
        engine_block_size: int = 16,
        engine_decode_chunk: int | str = 1,
        engine_params_sharding: Any = None,
        engine_prefix_cache: bool = False,
        fleet: Any = None,
        fleet_timeout_s: float = 120.0,
        fleet_poll_s: float = 0.01,
    ):
        self.env = env
        self.model = model
        self.num_prompts = num_prompts
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.ref_params = ref_params
        self.weight_scheme = weight_scheme
        # continuous batching: responses come from the paged-KV engine
        # (slot admission mid-batch) instead of one fixed-batch generate —
        # rows that hit eos early stop paying decode steps (the vLLM-side
        # behavior the reference gets from its AsyncVLLM backend)
        self.continuous_batching = continuous_batching
        self.engine_slots = engine_slots
        self.engine_block_size = engine_block_size
        # 1 (default) keeps sampling key-deterministic vs the fixed-batch
        # path; "auto" lets the engine tune its chunk from measured chunk
        # wall-time vs sync overhead (throughput over reproducibility)
        self.engine_decode_chunk = engine_decode_chunk
        # shardings the engine pins pushed params to (FSDP rollouts: the
        # sharded trainer passes its per-leaf param placements through)
        self.engine_params_sharding = engine_params_sharding
        # prefix-aware KV tier (rl_tpu.kvmem): a GRPO group's G rollouts
        # share ONE prompt, so every response after the group's first
        # prefills only the last prompt position via the radix tree's
        # exact-match fast path. Off by default to keep the engine path
        # bit-identical with prior behavior; flip on for shared-prompt
        # rollout workloads.
        self.engine_prefix_cache = engine_prefix_cache
        # batch-lane tenancy (ISSUE 19): instead of a PRIVATE engine, the
        # collector rides an existing ServingFleet's "batch" lane —
        # interactive traffic holds the SLO lane strictly ahead, rollouts
        # harvest whatever capacity is idle. Admission sheds
        # (ServiceSaturated) and post-admission sheds both back off and
        # resubmit: a slack tenant yields, never competes.
        self.fleet = fleet
        self.fleet_timeout_s = fleet_timeout_s
        self.fleet_poll_s = fleet_poll_s
        self._engine = None
        # (rewards, batch_arrays) -> rewards, applied BEFORE group advantages
        # (KLRewardTransform / PolicyVersion — reference envs/llm/transforms/)
        self.reward_transform = reward_transform

        self._gen = jax.jit(
            lambda params, toks, mask, key: generate(
                model,
                params,
                toks,
                mask,
                key,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                eos_id=eos_id,
            )
        )
        if ref_params is not None:
            from ..models import token_log_probs

            self._ref_lp = jax.jit(
                lambda toks, mask: token_log_probs(model, ref_params, toks, mask)
            )

    @hot_path(reason="drives the engine decode loop per rollout batch")
    def _engine_generate(self, params, toks, pmask, key, on_row_done=None):
        """Continuous-batching rollout shaped like ``generate``'s output:
        the G requests stream through engine slots; early-eos rows free
        their slot (and KV blocks) immediately.

        ``on_row_done(row)`` fires as each request's tokens land on the
        host — its row of the shared resp/rlp/rmask buffers is final at
        that point — so callers can consume completions first-come
        (score a prompt group's rewards while other groups still decode;
        the ``AsyncHostCollector`` harvest pattern)."""
        from ..models.generate import GenerateOutput
        from ..models.serving import ContinuousBatchingEngine

        G, P = toks.shape
        if self._engine is None:
            bucket = max(16, 1 << (P - 1).bit_length())
            slots = self.engine_slots or min(G, 8)
            self._engine = ContinuousBatchingEngine(
                self.model,
                params,
                n_slots=slots,
                block_size=self.engine_block_size,
                n_blocks=slots
                * (-(-self.model.cfg.max_seq_len // self.engine_block_size))
                + 1,
                prompt_buckets=(bucket,),
                eos_id=self.eos_id,
                temperature=self.temperature,
                decode_chunk=self.engine_decode_chunk,
                params_sharding=self.engine_params_sharding,
                prefix_cache=self.engine_prefix_cache,
            )
        eng = self._engine
        eng.params = params  # fresh policy weights each collect
        # the per-call key drives sampling (key-deterministic, like the
        # fixed-batch path): fold it into the engine's stream
        eng._key = jax.random.fold_in(key, 0)
        # env batches arrive host-side; np.asarray is a no-op there. A
        # device array here would mean a blocking d2h of data the caller
        # just uploaded — keep prompts on the host until the final concat.
        toks_np = np.asarray(toks)
        mask_np = np.asarray(pmask) > 0
        rids = [
            eng.submit(toks_np[g][mask_np[g]], self.max_new_tokens)
            for g in range(G)
        ]
        rid_row = {rid: g for g, rid in enumerate(rids)}
        N = self.max_new_tokens
        resp = np.zeros((G, N), np.int32)
        rlp = np.zeros((G, N), np.float32)
        rmask = np.zeros((G, N), bool)

        def _absorb(done):
            for rid, f in done.items():
                g = rid_row.pop(rid)
                n = len(f.tokens)
                resp[g, :n] = f.tokens
                rlp[g, :n] = f.log_probs
                # every produced token INCLUDING a terminal eos is real —
                # generate()'s response_mask convention (valid = was_alive;
                # the policy must see gradient on the stop decision)
                rmask[g, :n] = True
                if on_row_done is not None:
                    on_row_done(g, resp, rmask)

        # drive the engine incrementally, consuming completions while the
        # remaining slots keep decoding (run() would block to the end)
        while eng.step():
            _absorb(eng.harvest())
        _absorb(eng.harvest())
        if rid_row:
            raise RuntimeError(f"engine lost requests: {sorted(rid_row)}")
        full = jnp.concatenate([jnp.asarray(toks_np), jnp.asarray(resp)], axis=1)
        full_mask = jnp.concatenate(
            [jnp.asarray(mask_np), jnp.asarray(rmask)], axis=1
        )
        return GenerateOutput(
            tokens=full,
            response_tokens=jnp.asarray(resp),
            response_mask=jnp.asarray(rmask),
            response_log_probs=jnp.asarray(rlp),
            full_mask=full_mask,
        )

    @hot_path(reason="drives the fleet batch lane per rollout batch")
    def _fleet_generate(self, params, toks, pmask, key, on_row_done=None):
        """Batch-lane tenant rollout: the G requests ride an existing
        :class:`~rl_tpu.models.ServingFleet`'s ``batch`` lane, filling
        whatever capacity the interactive SLO lane leaves idle. Weight
        push is the fleet's rolling per-member swap (serving never
        globally stalls); sheds — admission-time saturation AND
        post-admission ``ShedRequest`` — back off and resubmit until the
        deadline. Results come through :meth:`ServingFleet.poll`, which
        never drains another tenant's rows. The per-call ``key`` is
        unused here: sampling streams belong to the member engines."""
        import time as _time

        from ..models.fleet import ShedRequest
        from ..models.generate import GenerateOutput
        from ..models.serving import ServiceSaturated

        fleet = self.fleet
        if params is not None:
            fleet.push_params(params)
        G, P = toks.shape
        toks_np = np.asarray(toks)
        mask_np = np.asarray(pmask) > 0
        N = self.max_new_tokens
        resp = np.zeros((G, N), np.int32)
        rlp = np.zeros((G, N), np.float32)
        rmask = np.zeros((G, N), bool)
        pending_rows = list(range(G))  # not yet admitted (or re-shed)
        outstanding: dict[int, int] = {}  # frid -> row
        deadline = _time.monotonic() + self.fleet_timeout_s
        while pending_rows or outstanding:
            still: list[int] = []
            for g in pending_rows:
                try:
                    frid = fleet.submit(
                        toks_np[g][mask_np[g]], N, lane="batch")
                    outstanding[frid] = g
                except ServiceSaturated:
                    still.append(g)  # the SLO lane owns the pool right now
            pending_rows = still
            for frid, res in fleet.poll(list(outstanding)).items():
                g = outstanding.pop(frid)
                if isinstance(res, ShedRequest):
                    pending_rows.append(g)  # bounded by the deadline below
                    continue
                n = len(res.tokens)
                resp[g, :n] = res.tokens
                rlp[g, :n] = res.log_probs
                rmask[g, :n] = True
                if on_row_done is not None:
                    on_row_done(g, resp, rmask)
            if pending_rows or outstanding:
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet batch lane: {len(pending_rows)} unadmitted + "
                        f"{len(outstanding)} outstanding rollout rows after "
                        f"{self.fleet_timeout_s}s"
                    )
                _time.sleep(self.fleet_poll_s)
        full = jnp.concatenate(
            [jnp.asarray(toks_np), jnp.asarray(resp)], axis=1)
        full_mask = jnp.concatenate(
            [jnp.asarray(mask_np), jnp.asarray(rmask)], axis=1)
        return GenerateOutput(
            tokens=full,
            response_tokens=jnp.asarray(resp),
            response_mask=jnp.asarray(rmask),
            response_log_probs=jnp.asarray(rlp),
            full_mask=full_mask,
        )

    def _engine_collect(self, params, toks, pmask, key, state, group_ids):
        """Engine rollout with FIRST-COME group scoring: the moment a
        prompt group's last response lands, its rewards are computed on
        the host while the other groups' slots keep decoding — reward
        work overlaps device decode instead of serializing after it.
        Falls back to end-of-rollout scoring when the env has no
        ``score_rows``."""
        can_score = hasattr(self.env, "score_rows")
        G = toks.shape[0]
        rewards = np.zeros(G, np.float32)
        group_rows: dict[int, list[int]] = {}
        for row, g in enumerate(np.asarray(group_ids)):
            group_rows.setdefault(int(g), []).append(row)
        remaining = {g: len(rows) for g, rows in group_rows.items()}

        def on_row_done(row, resp, rmask):
            if not can_score:
                return
            g = int(group_ids[row])
            remaining[g] -= 1
            if remaining[g] == 0:
                rows = group_rows[g]
                rewards[rows] = self.env.score_rows(state, resp, rmask, rows)

        gen = (
            self._fleet_generate
            if self.fleet is not None
            else self._engine_generate
        )
        out = gen(params, toks, pmask, key, on_row_done)
        if not can_score:
            return out, None
        return out, rewards

    def collect(self, params: Any, key: jax.Array) -> ArrayDict:
        """One GRPO batch: ArrayDict with tokens/attention_mask/
        assistant_mask/sample_log_prob/advantage/reward (+ref_log_prob).

        ``params=None`` pulls the weight scheme's latest snapshot;
        explicitly-passed params win (a pipelined caller snapshots
        ``(params, version)`` atomically and must generate with exactly
        that snapshot, not whatever the scheme holds by generation time).
        """
        if params is None:
            if self.weight_scheme is None:
                raise ValueError("params=None requires a weight_scheme to pull from")
            params = self.weight_scheme.pull()
        state, group_ids = self.env.sample_batch(self.num_prompts)
        toks = np.asarray(state["tokens"])
        pmask = np.asarray(state["attention_mask"], np.float32)
        if self.fleet is not None or self.continuous_batching:
            # the engine consumes prompts on the host (slot-packing and
            # submit copies) — handing it a device array would round-trip
            # the freshly-uploaded batch straight back through a blocking
            # transfer, so the upload happens once, inside _engine_generate
            out, rewards = self._engine_collect(params, toks, pmask, key, state, group_ids)
        else:
            out = self._gen(params, jnp.asarray(toks), jnp.asarray(pmask), key)
            rewards = None

        resp = np.asarray(out.response_tokens)
        rmask = np.asarray(out.response_mask)
        if rewards is None:
            _, rewards, _ = self.env.step(state, resp, rmask)

        G = toks.shape[0]
        P_len = toks.shape[1]
        T = P_len + self.max_new_tokens
        gid = jnp.asarray(group_ids)

        arrays: dict = {
            "tokens": out.tokens,
            "attention_mask": out.full_mask[:, :T].astype(jnp.float32),
            "assistant_mask": jnp.concatenate(
                [jnp.zeros((G, P_len), bool), out.response_mask], axis=1
            ),
            "sample_log_prob": jnp.concatenate(
                [jnp.zeros((G, P_len)), out.response_log_probs], axis=1
            ),
            "group_id": gid,
        }
        if self.ref_params is not None:
            arrays["ref_log_prob"] = self._ref_lp(
                arrays["tokens"], arrays["attention_mask"]
            )
        if self.reward_transform is not None:
            rewards = np.asarray(self.reward_transform(rewards, arrays))
        # advantages AFTER reward shaping, same ordering as the reference's
        # in-env KLRewardTransform (the estimator sees the shaped reward)
        adv = mc_advantage(jnp.asarray(rewards), gid, self.num_prompts)
        return ArrayDict(advantage=adv, reward=jnp.asarray(rewards), **arrays)
