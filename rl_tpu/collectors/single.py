"""Single-program collector: the rollout hot loop as one XLA computation.

Redesign of the reference's ``Collector`` hot loop (reference:
torchrl/collectors/_single.py:297, ``rollout``:2014 — a Python for-loop of
policy call + ``env.step_and_maybe_reset`` + device casts per step). Here the
whole loop is a ``lax.scan`` inside one jit ("Anakin" architecture,
Podracer/PAPERS.md): no per-step dispatch, no device casts, no worker
processes for pure-JAX envs.

The collector is functional: ``init(key)`` builds the carried
:class:`CollectorState`; ``collect(params, cstate)`` returns
``(batch, cstate)`` where ``batch`` is a time-major ``[T, B, …]`` ArrayDict
in the reference's ``{…, "next": …}`` layout. Iteration stays in Python (the
reference's ``for batch in collector``) via :meth:`__iter__`-style usage or
an explicit loop around a jitted ``collect``.

``policy`` is ``(params, td, key) -> td`` (a TDModule/ProbabilisticActor
partial-applied or any callable); ``None`` collects random actions
(``init_random_frames`` analog is a RandomPolicy phase).
"""

from __future__ import annotations

import math

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..data import ArrayDict
from ..envs.base import EnvBase

__all__ = ["Collector", "CollectorState"]

CollectorState = ArrayDict  # {"env": env_state, "carry": td, "rng": key, "step_count", "traj_ids"}


class Collector:
    """Collect fixed-size batches by scanning the env+policy.

    Args:
        env: (possibly vmapped/transformed) environment.
        policy: ``(params, td, key) -> td`` writing "action" (+extras), or
            ``None`` for random actions.
        frames_per_batch: total env frames per yielded batch
            (= scan_length × num_envs).
        total_frames: optional budget; :meth:`done` reports exhaustion
            (the reference's ``total_frames``).
        postproc: optional ``batch -> batch`` (e.g. MultiStep) applied
            inside the same jit.
    """

    def __init__(
        self,
        env: EnvBase,
        policy: Callable | None = None,
        frames_per_batch: int = 1024,
        total_frames: int | None = None,
        postproc: Callable[[ArrayDict], ArrayDict] | None = None,
        policy_state: ArrayDict | None = None,
    ):
        self.env = env
        self.policy = policy
        self.policy_state = policy_state
        num_envs = math.prod(env.batch_shape) if env.batch_shape else 1
        if frames_per_batch % num_envs:
            raise ValueError(
                f"frames_per_batch={frames_per_batch} not divisible by num_envs={num_envs}"
            )
        self.num_envs = num_envs
        self.scan_length = frames_per_batch // num_envs
        self.frames_per_batch = frames_per_batch
        self.total_frames = total_frames
        self.postproc = postproc

    # -- functional API -------------------------------------------------------

    def init(self, key: jax.Array) -> CollectorState:
        from ..utils.seeding import ensure_typed_key

        reset_key, carry_key = jax.random.split(ensure_typed_key(key))
        env_state, td = self.env.reset(reset_key)
        if self.policy_state is not None:
            # stateful-policy carry (exploration annealing, OU noise, RNN
            # hidden): lives in the carry td, stripped from recorded batches
            td = td.set("exploration", self.policy_state)
        traj_ids = (
            jnp.arange(self.num_envs).reshape(self.env.batch_shape)
            if self.env.batch_shape
            else jnp.asarray(0)
        )
        return ArrayDict(
            env=env_state,
            carry=td,
            rng=carry_key,
            step_count=jnp.asarray(0, jnp.int64 if jax.config.jax_enable_x64 else jnp.int32),
            traj_count=jnp.asarray(self.num_envs),
            traj_ids=traj_ids,
        )

    def collect(self, params: Any, cstate: CollectorState) -> tuple[ArrayDict, CollectorState]:
        """One batch. Jit/pjit this (or a composition containing it)."""

        def body(carry, step_key):
            env_state, td, traj_ids, traj_count = carry
            if self.policy is None:
                td = self.env.rand_action(td, step_key)
            else:
                td = self.policy(params, td, step_key)
            env_state, full_td, carry_td = self.env.step_and_reset(
                env_state, td.exclude("exploration")
            )
            if "exploration" in td:
                carry_td = carry_td.set("exploration", td["exploration"])
            done = full_td["next", "done"]
            # new trajectory ids where episodes ended (reference traj_ids
            # bookkeeping, collectors/utils.py)
            n_done = jnp.sum(done.astype(jnp.int32))
            new_ids = traj_count + jnp.cumsum(done.astype(jnp.int32)).reshape(done.shape) - 1
            traj_ids_next = jnp.where(done, new_ids, traj_ids)
            full_td = full_td.set("collector", ArrayDict(traj_ids=traj_ids))
            return (env_state, carry_td, traj_ids_next, traj_count + n_done), full_td

        scan_key, next_rng = jax.random.split(cstate["rng"])
        keys = jax.random.split(scan_key, self.scan_length)
        (env_state, carry_td, traj_ids, traj_count), batch = jax.lax.scan(
            body,
            (cstate["env"], cstate["carry"], cstate["traj_ids"], cstate["traj_count"]),
            keys,
        )
        if self.postproc is not None:
            batch = self.postproc(batch)
        new_state = ArrayDict(
            env=env_state,
            carry=carry_td,
            rng=next_rng,
            step_count=cstate["step_count"] + self.frames_per_batch,
            traj_count=traj_count,
            traj_ids=traj_ids,
        )
        return batch, new_state

    # -- ergonomic python-loop API -------------------------------------------

    def frames_collected(self, cstate: CollectorState) -> int:
        return int(cstate["step_count"])

    def done(self, cstate: CollectorState) -> bool:
        return self.total_frames is not None and self.frames_collected(cstate) >= self.total_frames

    def iterate(self, params: Any, key: jax.Array, jit: bool = True):
        """Generator over batches (the reference's ``for data in collector``)."""
        collect = jax.jit(self.collect) if jit else self.collect
        cstate = self.init(key)
        while not self.done(cstate):
            batch, cstate = collect(params, cstate)
            yield batch
