"""Communication backbone: backend atoms, rendezvous, control-plane channels.

Redesign of the reference's comm layer (reference: torchrl/_comm/ —
backend atoms backends.py:13-34 with contextvar scoping :191,221;
``Mailbox`` mailbox.py:185; ``CommandChannel`` command.py:42; ``Rendezvous``
protocols rendezvous.py:17,30,51,79).

On TPU the DATA plane is in-program XLA collectives over the mesh
(SURVEY.md §2.2: psum/all_gather/ppermute replace NCCL point-to-point) —
there is no tensor transport to build. What remains host-side is the
CONTROL plane: how peers find each other (Rendezvous → wraps
``jax.distributed.initialize``'s coordinator) and how commands/results move
between host processes/threads (Mailbox/CommandChannel over queues or TCP).
The backend-atom naming is kept verbatim — it is the one piece of the
reference worth copying as a design.
"""

from __future__ import annotations

import contextlib
import contextvars
import enum
import json
import queue
import socket
import socketserver
import threading
from typing import Any, Callable, Mapping

from .liveness import Interruptor, Watchdog
from .services import ServiceRegistry, TCPServiceRegistry, connect_registry

__all__ = [
    "Interruptor",
    "Watchdog",
    "ServiceRegistry",
    "TCPServiceRegistry",
    "connect_registry",
    "ServiceBackend",
    "TransportBackend",
    "service_backend",
    "transport_backend",
    "current_service_backend",
    "current_transport_backend",
    "Rendezvous",
    "MappingRendezvous",
    "EnvVarRendezvous",
    "JaxDistributedRendezvous",
    "Mailbox",
    "CommandChannel",
    "BinaryReply",
    "BLOB_KEY",
    "TCPCommandServer",
    "TCPCommandClient",
]


class ServiceBackend(enum.Enum):
    """WHERE code runs (reference backends.py:13)."""

    DIRECT = "direct"
    THREAD = "thread"
    PROCESS = "process"
    JAX_COLLECTIVE = "jax_collective"  # in-mesh, data plane handled by XLA
    RAY = "ray"  # import-gated


class TransportBackend(enum.Enum):
    """HOW bytes move (reference backends.py:21)."""

    AUTO = "auto"
    DIRECT = "direct"
    QUEUE = "queue"
    TCP = "tcp"
    DEVICE = "device"  # jax.device_put / collectives
    RAY = "ray"


_SERVICE = contextvars.ContextVar("rl_tpu_service_backend", default=ServiceBackend.DIRECT)
_TRANSPORT = contextvars.ContextVar("rl_tpu_transport_backend", default=TransportBackend.AUTO)


@contextlib.contextmanager
def service_backend(backend: ServiceBackend | str):
    """Scope the default service backend (reference backends.py:191)."""
    token = _SERVICE.set(ServiceBackend(backend) if isinstance(backend, str) else backend)
    try:
        yield
    finally:
        _SERVICE.reset(token)


@contextlib.contextmanager
def transport_backend(backend: TransportBackend | str):
    token = _TRANSPORT.set(TransportBackend(backend) if isinstance(backend, str) else backend)
    try:
        yield
    finally:
        _TRANSPORT.reset(token)


def current_service_backend() -> ServiceBackend:
    return _SERVICE.get()


def current_transport_backend() -> TransportBackend:
    return _TRANSPORT.get()


# -- rendezvous ---------------------------------------------------------------


class Rendezvous:
    """How peers discover each other (reference rendezvous.py:17)."""

    def addresses(self) -> Mapping[str, str]:
        raise NotImplementedError

    def my_rank(self) -> int:
        raise NotImplementedError

    def world_size(self) -> int:
        raise NotImplementedError


class MappingRendezvous(Rendezvous):
    """Static peer map (reference MappingRendezvous:30)."""

    def __init__(self, peers: Mapping[str, str], rank: int = 0):
        self._peers = dict(peers)
        self._rank = rank

    def addresses(self):
        return dict(self._peers)

    def my_rank(self):
        return self._rank

    def world_size(self):
        return len(self._peers)


class EnvVarRendezvous(Rendezvous):
    """From the standard cluster env vars (COORDINATOR_ADDRESS,
    PROCESS_ID/NUM_PROCESSES — what TPU pod launchers export)."""

    def __init__(self, prefix: str = ""):
        import os

        self.coordinator = os.environ.get(prefix + "COORDINATOR_ADDRESS", "localhost:0")
        self._rank = int(os.environ.get(prefix + "PROCESS_ID", 0))
        self._world = int(os.environ.get(prefix + "NUM_PROCESSES", 1))

    def addresses(self):
        return {"coordinator": self.coordinator}

    def my_rank(self):
        return self._rank

    def world_size(self):
        return self._world


class JaxDistributedRendezvous(Rendezvous):
    """Bind the rendezvous to ``jax.distributed.initialize`` — the TPU-native
    coordinator (maps 1:1 onto the reference's TCPStoreRendezvous:51)."""

    def __init__(
        self,
        coordinator_address: str | None = None,
        num_processes: int | None = None,
        process_id: int | None = None,
    ):
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        import jax as _j

        self._rank = _j.process_index()
        self._world = _j.process_count()
        self.coordinator = coordinator_address or "jax-coordinator"

    def addresses(self):
        return {"coordinator": self.coordinator}

    def my_rank(self):
        return self._rank

    def world_size(self):
        return self._world


# -- mailbox / command channel ------------------------------------------------


class Mailbox:
    """Async message channel between threads (reference mailbox.py:185):
    named queues with blocking receive and futures-free semantics."""

    def __init__(self):
        self._queues: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()

    def _q(self, name: str) -> queue.Queue:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = queue.Queue()
            return self._queues[name]

    def send(self, to: str, message: Any) -> None:
        self._q(to).put(message)

    def receive(self, name: str, timeout: float | None = None) -> Any:
        return self._q(name).get(timeout=timeout)

    def try_receive(self, name: str) -> Any | None:
        try:
            return self._q(name).get_nowait()
        except queue.Empty:
            return None


class CommandChannel:
    """Control-plane RPC between a driver and named workers (reference
    command.py:42): register handlers, send commands, await replies."""

    def __init__(self, mailbox: Mailbox | None = None):
        self.mailbox = mailbox or Mailbox()
        self._handlers: dict[str, Callable[[Any], Any]] = {}
        self._seq = 0

    def register_handler(self, command: str, fn: Callable[[Any], Any]) -> None:
        self._handlers[command] = fn

    def serve_once(self, worker: str, timeout: float | None = None) -> bool:
        """Process one pending command addressed to ``worker``; False if none
        arrived within ``timeout``."""
        try:
            msg = self.mailbox.receive(f"cmd:{worker}", timeout=timeout)
        except queue.Empty:
            return False
        cmd, payload, reply_to = msg
        if cmd not in self._handlers:
            self.mailbox.send(reply_to, ("error", f"unknown command {cmd!r}"))
            return True
        try:
            out = self._handlers[cmd](payload)
            self.mailbox.send(reply_to, ("ok", out))
        except Exception as e:  # noqa: BLE001 - control plane reports, not crashes
            self.mailbox.send(reply_to, ("error", repr(e)))
        return True

    def call(self, worker: str, command: str, payload: Any = None, timeout: float | None = 10.0) -> Any:
        self._seq += 1
        reply_to = f"reply:{worker}:{self._seq}"
        self.mailbox.send(f"cmd:{worker}", (command, payload, reply_to))
        try:
            status, out = self.mailbox.receive(reply_to, timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"no reply from worker {worker!r} to {command!r} within {timeout}s"
            ) from None
        if status != "ok":
            raise RuntimeError(f"command {command!r} on {worker!r} failed: {out}")
        return out


# reserved payload key a binary request's raw frame arrives under — old
# handlers never see it (old peers never send "nbin"), binary-aware
# handlers pop it
BLOB_KEY = "__blob__"


class BinaryReply:
    """A handler return value carrying a raw binary frame alongside the JSON
    ``out``. The server writes the header line with ``nbin=len(blob)`` and
    streams the bytes after the newline — no base64, no double copy."""

    __slots__ = ("out", "blob")

    def __init__(self, out: Any, blob: bytes):
        self.out = out
        self.blob = blob


class _JSONHandler(socketserver.StreamRequestHandler):
    def handle(self):
        line = self.rfile.readline()
        if not line:
            return
        blob_out = b""
        try:
            req = json.loads(line)
            # binary frame extension: a request announcing "nbin" is
            # followed by exactly that many raw bytes after the newline;
            # old peers never send the field, so this is wire-compatible
            nbin = int(req.get("nbin") or 0)
            payload = req.get("payload")
            if nbin:
                blob = self.rfile.read(nbin)
                if len(blob) != nbin:
                    raise ConnectionError("truncated binary frame")
                payload = dict(payload or {})
                payload[BLOB_KEY] = blob
            fn = self.server._handlers.get(req.get("command"))  # type: ignore[attr-defined]
            if fn is None:
                resp = {"status": "error", "out": f"unknown command {req.get('command')!r}"}
            else:
                from ..obs.trace import TraceContext, get_tracer, use_context

                # optional "trace" key on the frame: old peers never send
                # it, new peers tolerate its absence — the control plane
                # stays wire-compatible in both directions
                wire_ctx = TraceContext.from_wire(req.get("trace"))
                if wire_ctx is None:
                    out = fn(payload)
                else:
                    # adopt the caller's context on this handler thread:
                    # everything the handler does (spans, fleet submits,
                    # fault stamps) parents under the RPC that caused it
                    with use_context(wire_ctx), get_tracer().ctx_span(
                        f"comm/handle:{req.get('command')}"
                    ):
                        out = fn(payload)
                if isinstance(out, BinaryReply):
                    blob_out = out.blob
                    resp = {"status": "ok", "out": out.out, "nbin": len(blob_out)}
                else:
                    resp = {"status": "ok", "out": out}
        except Exception as e:  # noqa: BLE001
            resp = {"status": "error", "out": repr(e)}
            blob_out = b""
        # chaos site: the handler already ran — a drop here models a reply
        # lost on the wire, which only a client-side retry can survive
        from ..resilience.faults import should_drop

        if should_drop("comm.server.reply"):
            return
        self.wfile.write((json.dumps(resp) + "\n").encode() + blob_out)


class TCPCommandServer:
    """Cross-process command endpoint (line-delimited JSON over TCP) — the
    DCN control plane for multi-host orchestration."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = socketserver.ThreadingTCPServer((host, port), _JSONHandler)
        self._server._handlers = {}  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def register_handler(self, command: str, fn: Callable[[Any], Any]) -> None:
        self._server._handlers[command] = fn  # type: ignore[attr-defined]

    def start(self) -> "TCPCommandServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class TCPCommandClient:
    """One-shot JSON-RPC client with optional transport retry.

    ``retry`` is a :class:`rl_tpu.resilience.RetryPolicy`; when set,
    ``call(..., idempotent=True)`` survives refused connections, timeouts,
    and dropped replies. Server-side handler errors come back as
    ``RuntimeError`` and are never retried — the request reached the peer.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0, retry: Any = None):
        self.host, self.port, self.timeout = host, port, timeout
        self.retry = retry

    def _call_once(self, command: str, payload: Any, blob: bytes | None = None,
                   binary: bool = False) -> Any:
        from ..obs.trace import current_context, get_tracer

        req = {"command": command, "payload": payload}
        if blob is not None:
            req["nbin"] = len(blob)
        if current_context() is None:
            return self._send(req, blob=blob, binary=binary)
        # inside a traced request: the wire frame carries the RPC span's
        # context so the server-side handler links under THIS call (the
        # one TCP hop in the request tree); retried calls each get their
        # own span/frame, which is what a retry is
        with get_tracer().ctx_span(f"comm/call:{command}") as span_ctx:
            if span_ctx is not None:
                req["trace"] = span_ctx.to_wire()
            return self._send(req, blob=blob, binary=binary)

    def _send(self, req: Mapping[str, Any], blob: bytes | None = None,
              binary: bool = False) -> Any:
        command = req["command"]
        with socket.create_connection((self.host, self.port), timeout=self.timeout) as s:
            wire = (json.dumps(dict(req)) + "\n").encode()
            if blob is not None:
                wire += blob
            s.sendall(wire)
            data = b""
            while b"\n" not in data:
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
            if not data:
                # server accepted the connection but never replied (dropped
                # reply / handler crash): transport-shaped, hence retryable
                raise ConnectionError(
                    f"empty reply from {self.host}:{self.port} for {command!r}"
                )
            if b"\n" not in data:
                raise ConnectionError(
                    f"truncated reply from {self.host}:{self.port} for {command!r}"
                )
            head, rest = data.split(b"\n", 1)
            resp = json.loads(head)
            nbin = int(resp.get("nbin") or 0)
            while len(rest) < nbin:
                chunk = s.recv(65536)
                if not chunk:
                    break
                rest += chunk
        if len(rest) < nbin:
            raise ConnectionError(
                f"truncated binary reply from {self.host}:{self.port} for {command!r}"
            )
        if resp["status"] != "ok":
            raise RuntimeError(f"remote command {command!r} failed: {resp['out']}")
        if binary:
            return resp["out"], rest[:nbin]
        return resp["out"]

    def call(self, command: str, payload: Any = None, idempotent: bool = True) -> Any:
        if self.retry is None:
            return self._call_once(command, payload)
        return self.retry.call(self._call_once, command, payload, idempotent=idempotent)

    def call_binary(
        self, command: str, payload: Any = None, blob: bytes | None = None,
        idempotent: bool = True,
    ) -> tuple[Any, bytes]:
        """Like :meth:`call` but sends ``blob`` as a raw binary frame after
        the header line and returns ``(out, reply_blob)`` — the replay data
        plane's framing (33% smaller than base64, no BytesIO double copy)."""
        if self.retry is None:
            return self._call_once(command, payload, blob=blob, binary=True)
        return self.retry.call(
            self._call_once, command, payload, blob=blob, binary=True,
            idempotent=idempotent,
        )
