"""Liveness + preemption primitives for host-side moving parts.

Redesign of the reference's collector failure machinery (reference:
torchrl/collectors/_constants.py:53 ``_Interruptor`` — a shared flag the
main process raises to preempt in-flight rollouts so stragglers cannot
stall a synchronous barrier; torchrl/_utils.py:520 liveness checks on
worker pipes). On TPU the moving host parts are env pools, TCP services
and inference-server actors; the device program itself cannot straggle.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

__all__ = ["Interruptor", "Watchdog"]


class Interruptor:
    """Preemption flag: the trainer raises it, collectors drain and stop.

    Thread/process-safe enough for its job (an Event per side); the
    reference's mp.Value+lock maps onto a plain Event here because host
    collection threads share the process.
    """

    def __init__(self):
        self._stop = threading.Event()

    def start_collection(self) -> None:
        self._stop.clear()

    def stop_collection(self) -> None:
        self._stop.set()

    def collection_stopped(self) -> bool:
        return self._stop.is_set()


class Watchdog:
    """Heartbeat registry with a background reaper.

    Actors ``register``/``beat``; anything silent for ``timeout`` seconds is
    declared dead exactly once (``on_death`` callback + ``dead`` listing).
    Used by the inference server to stop waiting on vanished actors and by
    host pools/TCP services as a liveness check.
    """

    def __init__(
        self,
        timeout: float = 30.0,
        on_death: Callable[[str], Any] | None = None,
        check_interval: float | None = None,
    ):
        self.timeout = timeout
        self.on_death = on_death
        self.check_interval = check_interval or max(timeout / 4, 0.01)
        self._beats: dict[str, float] = {}
        self._dead: set[str] = set()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def register(self, name: str) -> None:
        with self._lock:
            self._beats[name] = time.monotonic()
            self._dead.discard(name)

    def beat(self, name: str) -> None:
        with self._lock:
            self._beats[name] = time.monotonic()
            self._dead.discard(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)
            self._dead.discard(name)

    def check(self) -> list[str]:
        """Sweep once; returns newly-dead names (each reported once)."""
        now = time.monotonic()
        newly = []
        with self._lock:
            for name, t in self._beats.items():
                if name not in self._dead and now - t > self.timeout:
                    self._dead.add(name)
                    newly.append(name)
        if newly:
            # liveness incidents show up in traces and /metrics, not just
            # log lines (lazy import: comm must stay importable standalone)
            from ..obs import get_registry, get_tracer

            tracer = get_tracer()
            counter = get_registry().counter(
                "rl_tpu_watchdog_deaths_total",
                "actors declared dead by the watchdog",
                labels=("name",),
            )
            for name in newly:
                tracer.instant("watchdog_death", {"name": name})
                counter.inc(1, {"name": name})
            # a declared-dead actor is a postmortem moment: black-box dump
            # (single None check when disarmed; the recorder rate-limits
            # itself, so a mass die-off doesn't flood the disk)
            from ..obs import get_flight_recorder

            rec = get_flight_recorder()
            if rec is not None:
                for name in newly:
                    rec.dump(f"watchdog_death-{name}")
        for name in newly:
            if self.on_death is not None:
                self.on_death(name)
        return newly

    @property
    def dead(self) -> list[str]:
        with self._lock:
            return sorted(self._dead)

    @property
    def alive(self) -> list[str]:
        with self._lock:
            return sorted(set(self._beats) - self._dead)

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 - a failing on_death callback
                # must not kill the reaper; liveness sweeps keep running
                import logging

                logging.getLogger("rl_tpu").exception("watchdog sweep failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
