"""Named service registry: discover shared helpers across workers.

Redesign of the reference's services layer (reference: torchrl/services/
base.py ``ServiceBase`` — a dict-like registry of named services;
ray_service.py backs it with named ray actors). Without a Ray runtime the
TPU-native backing is the line-JSON TCP control plane: one
:class:`ServiceRegistry` process holds {name -> address/metadata}, workers
register on startup and look peers up by name; a
:class:`~rl_tpu.comm.liveness.Watchdog` expires silent registrations.

In-process use needs no server: ``ServiceRegistry()`` is a plain registry
(the reference's dict-like surface: register/get/__contains__/list).
"""

from __future__ import annotations

from typing import Any

__all__ = ["ServiceRegistry", "TCPServiceRegistry", "connect_registry"]


class ServiceRegistry:
    """Dict-like named services (reference ServiceBase surface).

    Thread-safe: TCPServiceRegistry serves it from ThreadingTCPServer
    handler threads, so the duplicate-registration guard must be atomic.
    """

    def __init__(self, watchdog: Any = None):
        import threading

        self._services: dict[str, Any] = {}
        self._watchdog = watchdog
        self._lock = threading.Lock()

    def register(self, name: str, service: Any, replace: bool = False) -> None:
        with self._lock:
            if not replace and name in self._services:
                raise ValueError(f"service {name!r} already registered")
            self._services[name] = service
            # watchdog update inside the lock so registry and watchdog can
            # never disagree (watchdog's own lock nests without deadlock)
            if self._watchdog is not None:
                self._watchdog.register(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._services.pop(name, None)
            if self._watchdog is not None:
                self._watchdog.unregister(name)

    def get(self, name: str) -> Any:
        # Membership first (under the lock): a name in the watchdog's dead
        # set that was never registered — or already unregistered — must
        # report "unknown service", not "registered but not alive".
        with self._lock:
            if name not in self._services:
                raise KeyError(
                    f"unknown service {name!r}; have {sorted(self._services)}"
                )
            service = self._services[name]
        if self._watchdog is not None and name in self._watchdog.dead:
            raise KeyError(f"service {name!r} is registered but not alive")
        return service

    def heartbeat(self, name: str) -> None:
        if self._watchdog is not None:
            self._watchdog.beat(name)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._services

    def list(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._services)


class TCPServiceRegistry:
    """Serve a ServiceRegistry over the TCP control plane.

    Values are JSON metadata (typically {"host","port", ...} of the actual
    service endpoint) — the registry stores *addresses*, not live objects,
    exactly like named ray actors resolve to handles.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, watchdog: Any = None):
        from . import TCPCommandServer

        self.registry = ServiceRegistry(watchdog=watchdog)
        if watchdog is not None:
            watchdog.start()  # the promised expiry of silent registrations
        self._watchdog = watchdog
        self._server = TCPCommandServer(host, port)
        self._server.register_handler("register", self._register)
        self._server.register_handler("unregister", lambda p: self.registry.unregister(p["name"]))
        self._server.register_handler("get", lambda p: self.registry.get(p["name"]))
        self._server.register_handler("list", lambda p: self.registry.list())
        self._server.register_handler("heartbeat", lambda p: self.registry.heartbeat(p["name"]))
        self._server.start()

    def _register(self, payload):
        self.registry.register(
            payload["name"], payload["value"], replace=bool(payload.get("replace"))
        )

    @property
    def address(self) -> tuple[str, int]:
        return self._server.address

    def shutdown(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
        self._server.shutdown()


class connect_registry:
    """Client handle to a remote TCPServiceRegistry.

    ``retry`` (a :class:`rl_tpu.resilience.RetryPolicy`) makes lookups and
    heartbeats survive transient transport failures. ``register`` with
    ``replace=False`` is NOT idempotent — a dropped reply does not prove
    the registration was dropped, and replaying it would raise a spurious
    "already registered" — so it only retries when ``replace=True``.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0, retry: Any = None):
        from . import TCPCommandClient

        self._cli = TCPCommandClient(host, port, timeout=timeout, retry=retry)

    def register(self, name: str, value: Any, replace: bool = False) -> None:
        self._cli.call(
            "register",
            {"name": name, "value": value, "replace": replace},
            idempotent=bool(replace),
        )

    def unregister(self, name: str) -> None:
        self._cli.call("unregister", {"name": name})

    def get(self, name: str) -> Any:
        return self._cli.call("get", {"name": name})

    def list(self) -> dict:
        return self._cli.call("list", None)

    def heartbeat(self, name: str) -> None:
        self._cli.call("heartbeat", {"name": name})
