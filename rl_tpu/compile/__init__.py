"""rl_tpu.compile — kill cold-start: AOT warm-up, persistent executables,
shape-buckets, and compile observability (ROADMAP item 5).

Four pieces, layered:

- :mod:`~rl_tpu.compile.registry` — :class:`ProgramRegistry` /
  :class:`CachedProgram`: named hot programs with explicit executable
  tables, ``aot_warmup()`` (optionally backgrounded), and store-load →
  lower+compile resolution.
- :mod:`~rl_tpu.compile.store` — :class:`ExecutableStore`: serialized XLA
  executables keyed by abstract call signature; a warm restart loads in
  milliseconds instead of re-lowering for seconds.
- :mod:`~rl_tpu.compile.buckets` — :class:`ShapeBuckets`: the shared
  serving ladder (prompt lengths + admitted counts) that keeps request
  dynamism inside a fixed, warmable program set.
- :mod:`~rl_tpu.compile.metrics` — per-compile attribution
  (``compiles_total{program}``, ``compile_seconds``, tracer spans) and
  :class:`CompileDelta`, the steady-state no-recompile assertion.

The JAX persistent compilation cache is enabled by the first registry via
:func:`rl_tpu.config.enable_compile_cache` (opt-out
``RL_TPU_NO_COMPILE_CACHE``); the executable store and AOT dispatch have
their own opt-outs (``RL_TPU_NO_EXEC_STORE``, ``RL_TPU_NO_AOT``).
"""

from .buckets import ShapeBuckets, pow2ceil
from .metrics import (
    CompileDelta,
    compile_counts,
    compile_scope,
    compile_seconds_total,
    compiles_total,
    install_compile_listener,
)
from .registry import (
    CachedProgram,
    ProgramRegistry,
    WarmupHandle,
    get_program_registry,
    set_program_registry,
)
from .store import (
    ExecutableStore,
    abstract_like,
    default_store,
    set_default_store,
    signature_of,
)

__all__ = [
    "CachedProgram",
    "abstract_like",
    "CompileDelta",
    "ExecutableStore",
    "ProgramRegistry",
    "ShapeBuckets",
    "WarmupHandle",
    "compile_counts",
    "compile_scope",
    "compile_seconds_total",
    "compiles_total",
    "default_store",
    "get_program_registry",
    "install_compile_listener",
    "pow2ceil",
    "set_default_store",
    "set_program_registry",
    "signature_of",
]
