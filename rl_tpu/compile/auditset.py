"""The rlint ``--ir`` program set: tiny real configurations of the
framework's registered hot programs, compiled through an isolated
ProgramRegistry so every one passes the IR auditor.

The AST rules lint source; the R100-series rules need *lowered*
programs, which only exist once something registers and compiles them.
This module is the CLI's way to materialize that set without a bench or
a test run: shrunken-but-real serving / Anakin / off-policy builds, each
driven one step so the registry pays its normal ``lower().compile()``
(and therefore its audit) per program.

Store semantics are the interesting part: with ``fresh_store=True``
(``tools/rlint.py --ir``) every program compiles, so every program is
audited. With ``fresh_store=False`` (``--diff`` mode) the persistent
executable store is used as-is — programs whose fingerprint/signature
did not change load their serialized executable and *skip* the audit,
which is exactly the "only re-audit programs whose fingerprint changed"
contract.
"""

from __future__ import annotations

import contextlib
import io
import tempfile
import traceback
from typing import Any, Callable, Iterable

__all__ = ["AUDIT_TARGETS", "check_spec_programs", "run_ir_audit"]


def _build_serving() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import ContinuousBatchingEngine, TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=97, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    m = TransformerLM(cfg)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    eng = ContinuousBatchingEngine(
        m, params, n_slots=2, block_size=8, n_blocks=17,
        prompt_buckets=(16,), greedy=True,
    )
    eng.submit(np.arange(5) % 97, 4)
    eng.run()


def check_spec_programs(registry: Any) -> None:
    """The speculation-stays-compile-free gate: every slot-stream /
    speculative program family the engine can ever register must ride
    the EXISTING decode ladder — a verify or sdecode width outside
    ``_ChunkTuner.LADDER``, or a spec-path program name outside the
    known families, means the speculative path invented a new program
    signature and broke the steady-state CompileDelta == 0 contract.
    Raises ``RuntimeError`` (rlint --ir reports it and exits 1)."""
    from ..models.serving import _ChunkTuner

    ladder = set(_ChunkTuner.LADDER)
    known = ("serving.sprefill.", "serving.spprefill.", "serving.sadmit_update")
    for name in registry.names():
        if name.startswith(("serving.verify.k", "serving.sdecode.k")):
            k = name.rsplit("k", 1)[1]
            if not k.isdigit() or int(k) not in ladder:
                raise RuntimeError(
                    f"speculative program {name!r} is off the decode ladder "
                    f"{sorted(ladder)} — speculation must stay compile-free"
                )
        elif name.startswith("serving.s") and not name.startswith(known):
            raise RuntimeError(
                f"unknown speculative-path program family: {name!r} — new "
                "signatures outside the warmed ladder break CompileDelta == 0"
            )


def _build_serving_spec() -> None:
    """Speculative serving: prefix-cache engine with speculation on, the
    same prompt served twice so the second pass drafts from the first's
    donated continuation and dispatches a real ``serving.verify.k{K}``.
    Ends with the ladder check so rlint --ir gates the compile-free
    contract, not just the lowered IR."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import ContinuousBatchingEngine, TransformerConfig, TransformerLM
    from .registry import get_program_registry

    cfg = TransformerConfig(
        vocab_size=97, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32,
    )
    m = TransformerLM(cfg)
    params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    eng = ContinuousBatchingEngine(
        m, params, n_slots=2, block_size=8, n_blocks=17,
        prompt_buckets=(16,), greedy=True, prefix_cache=True,
        speculative=True, spec_lookahead=3,
    )
    prompt = np.arange(5) % 97
    eng.submit(prompt, 6)
    eng.run()  # donates the continuation into the radix tree
    eng.submit(prompt, 6)
    eng.run()  # replay: drafts from the tree, dispatches a verify
    if eng.spec_dispatches < 1:
        raise RuntimeError("speculative audit build never dispatched a verify")
    check_spec_programs(get_program_registry())


def _build_serving_kernels() -> None:
    """Kernel-tier serving: the same tiny engine lowered WITH the Pallas
    kernels (interpret mode — real kernel lowering without a chip), so
    ``rlint --ir`` audits the kernel-bearing jaxprs: R106 sees each
    declared ``kernel_hot_path`` satisfied, and the cost model prices the
    ``pallas_call`` targets instead of zeroing them. Different model dims
    than the stock build keep the two engines' program keys distinct in
    a shared store."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import ContinuousBatchingEngine, TransformerConfig, TransformerLM

    prev = os.environ.get("RL_TPU_KERNELS_INTERPRET")
    os.environ["RL_TPU_KERNELS_INTERPRET"] = "1"
    try:
        cfg = TransformerConfig(
            vocab_size=97, d_model=48, n_layers=1, n_heads=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32,
        )
        m = TransformerLM(cfg)
        params = m.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
        eng = ContinuousBatchingEngine(
            m, params, n_slots=2, block_size=8, n_blocks=17,
            prompt_buckets=(16,), greedy=True,
        )
        eng.submit(np.arange(5) % 97, 4)
        eng.run()
    finally:
        if prev is None:
            os.environ.pop("RL_TPU_KERNELS_INTERPRET", None)
        else:
            os.environ["RL_TPU_KERNELS_INTERPRET"] = prev


def _build_anakin() -> None:
    import jax

    from ..modules import (
        MLP,
        Categorical,
        ProbabilisticActor,
        TDModule,
        ValueOperator,
    )
    from ..objectives import ClipPPOLoss
    from ..trainers import AnakinConfig, AnakinProgram

    actor = ProbabilisticActor(
        TDModule(MLP(out_features=2, num_cells=(16, 16)),
                 ["observation"], ["logits"]),
        Categorical,
        dist_keys=("logits",),
    )
    critic = ValueOperator(MLP(out_features=1, num_cells=(16, 16)))
    loss = ClipPPOLoss(actor, critic)
    loss.make_value_estimator(gamma=0.99, lmbda=0.95)
    policy = lambda p, td, k: actor(p["actor"], td, k)  # noqa: E731
    cfg = AnakinConfig(
        num_envs=4, unroll_length=4, steps_per_dispatch=1,
        num_epochs=1, minibatch_size=8,
    )
    prog = AnakinProgram("cartpole", policy, loss, cfg,
                         device_metrics=False, max_episode_steps=10)
    ts = prog.init(jax.random.key(0))
    prog.dispatch(ts)


class _AuditHostEnv:
    """Deterministic 4-obs / 2-action host env — just enough spec surface
    for the async trainer's state layout; never actually stepped (the
    collector is not started, only :meth:`AsyncOffPolicyTrainer.aot_warmup`
    runs)."""

    def __init__(self):
        import numpy as np

        from ..data.specs import Categorical, Composite, Unbounded

        self._np = np
        self.observation_spec = Composite(observation=Unbounded((4,)))
        self.action_spec = Categorical(2)

    def reset(self, seed=0):
        return {"observation": self._np.zeros(4, self._np.float32)}

    def step(self, action):
        return self.reset(), 0.0, False, False

    def close(self):
        pass


def _build_offpolicy() -> None:
    import jax

    from ..collectors import AsyncHostCollector, ThreadedEnvPool
    from ..data import DeviceStorage, ReplayBuffer
    from ..modules import MLP, TDModule
    from ..objectives import DQNLoss
    from ..trainers import AsyncOffPolicyTrainer, OffPolicyConfig

    qnet = TDModule(MLP(out_features=2, num_cells=(16, 16)),
                    ["observation"], ["action_value"])
    loss = DQNLoss(qnet, gamma=0.99)
    pool = ThreadedEnvPool([_AuditHostEnv for _ in range(2)])
    coll = AsyncHostCollector(pool, None, frames_per_batch=16)
    buffer = ReplayBuffer(DeviceStorage(256))
    trainer = AsyncOffPolicyTrainer(
        coll, loss, buffer,
        OffPolicyConfig(batch_size=16, utd_ratio=1, init_random_frames=16),
    )
    try:
        ts = trainer.init(jax.random.key(0))
        # aot_warmup compiles the donated K-update scan — the program the
        # run loop dispatches — without starting the collector thread
        trainer.aot_warmup(ts)
    finally:
        pool.close()


AUDIT_TARGETS: dict[str, Callable[[], None]] = {
    "serving": _build_serving,
    "serving_spec": _build_serving_spec,
    "serving_kernels": _build_serving_kernels,
    "anakin": _build_anakin,
    "offpolicy": _build_offpolicy,
}


def run_ir_audit(
    include: Iterable[str] | None = None,
    *,
    auditor: Any = None,
    fresh_store: bool = True,
    quiet: bool = True,
) -> tuple[Any, dict]:
    """Compile the audit set through an isolated registry; returns
    ``(auditor, status)`` where status maps target name to ``"ok"`` or
    the failure summary (a broken builder is reported, never raised —
    the lint gate should judge findings, not environment quirks)."""
    from ..analysis.ir import IRAuditor
    from .registry import ProgramRegistry, set_program_registry
    from .store import ExecutableStore

    if auditor is None:
        auditor = IRAuditor()
    store = (
        ExecutableStore(root=tempfile.mkdtemp(prefix="rlint_ir_"))
        if fresh_store
        else None
    )
    registry = ProgramRegistry(store=store, auditor=auditor)
    prev = set_program_registry(registry)
    status: dict[str, str] = {}
    try:
        for name in include if include is not None else AUDIT_TARGETS:
            build = AUDIT_TARGETS.get(name)
            if build is None:
                status[name] = f"unknown target (want one of {sorted(AUDIT_TARGETS)})"
                continue
            try:
                ctx = (
                    contextlib.redirect_stdout(io.StringIO())
                    if quiet
                    else contextlib.nullcontext()
                )
                with ctx:
                    build()
                status[name] = "ok"
            except Exception as e:  # noqa: BLE001 — reported, not raised
                status[name] = f"build failed: {type(e).__name__}: {e}"
                if not quiet:
                    traceback.print_exc()
    finally:
        set_program_registry(prev)
    return auditor, status
