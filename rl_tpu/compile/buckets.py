"""Shape-bucketing: make request-shaped dynamism hit a FIXED program set.

XLA compiles one executable per distinct input signature, so serving has
two silent program multipliers:

- **prompt length**: every new length is a new prefill shape. The engine
  already rounds lengths up a *prompt ladder* (one prefill per bucket);
  this module makes that ladder a first-class shared config instead of a
  per-engine tuple, so :class:`~rl_tpu.models.fleet.ServingFleet` members
  can never drift apart.
- **admitted count**: the compact prefill batches only the slots admitted
  this round, so its leading dim ``A`` ranges over ``1..n_slots`` — up to
  ``n_slots x len(prompt ladder)`` programs from admission alone.
  :meth:`ShapeBuckets.admit_bucket` rounds ``A`` up a power-of-two ladder
  (capped at ``n_slots``); pad rows carry an all-False token mask, so the
  paged cache routes their writes to the reserved scratch block and the
  host simply never reads their sampled tokens. O(n_slots) admit shapes
  become O(log n_slots).

With both ladders warmed by ``aot_warmup()``, steady-state traffic is
*provably* recompile-free — :class:`~rl_tpu.compile.metrics.CompileDelta`
around a traffic window asserts the compile counter did not move.
"""

from __future__ import annotations

import dataclasses
import operator

__all__ = ["ShapeBuckets", "pow2ceil"]


def pow2ceil(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1)."""
    # operator.index, not int(): accepts np integer scalars but can never
    # force a device sync, so the hot admit path stays sync-free (R001).
    # n <= 1 handled explicitly: (-1).bit_length() is 1, not 0.
    n = operator.index(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class ShapeBuckets:
    """The shared serving bucket config (engine + fleet use ONE instance).

    Args:
        prompt: ascending prompt-length ladder; admission rounds each
            prompt length up to the next rung (one prefill program per
            rung instead of one per length).
        admit_pow2: round the admitted-count dim of the compact prefill
            up a power-of-two ladder (False keeps the legacy exact-count
            behavior: more programs, no pad rows).
        suffix: extra rungs for the PARTIAL prefill ladder (prefix-cache
            engines bucket the uncached suffix, which is usually much
            shorter than the prompt — e.g. ``(8, 16)`` keeps a mostly-hit
            workload off the big prompt rungs). The effective suffix
            ladder is ``sorted(set(suffix) | set(prompt))`` so any suffix
            a legal prompt can produce always has a rung.
    """

    prompt: tuple = (32, 128, 512)
    admit_pow2: bool = True
    suffix: tuple = ()

    def __post_init__(self):
        p = tuple(int(b) for b in self.prompt)
        if not p or any(b <= 0 for b in p) or list(p) != sorted(set(p)):
            raise ValueError(
                f"prompt ladder must be ascending positive ints, got {self.prompt}"
            )
        object.__setattr__(self, "prompt", p)
        s = tuple(int(b) for b in self.suffix)
        if any(b <= 0 for b in s) or list(s) != sorted(set(s)):
            raise ValueError(
                f"suffix rungs must be ascending positive ints, got {self.suffix}"
            )
        object.__setattr__(self, "suffix", s)

    # -- prompt ladder ---------------------------------------------------

    @property
    def max_prompt(self) -> int:
        return self.prompt[-1]

    def fits(self, length: int) -> bool:
        return length <= self.prompt[-1]

    def prompt_bucket(self, length: int) -> int:
        """Round a prompt length up to its ladder rung."""
        for b in self.prompt:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket {self.prompt[-1]}"
        )

    # -- suffix ladder (partial prefill) ---------------------------------

    def suffix_ladder(self) -> tuple:
        """The partial-prefill ladder: the prompt rungs plus any extra
        ``suffix`` rungs (warm-up set for ``serving.pprefill.*``)."""
        return tuple(sorted(set(self.suffix) | set(self.prompt)))

    def suffix_bucket(self, length: int) -> int:
        """Round an uncached-suffix length up to its ladder rung."""
        for b in self.suffix_ladder():
            if length <= b:
                return b
        raise ValueError(
            f"suffix length {length} exceeds the largest rung {self.prompt[-1]}"
        )

    # -- admit ladder ----------------------------------------------------

    def admit_bucket(self, count: int, cap: int) -> int:
        """Round an admitted count up its ladder rung (never past ``cap``,
        the engine's slot count)."""
        if count < 1 or count > cap:
            raise ValueError(f"admit count {count} outside 1..{cap}")
        if not self.admit_pow2:
            return count
        return min(pow2ceil(count), cap)

    def admit_sizes(self, cap: int) -> tuple:
        """Every admit-dim size programs can see (the warm-up set)."""
        if not self.admit_pow2:
            return tuple(range(1, cap + 1))
        sizes = []
        s = 1
        while s < cap:
            sizes.append(s)
            s *= 2
        sizes.append(cap)
        return tuple(sizes)

    def program_count(self, cap: int) -> int:
        """Prefill programs a fully-warmed engine holds (steady-state
        ceiling: the compile counter must not move past this set)."""
        return len(self.admit_sizes(cap)) * len(self.prompt)
