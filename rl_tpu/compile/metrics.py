"""Compile-event observability: who compiled, what, and for how long.

XLA compilation is the framework's biggest cold-start tax (ROADMAP item
5: 20-40s per program on the cpu tier), and — worse — *silent* steady-
state recompiles are how a serving engine quietly loses its latency SLO.
This module turns every backend compile into a first-class observable
event:

- ``install_compile_listener()`` hooks :mod:`jax.monitoring`'s
  ``/jax/core/compile/backend_compile_duration`` stream (emitted once
  per XLA backend compile, *not* per cache hit) and fans each event out
  to the process :class:`~rl_tpu.obs.registry.MetricsRegistry`
  (``rl_tpu_compiles_total{program}`` counter +
  ``rl_tpu_compile_seconds`` histogram) and the
  :class:`~rl_tpu.obs.trace.TraceRecorder` (one ``xla_compile:<name>``
  span per compile, stamped after the fact via ``end_span``).
- ``compile_scope(name)`` attributes compiles to a logical program name
  (a contextvar, so concurrent warm-up threads attribute correctly);
  compiles outside any scope land under ``"unattributed"`` — a nonzero
  unattributed count is itself a finding (some program bypassed the
  :class:`~rl_tpu.compile.registry.ProgramRegistry`).
- ``CompileDelta`` is the steady-state assertion primitive: wrap a
  traffic window in it and ``delta == 0`` *proves* no silent recompiles
  (used by the serve/fleet benches and ``bench_warmup``).

The listener cannot be unregistered (:mod:`jax.monitoring` only offers a
global clear, which would nuke JAX's own listeners), so installation is
idempotent and permanent for the process — the counters it feeds are
monotone, and all consumers read deltas.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Iterator

__all__ = [
    "CompileDelta",
    "compile_counts",
    "compile_scope",
    "compile_seconds_total",
    "compiles_total",
    "install_compile_listener",
]

# The jax.monitoring event emitted once per XLA backend compile. Trace /
# lowering durations are emitted under sibling keys; only the backend
# compile marks "XLA built a new executable", which is the event both
# the recompile assertions and the cold-start accounting care about.
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_UNATTRIBUTED = "unattributed"

_scope: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rl_tpu_compile_scope", default=_UNATTRIBUTED
)

_lock = threading.Lock()
_installed = False
_total = 0
_seconds_total = 0.0
_counts: dict[str, int] = {}
_seconds: dict[str, float] = {}

# compile_seconds spans 1ms toy programs to minutes-long fused trainers;
# the default obs buckets top out at 10s.
_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


@contextlib.contextmanager
def compile_scope(name: str) -> Iterator[None]:
    """Attribute any XLA compiles inside the block to ``name``."""
    token = _scope.set(str(name))
    try:
        yield
    finally:
        _scope.reset(token)


def current_scope() -> str:
    return _scope.get()


def _on_event(event: str, duration: float) -> None:
    label = _scope.get()
    global _total, _seconds_total
    with _lock:
        _total += 1
        _seconds_total += duration
        _counts[label] = _counts.get(label, 0) + 1
        _seconds[label] = _seconds.get(label, 0.0) + duration
    # obs wiring resolves the registry/tracer per event: tests swap both
    # via set_registry/set_tracer, and a cached handle would leak writes
    # into a previous test's registry.
    try:
        from rl_tpu.obs import get_registry, get_tracer

        reg = get_registry()
        reg.counter(
            "rl_tpu_compiles_total",
            "XLA backend compiles by logical program",
            labels=("program",),
        ).inc(labels={"program": label})
        reg.histogram(
            "rl_tpu_compile_seconds",
            "XLA backend compile duration",
            buckets=_COMPILE_BUCKETS,
        ).observe(duration)
        tracer = get_tracer()
        # the compile already happened — stamp a completed span covering it
        tracer.end_span(
            f"xla_compile:{label}",
            tracer._now_us() - duration * 1e6,
            {"seconds": round(duration, 4)},
        )
    except Exception:
        # observability must never break compilation itself
        pass


def _listener(event: str, duration_secs: float, **kwargs) -> None:
    if event.endswith(_COMPILE_EVENT_SUFFIX):
        _on_event(event, float(duration_secs))


def install_compile_listener() -> bool:
    """Idempotently register the compile-duration listener. Returns True
    when the hook is live (False if this jax lacks :mod:`jax.monitoring`)."""
    global _installed
    with _lock:
        if _installed:
            return True
    try:
        from jax import monitoring
    except Exception:
        return False
    fn = getattr(monitoring, "register_event_duration_secs_listener", None)
    if fn is None:
        return False
    with _lock:
        if _installed:  # lost the race to another thread
            return True
        fn(_listener)
        _installed = True
    return True


def compiles_total() -> int:
    """Process-lifetime count of XLA backend compiles (0 until the
    listener is installed)."""
    with _lock:
        return _total


def compile_seconds_total() -> float:
    with _lock:
        return _seconds_total


def compile_counts() -> dict[str, int]:
    """Snapshot of per-program compile counts."""
    with _lock:
        return dict(_counts)


def compile_seconds() -> dict[str, float]:
    """Snapshot of per-program cumulative compile seconds."""
    with _lock:
        return dict(_seconds)


class CompileDelta:
    """Count XLA compiles across a block: the steady-state assertion.

    ::

        with CompileDelta() as d:
            run_traffic(engine)
        assert d.delta == 0, d.explain()

    Installs the listener on entry (so the first use in a process still
    counts correctly) and snapshots per-program counts, so ``explain()``
    names exactly which programs recompiled.
    """

    def __init__(self):
        self.delta = 0
        self.seconds = 0.0
        self.by_program: dict[str, int] = {}
        self._t0 = 0
        self._s0 = 0.0
        self._c0: dict[str, int] = {}
        self.supported = False

    def __enter__(self) -> "CompileDelta":
        self.supported = install_compile_listener()
        with _lock:
            self._t0 = _total
            self._s0 = _seconds_total
            self._c0 = dict(_counts)
        return self

    def __exit__(self, *exc) -> None:
        with _lock:
            self.delta = _total - self._t0
            self.seconds = _seconds_total - self._s0
            self.by_program = {
                k: v - self._c0.get(k, 0)
                for k, v in _counts.items()
                if v - self._c0.get(k, 0) > 0
            }

    def explain(self) -> str:
        if not self.supported:
            return "compile counting unsupported (no jax.monitoring)"
        if not self.delta:
            return "no compiles"
        progs = ", ".join(f"{k}: {v}" for k, v in sorted(self.by_program.items()))
        return (
            f"{self.delta} compile(s) ({self.seconds:.2f}s) inside a window "
            f"expected to be steady-state [{progs}]"
        )


def _timed_compile(fn, *args, **kwargs):
    """Run ``fn`` (a lower/compile/deserialize step) and return
    ``(result, seconds)`` — shared helper for registry bookkeeping."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    return out, time.perf_counter() - t0
