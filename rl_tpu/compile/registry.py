"""ProgramRegistry: every hot jitted program, registered, AOT-compiled,
and persistently cached.

``jax.jit`` hides three costs behind the first call: trace, lower, and
backend-compile — 20-40s per fused program on the cpu tier (ROADMAP item
5), multiplied by every mesh topology, fleet member, and chunk size. The
registry replaces anonymous ``jax.jit(fn)`` sites with *named* programs:

- :meth:`ProgramRegistry.register` returns a :class:`CachedProgram` that
  is called exactly like the jitted function, but routes every dispatch
  through an explicit executable table instead of jit's hidden dispatch
  cache. A signature miss resolves store-load → lower+compile (never the
  reverse), so a warm process *loads* serialized executables and skips
  ``lower()`` entirely.
- :meth:`CachedProgram.add_signature` records the program's abstract call
  signature (``jax.ShapeDtypeStruct`` pytrees); :meth:`aot_warmup` then
  drives ``jit.lower().compile()`` (or the store load) for the whole
  registered set — optionally on a background thread, so warm-up overlaps
  host setup (env construction, checkpoint IO, TCP binds).
- every compile is attributed to its program name via
  :func:`~rl_tpu.compile.metrics.compile_scope`, feeding the
  ``rl_tpu_compiles_total{program}`` counter and the per-compile tracer
  span (observability satellite).

The registry holds programs by *weak* reference: a ``CachedProgram``
usually closes over its trainer/engine (bound methods), and a process
that constructs many short-lived engines (the test suite, a fleet churn
bench) must not leak every one of them through a global table.

Opt-outs: ``RL_TPU_NO_AOT=1`` keeps registration (names, metrics) but
dispatches through plain ``jax.jit``; the persistent layers have their
own knobs (``RL_TPU_NO_EXEC_STORE``, ``RL_TPU_NO_COMPILE_CACHE``).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Callable, Iterable

from .metrics import compile_scope, install_compile_listener
from .store import ExecutableStore, default_store

__all__ = [
    "CachedProgram",
    "ProgramRegistry",
    "WarmupHandle",
    "get_program_registry",
    "set_program_registry",
]

_ENV_NO_AOT = "RL_TPU_NO_AOT"
_ENV_NO_ATTR = "RL_TPU_NO_ATTRIBUTION"
_ENV_NO_IR_AUDIT = "RL_TPU_NO_IR_AUDIT"
_ENV_PEAK_FLOPS = "RL_TPU_PEAK_FLOPS"
_ENV_PEAK_BW = "RL_TPU_PEAK_BYTES_PER_S"
_ATTR_SAMPLE_EVERY = 8


def _attr_worker(q) -> None:
    """Attribution drain loop (its own daemon thread, never a dispatch
    thread): block until the sampled dispatch's first output leaf is
    device-ready, then credit the elapsed wall time to the program. The
    host sync lives HERE, off every hot path — dispatch only enqueues."""
    import jax

    while True:
        item = q.get()
        if item is None:
            return
        ref, t0, leaf = item
        try:
            jax.block_until_ready(leaf)
        except Exception:
            continue
        dt = time.perf_counter() - t0
        prog = ref()
        if prog is None:
            continue
        with prog._lock:
            prog.stats["device_s"] += dt
            prog.stats["device_samples"] += 1
            prog.stats["device_flops"] += prog.flops_per_call
        _notify_dispatch(prog, dt)


def _notify_dispatch(prog: "CachedProgram", dt: float) -> None:
    """Fan one sampled dispatch timing out to the armed profiler ring
    and drift detector (PR 18). Disarmed-by-default: each hook is a
    single None check when off. Runs ONLY on the attribution worker
    thread — never a dispatch thread — so the EWMA/z-score math and any
    triggered capture stay off every hot path (R001)."""
    try:
        from ..obs.drift import get_drift_detector
        from ..obs.profiling import get_profiler

        p = get_profiler()
        if p is not None:
            p.record_dispatch(prog.name, dt)
        d = get_drift_detector()
        if d is not None:
            d.observe(prog.name, dt, prog=prog)
    except Exception:
        pass


class _Attribution:
    """Sampled per-program device-time accounting.

    Every ``_ATTR_SAMPLE_EVERY``-th dispatch of a :class:`CachedProgram`
    enqueues ``(weakref(prog), t0, first_output_leaf)`` on a bounded
    queue; a lazily-started worker thread waits for the leaf and folds
    ``device_s`` / ``device_samples`` / ``device_flops`` into the
    program's ``stats`` (so :meth:`ProgramRegistry.stats` — and the
    flight recorder's ``programs.json`` — pick them up for free).
    Holding the leaf briefly pins its buffer; sampling plus the bounded
    queue keeps that footprint to a handful of arrays. A full queue
    drops the sample — that is just the sampler running behind, not an
    error. Opt out entirely with ``RL_TPU_NO_ATTRIBUTION=1``."""

    def __init__(self, maxsize: int = 256):
        import queue

        self._q: Any = queue.Queue(maxsize=maxsize)
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def submit(self, prog: "CachedProgram", t0: float, out: Any) -> None:
        if os.environ.get(_ENV_NO_ATTR, "") not in ("", "0"):
            return
        import jax

        leaves = jax.tree_util.tree_leaves(out)
        if not leaves:
            return
        self._ensure_thread()
        try:
            self._q.put_nowait((weakref.ref(prog), t0, leaves[0]))
        except Exception:
            pass

    def _ensure_thread(self) -> None:
        if self._thread is not None:
            return
        with self._lock:
            if self._thread is None:
                t = threading.Thread(
                    target=_attr_worker, args=(self._q,), name="prog-attr", daemon=True
                )
                t.start()
                self._thread = t


_ATTR = _Attribution()


def _memkey(args: tuple) -> tuple:
    """Cheap per-call signature: tree structure + per-leaf shape/dtype.

    This is the in-memory executable-table key, computed on EVERY
    dispatch — so no hashing, no sharding reprs, just the tuple jit's own
    dispatch would build. Shardings are deliberately excluded: one
    CachedProgram belongs to one trainer/engine, which pins placements at
    construction (the persistent-store key DOES include them)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (
        treedef,
        tuple(
            (getattr(x, "shape", None), str(getattr(x, "dtype", type(x).__name__)))
            for x in leaves
        ),
    )


class CachedProgram:
    """A registered program: called like ``jax.jit(fn)``, dispatched via
    an explicit executable table with store-load → compile resolution.

    ``stats`` counts the events the cold-start tests assert on:
    ``compiles`` (entered ``lower()``), ``loads`` (deserialized from the
    store), ``aot_hits`` (dispatched straight to a cached executable).
    """

    def __init__(
        self,
        name: str,
        fn: Callable,
        *,
        registry: "ProgramRegistry",
        fingerprint: str = "",
        ir_contract: dict | None = None,
        **jit_kwargs: Any,
    ):
        import jax

        self.name = name
        self.fn = fn
        self.fingerprint = fingerprint
        self.jit_kwargs = jit_kwargs
        self.ir_contract = dict(ir_contract or {})
        self._registry = registry
        self._jit = jax.jit(fn, **jit_kwargs)
        self._lock = threading.Lock()
        self._compiled: dict[tuple, Any] = {}
        self._unvalidated: set[tuple] = set()  # store-loads before 1st call
        self._signatures: list[tuple] = []
        self.flops_per_call = 0.0  # from cost_analysis, when the backend has it
        self.static_flops = 0.0    # from the IR auditor's static cost model
        self.static_bytes = 0.0
        self.ir_report: Any = None  # latest rl_tpu.analysis.ir.ProgramAudit
        self._attr_tick = 0
        self.stats = {
            "calls": 0,
            "aot_hits": 0,
            "compiles": 0,
            "loads": 0,
            "jit_calls": 0,
            "compile_s": 0.0,
            "load_s": 0.0,
            "device_s": 0.0,
            "device_samples": 0,
            "device_flops": 0.0,
        }

    # -- keys ------------------------------------------------------------

    def _store_extra(self) -> str:
        # donation/shardings change the executable; they are part of the
        # persistent identity (sorted for dict-order stability)
        return repr(sorted((k, repr(v)) for k, v in self.jit_kwargs.items()))

    def store_key(self, args: tuple) -> str:
        return self._registry.store.key_for(
            self.name, args, fingerprint=self.fingerprint, extra=self._store_extra()
        )

    # -- warm-up ---------------------------------------------------------

    def add_signature(self, *abstract_args: Any) -> "CachedProgram":
        """Record an abstract call signature (``ShapeDtypeStruct`` trees)
        for :meth:`warmup` / registry-level ``aot_warmup``. Idempotent on
        shape/dtype, so re-warming (restart paths call it again) doesn't
        grow the list."""
        mk = _memkey(abstract_args)
        with self._lock:
            if all(_memkey(s) != mk for s in self._signatures):
                self._signatures.append(abstract_args)
        return self

    @property
    def signatures(self) -> list[tuple]:
        with self._lock:
            return list(self._signatures)

    def warmup(self, *args: Any) -> tuple[str, float]:
        """Materialize the executable for one signature (abstract or
        concrete args — only shapes/dtypes are read). Returns
        ``(source, seconds)`` with source one of ``"memory"``/``"store"``
        /``"compile"``."""
        mk = _memkey(args)
        with self._lock:
            if mk in self._compiled:
                return ("memory", 0.0)
        key = self.store_key(args)
        t0 = time.perf_counter()
        prog = self._registry.store.load(key)
        if prog is not None:
            dt = time.perf_counter() - t0
            with self._lock:
                self._compiled[mk] = prog
                self._unvalidated.add(mk)
                self.stats["loads"] += 1
                self.stats["load_s"] += dt
            self._note_flops(prog)
            return ("store", dt)
        prog, dt = self._compile(args)
        return ("compile", dt)

    def _compile(self, args: tuple) -> tuple[Any, float]:
        mk = _memkey(args)
        t0 = time.perf_counter()
        with compile_scope(self.name):
            prog = self._jit.lower(*args).compile()
        dt = time.perf_counter() - t0
        with self._lock:
            self._compiled[mk] = prog
            self._unvalidated.discard(mk)
            self.stats["compiles"] += 1
            self.stats["compile_s"] += dt
        self._registry.store.save(
            key=self.store_key(args), compiled=prog, meta={"name": self.name}
        )
        self._note_flops(prog)
        self._ir_audit(args, mk, prog)
        return prog, dt

    # -- IR audit --------------------------------------------------------

    def _donated_leaf_count(self, args: tuple) -> int:
        import jax

        nums = self.jit_kwargs.get("donate_argnums")
        if nums is None:
            return 0
        if isinstance(nums, int):
            nums = (nums,)
        n = 0
        for i in nums:
            if 0 <= i < len(args):
                n += len(jax.tree_util.tree_leaves(args[i]))
        return n

    def _ir_audit(self, args: tuple, mk: tuple, compiled: Any) -> None:
        """Audit the program we just lowered+compiled (rlint deep tier).

        Runs ONLY on the compile path — a store-loaded executable was
        audited by the process that first built it — so dispatch never
        pays for this. Extraction is best-effort (``trace``/``as_text``
        are feature-detected); the rules themselves are pure and the
        whole thing is fenced so an audit bug can never break a build.
        Opt out with ``RL_TPU_NO_IR_AUDIT=1``.
        """
        if os.environ.get(_ENV_NO_IR_AUDIT, "") not in ("", "0"):
            return
        try:
            auditor = self._registry.auditor
            if auditor is None:
                from ..analysis.ir import get_ir_auditor

                auditor = get_ir_auditor()
            jaxpr = None
            trace = getattr(self._jit, "trace", None)
            if callable(trace):
                try:
                    jaxpr = trace(*args).jaxpr
                except Exception:
                    jaxpr = None
            try:
                text = compiled.as_text()
            except Exception:
                text = ""
            donate = self.jit_kwargs.get("donate_argnums")
            declared = donate is not None and donate != ()
            declared = declared or bool(self.jit_kwargs.get("donate_argnames"))
            report = auditor.audit(
                name=self.name,
                fingerprint=self.fingerprint,
                jaxpr=jaxpr,
                compiled_text=text,
                donated_leaves=self._donated_leaf_count(args),
                donation_declared=declared,
                contract=self.ir_contract,
                sig_key=mk,
            )
            with self._lock:
                self.ir_report = report
                if report.cost is not None:
                    self.static_flops = report.cost.flops
                    self.static_bytes = report.cost.bytes
        except Exception:
            pass

    def _note_flops(self, prog: Any) -> None:
        # cost_analysis is backend-dependent (absent on some platforms,
        # a one-element list on others) — best effort, never raises
        try:
            ca = prog.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                flops = float(ca.get("flops", 0.0))
                if flops > 0.0:
                    self.flops_per_call = flops
        except Exception:
            pass

    # -- dispatch --------------------------------------------------------

    def _dispatch(self, prog: Callable, args: tuple):
        """One executable dispatch, sampled for device-time attribution.
        The sampled path only stamps a timestamp and enqueues the output
        — the ready-wait happens on the attribution worker thread."""
        self._attr_tick += 1
        if self._attr_tick % _ATTR_SAMPLE_EVERY:
            return prog(*args)
        t0 = time.perf_counter()
        out = prog(*args)
        _ATTR.submit(self, t0, out)
        return out

    def __call__(self, *args: Any):
        self.stats["calls"] += 1
        if self._registry.aot_disabled:
            self.stats["jit_calls"] += 1
            with compile_scope(self.name):
                return self._dispatch(self._jit, args)
        mk = _memkey(args)
        with self._lock:
            prog = self._compiled.get(mk)
            fresh_load = mk in self._unvalidated
        if prog is None:
            src, _ = self.warmup(*args)
            fresh_load = src == "store"
            with self._lock:
                prog = self._compiled[mk]
        else:
            self.stats["aot_hits"] += 1
        if not fresh_load:
            return self._dispatch(prog, args)
        # first call of a deserialized executable: an incompatible entry
        # (stale jax/XLA, foreign topology) surfaces here — evict it and
        # fall back to a real compile rather than wedging the caller
        try:
            out = prog(*args)
        except Exception:
            self._registry.store.evict(self.store_key(args))
            with self._lock:
                self._compiled.pop(mk, None)
                self._unvalidated.discard(mk)
            prog, _ = self._compile(args)
            return prog(*args)
        with self._lock:
            self._unvalidated.discard(mk)
        return out

    def program_count(self) -> int:
        with self._lock:
            return len(self._compiled)


class WarmupHandle:
    """Background ``aot_warmup``: join via :meth:`result` (re-raises any
    warm-up failure there, never in the worker thread)."""

    def __init__(self, thread: threading.Thread, box: dict):
        self._thread = thread
        self._box = box

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: float | None = None) -> dict:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("aot_warmup still running")
        if "error" in self._box:
            raise self._box["error"]
        return self._box["result"]


class ProgramRegistry:
    """Process-wide table of named hot programs (weakly held).

    Construction wires the two persistent layers: the JAX compilation
    cache (:func:`rl_tpu.config.enable_compile_cache`, opt-out
    ``RL_TPU_NO_COMPILE_CACHE``) and the executable store (opt-out
    ``RL_TPU_NO_EXEC_STORE``), plus the compile-event listener feeding
    ``/metrics``.
    """

    def __init__(
        self,
        store: ExecutableStore | None = None,
        aot: bool | None = None,
        auditor: Any = None,
    ):
        from ..config import enable_compile_cache

        enable_compile_cache()
        install_compile_listener()
        self.store = store if store is not None else default_store()
        if aot is None:
            aot = os.environ.get(_ENV_NO_AOT, "") in ("", "0")
        self.aot_disabled = not aot
        # IR auditor receiving every compile's audit; None = the process
        # default (rl_tpu.analysis.ir.get_ir_auditor), which the tier-1
        # gate and /metrics read. Tests compiling deliberately-poisoned
        # fixtures pass an isolated IRAuditor here.
        self.auditor = auditor
        self._lock = threading.Lock()
        self._programs: dict[str, list] = {}  # name -> [weakref.ref]

    # -- registration ----------------------------------------------------

    def register(
        self,
        name: str,
        fn: Callable,
        *,
        fingerprint: str = "",
        ir_contract: dict | None = None,
        **jit_kwargs: Any,
    ) -> CachedProgram:
        """Create a :class:`CachedProgram` for ``fn`` under ``name``.
        ``jit_kwargs`` go to ``jax.jit`` (donate_argnums, in_shardings,
        ...); ``fingerprint`` distinguishes same-name/same-shape programs
        whose Python closures differ (model config, loss flavor);
        ``ir_contract`` declares semantic invariants the IR auditor
        enforces at compile time (``{"shard_local": True}`` = the program
        must never emit a collective — R103)."""
        prog = CachedProgram(
            name, fn, registry=self, fingerprint=fingerprint,
            ir_contract=ir_contract, **jit_kwargs
        )
        with self._lock:
            refs = self._programs.setdefault(name, [])
            refs.append(weakref.ref(prog))
        return prog

    def _alive(self, name: str) -> list[CachedProgram]:
        with self._lock:
            refs = self._programs.get(name, [])
            progs = [p for r in refs if (p := r()) is not None]
            self._programs[name] = [weakref.ref(p) for p in progs]
        return progs

    def names(self) -> list[str]:
        with self._lock:
            names = list(self._programs)
        return sorted(n for n in names if self._alive(n))

    def program(self, name: str) -> CachedProgram:
        """The most recently registered live program under ``name``."""
        progs = self._alive(name)
        if not progs:
            raise KeyError(f"no live program registered as {name!r}")
        return progs[-1]

    def programs(self) -> list[CachedProgram]:
        return [p for n in self.names() for p in self._alive(n)]

    # -- warm-up ---------------------------------------------------------

    def aot_warmup(
        self,
        names: Iterable[str] | None = None,
        *,
        programs: Iterable[CachedProgram] | None = None,
        background: bool = False,
    ) -> dict | WarmupHandle:
        """Drive ``lower().compile()`` (or store loads) for every recorded
        signature of the named programs (default: all live programs), or
        of an explicit ``programs`` iterable (how an engine warms exactly
        its own set). Returns ``{name: [(source, seconds), ...]}``, or a
        :class:`WarmupHandle` when ``background=True`` so warm-up overlaps
        host setup."""
        if programs is not None:
            todo = list(programs)
        else:
            want = list(names) if names is not None else self.names()
            todo = [p for name in want for p in self._alive(name)]

        def work() -> dict:
            out: dict[str, list] = {}
            for prog in todo:
                for sig in prog.signatures:
                    out.setdefault(prog.name, []).append(prog.warmup(*sig))
            return out

        if not background:
            return work()
        box: dict = {}

        def run():
            try:
                box["result"] = work()
            except BaseException as e:  # surfaced at .result()
                box["error"] = e

        t = threading.Thread(target=run, name="aot-warmup", daemon=True)
        t.start()
        return WarmupHandle(t, box)

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        """Aggregated per-name stats (all live instances summed)."""
        out: dict[str, dict] = {}
        for name in self.names():
            agg: dict[str, float] = {}
            n_exec = 0
            for p in self._alive(name):
                n_exec += p.program_count()
                for k, v in p.stats.items():
                    agg[k] = agg.get(k, 0) + v
            agg["executables"] = n_exec
            out[name] = agg
        return out


_default: ProgramRegistry | None = None
_default_lock = threading.Lock()


def get_program_registry() -> ProgramRegistry:
    """The process-default registry (created on first use)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ProgramRegistry()
            _wire_obs(_default)
        return _default


def set_program_registry(reg: ProgramRegistry | None) -> ProgramRegistry | None:
    """Swap the process default (tests pair this with a tmpdir store);
    returns the previous registry."""
    global _default
    with _default_lock:
        prev = _default
        _default = reg
        return prev


def _wire_obs(reg: ProgramRegistry) -> None:
    """Publish registry totals as gauges at scrape time (the per-compile
    counter/histogram are fed by the metrics listener, not here)."""
    try:
        from ..obs import get_registry

        obs = get_registry()
        g_progs = obs.gauge(
            "rl_tpu_aot_programs", "registered hot programs (live)"
        )
        g_exec = obs.gauge(
            "rl_tpu_aot_executables", "materialized executables across programs"
        )
        g_loads = obs.gauge(
            "rl_tpu_aot_store_loads", "executables deserialized from the store"
        )
        c_dev = obs.counter(
            "rl_tpu_program_device_seconds_total",
            "sampled device time attributed per program",
            labels=("program",),
        )
        c_samp = obs.counter(
            "rl_tpu_program_sampled_dispatches_total",
            "dispatches sampled for device-time attribution",
            labels=("program",),
        )
        g_mfu = obs.gauge(
            "rl_tpu_program_mfu",
            "model FLOPs utilization per program "
            "(set RL_TPU_PEAK_FLOPS to the accelerator peak to enable)",
            labels=("program",),
        )
        c_ir = obs.counter(
            "rl_tpu_ir_audit_findings_total",
            "IR-audit findings (R100-series) across audited programs",
            labels=("rule",),
        )
        g_audited = obs.gauge(
            "rl_tpu_ir_audited_programs",
            "program signatures audited at compile time",
        )
        g_pred = obs.gauge(
            "rl_tpu_program_predicted_mfu",
            "roofline-predicted MFU from the static IR cost model "
            "(needs RL_TPU_PEAK_FLOPS; RL_TPU_PEAK_BYTES_PER_S adds the "
            "transfer ceiling)",
            labels=("program",),
        )
        g_bw = obs.gauge(
            "rl_tpu_program_bandwidth_util",
            "memory-bandwidth utilization per program: static IR bytes "
            "per dispatch x sampled dispatch rate over "
            "RL_TPU_PEAK_BYTES_PER_S",
            labels=("program",),
        )
        # kernel-tier activation gauges (rl_tpu_kernel_active) live in
        # rl_tpu.kernels.registry; wiring them here keeps every /metrics
        # process that serves programs also reporting which Pallas
        # kernels those programs were lowered with
        try:
            from ..kernels.registry import wire_kernel_obs

            wire_kernel_obs()
        except Exception:
            pass

        def collect():
            stats = reg.stats()
            g_progs.set(float(len(stats)))
            g_exec.set(float(sum(s["executables"] for s in stats.values())))
            g_loads.set(float(sum(s["loads"] for s in stats.values())))
            try:
                peak = float(os.environ.get(_ENV_PEAK_FLOPS, "0") or 0.0)
            except ValueError:
                peak = 0.0
            try:
                bw = float(os.environ.get(_ENV_PEAK_BW, "0") or 0.0)
            except ValueError:
                bw = 0.0
            for name, s in stats.items():
                dev_s = float(s.get("device_s", 0.0))
                c_dev.set_total(dev_s, {"program": name})
                c_samp.set_total(float(s.get("device_samples", 0)), {"program": name})
                if peak > 0.0 and dev_s > 0.0:
                    mfu = float(s.get("device_flops", 0.0)) / dev_s / peak
                    g_mfu.set(mfu, {"program": name})
            if bw > 0.0:
                for p in reg.programs():
                    st = p.stats
                    dev_s = float(st.get("device_s", 0.0))
                    if dev_s > 0.0 and p.static_bytes > 0.0:
                        bps = (
                            p.static_bytes
                            * float(st.get("device_samples", 0))
                            / dev_s
                        )
                        g_bw.set(bps / bw, {"program": p.name})
            try:
                from ..analysis.ir import get_ir_auditor, roofline

                aud = reg.auditor or get_ir_auditor()
                for rule, n in aud.counts_by_rule().items():
                    c_ir.set_total(float(n), {"rule": rule})
                g_audited.set(float(aud.programs_audited()))
                if peak > 0.0:
                    for p in reg.programs():
                        rep = p.ir_report
                        if rep is None or rep.cost is None:
                            continue
                        rf = roofline(rep.cost, peak, bw)
                        if "predicted_mfu" in rf:
                            g_pred.set(rf["predicted_mfu"], {"program": p.name})
            except Exception:
                pass

        obs.register_collector(collect)
    except Exception:
        pass
