"""Persistent executable store: serialized XLA executables keyed by
program signature, so a restarted worker *loads* instead of recompiles.

Two cache layers exist and they solve different problems:

- JAX's persistent **compilation cache** (``jax_compilation_cache_dir``,
  enabled by default via :func:`rl_tpu.compile.ensure_persistent_cache`)
  caches the XLA *backend compile* keyed by optimized HLO. It still pays
  tracing + lowering on every process start, and its key is only
  computable *after* lowering.
- This **executable store** serializes the loaded executable itself
  (:mod:`jax.experimental.serialize_executable` —
  ``serialize``/``deserialize_and_load``) under a key computed purely
  from the *abstract call signature* (program name, arg
  shapes/dtypes/sharding spec, donation, backend, jax version). Because
  the key needs no tracing, a warm restart skips ``jit.lower()``
  entirely — which is where most cold-start time goes once the XLA
  cache is warm.

The key deliberately hashes the *registration-time* signature rather
than the jaxpr: two programs registered under the same name with the
same avals but different Python closures would collide, so the registry
includes a caller-supplied ``fingerprint`` (source hash) in the key.
Feature detection is per call — ``serialize`` raises on backends/
executables that don't support it, and every failure degrades to the
lower+compile path, never to an error.

Layout on disk: one ``<sha256>.jexec`` pickle per executable —
``(header_dict, payload, in_tree, out_tree)`` — plus a sibling
``.json`` header for ``ls``-ability. Writes are atomic (tmp + rename)
so concurrent fleet members racing on the same key are safe: last
writer wins with identical content.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
from typing import Any

__all__ = [
    "ExecutableStore",
    "abstract_like",
    "default_store",
    "set_default_store",
    "signature_of",
]

_ENV_DIR = "RL_TPU_EXEC_STORE_DIR"
_ENV_DISABLE = "RL_TPU_NO_EXEC_STORE"
_SUFFIX = ".jexec"


def _serialize_mod():
    """The serialize/deserialize entry points, or None when this jax
    build lacks them (graceful-fallback satellite)."""
    try:
        from jax.experimental import serialize_executable as se
    except Exception:
        return None
    if not hasattr(se, "serialize") or not hasattr(se, "deserialize_and_load"):
        return None
    return se


def _sharding_sig(sh: Any) -> str:
    """Normalize a leaf sharding for keying: default single-device
    placement reads as "" so a concrete array and the abstract
    ``ShapeDtypeStruct`` (sharding None) that describes it produce the
    SAME key — warm restarts build keys from abstract signatures."""
    if sh is None:
        return ""
    try:
        from jax.sharding import NamedSharding, SingleDeviceSharding

        if isinstance(sh, SingleDeviceSharding):
            return ""
        if isinstance(sh, NamedSharding):
            return f"NS({sorted(sh.mesh.shape.items())},{sh.spec})"
    except Exception:
        pass
    return repr(sh)


def abstract_like(tree: Any) -> Any:
    """Map a pytree of concrete arrays to ``ShapeDtypeStruct`` avals for
    AOT signatures. ``NamedSharding``s are preserved (an FSDP program's
    key must carry its layout); single-device placement is dropped so
    the aval keys identically to a hand-built abstract signature."""
    import jax
    from jax.sharding import NamedSharding

    def one(x):
        sh = getattr(x, "sharding", None)
        sh = sh if isinstance(sh, NamedSharding) else None
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

    return jax.tree.map(one, tree)


def signature_of(tree: Any) -> str:
    """Deterministic string signature of a pytree of arrays /
    ``ShapeDtypeStruct``s: tree structure + per-leaf shape/dtype/sharding.

    Computable from abstract avals alone — no tracing, no lowering —
    which is what lets a warm restart skip ``lower()`` entirely.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    parts = [str(treedef)]
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sh = getattr(leaf, "sharding", None)
        parts.append(f"{shape}:{dtype}:{_sharding_sig(sh)}")
    return "|".join(parts)


class ExecutableStore:
    """sha-keyed persistent store of serialized XLA executables.

    ``root=None`` resolves ``$RL_TPU_EXEC_STORE_DIR`` then
    ``~/.cache/rl_tpu/executables``; ``$RL_TPU_NO_EXEC_STORE=1``
    disables persistence (the in-memory layer still works, so duplicate
    programs within one process — e.g. N identical fleet engines —
    still compile once).
    """

    def __init__(self, root: str | None = None, *, memory_cache: bool = True):
        if root is None:
            root = os.environ.get(_ENV_DIR) or os.path.expanduser(
                "~/.cache/rl_tpu/executables"
            )
        self.root = root
        self.disabled = os.environ.get(_ENV_DISABLE, "") not in ("", "0")
        self._lock = threading.Lock()
        self._mem: dict[str, Any] | None = {} if memory_cache else None
        self.stats = {"hits": 0, "misses": 0, "saves": 0, "errors": 0, "mem_hits": 0}

    # -- keys -----------------------------------------------------------
    def key_for(
        self,
        name: str,
        args: Any,
        *,
        backend: str | None = None,
        fingerprint: str = "",
        extra: str = "",
    ) -> str:
        """Content key from the abstract call signature (never lowers)."""
        import jax

        if backend is None:
            backend = jax.default_backend()
        h = hashlib.sha256()
        for part in (
            "rl_tpu.exec.v1",
            jax.__version__,
            backend,
            name,
            fingerprint,
            extra,
            signature_of(args),
        ):
            h.update(part.encode())
            h.update(b"\0")
        return h.hexdigest()

    # -- paths ----------------------------------------------------------
    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + _SUFFIX)

    def has(self, key: str) -> bool:
        if self._mem is not None and key in self._mem:
            return True
        return not self.disabled and os.path.exists(self._path(key))

    def keys(self) -> list[str]:
        try:
            return sorted(
                f[: -len(_SUFFIX)]
                for f in os.listdir(self.root)
                if f.endswith(_SUFFIX)
            )
        except OSError:
            return []

    def evict(self, key: str) -> None:
        """Drop one entry everywhere (memory + disk); used when a loaded
        executable fails its first call (stale/foreign entry)."""
        with self._lock:
            if self._mem is not None:
                self._mem.pop(key, None)
        for p in (self._path(key), self._path(key)[: -len(_SUFFIX)] + ".json"):
            try:
                os.remove(p)
            except OSError:
                pass

    def clear(self) -> None:
        with self._lock:
            if self._mem is not None:
                self._mem.clear()
        for key in self.keys():
            for p in (self._path(key), self._path(key)[: -len(_SUFFIX)] + ".json"):
                try:
                    os.remove(p)
                except OSError:
                    pass

    # -- save/load ------------------------------------------------------
    def save(self, key: str, compiled: Any, *, meta: dict | None = None) -> bool:
        """Serialize ``compiled`` under ``key``. Returns False (never
        raises) when the backend/executable doesn't support serialization."""
        if self._mem is not None:
            with self._lock:
                self._mem[key] = compiled
        if self.disabled:
            return False
        se = _serialize_mod()
        if se is None:
            return False
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            header = {
                "version": 1,
                "key": key,
                "created": time.time(),
                **(meta or {}),
            }
            blob = pickle.dumps((header, payload, in_tree, out_tree), protocol=4)
        except Exception:
            with self._lock:
                self.stats["errors"] += 1
            return False
        try:
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._path(key))
            finally:
                if os.path.exists(tmp):
                    os.remove(tmp)
            with open(self._path(key)[: -len(_SUFFIX)] + ".json", "w") as f:
                json.dump({**header, "bytes": len(blob)}, f)
        except OSError:
            with self._lock:
                self.stats["errors"] += 1
            return False
        with self._lock:
            self.stats["saves"] += 1
        return True

    def load(self, key: str) -> Any | None:
        """Deserialize the executable stored under ``key``, or None on
        miss / unsupported / corrupt entry (corrupt entries are evicted)."""
        if self._mem is not None:
            with self._lock:
                hit = self._mem.get(key)
            if hit is not None:
                with self._lock:
                    self.stats["mem_hits"] += 1
                return hit
        if self.disabled:
            return None
        path = self._path(key)
        se = _serialize_mod()
        if se is None or not os.path.exists(path):
            with self._lock:
                self.stats["misses"] += 1
            return None
        try:
            with open(path, "rb") as f:
                header, payload, in_tree, out_tree = pickle.load(f)
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            # a corrupt/incompatible entry must not wedge startup: evict
            # it so the compile path rebuilds and overwrites.
            with self._lock:
                self.stats["errors"] += 1
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        if self._mem is not None:
            with self._lock:
                self._mem[key] = compiled
        with self._lock:
            self.stats["hits"] += 1
        return compiled


_default: ExecutableStore | None = None
_default_lock = threading.Lock()


def default_store() -> ExecutableStore:
    """Process-default store (what registered programs use unless a
    store is passed explicitly)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ExecutableStore()
        return _default


def set_default_store(store: ExecutableStore | None) -> ExecutableStore | None:
    """Swap the process default (tests isolate themselves with a tmpdir
    store); returns the previous one so callers can restore it."""
    global _default
    with _default_lock:
        prev = _default
        _default = store
        return prev
