"""Config system: component registry + recursive instantiation from dicts/YAML.

Redesign of the reference's hydra/omegaconf ConfigStore
(reference: torchrl/trainers/algorithms/configs/ — a ``*Config`` dataclass
with ``_target_`` per component, registered in groups; YAML recipes compose
object graphs). Same recipe shape without the hydra dependency:

- a config node is a mapping with ``_target_`` naming either a registered
  component (``"env/cartpole"``) or a dotted import path
  (``"rl_tpu.envs.CartPoleEnv"``);
- nested mappings/sequences instantiate depth-first;
- ``_partial_: true`` returns a ``functools.partial`` instead of calling.

>>> cfg = load_yaml("recipe.yaml")
>>> env = instantiate(cfg["env"])
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Any, Callable, Mapping, Sequence

__all__ = ["register", "get_component", "instantiate", "load_yaml", "to_dict", "REGISTRY"]

REGISTRY: dict[str, Callable] = {}


def register(name: str, target: Callable | None = None):
    """Register a component constructor; usable as decorator."""

    def deco(t):
        if name in REGISTRY and REGISTRY[name] is not t:
            raise ValueError(f"config component {name!r} already registered")
        REGISTRY[name] = t
        return t

    return deco(target) if target is not None else deco


def _resolve_dotted(path: str) -> Callable:
    mod, _, attr = path.rpartition(".")
    return getattr(importlib.import_module(mod), attr)


def get_component(target: str) -> Callable:
    entry = REGISTRY.get(target, _BUILTINS.get(target))
    if entry is not None:
        # builtin entries are dotted-path strings, resolved lazily so that
        # importing rl_tpu.config alone stays cheap
        return _resolve_dotted(entry) if isinstance(entry, str) else entry
    if "." in target:
        return _resolve_dotted(target)
    raise KeyError(f"unknown component {target!r} (not registered, not importable)")


def instantiate(node: Any) -> Any:
    """Depth-first instantiation of a config tree."""
    if isinstance(node, Mapping):
        out = {k: instantiate(v) for k, v in node.items() if not k.startswith("_")}
        if "_target_" in node:
            fn = get_component(node["_target_"])
            if node.get("_partial_", False):
                return functools.partial(fn, **out)
            return fn(**out)
        return out
    if isinstance(node, str):
        return node
    if isinstance(node, Sequence):
        return [instantiate(v) for v in node]
    return node


def load_yaml(path: str) -> dict:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def to_dict(obj: Any) -> Any:
    """Dataclass tree -> plain dict (for hparam logging / YAML dump)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_dict(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, Mapping):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


# Standard component registry (the reference's config groups). Values are
# dotted import paths resolved lazily by get_component.
_BUILTINS: dict[str, str] = {
    "env/pendulum": "rl_tpu.envs.PendulumEnv",
    "env/cartpole": "rl_tpu.envs.CartPoleEnv",
    "env/vmap": "rl_tpu.envs.VmapEnv",
    "env/transformed": "rl_tpu.envs.TransformedEnv",
    "transform/reward_sum": "rl_tpu.envs.RewardSum",
    "transform/reward_scaling": "rl_tpu.envs.RewardScaling",
    "transform/step_counter": "rl_tpu.envs.StepCounter",
    "transform/init_tracker": "rl_tpu.envs.InitTracker",
    "transform/cat_frames": "rl_tpu.envs.CatFrames",
    "transform/obs_norm": "rl_tpu.envs.ObservationNorm",
    "network/mlp": "rl_tpu.modules.MLP",
    "network/concat_mlp": "rl_tpu.modules.ConcatMLP",
    "network/conv": "rl_tpu.modules.ConvNet",
    "network/dueling": "rl_tpu.modules.DuelingMLP",
    "network/tanh_policy": "rl_tpu.modules.TanhPolicy",
    "module/td": "rl_tpu.modules.TDModule",
    "actor/probabilistic": "rl_tpu.modules.ProbabilisticActor",
    "actor/qvalue": "rl_tpu.modules.QValueActor",
    "operator/value": "rl_tpu.modules.ValueOperator",
    "loss/ppo_clip": "rl_tpu.objectives.ClipPPOLoss",
    "loss/ppo": "rl_tpu.objectives.PPOLoss",
    "loss/a2c": "rl_tpu.objectives.A2CLoss",
    "loss/sac": "rl_tpu.objectives.SACLoss",
    "loss/dqn": "rl_tpu.objectives.DQNLoss",
    "loss/td3": "rl_tpu.objectives.TD3Loss",
    "loss/ddpg": "rl_tpu.objectives.DDPGLoss",
    "loss/iql": "rl_tpu.objectives.IQLLoss",
    "loss/cql": "rl_tpu.objectives.CQLLoss",
    "loss/redq": "rl_tpu.objectives.REDQLoss",
    "storage/device": "rl_tpu.data.DeviceStorage",
    "storage/memmap": "rl_tpu.data.MemmapStorage",
    "sampler/random": "rl_tpu.data.RandomSampler",
    "sampler/prioritized": "rl_tpu.data.PrioritizedSampler",
    "sampler/slice": "rl_tpu.data.SliceSampler",
    "sampler/without_replacement": "rl_tpu.data.SamplerWithoutReplacement",
    "buffer/replay": "rl_tpu.data.ReplayBuffer",
    "program/on_policy": "rl_tpu.trainers.OnPolicyProgram",
    "program/on_policy_config": "rl_tpu.trainers.OnPolicyConfig",
    "program/off_policy": "rl_tpu.trainers.OffPolicyProgram",
    "program/off_policy_config": "rl_tpu.trainers.OffPolicyConfig",
}
