"""Config system: component registry + recursive instantiation from dicts/YAML.

Redesign of the reference's hydra/omegaconf ConfigStore
(reference: torchrl/trainers/algorithms/configs/ — a ``*Config`` dataclass
with ``_target_`` per component, registered in groups; YAML recipes compose
object graphs). Same recipe shape without the hydra dependency:

- a config node is a mapping with ``_target_`` naming either a registered
  component (``"env/cartpole"``) or a dotted import path
  (``"rl_tpu.envs.CartPoleEnv"``);
- nested mappings/sequences instantiate depth-first;
- ``_partial_: true`` returns a ``functools.partial`` instead of calling.

>>> cfg = load_yaml("recipe.yaml")
>>> env = instantiate(cfg["env"])
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Any, Callable, Mapping, Sequence

__all__ = [
    "register", "get_component", "instantiate", "load_yaml", "to_dict",
    "REGISTRY", "enable_compile_cache",
]

# -- persistent compilation cache (ROADMAP item 5) --------------------------
#
# Wired by default: the first ProgramRegistry (any registered trainer or
# serving engine) calls enable_compile_cache(), so every XLA backend compile
# lands in an on-disk cache keyed by optimized HLO and a process restart
# skips the backend-compile half of cold start. Opt out with
# RL_TPU_NO_COMPILE_CACHE=1; point the cache elsewhere (CI sandboxes, test
# tmpdirs) with RL_TPU_COMPILE_CACHE_DIR.

_ENV_NO_CACHE = "RL_TPU_NO_COMPILE_CACHE"
_ENV_CACHE_DIR = "RL_TPU_COMPILE_CACHE_DIR"


def enable_compile_cache() -> str | None:
    """Idempotently enable JAX's persistent compilation cache. Returns the
    cache dir in use, or None when opted out. A dir already configured
    (bench/_setup_jax, tests/conftest) is respected, not overridden."""
    import os

    if os.environ.get(_ENV_NO_CACHE, "") not in ("", "0"):
        return None
    import jax

    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    path = os.environ.get(_ENV_CACHE_DIR) or os.path.expanduser(
        "~/.cache/rl_tpu_jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", path)
    # fused trainer programs are the target; sub-second toy programs churn
    # the cache for no win
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path

REGISTRY: dict[str, Callable] = {}


def register(name: str, target: Callable | None = None):
    """Register a component constructor; usable as decorator."""

    def deco(t):
        if name in REGISTRY and REGISTRY[name] is not t:
            raise ValueError(f"config component {name!r} already registered")
        REGISTRY[name] = t
        return t

    return deco(target) if target is not None else deco


def _resolve_dotted(path: str) -> Callable:
    mod, _, attr = path.rpartition(".")
    return getattr(importlib.import_module(mod), attr)


def get_component(target: str) -> Callable:
    entry = REGISTRY.get(target, _BUILTINS.get(target))
    if entry is not None:
        # builtin entries are dotted-path strings, resolved lazily so that
        # importing rl_tpu.config alone stays cheap
        return _resolve_dotted(entry) if isinstance(entry, str) else entry
    if "." in target:
        return _resolve_dotted(target)
    raise KeyError(f"unknown component {target!r} (not registered, not importable)")


def instantiate(node: Any) -> Any:
    """Depth-first instantiation of a config tree."""
    if isinstance(node, Mapping):
        out = {k: instantiate(v) for k, v in node.items() if not k.startswith("_")}
        if "_target_" in node:
            fn = get_component(node["_target_"])
            if node.get("_partial_", False):
                return functools.partial(fn, **out)
            return fn(**out)
        return out
    if isinstance(node, str):
        return node
    if isinstance(node, Sequence):
        return [instantiate(v) for v in node]
    return node


def load_yaml(path: str) -> dict:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f)


def to_dict(obj: Any) -> Any:
    """Dataclass tree -> plain dict (for hparam logging / YAML dump)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_dict(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, Mapping):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


# Standard component registry (the reference's config groups,
# trainers/algorithms/configs/__init__.py registers a *Config per component).
# Values are dotted import paths resolved lazily by get_component, built from
# per-group tables below so importing rl_tpu.config stays import-cheap.
_BUILTINS: dict[str, str] = {}


def _snake(name: str) -> str:
    import re

    # lower→Upper, UPPER→Upper-lower and digit→Upper-lower boundaries
    # (A2C→a2c, TD3→td3, DreamerV3Actor→dreamer_v3_actor)
    return re.sub(
        r"(?<=[a-z])(?=[A-Z])|(?<=[A-Z0-9])(?=[A-Z][a-z])", "_", name
    ).lower()


def _add_group(group: str, module: str, names: Sequence[str], strip: str = "") -> None:
    for n in names:
        short = n[: -len(strip)] if strip and n.endswith(strip) and n != strip else n
        _BUILTINS.setdefault(f"{group}/{_snake(short)}", f"{module}.{n}")


_add_group("env", "rl_tpu.envs", [
    "CartPoleEnv", "PendulumEnv", "MountainCarEnv", "MountainCarContinuousEnv",
    "AcrobotEnv", "TicTacToeEnv", "TradingEnv", "NavigationEnv",
    "VmapEnv", "TransformedEnv", "ModelBasedEnv",
    "FrameSkipEnv", "NoopResetEnv", "ConditionalSkipEnv", "MultiActionEnv",
], strip="Env")
_add_group("env", "rl_tpu.envs.llm", ["ChatEnv", "DatasetChatEnv"], strip="Env")
_add_group("env", "rl_tpu.envs.libs.gym", ["GymEnv"], strip="Env")
_add_group("transform", "rl_tpu.envs", [
    "Compose", "RewardSum", "RewardScaling", "RewardClipping", "StepCounter",
    "InitTracker", "CatFrames", "CatTensors", "ObservationNorm", "VecNorm",
    "DoubleToFloat", "DTypeCast", "FlattenObservation", "UnsqueezeTransform",
    "SqueezeTransform", "RenameTransform", "ActionScaling", "TimeMaxPool",
    "GrayScale", "Resize", "CenterCrop", "ToFloatImage",
    "ActionMask", "ActionDiscretizer", "BinarizeReward", "ClipTransform",
    "EndOfLifeTransform", "ExcludeTransform", "SelectTransform", "FiniteCheck",
    "Hash", "LineariseRewards", "ModuleTransform", "PermuteTransform",
    "SignTransform", "StackTransform", "TensorDictPrimer", "Timer",
    "TrajCounter", "TargetReturn", "Crop", "DiscreteActionProjection",
    "UnaryTransform", "RandomTruncationTransform",
], strip="Transform")
_add_group("network", "rl_tpu.modules", [
    "MLP", "ConcatMLP", "ConvNet", "DuelingMLP", "TanhPolicy", "NoisyDense",
    "MultiAgentMLP", "QMixer", "VDNMixer", "NormalParamExtractor",
])
_add_group("module", "rl_tpu.modules", ["TDModule", "TDSequential"], strip="Module")
_add_group("actor", "rl_tpu.modules", [
    "ProbabilisticActor", "QValueActor", "RandomPolicy", "MultiStepActorWrapper",
], strip="Actor")
_add_group("operator", "rl_tpu.modules", ["ValueOperator", "ActorValueOperator"], strip="Operator")
_add_group("exploration", "rl_tpu.modules", [
    "EGreedyModule", "AdditiveGaussianModule", "OrnsteinUhlenbeckModule",
    "GSDEModule", "ConsistentDropout",
], strip="Module")
_add_group("dist", "rl_tpu.modules", [
    "Normal", "TanhNormal", "TruncatedNormal", "Delta", "TanhDelta",
    "Categorical", "OneHotCategorical", "MaskedCategorical", "Ordinal",
    "OneHotOrdinal",
])
_add_group("planner", "rl_tpu.modules", ["CEMPlanner", "MPPIPlanner"], strip="Planner")
_add_group("loss", "rl_tpu.objectives", [
    "PPOLoss", "ClipPPOLoss", "KLPENPPOLoss", "A2CLoss", "ReinforceLoss",
    "SACLoss", "DiscreteSACLoss", "DQNLoss", "DistributionalDQNLoss",
    "DDPGLoss", "TD3Loss", "TD3BCLoss", "CQLLoss", "DiscreteCQLLoss",
    "IQLLoss", "REDQLoss", "CrossQLoss", "BCLoss", "GAILLoss", "ACTLoss",
    "IPPOLoss", "MAPPOLoss", "QMixerLoss", "DreamerActorLoss",
    "DreamerValueLoss", "DreamerV3ModelLoss", "DreamerV3ActorLoss",
    "DreamerV3ValueLoss",
], strip="Loss")
_add_group("estimator", "rl_tpu.objectives", [
    "GAE", "MultiAgentGAE", "TD0Estimator", "TD1Estimator",
    "TDLambdaEstimator", "VTrace",
], strip="Estimator")
_add_group("updater", "rl_tpu.objectives", ["SoftUpdate", "HardUpdate"], strip="Update")
_add_group("storage", "rl_tpu.data.replay", [
    "DeviceStorage", "ListStorage", "MemmapStorage", "CompressedListStorage",
    "StorageEnsemble",
], strip="Storage")
_add_group("sampler", "rl_tpu.data.replay", [
    "RandomSampler", "SamplerWithoutReplacement", "PrioritizedSampler",
    "HostPrioritizedSampler", "SliceSampler", "SliceSamplerWithoutReplacement",
    "PrioritizedSliceSampler", "StalenessAwareSampler",
], strip="Sampler")
_add_group("writer", "rl_tpu.data.replay", [
    "RoundRobinWriter", "MaxValueWriter", "ImmutableDatasetWriter",
], strip="Writer")
_add_group("buffer", "rl_tpu.data.replay", ["ReplayBuffer", "ReplayBufferEnsemble"], strip="Buffer")
_add_group("postproc", "rl_tpu.data", [
    "MultiStep", "DensifyReward", "Reward2GoTransform", "BurnInTransform",
], strip="Transform")
_add_group("model", "rl_tpu.models", [
    "RSSM", "RSSMv3", "TransformerLM", "DecisionTransformer", "ACTModel",
], strip="Model")
_add_group("collector", "rl_tpu.collectors", [
    "Collector", "HostCollector", "LLMCollector",
], strip="Collector")
_add_group("pool", "rl_tpu.collectors", ["ThreadedEnvPool", "ProcessEnvPool"], strip="EnvPool")
_add_group("serve", "rl_tpu.modules", ["InferenceServer"])
_add_group("comm", "rl_tpu.comm", [
    "Watchdog", "Interruptor", "ServiceRegistry", "TCPServiceRegistry",
])
_add_group("storage", "rl_tpu.data", ["VideoCodecStorage"], strip="Storage")
_add_group("postproc", "rl_tpu.data", ["AddActionChunks"])
_add_group("logger", "rl_tpu.record.loggers", [
    "CSVLogger", "TensorboardLogger", "WandbLogger", "MLFlowLogger",
    "NullLogger", "MultiLogger",
], strip="Logger")
_add_group("scheme", "rl_tpu.weight_update.schemes", [
    "SharedProgramScheme", "DevicePutScheme", "DoubleBufferScheme",
], strip="Scheme")
_add_group("trainer", "rl_tpu.trainers", ["Trainer"])
_add_group("program", "rl_tpu.trainers", [
    "OnPolicyProgram", "OffPolicyProgram", "OnPolicyConfig", "OffPolicyConfig",
], strip="Program")
_BUILTINS.update({
    # aliases kept from the round-1 registry + builder entry points
    "env/cartpole": "rl_tpu.envs.CartPoleEnv",
    "env/hopper": "rl_tpu.envs.HopperEnv",
    "env/team_counting": "rl_tpu.testing.MultiAgentCountingEnv",
    "env/walker2d": "rl_tpu.envs.Walker2dEnv",
    "env/mountaincar": "rl_tpu.envs.MountainCarEnv",
    "env/tictactoe": "rl_tpu.envs.TicTacToeEnv",
    "actor/qvalue": "rl_tpu.modules.QValueActor",
    "transform/obs_norm": "rl_tpu.envs.ObservationNorm",
    "loss/td3_bc": "rl_tpu.objectives.TD3BCLoss",
    "loss/c51": "rl_tpu.objectives.DistributionalDQNLoss",
    "loss/kl_pen_ppo": "rl_tpu.objectives.KLPENPPOLoss",
    "model/rssm_v3": "rl_tpu.models.RSSMv3",
    "postproc/reward2go": "rl_tpu.data.Reward2GoTransform",
    "sampler/without_replacement": "rl_tpu.data.SamplerWithoutReplacement",
    "buffer/replay": "rl_tpu.data.ReplayBuffer",
    "env/gym": "rl_tpu.envs.libs.gym.GymEnv",
    "env/brax": "rl_tpu.envs.libs.brax.BraxEnv",
    "env/jumanji": "rl_tpu.envs.libs.jumanji.JumanjiEnv",
    "env/pettingzoo": "rl_tpu.envs.libs.pettingzoo.PettingZooEnv",
    "loss/ppo_clip": "rl_tpu.objectives.ClipPPOLoss",
    "network/conv": "rl_tpu.modules.ConvNet",
    "network/dueling": "rl_tpu.modules.DuelingMLP",
    "module/td": "rl_tpu.modules.TDModule",
    "program/on_policy_config": "rl_tpu.trainers.OnPolicyConfig",
    "program/off_policy_config": "rl_tpu.trainers.OffPolicyConfig",
    "trainer/ppo": "rl_tpu.trainers.make_ppo_trainer",
    "trainer/a2c": "rl_tpu.trainers.make_a2c_trainer",
    "trainer/impala": "rl_tpu.trainers.make_impala_trainer",
    "trainer/mappo": "rl_tpu.trainers.make_mappo_trainer",
    "trainer/sac": "rl_tpu.trainers.make_sac_trainer",
    "trainer/dqn": "rl_tpu.trainers.make_dqn_trainer",
    "trainer/td3": "rl_tpu.trainers.make_td3_trainer",
    "trainer/ddpg": "rl_tpu.trainers.make_ddpg_trainer",
    "trainer/redq": "rl_tpu.trainers.make_redq_trainer",
    "trainer/crossq": "rl_tpu.trainers.make_crossq_trainer",
    "trainer/qmix": "rl_tpu.trainers.make_qmix_trainer",
    "trainer/iql_offline": "rl_tpu.trainers.train_iql",
    "trainer/cql_offline": "rl_tpu.trainers.train_cql",
    "trainer/grpo": "rl_tpu.trainers.GRPOTrainer",
    "tokenizer/simple": "rl_tpu.data.llm.SimpleTokenizer",
    "dataset/arithmetic": "rl_tpu.envs.llm.arithmetic_dataset",
    "dataset/copy": "rl_tpu.envs.llm.copy_dataset",
    "scorer/exact_match": "rl_tpu.envs.llm.ExactMatchScorer",
    "scorer/sum": "rl_tpu.envs.llm.SumScorer",
    "scorer/format": "rl_tpu.envs.llm.FormatScorer",
    "llm_transform/kl_reward": "rl_tpu.envs.llm.KLRewardTransform",
    "llm_transform/policy_version": "rl_tpu.envs.llm.PolicyVersion",
    "llm_transform/python_tool": "rl_tpu.envs.llm.PythonToolTransform",
    # round-4 components
    "env/chess": "rl_tpu.envs.ChessEnv",
    "env/toy_vla": "rl_tpu.envs.ToyVLAEnv",
    "env/dm_control": "rl_tpu.envs.libs.dm_control.DMControlEnv",
    "actor/diffusion": "rl_tpu.modules.DiffusionActor",
    "actor/tiny_vla": "rl_tpu.modules.TinyVLA",
    "model/gp_world": "rl_tpu.modules.GPWorldModel",
    "loss/diffusion_bc": "rl_tpu.objectives.DiffusionBCLoss",
    "loss/pilco_cost": "rl_tpu.objectives.ExponentialQuadraticCost",
    "loss/dpo": "rl_tpu.objectives.llm.DPOLoss",
    "loss/pairwise_reward": "rl_tpu.objectives.llm.PairwiseRewardLoss",
    "dataset/gsm8k": "rl_tpu.envs.llm.gsm8k_dataset",
    "dataset/countdown": "rl_tpu.envs.llm.countdown_dataset",
    "dataset/ifeval": "rl_tpu.envs.llm.ifeval_dataset",
    "dataset/math_expression": "rl_tpu.envs.llm.math_expression_dataset",
    "dataset/minari_h5": "rl_tpu.data.MinariH5Dataset",
    "dataset/atari_dqn": "rl_tpu.data.AtariDQNDataset",
    "dataset/lerobot": "rl_tpu.data.LeRobotDataset",
    "scorer/gsm8k": "rl_tpu.envs.llm.GSM8KScorer",
    "scorer/countdown": "rl_tpu.envs.llm.CountdownScorer",
    "scorer/ifeval": "rl_tpu.envs.llm.IFEvalScorer",
    "tokenizer/action_uniform": "rl_tpu.data.UniformActionTokenizer",
    "tokenizer/action_vocab_tail": "rl_tpu.data.VocabTailActionTokenizer",
    "collector/mesh": "rl_tpu.collectors.MeshCollector",
})
