"""Typed recipe dataclasses over the component registry.

Redesign of the reference's hydra config surface (reference:
torchrl/trainers/algorithms/configs/__init__.py — dataclasses registered in
a ConfigStore, one per component, composed from YAML into full algorithm
recipes). Here each ``*Recipe`` dataclass mirrors the keyword surface of one
``make_*_trainer`` builder; ``as_node()`` lowers it to a ``_target_`` config
tree (the exchange format), ``dump_yaml``/``load_recipe`` round-trip it, and
``build()`` instantiates the actual Trainer via :mod:`rl_tpu.config`.

YAML and dataclasses are two views of the same node tree, so a user can
author either and the driver path is identical:

>>> PPORecipe(env=EnvNode("env/cartpole"), total_steps=1000).build().train(0)
>>> load_recipe("examples/configs/ppo_cartpole.yaml").train(0)
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

from .config import instantiate, load_yaml

__all__ = [
    "EnvNode",
    "Node",
    "Recipe",
    "PPORecipe",
    "A2CRecipe",
    "SACRecipe",
    "DQNRecipe",
    "TD3Recipe",
    "as_node",
    "from_node",
    "dump_yaml",
    "load_recipe",
    "RECIPES",
]


@dataclass
class Node:
    """A generic registry-addressed component: ``target`` + kwargs."""

    target: str
    kwargs: dict[str, Any] = field(default_factory=dict)

    def as_node(self) -> dict:
        return {"_target_": self.target, **{k: as_node(v) for k, v in self.kwargs.items()}}


@dataclass
class EnvNode:
    """Environment node with optional vmap batching and transform stack."""

    target: str
    num_envs: int = 0  # 0 = leave unbatched
    transforms: list[Node] = field(default_factory=list)
    kwargs: dict[str, Any] = field(default_factory=dict)

    def as_node(self) -> dict:
        node: dict = {"_target_": self.target, **{k: as_node(v) for k, v in self.kwargs.items()}}
        if self.num_envs:
            node = {"_target_": "env/vmap", "env": node, "num_envs": self.num_envs}
        if self.transforms:
            ts = [t.as_node() for t in self.transforms]
            tf = ts[0] if len(ts) == 1 else {"_target_": "transform/compose", "transforms": ts}
            node = {"_target_": "env/transformed", "env": node, "transform": tf}
        return node


@dataclass
class Recipe:
    """Base: fields lower to kwargs of the trainer builder named by TARGET."""

    TARGET = ""  # class attr, overridden

    def as_node(self) -> dict:
        out: dict = {"_target_": type(self).TARGET}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name == "extra":
                out.update({k: as_node(x) for k, x in v.items()})
            else:
                # None is kept: the builders accept it, and dropping it would
                # silently revert fields (e.g. DQN n_step=None) to defaults
                out[f.name] = as_node(v)
        return out

    def build(self):
        return instantiate(self.as_node())


@dataclass
class PPORecipe(Recipe):
    TARGET = "trainer/ppo"
    env: EnvNode = field(default_factory=lambda: EnvNode("env/cartpole", num_envs=8))
    total_steps: int = 100
    frames_per_batch: int = 2048
    gamma: float = 0.99
    lmbda: float = 0.95
    log_interval: int = 10
    logger: Node | None = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class A2CRecipe(Recipe):
    TARGET = "trainer/a2c"
    env: EnvNode = field(default_factory=lambda: EnvNode("env/cartpole", num_envs=8))
    total_steps: int = 100
    frames_per_batch: int = 1024
    gamma: float = 0.99
    lmbda: float = 0.95
    learning_rate: float = 7e-4
    log_interval: int = 10
    logger: Node | None = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class SACRecipe(Recipe):
    TARGET = "trainer/sac"
    env: EnvNode = field(default_factory=lambda: EnvNode("env/pendulum", num_envs=8))
    total_steps: int = 100
    frames_per_batch: int = 1024
    buffer_capacity: int = 1_000_000
    prioritized: bool = False
    n_step: int | None = None
    gamma: float = 0.99
    log_interval: int = 10
    logger: Node | None = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class DQNRecipe(Recipe):
    TARGET = "trainer/dqn"
    env: EnvNode = field(default_factory=lambda: EnvNode("env/cartpole", num_envs=8))
    total_steps: int = 100
    frames_per_batch: int = 512
    buffer_capacity: int = 1_000_000
    prioritized: bool = True
    n_step: int | None = 3
    gamma: float = 0.99
    eps_init: float = 1.0
    eps_end: float = 0.05
    annealing_num_steps: int = 100_000
    log_interval: int = 10
    logger: Node | None = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class TD3Recipe(Recipe):
    TARGET = "trainer/td3"
    env: EnvNode = field(default_factory=lambda: EnvNode("env/pendulum", num_envs=8))
    total_steps: int = 100
    frames_per_batch: int = 1024
    buffer_capacity: int = 1_000_000
    gamma: float = 0.99
    exploration_sigma: float = 0.1
    log_interval: int = 10
    logger: Node | None = None
    extra: dict[str, Any] = field(default_factory=dict)


RECIPES = {r.TARGET: r for r in (PPORecipe, A2CRecipe, SACRecipe, DQNRecipe, TD3Recipe)}


def as_node(v: Any) -> Any:
    """Lower dataclass views (Recipe/EnvNode/Node) into plain node trees."""
    if hasattr(v, "as_node"):
        return v.as_node()
    if isinstance(v, dict):
        return {k: as_node(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [as_node(x) for x in v]
    return v


def from_node(node: dict) -> Recipe:
    """Lift a trainer node tree back into its typed Recipe (round-trip)."""
    cls = RECIPES[node["_target_"]]
    names = {f.name for f in dataclasses.fields(cls)}
    kw: dict[str, Any] = {}
    extra: dict[str, Any] = {}
    for k, v in node.items():
        if k == "_target_":
            continue
        if k == "env":
            kw["env"] = _env_from_node(v)
        elif k == "logger" and isinstance(v, dict):
            kw["logger"] = Node(v["_target_"], {x: y for x, y in v.items() if x != "_target_"})
        elif k in names:
            kw[k] = v
        else:
            extra[k] = v
    return cls(extra=extra, **kw)


def _env_from_node(node: dict) -> EnvNode:
    transforms: list[Node] = []
    num_envs = 0
    if node.get("_target_") == "env/transformed":
        tf = node["transform"]
        ts = tf["transforms"] if tf.get("_target_") == "transform/compose" else [tf]
        transforms = [
            Node(t["_target_"], {k: v for k, v in t.items() if k != "_target_"}) for t in ts
        ]
        node = node["env"]
    if node.get("_target_") == "env/vmap":
        num_envs = node["num_envs"]
        node = node["env"]
    kwargs = {k: v for k, v in node.items() if k != "_target_"}
    return EnvNode(node["_target_"], num_envs=num_envs, transforms=transforms, kwargs=kwargs)


def dump_yaml(recipe: Recipe, path: str) -> None:
    import yaml

    with open(path, "w") as f:
        yaml.safe_dump({"trainer": recipe.as_node()}, f, sort_keys=False)


def load_recipe(path: str):
    """YAML recipe file -> ready-to-run Trainer (the YAML-alone driver path)."""
    cfg = load_yaml(path)
    return instantiate(cfg["trainer"] if "trainer" in cfg else cfg)
