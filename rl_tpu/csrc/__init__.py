"""Native extension loader: host segment trees with graceful fallback.

Mirrors the reference's optional-extension pattern (reference:
torchrl/_extension.py:40 ``_init_extension`` / :54 ``EXTENSION_WARNING`` —
soft-fail to Python when the compiled module is missing): the C++ tree
(segment_tree.cpp) is compiled on first import with g++ into a cached
shared library and bound via ctypes; if no toolchain is available, a
numpy fallback with identical semantics loads instead
(``SumSegmentTree.IS_NATIVE`` tells you which you got).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings

import numpy as np

__all__ = ["SumSegmentTree", "MinSegmentTree", "EXTENSION_WARNING"]

EXTENSION_WARNING = (
    "rl_tpu C++ segment-tree extension could not be built; falling back to "
    "the numpy implementation (slower host-side prioritized sampling)."
)

_LIB = None


def _build_and_load():
    global _LIB
    if _LIB is not None:
        return _LIB
    src = os.path.join(os.path.dirname(__file__), "segment_tree.cpp")
    cache_dir = os.path.join(os.path.dirname(__file__), "_build")
    lib_path = os.path.join(cache_dir, "libsegment_tree.so")
    try:
        if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < os.path.getmtime(src):
            os.makedirs(cache_dir, exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", lib_path],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(lib_path)
    except (OSError, subprocess.CalledProcessError) as e:  # pragma: no cover
        warnings.warn(f"{EXTENSION_WARNING} ({e})")
        _LIB = False
        return False

    lib.st_new.restype = ctypes.c_void_p
    lib.st_new.argtypes = [ctypes.c_int64, ctypes.c_int32]
    lib.st_free.argtypes = [ctypes.c_void_p]
    lib.st_capacity.restype = ctypes.c_int64
    lib.st_capacity.argtypes = [ctypes.c_void_p]
    lib.st_set.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_double]
    lib.st_get.restype = ctypes.c_double
    lib.st_get.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.st_set_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
    ]
    lib.st_get_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
    ]
    lib.st_reduce.restype = ctypes.c_double
    lib.st_reduce.argtypes = [ctypes.c_void_p]
    lib.st_reduce_range.restype = ctypes.c_double
    lib.st_reduce_range.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.st_prefix_search.restype = ctypes.c_int64
    lib.st_prefix_search.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.st_prefix_search_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
    ]
    _LIB = lib
    return lib


def _i64(a):
    return np.ascontiguousarray(a, np.int64)


def _f64(a):
    return np.ascontiguousarray(a, np.float64)


class _NativeTree:
    IS_NATIVE = True

    def __init__(self, capacity: int, is_min: bool):
        lib = _build_and_load()
        if lib is False:  # pragma: no cover
            raise ImportError(EXTENSION_WARNING)
        self._lib = lib
        self.capacity = capacity
        self._h = ctypes.c_void_p(lib.st_new(capacity, 1 if is_min else 0))
        if not self._h:
            raise MemoryError("segment tree allocation failed")

    def __del__(self):
        if getattr(self, "_h", None) and self._lib:
            self._lib.st_free(self._h)

    def __setitem__(self, idx, value):
        if np.isscalar(idx) or np.ndim(idx) == 0:
            self._lib.st_set(self._h, int(idx), float(value))
        else:
            idx = _i64(idx)
            vals = _f64(np.broadcast_to(value, idx.shape))
            self._lib.st_set_batch(
                self._h,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                idx.size,
            )

    def __getitem__(self, idx):
        if np.isscalar(idx) or np.ndim(idx) == 0:
            return self._lib.st_get(self._h, int(idx))
        idx = _i64(idx)
        out = np.empty(idx.shape, np.float64)
        self._lib.st_get_batch(
            self._h,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            idx.size,
        )
        return out

    def reduce(self, start: int = 0, end: int | None = None) -> float:
        if start == 0 and end is None:
            return self._lib.st_reduce(self._h)
        end = self.capacity if end is None else end
        return self._lib.st_reduce_range(self._h, start, end)


class SumSegmentTree(_NativeTree):
    """O(log N) sum tree with prefix-sum search (reference SumSegmentTree,
    csrc/segment_tree.h:243). Falls back to the numpy implementation when
    no toolchain is available (build happens lazily at FIRST construction —
    importing rl_tpu stays side-effect free)."""

    def __new__(cls, capacity: int):
        if _build_and_load() is False:  # pragma: no cover
            return _NumpySumTree(capacity)
        return super().__new__(cls)

    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=False)

    def scan(self, us) -> np.ndarray:
        """For each u: smallest idx with prefix-sum(0..idx) > u."""
        us = _f64(np.atleast_1d(us))
        out = np.empty(us.shape, np.int64)
        self._lib.st_prefix_search_batch(
            self._h,
            us.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            us.size,
        )
        return out


class MinSegmentTree(_NativeTree):
    """O(log N) min tree (reference MinSegmentTree, csrc/segment_tree.h:303)."""

    def __new__(cls, capacity: int):
        if _build_and_load() is False:  # pragma: no cover
            return _NumpyMinTree(capacity)
        return super().__new__(cls)

    def __init__(self, capacity: int):
        super().__init__(capacity, is_min=True)


class _NumpySumTree:
    """Fallback with identical semantics (O(N) scan)."""

    IS_NATIVE = False

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._v = np.zeros(capacity, np.float64)

    def __setitem__(self, idx, value):
        self._v[idx] = value

    def __getitem__(self, idx):
        return self._v[idx]

    def reduce(self, start: int = 0, end: int | None = None) -> float:
        return float(self._v[start:end].sum())

    def scan(self, us):
        cs = np.cumsum(self._v)
        return np.clip(np.searchsorted(cs, np.atleast_1d(us), side="right"), 0, self.capacity - 1)


class _NumpyMinTree:
    IS_NATIVE = False

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._v = np.full(capacity, np.inf, np.float64)

    def __setitem__(self, idx, value):
        self._v[idx] = value

    def __getitem__(self, idx):
        return self._v[idx]

    def reduce(self, start: int = 0, end: int | None = None) -> float:
        return float(self._v[start:end].min())


