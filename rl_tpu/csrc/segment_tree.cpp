// Host-side segment trees for prioritized replay.
//
// TPU-native counterpart of the reference's C++/CUDA trees (reference:
// torchrl/csrc/segment_tree.h:42,243,303 — non-recursive Sum/Min segment
// trees backing PrioritizedSampler, bound through pybind11 as
// torchrl._torchrl). Here: a dependency-free C ABI (loaded with ctypes, no
// pybind11 in the image) with batched entry points so the Python call
// overhead amortizes over whole sample batches.
//
// The DEVICE path for PER is the parallel prefix-sum sampler
// (rl_tpu/data/replay/samplers.py); this host tree serves host-resident
// buffers (MemmapStorage-scale) where O(log N) point ops beat a full
// O(N) prefix pass.
//
// Layout: classic iterative segment tree over 2*size slots, size = next
// power of two >= capacity; leaves at [size, size+capacity).

#include <cstdint>
#include <cstring>
#include <limits>
#include <new>

namespace {

struct Tree {
  int64_t capacity;
  int64_t size;  // leaves offset (power of two)
  double* data;  // 2*size
  bool is_min;
};

inline double combine(const Tree* t, double a, double b) {
  return t->is_min ? (a < b ? a : b) : (a + b);
}

inline double identity(const Tree* t) {
  return t->is_min ? std::numeric_limits<double>::infinity() : 0.0;
}

Tree* tree_new(int64_t capacity, bool is_min) {
  if (capacity <= 0) return nullptr;
  int64_t size = 1;
  while (size < capacity) size <<= 1;
  Tree* t = new (std::nothrow) Tree;
  if (!t) return nullptr;
  t->capacity = capacity;
  t->size = size;
  t->is_min = is_min;
  t->data = new (std::nothrow) double[2 * size];
  if (!t->data) {
    delete t;
    return nullptr;
  }
  const double id0 = is_min ? std::numeric_limits<double>::infinity() : 0.0;
  for (int64_t i = 0; i < 2 * size; ++i) t->data[i] = id0;
  return t;
}

void point_set(Tree* t, int64_t idx, double value) {
  int64_t i = t->size + idx;
  t->data[i] = value;
  for (i >>= 1; i >= 1; i >>= 1)
    t->data[i] = combine(t, t->data[2 * i], t->data[2 * i + 1]);
}

double range_query(const Tree* t, int64_t l, int64_t r) {  // [l, r)
  double res_l = identity(t), res_r = identity(t);
  int64_t lo = t->size + l, hi = t->size + r;
  while (lo < hi) {
    if (lo & 1) res_l = combine(t, res_l, t->data[lo++]);
    if (hi & 1) res_r = combine(t, t->data[--hi], res_r);
    lo >>= 1;
    hi >>= 1;
  }
  return combine(t, res_l, res_r);
}

}  // namespace

extern "C" {

void* st_new(int64_t capacity, int32_t is_min) {
  return tree_new(capacity, is_min != 0);
}

void st_free(void* h) {
  Tree* t = static_cast<Tree*>(h);
  if (t) {
    delete[] t->data;
    delete t;
  }
}

int64_t st_capacity(void* h) { return static_cast<Tree*>(h)->capacity; }

void st_set(void* h, int64_t idx, double value) {
  point_set(static_cast<Tree*>(h), idx, value);
}

double st_get(void* h, int64_t idx) {
  Tree* t = static_cast<Tree*>(h);
  return t->data[t->size + idx];
}

void st_set_batch(void* h, const int64_t* idxs, const double* values, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) point_set(t, idxs[i], values[i]);
}

void st_get_batch(void* h, const int64_t* idxs, double* out, int64_t n) {
  Tree* t = static_cast<Tree*>(h);
  for (int64_t i = 0; i < n; ++i) out[i] = t->data[t->size + idxs[i]];
}

// full-range reduction (sum tree: total mass; min tree: global min)
double st_reduce(void* h) {
  Tree* t = static_cast<Tree*>(h);
  return t->data[1];
}

double st_reduce_range(void* h, int64_t l, int64_t r) {
  return range_query(static_cast<Tree*>(h), l, r);
}

// prefix-sum search (sum trees): smallest idx such that
// sum(data[0..idx]) > u. The reference's `scan` op (segment_tree.h:243).
int64_t st_prefix_search(void* h, double u) {
  Tree* t = static_cast<Tree*>(h);
  int64_t i = 1;
  while (i < t->size) {
    i <<= 1;
    if (t->data[i] <= u) {
      u -= t->data[i];
      i += 1;
    }
  }
  int64_t idx = i - t->size;
  return idx < t->capacity ? idx : t->capacity - 1;
}

void st_prefix_search_batch(void* h, const double* us, int64_t* out, int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = st_prefix_search(h, us[i]);
}

}  // extern "C"
