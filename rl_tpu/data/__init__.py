from .arraydict import ArrayDict
from .specs import (
    Binary,
    Bounded,
    Categorical,
    Composite,
    MultiCategorical,
    MultiOneHot,
    NonTensor,
    OneHot,
    Spec,
    Unbounded,
    make_composite_from_arraydict,
    stack_specs,
)

__all__ = [
    "ArrayDict",
    "Spec",
    "Bounded",
    "Unbounded",
    "Categorical",
    "MultiCategorical",
    "OneHot",
    "MultiOneHot",
    "Binary",
    "NonTensor",
    "Composite",
    "stack_specs",
    "make_composite_from_arraydict",
]
