"""ArrayDict: the framework's data model.

Every interface in this framework speaks ArrayDict — a nested, immutable
mapping of names to ``jax.Array`` leaves, registered as a JAX pytree. It is
the TPU-native equivalent of the reference's TensorDict (the external
``tensordict`` package; see reference torchrl docs and
torchrl/data/tensor_specs.py for how specs and data interlock): envs consume
and produce ArrayDicts, policies declare ``in_keys``/``out_keys`` over them,
replay buffers store them, and losses read/write them.

Design differences from TensorDict, chosen for JAX/XLA:

- **Immutable.** All mutators return a new ArrayDict. This is what makes it a
  well-behaved pytree under ``jit``/``vmap``/``scan`` and lets XLA alias
  buffers aggressively (donation works on whole ArrayDicts).
- **Inferred batch shape.** TensorDict stores an explicit ``batch_size``;
  under ``vmap`` a stored shape would go stale (vmap strips one leading axis
  from every leaf but cannot rewrite static metadata). We instead *infer*
  ``batch_shape`` as the longest common leading prefix of all leaf shapes, so
  it is correct inside any transform by construction.
- **Keys are strings; nesting is real.** ``d["a", "b"]`` traverses nested
  ArrayDicts, like TensorDict's nested keys.
"""

from __future__ import annotations

import operator
from collections.abc import Mapping
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ArrayDict", "NESTED_SEP"]

NESTED_SEP = "."

_LeafT = Any  # jax.Array | np.ndarray | python scalar (encoded on insert)


def _is_leaf(x: Any) -> bool:
    return not isinstance(x, (ArrayDict, Mapping))


class ArrayDict(Mapping):
    """Immutable nested mapping of names to arrays, registered as a pytree.

    >>> td = ArrayDict(obs=jnp.zeros((4, 3)), reward=jnp.zeros((4,)))
    >>> td.batch_shape
    (4,)
    >>> td2 = td.replace(reward=td["reward"] + 1.0)
    """

    __slots__ = ("_data",)

    def __init__(self, data: Mapping[str, Any] | None = None, /, **kwargs: Any):
        merged: dict[str, Any] = {}
        if data is not None:
            merged.update(data)
        merged.update(kwargs)
        out: dict[str, Any] = {}
        for k, v in merged.items():
            if not isinstance(k, str):
                raise TypeError(f"ArrayDict keys must be str, got {type(k)}")
            if isinstance(v, ArrayDict):
                out[k] = v
            elif isinstance(v, Mapping):
                out[k] = ArrayDict(v)
            else:
                out[k] = v
        # Sorted keys give a canonical flatten order (stable across
        # construction order, required for pytree-structure equality).
        object.__setattr__(self, "_data", dict(sorted(out.items())))

    # -- construction helpers -------------------------------------------------

    @classmethod
    def _unsafe(cls, data: dict[str, Any]) -> "ArrayDict":
        """Wrap an already-canonical dict without re-validation (hot path)."""
        self = object.__new__(cls)
        object.__setattr__(self, "_data", data)
        return self

    @classmethod
    def from_flat(cls, flat: Mapping[Any, Any]) -> "ArrayDict":
        """Build from a mapping whose keys may be tuples or 'a.b' paths."""
        out = cls()
        for k, v in flat.items():
            out = out.set(k, v)
        return out

    # -- Mapping protocol -----------------------------------------------------

    def __getitem__(self, key: Any) -> Any:
        if isinstance(key, str):
            if NESTED_SEP in key:
                return self[tuple(key.split(NESTED_SEP))]
            return self._data[key]
        if isinstance(key, tuple) and key and all(isinstance(k, str) for k in key):
            node: Any = self
            for k in key:
                node = node[k]
            return node
        # everything else is tensor-style indexing over the batch dims
        return self.apply(operator.itemgetter(key))

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: Any) -> bool:
        is_path = isinstance(key, str) or (
            isinstance(key, tuple) and bool(key) and all(isinstance(k, str) for k in key)
        )
        if not is_path:
            return False
        try:
            self[key]
        except (KeyError, TypeError):
            # TypeError: path traverses through an array leaf
            return False
        return True

    def keys(self, nested: bool = False, leaves_only: bool = False):
        if not nested:
            return self._data.keys()
        out = []
        for k, v in self._data.items():
            if isinstance(v, ArrayDict):
                if not leaves_only:
                    out.append((k,))
                out.extend((k, *sub) for sub in v.keys(True, leaves_only))
            else:
                out.append((k,))
        return out

    def items(self, nested: bool = False, leaves_only: bool = False):
        if not nested:
            return self._data.items()
        return [(k, self[k]) for k in self.keys(True, leaves_only)]

    def values(self):
        return self._data.values()

    # -- functional mutators --------------------------------------------------

    def set(self, key: str | tuple, value: Any) -> "ArrayDict":
        """Return a copy with ``key`` set (creating nested nodes as needed)."""
        if isinstance(key, str):
            if NESTED_SEP in key:
                key = tuple(key.split(NESTED_SEP))
            else:
                key = (key,)
        if not key:
            raise KeyError("empty key")
        head, *rest = key
        data = dict(self._data)
        if rest:
            child = data.get(head)
            if not isinstance(child, ArrayDict):
                child = ArrayDict()
            data[head] = child.set(tuple(rest), value)
        else:
            if isinstance(value, Mapping) and not isinstance(value, ArrayDict):
                value = ArrayDict(value)
            data[head] = value
        return ArrayDict._unsafe(dict(sorted(data.items())))

    def replace(self, **kwargs: Any) -> "ArrayDict":
        out = self
        for k, v in kwargs.items():
            out = out.set(k, v)
        return out

    def update(self, other: Mapping[str, Any] | None = None, **kw: Any) -> "ArrayDict":
        """Recursive merge: nested ArrayDicts merge key-wise, leaves overwrite."""
        out = self
        items = list((other or {}).items()) + list(kw.items())
        for k, v in items:
            cur = out._data.get(k) if isinstance(k, str) and NESTED_SEP not in k else None
            if isinstance(cur, ArrayDict) and isinstance(v, Mapping):
                out = out.set(k, cur.update(v))
            else:
                out = out.set(k, v)
        return out

    def delete(self, key: str | tuple) -> "ArrayDict":
        if isinstance(key, str):
            key = tuple(key.split(NESTED_SEP)) if NESTED_SEP in key else (key,)
        head, *rest = key
        data = dict(self._data)
        if rest:
            child = data[head]
            if not isinstance(child, ArrayDict):
                # Guard: a jax.Array also has a .delete() (buffer free!).
                raise KeyError(key)
            data[head] = child.delete(tuple(rest))
        else:
            del data[head]
        return ArrayDict._unsafe(data)

    def select(self, *keys: str | tuple, strict: bool = True) -> "ArrayDict":
        out = ArrayDict()
        for k in keys:
            try:
                out = out.set(k, self[k])
            except KeyError:
                if strict:
                    raise
        return out

    def exclude(self, *keys: str | tuple) -> "ArrayDict":
        out = self
        for k in keys:
            try:
                out = out.delete(k)
            except KeyError:
                pass
        return out

    def rename_key(self, old: str | tuple, new: str | tuple) -> "ArrayDict":
        val = self[old]
        return self.delete(old).set(new, val)

    def flatten_keys(self, sep: str = NESTED_SEP) -> "ArrayDict":
        out: dict[str, Any] = {}
        for path in self.keys(nested=True, leaves_only=True):
            out[sep.join(path)] = self[path]
        return ArrayDict._unsafe(dict(sorted(out.items())))

    def unflatten_keys(self, sep: str = NESTED_SEP) -> "ArrayDict":
        out = ArrayDict()
        for k, v in self._data.items():
            out = out.set(tuple(k.split(sep)), v)
        return out

    # -- shape ----------------------------------------------------------------

    @property
    def batch_shape(self) -> tuple[int, ...]:
        """Longest common leading prefix of all leaf shapes."""
        shapes = [np.shape(v) for v in self.leaves()]
        if not shapes:
            return ()
        prefix = shapes[0]
        for s in shapes[1:]:
            n = 0
            for a, b in zip(prefix, s):
                if a != b:
                    break
                n += 1
            prefix = prefix[:n]
            if not prefix:
                break
        return tuple(prefix)

    shape = batch_shape

    @property
    def batch_ndim(self) -> int:
        return len(self.batch_shape)

    def numel(self) -> int:
        return int(np.prod(self.batch_shape)) if self.batch_shape else 1

    def leaves(self) -> list[_LeafT]:
        out = []
        for v in self._data.values():
            if isinstance(v, ArrayDict):
                out.extend(v.leaves())
            else:
                out.append(v)
        return out

    def apply(self, fn: Callable[[Any], Any]) -> "ArrayDict":
        """Apply ``fn`` to every leaf, returning a new ArrayDict."""
        data = {
            k: (v.apply(fn) if isinstance(v, ArrayDict) else fn(v))
            for k, v in self._data.items()
        }
        return ArrayDict._unsafe(data)

    def named_apply(self, fn: Callable[[tuple, Any], Any]) -> "ArrayDict":
        def rec(node: "ArrayDict", prefix: tuple) -> "ArrayDict":
            data = {
                k: (
                    rec(v, prefix + (k,))
                    if isinstance(v, ArrayDict)
                    else fn(prefix + (k,), v)
                )
                for k, v in node._data.items()
            }
            return ArrayDict._unsafe(data)

        return rec(self, ())

    def reshape(self, *shape: int) -> "ArrayDict":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        nb = self.batch_ndim
        return self.apply(lambda x: jnp.reshape(x, shape + jnp.shape(x)[nb:]))

    def flatten_batch(self) -> "ArrayDict":
        return self.reshape(-1)

    def squeeze(self, axis: int = 0) -> "ArrayDict":
        return self.apply(lambda x: jnp.squeeze(x, axis=axis))

    def unsqueeze(self, axis: int = 0) -> "ArrayDict":
        return self.apply(lambda x: jnp.expand_dims(x, axis=axis))

    def expand(self, *sizes: int) -> "ArrayDict":
        sizes = tuple(sizes[0]) if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)) else sizes
        nb = self.batch_ndim

        def _exp(x):
            tail = jnp.shape(x)[nb:]
            return jnp.broadcast_to(x, tuple(sizes) + tail)

        return self.apply(_exp)

    # -- combination ----------------------------------------------------------

    @staticmethod
    def stack(dicts: list["ArrayDict"], axis: int = 0) -> "ArrayDict":
        return jax.tree.map(lambda *xs: jnp.stack(xs, axis=axis), *dicts)

    @staticmethod
    def concat(dicts: list["ArrayDict"], axis: int = 0) -> "ArrayDict":
        return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=axis), *dicts)

    # -- conversion -----------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            k: (v.to_dict() if isinstance(v, ArrayDict) else v)
            for k, v in self._data.items()
        }

    def astype(self, dtype) -> "ArrayDict":
        return self.apply(lambda x: jnp.asarray(x, dtype=dtype))

    def device_put(self, device_or_sharding) -> "ArrayDict":
        return jax.device_put(self, device_or_sharding)

    # -- dunder ---------------------------------------------------------------

    def __repr__(self) -> str:
        def fmt(v: Any) -> str:
            if isinstance(v, ArrayDict):
                return repr(v)
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                return f"Array{tuple(v.shape)}[{v.dtype}]"
            return repr(v)

        inner = ", ".join(f"{k}: {fmt(v)}" for k, v in self._data.items())
        return f"ArrayDict(batch_shape={self.batch_shape}, {{{inner}}})"

    def __eq__(self, other: Any) -> Any:
        if not isinstance(other, ArrayDict):
            return NotImplemented
        if jax.tree_util.tree_structure(self) != jax.tree_util.tree_structure(other):
            return False
        return jax.tree.map(lambda a, b: a == b, self, other)

    def __hash__(self):
        raise TypeError("ArrayDict is unhashable (contains arrays)")

    def __setattr__(self, *a):
        raise AttributeError("ArrayDict is immutable; use .set/.replace")


def _flatten_with_keys(td: ArrayDict):
    children = [(jax.tree_util.DictKey(k), v) for k, v in td._data.items()]
    return children, tuple(td._data.keys())


def _flatten(td: ArrayDict):
    return list(td._data.values()), tuple(td._data.keys())


def _unflatten(keys: tuple, children) -> ArrayDict:
    return ArrayDict._unsafe(dict(zip(keys, children)))


jax.tree_util.register_pytree_with_keys(
    ArrayDict, _flatten_with_keys, _unflatten, flatten_func=_flatten
)
