"""Offline dataset loaders -> replay buffers.

Redesign of the reference's dataset layer (reference: torchrl/data/datasets/
common.py base + d4rl.py/minari_data.py/atari_dqn.py etc.: each downloads
and memmaps episodes into a TensorStorage-backed ReplayBuffer). The image
has no network egress, so downloads are out of scope; what ships is the
schema + ingestion path the loaders share:

- :func:`dataset_from_arrays`: transitions dict -> (Memmap|Device)Storage
  ReplayBuffer with ImmutableDatasetWriter, reward-to-go and
  timestep annotations for DT-style training.
- :class:`MinariDataset` / :class:`D4RLDataset`: thin import-gated adapters
  mapping those libraries' episode dicts onto ``dataset_from_arrays``.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .arraydict import ArrayDict
from .replay import (
    DeviceStorage,
    ImmutableDatasetWriter,
    MemmapStorage,
    RandomSampler,
    ReplayBuffer,
    RoundRobinWriter,
)

__all__ = ["dataset_from_arrays", "MinariDataset", "D4RLDataset"]


def dataset_from_arrays(
    observations: np.ndarray,
    actions: np.ndarray,
    rewards: np.ndarray,
    terminations: np.ndarray,
    truncations: np.ndarray | None = None,
    next_observations: np.ndarray | None = None,
    device: bool = True,
    scratch_dir: str | None = None,
    sampler=None,
    batch_size: int | None = 256,
) -> tuple[ReplayBuffer, ArrayDict]:
    """Build an immutable offline buffer from transition arrays.

    Returns ``(buffer, state)``. The stored layout matches the collector's
    ({obs, action, "next": {...}}), plus "returns_to_go" and "timesteps"
    (undiscounted returns within episodes; DT consumables).
    """
    n = len(observations)
    truncations = (
        np.zeros(n, bool) if truncations is None else np.asarray(truncations, bool)
    )
    terminations = np.asarray(terminations, bool)
    done = terminations | truncations
    if next_observations is None:
        # within an episode, next obs is the following row; at cuts reuse obs
        next_observations = np.concatenate([observations[1:], observations[-1:]])
        next_observations = np.where(
            done[:, None] if next_observations.ndim == 2 else done.reshape((-1,) + (1,) * (next_observations.ndim - 1)),
            observations,
            next_observations,
        )

    # reward-to-go + timesteps per episode (vectorized segmented pass)
    rewards = np.asarray(rewards, np.float32)
    ends = np.flatnonzero(done)
    if ends.size == 0 or ends[-1] != n - 1:
        ends = np.append(ends, n - 1)
    # suffix sums overall; rtg_i = suffix[i] - suffix after the episode end
    suffix = np.cumsum(rewards[::-1])[::-1]
    boundary_of = ends[np.searchsorted(ends, np.arange(n), side="left")]
    after = np.where(
        boundary_of + 1 < n, np.append(suffix, 0.0)[boundary_of + 1], 0.0
    )
    rtg = (suffix - after).astype(np.float32)
    starts = np.concatenate([[0], ends[:-1] + 1])
    lengths = ends - starts + 1
    ts = (np.arange(n) - np.repeat(starts, lengths)).astype(np.int32)

    items = ArrayDict(
        observation=jnp.asarray(observations),
        action=jnp.asarray(actions),
        returns_to_go=jnp.asarray(rtg)[:, None],
        timesteps=jnp.asarray(ts),
        next=ArrayDict(
            observation=jnp.asarray(next_observations),
            reward=jnp.asarray(rewards, jnp.float32),
            terminated=jnp.asarray(terminations),
            truncated=jnp.asarray(truncations),
            done=jnp.asarray(done),
        ),
    )
    storage = (
        DeviceStorage(n) if device else MemmapStorage(n, scratch_dir=scratch_dir)
    )
    # writes go through a RoundRobinWriter once, then the buffer is sealed
    rb = ReplayBuffer(storage, sampler or RandomSampler(), RoundRobinWriter(), batch_size=batch_size)
    state = rb.init(items[0])
    state = rb.extend(state, items)
    rb.writer = ImmutableDatasetWriter()
    return rb, state


class MinariDataset:
    """minari adapter (import-gated; reference minari_data.py)."""

    def __init__(self, dataset_id: str, **kw):
        try:
            import minari
        except ImportError as e:  # pragma: no cover
            raise ImportError("MinariDataset requires the minari package") from e
        ds = minari.load_dataset(dataset_id)
        obs, next_obs, act, rew, term, trunc = [], [], [], [], [], []
        for ep in ds.iterate_episodes():
            T = len(ep.rewards)
            # minari stores T+1 observations: rows 1..T are the TRUE
            # successors (incl. the final post-truncation obs)
            obs.append(ep.observations[:T])
            next_obs.append(ep.observations[1 : T + 1])
            act.append(ep.actions[:T])
            rew.append(ep.rewards)
            t = np.zeros(T, bool)
            t[-1] = bool(ep.terminations[-1])
            term.append(t)
            tr = np.zeros(T, bool)
            tr[-1] = bool(ep.truncations[-1])
            trunc.append(tr)
        self.buffer, self.state = dataset_from_arrays(
            np.concatenate(obs),
            np.concatenate(act),
            np.concatenate(rew),
            np.concatenate(term),
            np.concatenate(trunc),
            next_observations=np.concatenate(next_obs),
            **kw,
        )


class D4RLDataset:
    """d4rl adapter (import-gated; reference d4rl.py)."""

    def __init__(self, env_id: str, **kw):
        try:
            import d4rl  # noqa: F401
            import gym as d4rl_gym
        except ImportError as e:  # pragma: no cover
            raise ImportError("D4RLDataset requires d4rl + legacy gym") from e
        env = d4rl_gym.make(env_id)
        data = env.get_dataset()
        self.buffer, self.state = dataset_from_arrays(
            data["observations"],
            data["actions"],
            data["rewards"],
            data["terminals"],
            data.get("timeouts"),
            next_observations=data.get("next_observations"),
            **kw,
        )
