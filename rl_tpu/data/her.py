"""Hindsight experience replay: goal relabeling.

Redesign of the reference's HER (reference:
torchrl/data/replay_buffers/her.py:463 — relabeling via a sampler wrapper).
Here relabeling is a pure jit-safe function over time-major batches,
usable as a collector postproc or a buffer transform: the "future" strategy
samples an achieved goal from a later step of the SAME episode and
recomputes the reward.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from .arraydict import ArrayDict

__all__ = ["her_relabel", "HERRelabeler"]


def her_relabel(
    batch: ArrayDict,
    key: jax.Array,
    reward_fn: Callable[[jax.Array, jax.Array], jax.Array],
    achieved_key=("next", "achieved_goal"),
    desired_key="desired_goal",
    relabel_prob: float = 0.8,
) -> ArrayDict:
    """Future-strategy HER over a time-major [T, …] batch.

    For each step t (with probability ``relabel_prob``): draw u ∈ [t, T)
    within the same episode, set desired_goal := achieved_goal[u], and
    recompute ``reward = reward_fn(achieved[t], new_desired)``. Episode
    boundaries come from ("next","done").
    """
    T = batch.batch_shape[0]
    done = batch["next", "done"]
    achieved = batch[achieved_key]
    desired = batch[desired_key]

    k_u, k_p = jax.random.split(key)
    shape = done.shape
    t_full = jnp.broadcast_to(
        jnp.arange(T).reshape((T,) + (1,) * (len(shape) - 1)), shape
    )
    # last index of each step's episode: reverse scan carrying the nearest
    # done-at-or-after-t (T-1 for the trailing partial episode) — so the
    # draw below is exactly uniform over the episode's remaining steps
    def body(carry, xs):
        d, t = xs
        end = jnp.where(d, t, carry)
        return end, end

    _, ep_end = jax.lax.scan(
        body,
        jnp.full(shape[1:], T - 1),
        (done, t_full),
        reverse=True,
    )
    u = jax.random.randint(k_u, shape, t_full, ep_end + 1)

    gathered = jnp.take_along_axis(
        achieved, u.reshape(u.shape + (1,) * (achieved.ndim - u.ndim)), axis=0
    )
    relabel = jax.random.bernoulli(k_p, relabel_prob, shape)
    rmask = relabel.reshape(relabel.shape + (1,) * (gathered.ndim - relabel.ndim))
    new_desired = jnp.where(rmask, gathered, desired)
    new_reward = reward_fn(achieved, new_desired)
    new_reward = jnp.where(relabel, new_reward, batch["next", "reward"])

    out = batch.set(desired_key, new_desired)
    out = out.set(("next", "reward"), new_reward)
    if isinstance(desired_key, str) and ("next", desired_key) in out:
        out = out.set(("next", desired_key), new_desired)
    return out


class HERRelabeler:
    """Collector-postproc / buffer-transform form of :func:`her_relabel`.

    The postproc signature has no key argument, and python-side key state
    would be baked at trace time — so the relabel key is derived in-graph by
    folding batch-varying content (trajectory ids) into a base key.
    """

    def __init__(self, reward_fn, relabel_prob: float = 0.8, seed: int = 0, **keys):
        self.reward_fn = reward_fn
        self.relabel_prob = relabel_prob
        self.keys = keys
        self._base = jax.random.key(seed)

    def __call__(self, batch: ArrayDict) -> ArrayDict:
        salt = (
            jnp.sum(batch["collector", "traj_ids"]).astype(jnp.uint32)
            if ("collector", "traj_ids") in batch
            else jnp.asarray(0, jnp.uint32)
        )
        k = jax.random.fold_in(self._base, salt)
        return her_relabel(
            batch, k, self.reward_fn, relabel_prob=self.relabel_prob, **self.keys
        )
