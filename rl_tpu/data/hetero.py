"""Heterogeneous spec machinery (round-3 VERDICT missing #3).

The reference represents ragged multi-agent groups with lazy stacked
specs/tensordicts (reference torchrl/data/tensor_specs.py: ``Choice``:4243,
``Stacked``:1496, ``StackedComposite``:6463) — per-member tensors keep
their own shapes and stay un-materialized. Lazy raggedness cannot exist
inside an XLA program (static shapes), so the TPU-native form is
**mask-backed padding**: a stacked spec pads every member to the
element-wise max shape, knows each member's true region, and exposes the
validity mask as a STATIC array the policy/loss can fold in. Sampling,
projection and containment all respect per-member domains, so hetero
groups are first-class at the spec level while the data stays one dense
``[n_members, *padded]`` array — exactly what vmapped networks and pjit
shardings want.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict
from .specs import Composite, NonTensor, Spec, _canon_shape

__all__ = ["Choice", "Stacked", "StackedComposite", "pad_stack"]


@dataclasses.dataclass(frozen=True)
class Choice(Spec):
    """Uniformly sample one of several same-shape specs per draw
    (reference tensor_specs.py:4243).

    All choices must share type, shape and dtype (reference constraint).
    ``rand`` picks a choice with the key and samples it — jit-safe via
    ``lax.switch`` for tensor specs; NonTensor choices resolve host-side.
    """

    choices: tuple = ()

    def __post_init__(self):
        choices = tuple(self.choices)
        if not choices:
            raise ValueError("Choice requires at least one choice")
        first = choices[0]
        if not all(type(c) is type(first) for c in choices[1:]):
            raise TypeError("All choices must be the same type")
        if not all(c.shape == first.shape for c in choices[1:]):
            raise ValueError("All choices must have the same shape")
        if not all(c.dtype == first.dtype for c in choices[1:]):
            raise ValueError("All choices must have the same dtype")
        object.__setattr__(self, "choices", choices)
        object.__setattr__(self, "shape", first.shape)
        object.__setattr__(self, "dtype", first.dtype)

    def rand(self, key: jax.Array, batch_shape: tuple[int, ...] = ()):
        if isinstance(self.choices[0], NonTensor):
            idx = int(jax.random.randint(key, (), 0, len(self.choices)))
            return self.choices[idx].rand(key, batch_shape)
        kidx, ksample = jax.random.split(key)
        idx = jax.random.randint(kidx, (), 0, len(self.choices))
        return jax.lax.switch(
            idx,
            [lambda k, c=c: c.rand(k, batch_shape) for c in self.choices],
            ksample,
        )

    def zero(self, batch_shape: tuple[int, ...] = ()):
        return self.choices[0].zero(batch_shape)

    def is_in(self, val) -> bool:
        return any(c.is_in(val) for c in self.choices)

    def project(self, val):
        if self.is_in(val):
            return jnp.asarray(val, self.dtype)
        return self.choices[0].project(val)

    def __len__(self) -> int:
        return len(self.choices)


def _padded_shape(shapes: Sequence[tuple[int, ...]]) -> tuple[int, ...]:
    ndim = max((len(s) for s in shapes), default=0)
    if any(len(s) != ndim for s in shapes):
        raise ValueError(f"member shapes must share ndim: {shapes}")
    return tuple(max(s[d] for s in shapes) for d in range(ndim))


@dataclasses.dataclass(frozen=True)
class Stacked(Spec):
    """Mask-backed ragged stack of leaf specs (reference Stacked:1496).

    Members share dtype and ndim but may differ in per-dim sizes (and in
    domain: e.g. ``Categorical(n=3)`` next to ``Categorical(n=5)``). The
    materialized value is dense ``[..., n_members, *padded]``; each
    member's true region is ``member_shapes[i]`` and :meth:`mask` returns
    the static validity mask. ``rand``/``project``/``is_in`` apply each
    member's own domain inside its region; the padding region is zeros.
    """

    specs: tuple = ()
    present: tuple = ()  # per-member validity; () = all present

    def __post_init__(self):
        specs = tuple(self.specs)
        if not specs:
            raise ValueError("Stacked requires at least one member spec")
        dtypes = {jnp.dtype(s.dtype) for s in specs}
        if len(dtypes) != 1:
            raise ValueError(f"Stacked members must share dtype, got {dtypes}")
        present = tuple(self.present) or (True,) * len(specs)
        if len(present) != len(specs):
            raise ValueError("present must align with specs")
        padded = _padded_shape([s.shape for s in specs])
        object.__setattr__(self, "specs", specs)
        object.__setattr__(self, "present", present)
        object.__setattr__(self, "shape", (len(specs),) + padded)
        object.__setattr__(self, "dtype", specs[0].dtype)

    @property
    def member_shapes(self) -> tuple[tuple[int, ...], ...]:
        return tuple(s.shape for s in self.specs)

    @property
    def padded_shape(self) -> tuple[int, ...]:
        return self.shape[1:]

    def mask(self, batch_shape: tuple[int, ...] = ()) -> jax.Array:
        """Static [n, *padded] validity mask (True inside member regions),
        broadcast over ``batch_shape``."""
        m = np.zeros(self.shape, bool)
        for i, s in enumerate(self.specs):
            region = (i,) + tuple(slice(0, d) for d in s.shape)
            # presence is explicit, not shape-derived: a scalar member's
            # region covers its whole row, so an ABSENT scalar needs the
            # flag to stay masked out
            m[region] = self.present[i]
        out = jnp.asarray(m)
        bs = _canon_shape(batch_shape)
        return jnp.broadcast_to(out, bs + self.shape) if bs else out

    def _member_region(self, i: int) -> tuple:
        return (Ellipsis, i) + tuple(slice(0, d) for d in self.specs[i].shape)

    def rand(self, key: jax.Array, batch_shape: tuple[int, ...] = ()):
        bs = _canon_shape(batch_shape)
        out = jnp.zeros(bs + self.shape, self.dtype)
        for i, s in enumerate(self.specs):
            if not self.present[i]:
                continue  # absent member stays zero
            r = s.rand(jax.random.fold_in(key, i), bs)
            out = out.at[self._member_region(i)].set(r)
        return out

    def is_in(self, val) -> bool:
        val = jnp.asarray(val)
        if tuple(val.shape[val.ndim - len(self.shape):]) != self.shape:
            return False
        if val.dtype != jnp.dtype(self.dtype):
            return False
        for i, s in enumerate(self.specs):
            if not self.present[i]:
                continue  # absent member's slot is padding, any value ok
            region = val[self._member_region(i)]
            if not bool(s._domain_ok(region)):
                return False
        return True

    def project(self, val):
        val = jnp.asarray(val, self.dtype)
        out = jnp.zeros_like(val)
        for i, s in enumerate(self.specs):
            if not self.present[i]:
                continue  # absent member's slot projects to zero
            region = self._member_region(i)
            out = out.at[region].set(s.project(val[region]))
        return out

    def __len__(self) -> int:
        return len(self.specs)

    def __getitem__(self, i: int) -> Spec:
        return self.specs[i]


def _erase(spec_like: Spec) -> Spec:
    """A zero-size stand-in for a member that lacks this key: its mask is
    all-False and it contributes nothing to the padded shape. Always an
    Unbounded — domain classes (Bounded/MultiCategorical) reject zero
    shapes against their per-element bounds, and an absent member has no
    domain anyway."""
    from .specs import Unbounded

    return Unbounded(
        shape=(0,) * len(spec_like.shape), dtype=spec_like.dtype
    )


class StackedComposite(Composite):
    """Mask-backed ragged stack of Composites (reference
    StackedComposite:6463) — the spec of a heterogeneous agent group.

    Presents as a regular Composite whose children are :class:`Stacked`
    leaves (nested composites recurse), so ``rand``/``zero``/``is_in``/
    ``project`` and ``check_env_specs`` work unchanged on the dense padded
    data. Per-member composites remain accessible via :attr:`members` /
    :meth:`member`, and :meth:`masks` returns the ArrayDict of static
    validity masks, one per leaf key — the thing MARL losses fold in.

    Keys present in only some members are supported: absent members get a
    zero-size region (mask all False).
    """

    def __init__(self, members: Sequence[Composite]):
        members = tuple(members)
        if not members:
            raise ValueError("StackedComposite requires at least one member")
        keys: list[str] = []
        for m in members:
            for k in m.keys():
                if k not in keys:
                    keys.append(k)
        children: dict[str, Spec] = {}
        for k in keys:
            subs = [m[k] if k in m else None for m in members]
            present = [s for s in subs if s is not None]
            if isinstance(present[0], Composite):
                children[k] = StackedComposite(
                    [s if s is not None else Composite() for s in subs]
                )
            else:
                proto = present[0]
                children[k] = Stacked(
                    specs=tuple(
                        s if s is not None else _erase(proto) for s in subs
                    ),
                    present=tuple(s is not None for s in subs),
                )
        super().__init__(children)
        object.__setattr__(self, "members", members)

    def member(self, i: int) -> Composite:
        return self.members[i]

    def masks(self, batch_shape: tuple[int, ...] = ()) -> ArrayDict:
        out = ArrayDict()
        for k, child in self.items():
            if isinstance(child, StackedComposite):
                out = out.set(k, child.masks(batch_shape))
            elif isinstance(child, Stacked):
                out = out.set(k, child.mask(batch_shape))
        return out

    def __len__(self) -> int:
        return len(self.members)


def _pad_stack_leaves(
    leaves: Sequence[Any], present: Sequence[bool], axis: int
) -> tuple[Any, Any]:
    arrs = [np.asarray(x) for x in leaves]
    padded = _padded_shape([a.shape for a in arrs])
    dtype = next(
        (a.dtype for a, p in zip(arrs, present) if p), arrs[0].dtype
    )
    out = np.zeros((len(arrs),) + padded, dtype)
    mask = np.zeros((len(arrs),) + padded, bool)
    for i, (a, p) in enumerate(zip(arrs, present)):
        region = (i,) + tuple(slice(0, d) for d in a.shape)
        out[region] = a
        # explicit presence flag, not shape: a () scalar's region covers
        # the whole row, so shape alone can't mark an absent member
        mask[region] = p
    if axis != 0:
        out = np.moveaxis(out, 0, axis)
        mask = np.moveaxis(mask, 0, axis)
    return jnp.asarray(out), jnp.asarray(mask)


def pad_stack(
    items: Sequence[ArrayDict | Any], axis: int = 0
) -> tuple[Any, Any]:
    """Stack ragged pytrees/arrays into dense padded arrays + masks.

    The data-side companion of :class:`Stacked`/:class:`StackedComposite`
    (the reference's ``torch.stack`` of ragged tensordicts produces a lazy
    stack; here the result is dense + mask). Returns ``(stacked, mask)``
    with a new leading member axis; keys missing from a member are
    zero-filled with an all-False mask row (dtype taken from the present
    members).
    """
    if not items:
        raise ValueError("pad_stack requires at least one item")
    if not isinstance(items[0], ArrayDict):
        return _pad_stack_leaves(items, [True] * len(items), axis)

    keys: list = []
    for td in items:
        for k in td.keys(nested=True, leaves_only=True):
            if k not in keys:
                keys.append(k)
    data, masks = ArrayDict(), ArrayDict()
    for k in keys:
        present = [k in td for td in items]
        proto = np.asarray(next(td[k] for td, p in zip(items, present) if p))
        leaves = [
            np.asarray(td[k]) if p
            # absent member: zero-size along every dim (scalars keep shape
            # () and are masked out via the presence flag)
            else np.zeros(
                (0,) * proto.ndim if proto.ndim else (), proto.dtype
            )
            for td, p in zip(items, present)
        ]
        stacked, m = _pad_stack_leaves(leaves, present, axis)
        data = data.set(k, stacked)
        masks = masks.set(k, m)
    return data, masks
