from .history import History, Message

__all__ = ["History", "Message"]
