from .history import History, Message
from .preference import PairwiseDataset, RewardData
from .tokenizer import SimpleTokenizer

__all__ = ["History", "Message", "PairwiseDataset", "RewardData", "SimpleTokenizer"]
