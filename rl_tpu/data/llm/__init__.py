from .history import History, Message
from .tokenizer import SimpleTokenizer

__all__ = ["History", "Message", "SimpleTokenizer"]
