"""Chat history: host-side conversation container + tokenization with
assistant-span masking.

Redesign of the reference's ``History`` TensorClass (reference:
torchrl/data/llm/history.py:465 — chat-template application and
assistant-token masking :157-254): host-side python structure (strings never
enter XLA) that renders to token arrays + masks via a HF tokenizer
(import-gated) or a simple built-in template for tests.

The produced arrays are exactly what the GRPO/SFT losses consume:
``tokens``, ``attention_mask``, ``assistant_mask`` (True on tokens the
assistant generated — the loss support).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

__all__ = ["Message", "History"]


@dataclasses.dataclass(frozen=True)
class Message:
    role: str  # "system" | "user" | "assistant" | "tool"
    content: str


@dataclasses.dataclass
class History:
    """An ordered chat conversation."""

    messages: list[Message] = dataclasses.field(default_factory=list)

    # -- construction ---------------------------------------------------------

    @classmethod
    def from_chats(cls, chats: list[list[dict]]) -> list["History"]:
        return [
            cls([Message(m["role"], m["content"]) for m in chat]) for chat in chats
        ]

    def append(self, role: str, content: str) -> "History":
        return History(self.messages + [Message(role, content)])

    def __len__(self) -> int:
        return len(self.messages)

    @property
    def last(self) -> Message | None:
        return self.messages[-1] if self.messages else None

    # -- rendering ------------------------------------------------------------

    def render(self, template: str = "simple", add_generation_prompt: bool = False) -> str:
        """Flat text via a minimal role-tagged template (tests / built-in
        models). HF chat templates go through :meth:`tokenize`."""
        parts = [f"<|{m.role}|>{m.content}<|end|>" for m in self.messages]
        if add_generation_prompt:
            parts.append("<|assistant|>")
        return "".join(parts)

    def tokenize(
        self,
        tokenizer: Any,
        max_len: int | None = None,
        left_pad: bool = True,
        add_generation_prompt: bool = False,
    ) -> dict[str, np.ndarray]:
        """Render + tokenize with assistant-span mask.

        ``tokenizer`` is either a HF tokenizer (uses ``apply_chat_template``
        when available) or any object with ``encode(str) -> list[int]``.
        Spans are computed by tokenizing messages incrementally, so the
        assistant mask is exact under concatenative tokenizers (the built-in
        template guarantees this; BPE boundary effects with HF templates are
        the same caveat the reference documents).
        """
        ids: list[int] = []
        assistant: list[bool] = []
        for m in self.messages:
            chunk = f"<|{m.role}|>{m.content}<|end|>"
            toks = list(tokenizer.encode(chunk))
            ids.extend(toks)
            assistant.extend([m.role == "assistant"] * len(toks))
        if add_generation_prompt:
            toks = list(tokenizer.encode("<|assistant|>"))
            ids.extend(toks)
            assistant.extend([False] * len(toks))

        tokens = np.asarray(ids, np.int32)
        amask = np.asarray(assistant, bool)
        attn = np.ones_like(amask)
        if max_len is not None:
            if len(tokens) > max_len:
                tokens, amask, attn = tokens[-max_len:], amask[-max_len:], attn[-max_len:]
            else:
                pad = max_len - len(tokens)
                z = np.zeros(pad, tokens.dtype)
                f = np.zeros(pad, bool)
                if left_pad:
                    tokens = np.concatenate([z, tokens])
                    amask = np.concatenate([f, amask])
                    attn = np.concatenate([f, attn])
                else:
                    tokens = np.concatenate([tokens, z])
                    amask = np.concatenate([amask, f])
                    attn = np.concatenate([attn, f])
        return {"tokens": tokens, "assistant_mask": amask, "attention_mask": attn}

    @staticmethod
    def batch_tokenize(
        histories: list["History"], tokenizer: Any, max_len: int, **kw
    ) -> dict[str, np.ndarray]:
        outs = [h.tokenize(tokenizer, max_len=max_len, **kw) for h in histories]
        return {k: np.stack([o[k] for o in outs]) for k in outs[0]}
