"""Pairwise preference data for reward-model training.

Redesign of the reference's RLHF reward layer (reference:
torchrl/data/llm/reward.py — ``RewardData``:19 token/mask/(reward,
end_scores) container; ``PairwiseDataset``:29 chosen/rejected pair
memmaps built from the hub CarperAI comparison set). Zero-egress form:
pairs are built locally from (prompt, chosen, rejected) text triples with
any tokenizer exposing ``encode``; arrays are dense [n, L] with padding
masks — the layout a Bradley-Terry reward model consumes
(:class:`rl_tpu.objectives.PairwiseRewardLoss`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = ["RewardData", "PairwiseDataset"]


@dataclasses.dataclass
class RewardData:
    """Token batch for one side of the comparison (reference reward.py:19):
    ``input_ids``/``attention_mask`` [n, L]; ``rewards``/``end_scores``
    are filled by the reward model at scoring time."""

    input_ids: Any
    attention_mask: Any
    rewards: Any | None = None
    end_scores: Any | None = None

    @property
    def batch(self) -> ArrayDict:
        return ArrayDict(
            input_ids=self.input_ids, attention_mask=self.attention_mask
        )


def _encode_block(tokenizer, prompts, responses, max_length: int):
    """Tokenize prompt+response with RESPONSE-preserving truncation: an
    over-long prompt is cut from the LEFT so the response (the part that
    differs between chosen and rejected) always survives — joint tail
    truncation would make both sides of a long pair byte-identical and
    silently zero their gradient."""
    ids = np.zeros((len(prompts), max_length), np.int32)
    mask = np.zeros((len(prompts), max_length), np.float32)
    for i, (p, r) in enumerate(zip(prompts, responses)):
        ptoks = list(tokenizer.encode(p))
        rtoks = list(tokenizer.encode(r))[:max_length]
        keep_p = max(0, max_length - len(rtoks))
        toks = ptoks[len(ptoks) - keep_p :] + rtoks if keep_p else rtoks
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = 1.0
    return jnp.asarray(ids), jnp.asarray(mask)


@dataclasses.dataclass
class PairwiseDataset:
    """Chosen/rejected comparison pairs (reference reward.py:29).

    Build with :meth:`from_pairs`; feed ``chosen_data``/``rejected_data``
    through a reward model and train with
    :class:`rl_tpu.objectives.PairwiseRewardLoss` (Bradley-Terry).
    """

    chosen_data: RewardData
    rejected_data: RewardData

    @classmethod
    def from_pairs(
        cls,
        tokenizer,
        pairs: Sequence[tuple[str, str, str]],
        max_length: int = 256,
    ) -> "PairwiseDataset":
        """``pairs`` = (prompt, chosen_response, rejected_response) text
        triples; both sides tokenize as prompt+response (the reference's
        comparison layout)."""
        prompts = [p for p, _, _ in pairs]
        cids, cmask = _encode_block(
            tokenizer, prompts, [c for _, c, _ in pairs], max_length
        )
        rids, rmask = _encode_block(
            tokenizer, prompts, [r for _, _, r in pairs], max_length
        )
        return cls(
            chosen_data=RewardData(cids, cmask),
            rejected_data=RewardData(rids, rmask),
        )

    @property
    def batch(self) -> ArrayDict:
        """One ArrayDict view: {chosen: {...}, rejected: {...}}."""
        return ArrayDict(
            chosen=self.chosen_data.batch, rejected=self.rejected_data.batch
        )

    def __len__(self) -> int:
        return int(self.chosen_data.input_ids.shape[0])
