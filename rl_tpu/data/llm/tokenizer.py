"""Self-contained word+char tokenizer for fixture-based RLHF recipes.

The reference's LLM stack assumes a HuggingFace ``transformers`` tokenizer
(reference: torchrl/envs/llm/chat.py tokenizer= plumbing, sota grpo recipes
load one from the hub). This image has no hub access, so recipes need a
local trainable tokenizer with the same surface (``encode``/``decode``/
``vocab_size``/special ids). Word-level with character fallback: every
corpus word gets an id, unknown strings degrade to per-character ids, so
round-trip ``decode(encode(s)) == s`` holds for any input over the trained
charset.
"""

from __future__ import annotations

import re
from typing import Iterable

__all__ = ["SimpleTokenizer"]

_SPLIT = re.compile(r"\w+|[^\w\s]|\s")


class SimpleTokenizer:
    """Trainable word+char tokenizer.

    ids: 0=pad, 1=bos, 2=eos, 3=unk, then single characters, then words.
    """

    PAD, BOS, EOS, UNK = 0, 1, 2, 3

    def __init__(self, corpus: Iterable[str] = (), max_vocab: int = 4096):
        chars: set[str] = set()
        words: dict[str, int] = {}
        for text in corpus:
            chars.update(text)
            for w in _SPLIT.findall(text):
                if len(w) > 1:
                    words[w] = words.get(w, 0) + 1
        self._itos: list[str] = ["<pad>", "<bos>", "<eos>", "<unk>"]
        self._itos += sorted(chars)
        for w, _ in sorted(words.items(), key=lambda kv: (-kv[1], kv[0])):
            if len(self._itos) >= max_vocab:
                break
            self._itos.append(w)
        self._stoi = {s: i for i, s in enumerate(self._itos)}

    @property
    def vocab_size(self) -> int:
        return len(self._itos)

    @property
    def eos_token_id(self) -> int:
        return self.EOS

    @property
    def pad_token_id(self) -> int:
        return self.PAD

    def encode(self, text: str) -> list[int]:
        out: list[int] = []
        for piece in _SPLIT.findall(text):
            tid = self._stoi.get(piece)
            if tid is not None:
                out.append(tid)
            else:  # character fallback (then UNK for untrained chars)
                out.extend(self._stoi.get(c, self.UNK) for c in piece)
        return out

    def decode(self, ids: Iterable[int]) -> str:
        return "".join(
            self._itos[i]
            for i in ids
            if 0 <= int(i) < len(self._itos) and int(i) not in (self.PAD, self.BOS, self.EOS)
        )
