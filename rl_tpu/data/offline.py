"""Format-exact offline dataset ingestion (round-3 VERDICT missing #2).

Two loaders that read the reference datasets' ON-DISK formats directly —
no dataset library required — and reassemble episodes into this
framework's replay layout:

- :class:`MinariH5Dataset` — Minari's ``main_data.hdf5`` layout
  (reference torchrl/data/datasets/minari_data.py:272 ``_download_and_
  preproc``): HDF5 groups ``episode_<n>``, each holding ``observations``
  with **T+1** rows (dict observations become HDF5 subgroups),
  ``actions``/``rewards``/``terminations``/``truncations`` with T rows.
  Episode reassembly follows the reference exactly: root obs = rows
  ``[:-1]``, next obs = rows ``[1:]`` (so the final post-termination
  observation is kept as the last transition's successor), reward and the
  termination flags land under ``next``, and an ``episode`` id column
  records provenance. Length mismatches raise, as in the reference.

- :class:`AtariDQNDataset` — the DQN Replay Dataset shard layout
  (reference torchrl/data/datasets/atari_dqn.py:608 ``_preproc_run``):
  gzipped ``.npy`` files ``$store$_observation.<ckpt>.gz``,
  ``$store$_action…``, ``$store$_reward…``, ``$store$_terminal…`` per
  checkpoint. Observations are stored ONCE per step; the loader keeps the
  reference's memmap trick — an observation file of ``T+1`` rows where
  ``next_observation`` is the ``[1:]`` view — via a storage subclass whose
  ``get`` gathers row ``i+1`` for the next obs instead of materializing a
  second copy. ``terminal`` maps to ``terminated``; reward/flags land
  under ``next``.

Both feed the standard ``ReplayBuffer`` composition, so the existing
offline objectives (IQL/CQL/BC/DT) consume them unchanged.
"""

from __future__ import annotations

import gzip
import io
import os
import re
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict
from .replay import (
    ImmutableDatasetWriter,
    MemmapStorage,
    RandomSampler,
    ReplayBuffer,
    RoundRobinWriter,
)

__all__ = ["MinariH5Dataset", "AtariDQNDataset", "LeRobotDataset",
           "D4RLH5Dataset", "OpenXDataset",
           "RobosetDataset", "VD4RLDataset", "OpenMLDataset", "GenDGRLDataset",
           "atari_name_to_key", "lerobot_key"]

# reference minari_data.py:57 _NAME_MATCH
_MINARI_NAME_MATCH = {
    "observations": "observation",
    "rewards": "reward",
    "truncations": "truncated",
    "terminations": "terminated",
    "actions": "action",
    "infos": "info",
}


def _episode_leaves(group) -> dict[tuple, np.ndarray]:
    """Flatten an HDF5 episode entry (dataset or nested group) to
    ``{path: array}``."""
    import h5py

    out = {}

    def walk(prefix, node):
        if isinstance(node, h5py.Dataset):
            out[prefix] = np.asarray(node)
        else:
            for name, child in node.items():
                walk(prefix + (name,), child)

    walk((), group)
    return out


def _zero_shift(arr: np.ndarray) -> np.ndarray:
    """Successor view: rows [1:] with a ZERO final row (the convention the
    RLDS-flavored loaders share for the last step of an episode)."""
    out = np.zeros_like(arr)
    out[:-1] = arr[1:]
    return out


def _concat_rows(rows, what: str):
    """Concatenate per-episode ArrayDict rows into one flat dataset
    (shared epilogue of every multi-episode loader)."""
    if len(rows) == 1:
        return rows[0]
    import jax

    _check_row_schemas(rows, what)
    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *rows)


def _check_row_schemas(rows, what: str):
    """Equal leaf-key sets across per-episode rows, with a useful error
    (a raw pytree concat mismatch names no episode or key)."""
    ref_keys = set(rows[0].keys(nested=True, leaves_only=True))
    for i, r in enumerate(rows[1:], 1):
        keys = set(r.keys(nested=True, leaves_only=True))
        if keys != ref_keys:
            raise ValueError(
                f"{what} {i} schema mismatch vs {what} 0: "
                f"missing {sorted(ref_keys - keys)}, "
                f"extra {sorted(keys - ref_keys)}"
            )


def _sealed_buffer(items, n, *, sampler, batch_size, scratch_dir):
    """Shared tail of every offline loader: memmap storage, one extend,
    then seal behind ImmutableDatasetWriter."""
    rb = ReplayBuffer(
        MemmapStorage(n, scratch_dir=scratch_dir),
        sampler or RandomSampler(),
        RoundRobinWriter(),
        batch_size=batch_size,
    )
    state = rb.init(items[0])
    state = rb.extend(state, items)
    rb.writer = ImmutableDatasetWriter()
    return rb, state


class _OfflineDataset:
    """Shared sample() surface of the offline loaders."""

    def sample(self, key, batch_size: int | None = None):
        batch, state = self.buffer.sample(self.state, key, batch_size)
        self.state = state
        return batch


class MinariH5Dataset(_OfflineDataset):
    """Load a Minari ``main_data.hdf5`` file into a replay buffer.

    Args:
        path: the HDF5 file (Minari cache layout:
            ``<root>/<dataset_id>/data/main_data.hdf5``).
        batch_size: default sample batch size.
        sampler: defaults to :class:`RandomSampler`.
        scratch_dir: memmap directory (the reassembled dataset is
            disk-backed, reference memmap layout); ``None`` = temp dir.
        split_trajs: if True, also expose :attr:`trajectories` — the
            padded ``[n_episodes, max_len]`` view with a ``mask`` key
            (reference ``split_trajs`` semantics).

    Attributes:
        buffer / state: the sealed ReplayBuffer and its state.
        n_episodes / n_steps: dataset shape facts.
    """

    def __init__(
        self,
        path: str | Path,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
        split_trajs: bool = False,
    ):
        import h5py

        episodes = []
        with h5py.File(str(path), "r") as f:
            ep_keys = sorted(
                (k for k in f.keys() if k.startswith("episode_")),
                key=lambda k: int(k[len("episode_"):]),
            )
            if not ep_keys:
                raise ValueError(f"{path}: no episode_<n> groups found")
            for ep_key in ep_keys:
                ep_num = int(ep_key[len("episode_"):])
                g = f[ep_key]
                leaves = {}
                for name, node in g.items():
                    match = _MINARI_NAME_MATCH.get(name)
                    if match is None:
                        continue  # total_steps/seed attrs etc.
                    for sub, arr in _episode_leaves(node).items():
                        leaves[(match,) + sub] = arr
                episodes.append((ep_num, leaves))

        rows = []
        for ep_num, leaves in episodes:
            T = None
            for path_, arr in leaves.items():
                if path_[0] == "action":
                    T = arr.shape[0]
                    break
            if T is None:
                raise RuntimeError(f"episode {ep_num}: no actions entry")
            td = ArrayDict(episode=np.full((T,), ep_num, np.int32))
            nxt = ArrayDict()
            for path_, arr in leaves.items():
                head = path_[0]
                if head in ("observation", "info"):
                    # T+1 convention: rows [1:] are the true successors
                    if arr.shape[0] != T + 1:
                        raise RuntimeError(
                            f"episode {ep_num}: mismatching steps for "
                            f"{path_}: expected {T + 1} rows, got {arr.shape[0]}"
                        )
                    td = td.set(path_, arr[:-1])
                    nxt = nxt.set(path_, arr[1:])
                elif head in ("reward", "terminated", "truncated"):
                    if arr.shape[0] != T:
                        raise RuntimeError(
                            f"episode {ep_num}: mismatching steps for "
                            f"{path_}: expected {T} rows, got {arr.shape[0]}"
                        )
                    dtype = np.float32 if head == "reward" else np.bool_
                    nxt = nxt.set(path_, np.asarray(arr, dtype))
                else:  # action
                    if arr.shape[0] != T:
                        raise RuntimeError(
                            f"episode {ep_num}: mismatching steps for "
                            f"{path_}: expected {T} rows, got {arr.shape[0]}"
                        )
                    td = td.set(path_, np.asarray(arr))
            nxt = nxt.set("done", nxt["terminated"] | nxt["truncated"])
            rows.append(td.set("next", nxt))

        flat = _concat_rows(rows, "episode")
        self.n_episodes = len(rows)
        self.n_steps = int(flat["episode"].shape[0])

        self.buffer, self.state = _sealed_buffer(
            flat, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )

        self.trajectories = None
        if split_trajs:
            lens = [int(r["episode"].shape[0]) for r in rows]
            L = max(lens)

            def pad(r, T):
                import jax

                return jax.tree.map(
                    lambda x: jnp.concatenate(
                        [x, jnp.zeros((L - T,) + x.shape[1:], x.dtype)]
                    ),
                    r,
                )

            import jax

            padded = [
                pad(r, T).set(
                    "mask", jnp.arange(L) < T
                )
                for r, T in zip(rows, lens)
            ]
            self.trajectories = jax.tree.map(
                lambda *xs: jnp.stack(xs, axis=0), *padded
            )


def atari_name_to_key(name: str) -> tuple:
    """reference atari_dqn.py:653 ``_process_name``: ``$store$_X`` files
    are the transition data; ``terminal`` maps to ``terminated``."""
    if name.endswith("_ckpt"):
        name = name[:-5]
    if "store" in name:
        key = ("data", name.split("_")[1])
    else:
        key = (name,)
    if key[-1] == "terminal":
        key = key[:-1] + ("terminated",)
    return key


class _ShiftedNextObsStorage(MemmapStorage):
    """Memmap storage holding ``observation`` with T+1 rows, where the next
    observation of row ``i`` IS row ``i+1`` (the reference's
    ``mmap[:-1]``/``mmap[1:]`` aliasing, atari_dqn.py:620) — next obs is
    gathered at sample time, never stored twice."""

    def __init__(self, capacity: int, obs_map: np.memmap, scratch_dir=None):
        super().__init__(capacity, scratch_dir=scratch_dir)
        self._obs_map = obs_map  # [capacity + 1, ...]

    def get(self, state, idx):
        idx = np.asarray(idx)
        out = super().get(state, idx)
        return out.set("observation", jnp.asarray(self._obs_map[idx])).set(
            ("next", "observation"), jnp.asarray(self._obs_map[idx + 1])
        )


class AtariDQNDataset(_OfflineDataset):
    """Load one run of DQN-Replay-format shards from a directory.

    Expects the reference's file naming (atari_dqn.py:608):
    ``$store$_observation.<ckpt>.gz``, ``$store$_action.<ckpt>.gz``,
    ``$store$_reward.<ckpt>.gz``, ``$store$_terminal.<ckpt>.gz`` — each a
    gzipped ``.npy``. Multiple checkpoints concatenate in ckpt order.

    The observation shard has T rows (one per step); the loader allocates
    a T+1-row memmap whose tail duplicates the final frame, and serves
    ``next_observation`` as the ``[i+1]`` gather — storage cost is one
    frame, not a second copy of the dataset.
    """

    def __init__(
        self,
        root: str | Path,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        root = Path(root)
        shards: dict[int, dict[tuple, Path]] = {}
        pat = re.compile(r"^(?P<name>.+)\.(?P<ckpt>\d+)\.gz$")
        for p in sorted(root.iterdir()):
            m = pat.match(p.name)
            if not m:
                continue
            key = atari_name_to_key(m.group("name"))
            shards.setdefault(int(m.group("ckpt")), {})[key] = p
        if not shards:
            raise ValueError(f"{root}: no '<name>.<ckpt>.gz' shards found")

        def load(p: Path) -> np.ndarray:
            with gzip.GzipFile(p, mode="rb") as f:
                return np.load(io.BytesIO(f.read()))

        # small leaves concatenate in RAM; OBSERVATION shards (the bulk of
        # the dataset) stream one checkpoint at a time straight into the
        # memmap — peak residency is one decompressed shard, not the run
        parts: dict[tuple, list[np.ndarray]] = {}
        obs_key = ("data", "observation")
        obs_shards = []
        for ckpt in sorted(shards):
            for key, p in shards[ckpt].items():
                if key[0] != "data":
                    continue  # bookkeeping files (add_count, invalid_range)
                if key == obs_key:
                    obs_shards.append(p)
                else:
                    parts.setdefault(key, []).append(load(p))
        data = {k: np.concatenate(v) for k, v in parts.items()}
        required = {("data", "action"), ("data", "reward"),
                    ("data", "terminated")}
        missing = required - set(data)
        if not obs_shards:
            missing.add(obs_key)
        if missing:
            raise ValueError(f"{root}: missing shards for {sorted(missing)}")

        n = data[("data", "action")].shape[0]
        self.n_steps = n

        # T+1 observation memmap (reference layout); final successor
        # duplicates the last frame (terminal row - never a learning target)
        import tempfile

        scratch = scratch_dir or tempfile.mkdtemp(prefix="rl_tpu_atari_")
        os.makedirs(scratch, exist_ok=True)
        obs_map = None
        cursor = 0
        for p in obs_shards:
            shard = load(p)
            if obs_map is None:
                obs_map = np.memmap(
                    os.path.join(scratch, "observation_plus1.dat"),
                    dtype=shard.dtype, mode="w+",
                    shape=(n + 1,) + shard.shape[1:],
                )
            obs_map[cursor:cursor + shard.shape[0]] = shard
            cursor += shard.shape[0]
        if cursor != n:
            raise ValueError(
                f"{root}: observation rows ({cursor}) != action rows ({n})"
            )
        obs_map[-1] = obs_map[-2] if n else 0

        term = data[("data", "terminated")].astype(bool)
        # observations deliberately absent: they live only in obs_map and
        # are gathered (i / i+1) at sample time by the storage subclass
        items = ArrayDict(
            action=np.asarray(data[("data", "action")]),
            next=ArrayDict(
                reward=np.asarray(data[("data", "reward")], np.float32),
                terminated=term,
                truncated=np.zeros(n, bool),
                done=term,
            ),
        )
        storage = _ShiftedNextObsStorage(n, obs_map, scratch_dir=scratch)
        rb = ReplayBuffer(
            storage,
            sampler or RandomSampler(),
            RoundRobinWriter(),
            batch_size=batch_size,
        )
        state = rb.init(items[0])
        state = rb.extend(state, items)
        rb.writer = ImmutableDatasetWriter()
        self.buffer, self.state = rb, state


# reference lerobot.py:39 _DEFAULT_KEY_MAP
_LEROBOT_KEY_MAP = {
    "action": ("action",),
    "observation.state": ("observation", "state"),
    "episode_index": ("episode",),
    "frame_index": ("frame",),
    "task": ("language_instruction",),
    "next.reward": ("next", "reward"),
    "next.done": ("next", "done"),
}
_LEROBOT_IMAGE_PREFIX = "observation.images."


def lerobot_key(name: str) -> tuple:
    """LeRobot column name -> framework nested key (reference
    lerobot.py:52 ``_map_lerobot_key``): the canonical map, the camera
    prefix rule, else dotted-name splitting."""
    if name in _LEROBOT_KEY_MAP:
        return _LEROBOT_KEY_MAP[name]
    if name.startswith(_LEROBOT_IMAGE_PREFIX):
        return ("observation", "image", name[len(_LEROBOT_IMAGE_PREFIX):])
    return tuple(name.split(".")) if "." in name else (name,)


class LeRobotDataset(_OfflineDataset):
    """Direct reader for the LeRobot v2.x on-disk layout (reference
    torchrl/data/datasets/lerobot.py ``_LeRobotSnapshot``/
    ``LeRobotExperienceReplay`` — no `datasets` library needed, pyarrow
    reads the parquets):

    - ``meta/info.json`` — fps + feature schema facts;
    - ``meta/episodes.jsonl`` — per-episode lengths/tasks;
    - ``meta/tasks.jsonl`` — task_index -> instruction strings;
    - ``data/**/episode_*.parquet`` (or chunked files) — the frames, with
      the reference's column conventions (``observation.state``,
      ``action``, ``episode_index``, ``frame_index``, ``task_index``,
      optional ``next.reward``/``next.done``).

    Frames reassemble into the framework replay layout; ``task_index``
    resolves to the instruction string list (host-side). Videos are out
    of scope (zero-egress image has no clips; VideoCodecStorage covers
    the decode path).
    """

    def __init__(
        self,
        root: str | Path,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        import json

        import pyarrow.parquet as pq

        root = Path(root)
        with open(root / "meta" / "info.json") as f:
            self.info = json.load(f)
        tasks: dict[int, str] = {}
        tasks_path = root / "meta" / "tasks.jsonl"
        if tasks_path.exists():
            for line in tasks_path.read_text().splitlines():
                if line.strip():
                    row = json.loads(line)
                    tasks[int(row["task_index"])] = row["task"]
        self.tasks = tasks
        self.episodes_meta = []
        ep_path = root / "meta" / "episodes.jsonl"
        if ep_path.exists():
            for line in ep_path.read_text().splitlines():
                if line.strip():
                    self.episodes_meta.append(json.loads(line))

        files = sorted((root / "data").rglob("*.parquet"))
        if not files:
            raise ValueError(f"no data parquet files under {root / 'data'}")
        tables = [pq.read_table(str(p)) for p in files]
        cols: dict[str, np.ndarray] = {}
        for name in tables[0].column_names:
            parts = [t.column(name).to_numpy(zero_copy_only=False) for t in tables]
            arr = np.concatenate(parts)
            if arr.dtype == object:  # list-typed columns (state/action vecs)
                arr = np.stack([np.asarray(x) for x in arr])
            cols[name] = arr
        n = len(next(iter(cols.values())))
        self.n_steps = n

        td = ArrayDict()
        for name, arr in cols.items():
            if name == "task_index":
                idx = arr.astype(np.int64)
                self.instructions = [tasks.get(int(i), "") for i in idx]
                td = td.set(("task_index",), idx.astype(np.int32))
                continue
            if name in ("index", "timestamp"):
                td = td.set((name,), arr)
                continue
            key = lerobot_key(name)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            td = td.set(key, arr)

        # episode boundaries: the reference derives done from episode_index
        # changes when next.done is absent
        if ("next", "done") not in td and ("episode",) in td:
            ep = np.asarray(td[("episode",)])
            done = np.zeros(n, bool)
            done[:-1] = ep[:-1] != ep[1:]
            done[-1] = True
            td = td.set(("next", "done"), done)

        self.buffer, self.state = _sealed_buffer(
            td, n, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )


class D4RLH5Dataset(_OfflineDataset):
    """Load a D4RL HDF5 file (the direct-download layout) into a replay
    buffer — format-exact with the reference's processing pipeline
    (reference torchrl/data/datasets/d4rl.py:250 ``_get_dataset_direct_
    download`` -> :377 ``_process_data_from_env`` -> :450
    ``_shift_reward_done``).

    On-disk keys: ``observations`` / ``actions`` / ``rewards`` /
    ``terminals`` (+ optional ``timeouts``, ``next_observations``,
    ``infos/*``, ``metadata/*``), all with T rows (D4RL stores reward and
    the terminal flag aligned with the transition ``(s_t, a_t)``).

    Reference-exact quirks reproduced here:

    - ``use_truncated_as_done`` (default True): ``done = terminals |
      timeouts``; otherwise ``done = terminals`` only.
    - reward/done/terminated/truncated land UNSHIFTED under ``next``
      (the reward earned BY this transition), then the ROOT copies are
      shifted forward one step with a zero first row
      (``_shift_reward_done``): root flags mark "the previous transition
      ended an episode".
    - with ``next_observations`` present, rows align 1:1 and the LAST
      row is dropped (reference ``dataset[:-1]``); without it, next obs
      is the global ``observations[1:]`` shift and the last row is
      dropped — episode-boundary transitions are KEPT, exactly as the
      reference's direct-download path keeps them (its d4rl
      ``qlearning_dataset`` path is the one that filters; callers who
      want boundary-free data filter on ``next.done``).
    - ``metadata/*`` is exposed as :attr:`metadata`, not stored;
      ``infos/*`` lands under ``info`` (root and shifted-next views).

    Shape deviation (deliberate): reward and the done flags are stored
    with the framework's scalar-per-step convention ``[T]`` — this
    framework's reward specs are shape ``()`` — not the reference's
    trailing-singleton ``[T, 1]``.
    """

    def __init__(
        self,
        path: str | Path,
        *,
        use_truncated_as_done: bool = True,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        import h5py

        raw: dict[tuple, np.ndarray] = {}
        self.metadata: dict = {}
        with h5py.File(str(path), "r") as f:
            def visit(name, node):
                if not hasattr(node, "shape"):  # group
                    return
                parts = tuple(name.split("/"))
                if parts[0] == "metadata":
                    self.metadata["/".join(parts[1:])] = np.asarray(node[()])
                    return
                raw[parts] = np.asarray(node[()])

            f.visititems(lambda n, o: visit(n, o))

        for req in ("observations", "actions", "rewards", "terminals"):
            if (req,) not in raw:
                raise ValueError(f"{path}: missing required D4RL key {req!r}")
        T = raw[("rewards",)].shape[0]

        obs = raw.pop(("observations",))
        act = raw.pop(("actions",))
        rew = np.asarray(raw.pop(("rewards",)), np.float32).reshape(T)
        terminated = np.asarray(raw.pop(("terminals",)), bool).reshape(T)
        truncated = (
            np.asarray(raw.pop(("timeouts",)), bool).reshape(T)
            if ("timeouts",) in raw
            else None
        )
        next_obs = raw.pop(("next_observations",), None)
        infos = {p[1:]: a for p, a in raw.items() if p[0] == "infos"}

        if truncated is not None and use_truncated_as_done:
            done = terminated | truncated
        else:
            done = terminated.copy()

        # next view: unshifted flags/reward; root view: shifted (+zero row 0)
        def shift(x):
            out = np.zeros_like(x)
            out[1:] = x[:-1]
            return out

        n = T - 1  # reference: dataset = dataset[:-1]
        td = ArrayDict(
            observation=obs[:-1],
            action=act[:-1],
            reward=shift(rew)[:-1],
            done=shift(done)[:-1],
            terminated=shift(terminated)[:-1],
        )
        nxt = ArrayDict(
            observation=(next_obs[:-1] if next_obs is not None else obs[1:]),
            reward=rew[:-1],
            done=done[:-1],
            terminated=terminated[:-1],
        )
        if truncated is not None:
            td = td.set("truncated", shift(truncated)[:-1])
            nxt = nxt.set("truncated", truncated[:-1])
        for sub, arr in infos.items():
            td = td.set(("info",) + sub, arr[:-1])
            nxt = nxt.set(("info",) + sub, arr[1:])
        td = td.set("next", nxt)

        self.n_steps = n
        self.buffer, self.state = _sealed_buffer(
            td, n, sampler=sampler, batch_size=batch_size, scratch_dir=scratch_dir
        )


# reference openx.py:752 OPENX_KEY_MAP (RLDS step schema -> TED layout)
_OPENX_KEY_MAP = {
    "is_first": ("is_init",),
    "is_last": ("next", "done"),
    "is_terminal": ("next", "terminated"),
    "reward": ("next", "reward"),
}


class OpenXDataset(_OfflineDataset):
    """Open X-Embodiment episodes (the RLDS step schema) into a replay
    buffer — format-exact with the reference's conversion (reference
    torchrl/data/datasets/openx.py:760 ``_format_data``; the reference
    reads the HF mirror's ``data.pickle["steps"]`` records and this
    loader accepts exactly that step layout).

    Args:
        episodes: an iterable of episodes; each episode is either a list
            of RLDS step dicts (keys ``observation`` (possibly nested),
            ``action``, ``reward``, ``is_first``, ``is_last``,
            ``is_terminal``, optional ``language_instruction`` /
            ``discount``) or a dict with a ``"steps"`` list (the
            ``data.pickle`` record shape). Pickle files holding either
            form are accepted as paths.

    Reference-exact conversion, per episode:

    - ``next.observation`` = observations shifted by one, ZERO-padded at
      the end (reference ``pad(observation_[1:], [0, 1])`` — the final
      step keeps a zero successor, not a copy);
    - ``is_first -> is_init``, ``is_last -> next.done``, ``is_terminal ->
      next.terminated``, ``reward -> next.reward``;
    - ``next.truncated = next.done & ~next.terminated``;
    - root done/terminated/truncated are ZERO (the reference zeroes them;
      root ``is_init`` carries the episode-start marker);
    - an ``episode`` id column is added. Flags/reward keep the
      framework's scalar-per-step shape ``[T]`` (deviation from the
      reference's trailing singleton, matching this framework's specs).

    ``language_instruction`` (when present) is exposed per-step via
    :attr:`instructions` (host strings — the reference stores NonTensorData).
    """

    def __init__(
        self,
        episodes,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        rows = []
        self.instructions: list[str] = []
        n_eps = 0
        episodes = list(episodes)
        if not episodes:
            raise ValueError("OpenXDataset: no episodes given (empty iterable)")
        for ep_id, episode in enumerate(episodes):
            if isinstance(episode, (str, Path)):
                import pickle

                with open(episode, "rb") as fh:
                    episode = pickle.load(fh)
            if isinstance(episode, dict):
                episode = episode["steps"]
            steps = list(episode)
            if not steps:
                raise ValueError(f"episode {ep_id}: empty step list")
            T = len(steps)
            n_eps += 1

            def stack(key_path):
                vals = []
                for s in steps:
                    v = s
                    for k in key_path:
                        v = v[k]
                    vals.append(np.asarray(v))
                return np.stack(vals, axis=0)

            td = ArrayDict(episode=np.full((T,), ep_id, np.int32))
            nxt = ArrayDict()

            # observation subtree (possibly nested dicts)
            def obs_leaves(prefix, node):
                if isinstance(node, dict):
                    for k, v in node.items():
                        yield from obs_leaves(prefix + (k,), v)
                else:
                    yield prefix

            for leaf in obs_leaves((), steps[0]["observation"]):
                arr = stack(("observation",) + leaf)
                # zero-padded successor, reference pad(observation_[1:], [0,1])
                td = td.set(("observation",) + leaf, arr)
                nxt = nxt.set(("observation",) + leaf, _zero_shift(arr))

            td = td.set("action", stack(("action",)))
            if "discount" in steps[0]:
                td = td.set("discount", np.asarray(stack(("discount",)), np.float32))

            flags = {}
            for src, dst in _OPENX_KEY_MAP.items():
                arr = stack((src,))
                arr = np.asarray(arr, np.float32 if src == "reward" else bool)
                flags[dst] = arr.reshape(T)
            td = td.set("is_init", flags[("is_init",)])
            nxt = nxt.set("done", flags[("next", "done")])
            nxt = nxt.set("terminated", flags[("next", "terminated")])
            nxt = nxt.set("reward", flags[("next", "reward")])
            nxt = nxt.set(
                "truncated", nxt["done"] & ~nxt["terminated"]
            )
            # reference zeroes the root copies of every flag
            for k in ("done", "terminated", "truncated"):
                td = td.set(k, np.zeros_like(nxt[k]))

            # per-ROW list (padded with "" for instruction-less episodes) so
            # instructions[i] always matches global row i; RLDS/TF-origin
            # records store bytes — decode rather than str() them
            def _instr(v):
                return v.decode("utf-8", "replace") if isinstance(v, bytes) else str(v)

            self.instructions.extend(
                _instr(s.get("language_instruction", "")) for s in steps
            )
            rows.append(td.set("next", nxt))

        flat = _concat_rows(rows, "episode")
        self.n_episodes = n_eps
        self.n_steps = int(flat["episode"].shape[0])
        self.buffer, self.state = _sealed_buffer(
            flat, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )


class RobosetDataset(_OfflineDataset):
    """RoboHive/RoboSet trajectory H5 files (reference
    torchrl/data/datasets/roboset.py:246 ``_preproc_h5``): each file holds
    ``Trial<n>`` groups with T-row ``observations`` / ``actions`` /
    ``rewards`` / ``done`` (+ ``env_infos/*`` subgroups).

    Reference-exact reassembly: observations/env_infos keep their full T
    rows at the root with ``next`` = rows ``[1:]`` and a ZERO final
    successor (roboset.py:324 copies ``val[1:]`` into ``next[:-1]`` of a
    zero-initialized buffer); rewards land under ``next`` only; ``done``
    lands at BOTH root and next with ``next.terminated`` copied from
    ``next.done`` (roboset.py:333); ``episode`` and ``seed`` provenance
    columns. Scalar per-step shapes (framework convention — the
    reference's trailing unsqueeze is dropped, as with the other loaders).
    """

    def __init__(
        self,
        h5_files,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        import h5py

        if isinstance(h5_files, (str, Path)):
            h5_files = [h5_files]
        rows = []
        n_eps = 0
        for seed, path in enumerate(h5_files):
            with h5py.File(str(path), "r") as f:
                trials = sorted(
                    (k for k in f.keys() if k.startswith("Trial")),
                    key=lambda k: int(k[len("Trial"):]),
                )
                if not trials:
                    raise ValueError(f"{path}: no Trial<n> groups found")
                for tk in trials:
                    g = f[tk]
                    ep_num = int(tk[len("Trial"):])
                    T = g["actions"].shape[0]
                    td = ArrayDict(
                        episode=np.full((T,), ep_num, np.int32),
                        seed=np.full((T,), seed, np.int32),
                        action=np.asarray(g["actions"][()]),
                    )
                    nxt = ArrayDict()
                    for name, node in g.items():
                        if name in ("actions",):
                            continue
                        if name == "observations":
                            arr = np.asarray(node[()])
                            self._check_T(arr, T, name, tk)
                            td = td.set("observation", arr)
                            nxt = nxt.set("observation", _zero_shift(arr))
                        elif name == "env_infos":
                            for sub, leaf in _episode_leaves(node).items():
                                self._check_T(leaf, T, name, tk)
                                td = td.set(("info",) + sub, leaf)
                                nxt = nxt.set(("info",) + sub, _zero_shift(leaf))
                        elif name == "rewards":
                            arr = np.asarray(node[()], np.float32)
                            self._check_T(arr, T, name, tk)
                            nxt = nxt.set("reward", arr.reshape(T))
                        elif name == "done":
                            arr = np.asarray(node[()], bool)
                            self._check_T(arr, T, name, tk)
                            arr = arr.reshape(T)
                            td = td.set("done", arr)
                            nxt = nxt.set("done", arr)
                            nxt = nxt.set("terminated", arr.copy())
                        else:  # pass-through (reference identity NAME_MATCH)
                            arr = np.asarray(node[()])
                            self._check_T(arr, T, name, tk)
                            td = td.set(name, arr)
                    rows.append(td.set("next", nxt))
                    n_eps += 1

        flat = _concat_rows(rows, "trial")
        self.n_episodes = n_eps
        self.n_steps = int(flat["episode"].shape[0])
        self.buffer, self.state = _sealed_buffer(
            flat, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )

    @staticmethod
    def _check_T(arr, T, name, trial):
        if arr.shape[0] != T:
            raise RuntimeError(
                f"Mismatching number of steps for key {name} in {trial}: "
                f"expected {T} but got {arr.shape[0]}."
            )


# reference vd4rl.py:420 _NAME_MATCH (identity default)
_VD4RL_NAME_MATCH = {
    "is_first": ("is_init",),
    "is_last": ("next", "done"),
    "is_terminal": ("next", "terminated"),
    "reward": ("next", "reward"),
    "image": ("pixels",),
    "observation": ("pixels",),
    "discount": ("discount",),
    "action": ("action",),
}


class VD4RLDataset(_OfflineDataset):
    """V-D4RL episode files — npz or hdf5 with flat T-row arrays in the
    RLDS-flavored V-D4RL schema (reference torchrl/data/datasets/
    vd4rl.py:270 ``_download_and_preproc`` + :340 ``_process_data``).

    Reference-exact conversion: ``image``/``observation`` -> ``pixels``;
    ``is_first -> is_init``, ``is_last -> next.done``, ``is_terminal ->
    next.terminated``, ``reward -> next.reward``; every UNMATCHED key
    lands under ``("state", name)`` (proprioception); ``next.pixels`` /
    ``next.state`` are the one-row shift with a ZERO final successor;
    ``next.truncated = next.done & ~next.terminated``; root flags zeroed.
    Scalar per-step shapes (framework convention).
    """

    def __init__(
        self,
        files,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        if isinstance(files, (str, Path)):
            files = [files]
        rows = []
        for ep_id, path in enumerate(files):
            arrays = self._load_file(path)
            if "action" not in arrays:
                raise ValueError(f"{path}: no 'action' key")
            T = arrays["action"].shape[0]
            td = ArrayDict(episode=np.full((T,), ep_id, np.int32))
            nxt = ArrayDict()
            state_keys = []
            for name, arr in arrays.items():
                if arr.shape[0] != T:
                    raise RuntimeError(
                        f"{path}: key {name} has {arr.shape[0]} rows, expected {T}"
                    )
                dest = _VD4RL_NAME_MATCH.get(name)
                if dest is None:
                    td = td.set(("state", name), arr)
                    state_keys.append(name)
                elif dest[0] == "next":
                    kind = dest[1]
                    arr = np.asarray(
                        arr, np.float32 if kind == "reward" else bool
                    ).reshape(T)
                    nxt = nxt.set(kind, arr)
                elif dest == ("is_init",):
                    td = td.set("is_init", np.asarray(arr, bool).reshape(T))
                else:
                    td = td.set(dest, arr)

            if "image" in arrays and "observation" in arrays:
                raise ValueError(
                    f"{path}: both 'image' and 'observation' present — "
                    f"both map to pixels and one would be silently dropped"
                )
            if "pixels" in td:
                nxt = nxt.set("pixels", _zero_shift(td["pixels"]))
            for name in state_keys:
                nxt = nxt.set(("state", name), _zero_shift(td["state", name]))
            if "done" not in nxt:
                raise ValueError(f"{path}: no 'is_last' key")
            if "terminated" not in nxt:
                nxt = nxt.set("terminated", np.zeros(T, bool))
            nxt = nxt.set("truncated", nxt["done"] & ~nxt["terminated"])
            for k in ("done", "terminated", "truncated"):
                td = td.set(k, np.zeros(T, bool))
            rows.append(td.set("next", nxt))

        flat = _concat_rows(rows, "file")
        self.n_episodes = len(rows)
        self.n_steps = int(flat["episode"].shape[0])
        self.buffer, self.state = _sealed_buffer(
            flat, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )

    @staticmethod
    def _load_file(path) -> dict[str, np.ndarray]:
        path = str(path)
        if path.endswith(".npz"):
            npz = np.load(path)
            return {name: np.asarray(npz[name]) for name in npz.files}
        import h5py

        out = {}
        with h5py.File(path, "r") as f:
            for name, node in f.items():
                if hasattr(node, "shape"):
                    out[name] = np.asarray(node[()])
        return out


class OpenMLDataset(_OfflineDataset):
    """Tabular contextual-bandit datasets (reference torchrl/data/
    datasets/openml.py:23 ``OpenMLExperienceReplay``): rows are
    ``{"X": features, "y": integer outcome}``; :attr:`max_outcome_val`
    mirrors the reference attribute (openml.py:88).

    Construct with arrays (``OpenMLDataset(X, y)`` /
    :meth:`from_arrays`); the NAMED form is the classmethod
    :meth:`from_name` (``OpenMLDataset.from_name("adult_num")``), which
    needs scikit-learn + pandas and network access exactly like the
    reference — it is import-gated.
    """

    def __init__(self, X, y, *, batch_size: int | None = 256, sampler=None,
                 scratch_dir: str | None = None):
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.int64)
        if X.shape[0] != y.shape[0]:
            raise ValueError(f"X has {X.shape[0]} rows, y has {y.shape[0]}")
        self.max_outcome_val = int(y.max())
        self.n_steps = int(X.shape[0])
        td = ArrayDict(X=X, y=y.astype(np.int32))
        self.buffer, self.state = _sealed_buffer(
            td, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )

    @classmethod
    def from_arrays(cls, X, y, **kw) -> "OpenMLDataset":
        return cls(X, y, **kw)

    @classmethod
    def from_name(cls, name: str, **kw) -> "OpenMLDataset":
        try:
            from sklearn.datasets import fetch_openml  # noqa: F401
        except ImportError as e:  # pragma: no cover - optional dep
            raise ImportError(
                "OpenMLDataset.from_name requires scikit-learn + pandas "
                "(not in this image); use from_arrays"
            ) from e
        from sklearn.preprocessing import LabelEncoder, StandardScaler

        fetch_map = {
            "adult_num": ("adult", 1), "mushroom_num": ("mushroom", 1),
            "covertype": ("covertype", 3), "shuttle": ("shuttle", 1),
            "magic": ("MagicTelescope", 1),
        }
        if name not in fetch_map:
            raise KeyError(f"unsupported OpenML dataset {name!r}")
        ds, version = fetch_map[name]
        X, y = fetch_openml(ds, version=version, return_X_y=True)
        enc = LabelEncoder()
        for col in X.select_dtypes(include=["category"]).columns:
            X[col] = enc.fit_transform(X[col])
        y = enc.fit_transform(y)
        X = StandardScaler().fit_transform(X)
        return cls(X, y, **kw)


class GenDGRLDataset(_OfflineDataset):
    """Gen-DGRL (ProcGen) trajectories (reference torchrl/data/datasets/
    gen_dgrl.py:179 ``_download_and_preproc``): each trajectory is a
    pickled-dict ``.npy`` with ``observations`` (T+1 uint8 frames),
    ``actions`` / ``rewards`` / ``dones`` (T rows), shipped inside
    ``tar`` / ``tar.xz`` archives.

    Accepts a tar(.xz) path, a directory of ``.npy`` files, a list of
    ``.npy`` paths, or a list of already-loaded dicts. Reference-exact
    conversion (gen_dgrl.py:273-295): observation rows ``[:-1]`` at the
    root with ``next.observation = observations[1:]`` (uint8 preserved);
    ``dones -> next.done`` with ``next.terminated = next.done`` and
    ``next.truncated`` zeros; root flags zeroed; ``rewards ->
    next.reward``. Scalar per-step shapes (framework convention); an
    ``episode`` id column is added.
    """

    def __init__(
        self,
        source,
        *,
        batch_size: int | None = 256,
        sampler=None,
        scratch_dir: str | None = None,
    ):
        rows = []
        for ep_id, traj in enumerate(self._iter_trajs(source)):
            for req in ("observations", "actions", "rewards", "dones"):
                if req not in traj:
                    raise ValueError(f"trajectory {ep_id}: missing key {req!r}")
            obs = np.asarray(traj["observations"], np.uint8)
            act = np.asarray(traj["actions"])
            rew = np.asarray(traj["rewards"], np.float32)
            done = np.asarray(traj["dones"], bool)
            if obs.shape[0] < 2:
                raise ValueError(
                    f"trajectory {ep_id}: needs >= 2 observation rows "
                    f"(got {obs.shape[0]}) — observations carry the final "
                    f"successor"
                )
            T = obs.shape[0] - 1  # observations carry the final successor
            for name, arr in (("actions", act), ("rewards", rew), ("dones", done)):
                if arr.shape[0] != T:
                    raise RuntimeError(
                        f"trajectory {ep_id}: key {name} has {arr.shape[0]} "
                        f"rows, expected {T} (observations has {T + 1})"
                    )
            td = ArrayDict(
                episode=np.full((T,), ep_id, np.int32),
                observation=obs[:-1],
                action=act,
                done=np.zeros(T, bool),
                terminated=np.zeros(T, bool),
                truncated=np.zeros(T, bool),
            )
            nxt = ArrayDict(
                observation=obs[1:],
                reward=rew.reshape(T),
                done=done.reshape(T),
                terminated=done.reshape(T).copy(),
                truncated=np.zeros(T, bool),
            )
            rows.append(td.set("next", nxt))
        if not rows:
            raise ValueError("GenDGRLDataset: no trajectories found")

        flat = _concat_rows(rows, "trajectory")
        self.n_episodes = len(rows)
        self.n_steps = int(flat["episode"].shape[0])
        self.buffer, self.state = _sealed_buffer(
            flat, self.n_steps, sampler=sampler, batch_size=batch_size,
            scratch_dir=scratch_dir,
        )

    @staticmethod
    def _iter_trajs(source):
        import tarfile

        if isinstance(source, (str, Path)):
            s = str(source)
            if s.endswith((".tar", ".tar.xz", ".txz")):
                mode = "r:xz" if s.endswith(("xz",)) else "r"
                with tarfile.open(s, mode) as tar:
                    # name-sorted: episode ids must not depend on packaging
                    for member in sorted(tar.getmembers(), key=lambda m: m.name):
                        if not member.isfile() or not member.name.endswith(".npy"):
                            continue
                        buf = tar.extractfile(member)
                        yield np.load(buf, allow_pickle=True).tolist()
                return
            if os.path.isdir(s):
                for name in sorted(os.listdir(s)):
                    if name.endswith(".npy"):
                        yield np.load(
                            os.path.join(s, name), allow_pickle=True
                        ).tolist()
                return
            yield np.load(s, allow_pickle=True).tolist()
            return
        for item in source:
            if isinstance(item, dict):
                yield item
            else:
                yield np.load(str(item), allow_pickle=True).tolist()
