"""Post-collection batch processors.

``MultiStep``: n-step return folding (reference:
torchrl/data/postprocs/postprocs.py:85 ``MultiStep``): rewrites each
transition's reward to the discounted n-step sum and its "next" observation
to the state n steps ahead (stopping at episode boundaries), so one-step TD
losses train on n-step targets unchanged.

Applied inside the collector's jit (``Collector(postproc=MultiStep(...))``),
operating on time-major ``[T, ...]`` rollout batches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .arraydict import ArrayDict

__all__ = ["MultiStep", "DensifyReward"]


class MultiStep:
    """n-step reward folding over a time-major batch.

    For each t: ``R_t = Σ_{k<n} γ^k r_{t+k}`` (sum stops after a done);
    ("next", obs/done/terminated) become those of the step where the sum
    stopped (t+n-1 or the terminal step); writes "steps_to_next_obs" (the k
    actually folded) and keeps the original reward at
    ("next", "original_reward") — matching reference key conventions.
    """

    def __init__(self, gamma: float = 0.99, n_steps: int = 3):
        self.gamma = gamma
        self.n_steps = n_steps

    def __call__(self, batch: ArrayDict) -> ArrayDict:
        T = batch.batch_shape[0]
        nxt = batch["next"]
        reward = nxt["reward"]
        done = nxt["done"]

        nd = (~done).astype(jnp.float32)
        # alive at iteration k = 1 if steps t..t+k-1 are all not-done
        folded = reward
        alive = jnp.ones_like(nd)
        # index of the transition supplying the "next" content
        base = jnp.broadcast_to(
            jnp.arange(T).reshape((T,) + (1,) * (done.ndim - 1)), done.shape
        )
        src = base
        steps = jnp.ones_like(done, jnp.int32)
        avail = jnp.ones_like(nd)
        for k in range(1, self.n_steps):
            # window extends only while step t+k exists (the batch end is a
            # cut, zero-padded by _shift_back) and t+k-1 was not done
            avail = _shift_back(avail, 1)
            alive = alive * _shift_back(nd, k - 1) * avail
            r_k = _shift_back(reward, k)
            folded = folded + (self.gamma**k) * alive * r_k
            adv_src = _shift_back(base, k, fill_last=True)  # = min(t+k, T-1)
            src = jnp.where(alive > 0, adv_src, src)
            steps = steps + (alive > 0).astype(jnp.int32)

        def gather_t(x):
            if x.ndim < src.ndim:
                return x
            s = src.reshape(src.shape + (1,) * (x.ndim - src.ndim))
            s = jnp.broadcast_to(s, src.shape + x.shape[src.ndim :])
            return jnp.take_along_axis(x, s.astype(jnp.int32), axis=0)

        new_next = nxt.apply(gather_t)
        new_next = new_next.set("reward", folded)
        new_next = new_next.set("original_reward", reward)
        out = batch.set("next", new_next).set("steps_to_next_obs", steps)
        return out


def _shift_back(x: jax.Array, k: int, fill_last: bool = False) -> jax.Array:
    """x[t] <- x[t+k] along axis 0, padding the tail."""
    if k == 0:
        return x
    pad_val = x[-1:] if fill_last else jnp.zeros_like(x[:1])
    tail = jnp.repeat(pad_val, k, axis=0)
    return jnp.concatenate([x[k:], tail], axis=0)


class DensifyReward:
    """Spread a sparse terminal reward uniformly over the episode
    (reference postprocs.py:299)."""

    def __init__(self, reward_key=("next", "reward"), done_key=("next", "done")):
        self.reward_key = reward_key
        self.done_key = done_key

    def __call__(self, batch: ArrayDict) -> ArrayDict:
        # segment-mean of the episode's total reward, assigned to every step:
        # total_t = reward-to-go_t + reward-so-far_{t} - r_t (both scans cut
        # at episode boundaries), length_t likewise; dense = total / length.
        from ..ops.value import linear_recurrence_forward, linear_recurrence_reverse

        reward = batch[self.reward_key]
        done = batch[self.done_key].astype(jnp.float32)
        not_done = 1.0 - done
        ones = jnp.ones_like(reward)

        rtg = linear_recurrence_reverse(not_done, reward)
        steps_to_go = linear_recurrence_reverse(not_done, ones)
        # forward pass: a_t gates on the PREVIOUS step's done (episode starts
        # after a done), so shift the not_done gate by one
        prev_nd = jnp.concatenate([jnp.zeros_like(not_done[:1]), not_done[:-1]], axis=0)
        so_far = linear_recurrence_forward(prev_nd, reward)
        steps_so_far = linear_recurrence_forward(prev_nd, ones)

        totals = rtg + so_far - reward
        lengths = steps_to_go + steps_so_far - 1.0
        dense = totals / jnp.clip(lengths, 1.0)
        return batch.set(self.reward_key, dense)
