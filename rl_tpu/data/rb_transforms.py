"""Replay-side batch transforms.

The reference applies ``Transform``s on replay-buffer output (reference:
torchrl/envs/transforms/rb_transforms.py ``MultiStepTransform``;
torchrl/envs/transforms/transforms.py ``Reward2GoTransform``,
``BurnInTransform``). Here a replay transform is simply a pure callable
``batch -> batch`` passed to ``ReplayBuffer(transform=...)`` or applied by
the trainer — it runs inside the training jit, so these stay shape-static.

Batches are time-minor ``[B, T, ...]`` (slice-sampler output) or time-major
``[T, ...]`` (collector output); ``time_axis`` selects.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .arraydict import ArrayDict

__all__ = ["BurnInTransform", "Reward2GoTransform"]


class Reward2GoTransform:
    """Write the discounted reward-to-go of each step (reference
    Reward2GoTransform): ``rtg_t = Σ_{k>=t} γ^{k-t} r_k`` restarting at
    episode boundaries. Used for return-conditioned policies (Decision
    Transformer) and REINFORCE-style targets.

    Apply on TIME-CONTIGUOUS batches only: collector output (time-major,
    ``time_axis=0``, e.g. ``Collector(postproc=...)``) or slice-sampler
    ``[B, T]`` sub-trajectories (``time_axis=1``). Applying it on randomly
    sampled flat batches would chain unrelated transitions — the reference
    applies it buffer-INPUT-side (``inv``) for the same reason.
    """

    def __init__(
        self,
        gamma: float = 1.0,
        in_key=("next", "reward"),
        out_key: str = "reward_to_go",
        time_axis: int = 0,
    ):
        self.gamma = gamma
        self.in_key = in_key if isinstance(in_key, tuple) else (in_key,)
        self.out_key = out_key if isinstance(out_key, tuple) else (out_key,)
        self.time_axis = time_axis

    def __call__(self, batch: ArrayDict) -> ArrayDict:
        from ..ops.value import reward2go

        reward = batch[self.in_key]
        done = batch["next", "done"]
        if self.time_axis != 0:
            reward = jnp.moveaxis(reward, self.time_axis, 0)
            done = jnp.moveaxis(done, self.time_axis, 0)
        rtg = reward2go(reward, done, self.gamma)
        if self.time_axis != 0:
            rtg = jnp.moveaxis(rtg, 0, self.time_axis)
        return batch.set(self.out_key, rtg)


class BurnInTransform:
    """Warm up recurrent state on the first ``burn_in`` steps of each sampled
    sub-trajectory, then drop them from the training slice (reference
    BurnInTransform — the R2D2 trick).

    ``module`` is an rl_tpu recurrent module (LSTMModule/GRUModule); the
    computed carry is written at the module's carry keys so the subsequent
    sequence forward starts from the burned-in state rather than zeros.
    Operates on ``[B, T, ...]`` batches (slice-sampler layout).
    """

    def __init__(self, module, params, burn_in: int):
        self.module = module
        self.params = params
        self.burn_in = burn_in

    def __call__(self, batch: ArrayDict) -> ArrayDict:
        m = self.module
        seq = batch[m.in_key]
        if seq.ndim < 3:
            raise ValueError(
                "BurnInTransform needs [B, T, ...] sub-trajectory batches "
                f"(got shape {seq.shape}); reshape slice-sampler output to "
                "[B, T] before applying (flat [B*T] batches would slice the "
                "feature axis as time)"
            )
        x = seq[:, : self.burn_in]
        B, T = seq.shape[0], seq.shape[1]
        is_init = (
            batch[m.is_init_key][:, : self.burn_in]
            if m.is_init_key in batch
            else jnp.zeros((B, self.burn_in), bool)
        )

        def body(carry, xs):
            xt, it = xs
            carry = m._mask_carry(carry, it)
            carry, _ = m.cell.apply({"params": self.params}, carry, xt)
            return carry, None

        carry = m.zero_carry(B)
        xs = (jnp.moveaxis(x, 1, 0), jnp.moveaxis(is_init, 1, 0))
        carry, _ = jax.lax.scan(body, carry, xs)
        carry = jax.lax.stop_gradient(carry)

        # slice only [B, T, ...] leaves; bookkeeping leaves with other
        # shapes (sample indices, weights) pass through unchanged
        out = jax.tree_util.tree_map(
            lambda a: a[:, self.burn_in :]
            if a.ndim >= 2 and a.shape[:2] == (B, T)
            else a,
            batch,
        )
        for k, c in zip(m._carry_keys(), carry):
            out = out.set(k, c)
        return out
