from .buffer import ReplayBuffer
from .host_per import HostPrioritizedSampler
from .service import RemoteReplayBuffer, ReplayService
from .samplers import (
    PrioritizedSampler,
    RandomSampler,
    Sampler,
    SamplerWithoutReplacement,
    SliceSampler,
    PrioritizedSliceSampler,
    SliceSamplerWithoutReplacement,
    StalenessAwareSampler,
)
from .storages import DeviceStorage, ListStorage, MemmapStorage, Storage
from .writers import ImmutableDatasetWriter, MaxValueWriter, RoundRobinWriter, Writer

__all__ = [
    "ReplayService",
    "RemoteReplayBuffer",
    "HostPrioritizedSampler",
    "ReplayBuffer",
    "Storage",
    "DeviceStorage",
    "MemmapStorage",
    "ListStorage",
    "Sampler",
    "RandomSampler",
    "SamplerWithoutReplacement",
    "PrioritizedSampler",
    "SliceSampler",
    "SliceSamplerWithoutReplacement",
    "PrioritizedSliceSampler",
    "StalenessAwareSampler",
    "Writer",
    "RoundRobinWriter",
    "MaxValueWriter",
    "ImmutableDatasetWriter",
]
