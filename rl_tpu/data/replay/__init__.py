from .buffer import ReplayBuffer
from .host_per import HostPrioritizedSampler
from .service import RemoteReplayBuffer, ReplaySaturated, ReplayService
from .sharded import ReplayShard, ShardedReplayBuffer, ShardUnavailable
from .samplers import (
    PrioritizedSampler,
    RandomSampler,
    Sampler,
    SamplerWithoutReplacement,
    SliceSampler,
    PrioritizedSliceSampler,
    SliceSamplerWithoutReplacement,
    StalenessAwareSampler,
)
from .storages import (
    CompressedListStorage,
    DeviceStorage,
    ListStorage,
    MemmapStorage,
    Storage,
    StorageEnsemble,
)
from .ensemble import ReplayBufferEnsemble
from .checkpointers import load_buffer_state, save_buffer_state
from .scheduler import LinearScheduler, SchedulerList, StepScheduler
from .query import insertion_order_indices, iterate_ordered, read_latest, read_range
from .writers import ImmutableDatasetWriter, MaxValueWriter, RoundRobinWriter, Writer

__all__ = [
    "ReplayService",
    "RemoteReplayBuffer",
    "ReplaySaturated",
    "ReplayShard",
    "ShardedReplayBuffer",
    "ShardUnavailable",
    "HostPrioritizedSampler",
    "ReplayBuffer",
    "Storage",
    "DeviceStorage",
    "MemmapStorage",
    "ListStorage",
    "CompressedListStorage",
    "StorageEnsemble",
    "ReplayBufferEnsemble",
    "save_buffer_state",
    "load_buffer_state",
    "LinearScheduler",
    "StepScheduler",
    "SchedulerList",
    "read_range",
    "read_latest",
    "iterate_ordered",
    "insertion_order_indices",
    "Sampler",
    "RandomSampler",
    "SamplerWithoutReplacement",
    "PrioritizedSampler",
    "SliceSampler",
    "SliceSamplerWithoutReplacement",
    "PrioritizedSliceSampler",
    "StalenessAwareSampler",
    "Writer",
    "RoundRobinWriter",
    "MaxValueWriter",
    "ImmutableDatasetWriter",
]
