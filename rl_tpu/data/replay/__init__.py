from .buffer import ReplayBuffer
from .samplers import (
    PrioritizedSampler,
    RandomSampler,
    Sampler,
    SamplerWithoutReplacement,
    SliceSampler,
)
from .storages import DeviceStorage, ListStorage, MemmapStorage, Storage
from .writers import ImmutableDatasetWriter, MaxValueWriter, RoundRobinWriter, Writer

__all__ = [
    "ReplayBuffer",
    "Storage",
    "DeviceStorage",
    "MemmapStorage",
    "ListStorage",
    "Sampler",
    "RandomSampler",
    "SamplerWithoutReplacement",
    "PrioritizedSampler",
    "SliceSampler",
    "Writer",
    "RoundRobinWriter",
    "MaxValueWriter",
    "ImmutableDatasetWriter",
]
