"""Composed replay buffer.

Redesign of the reference's ``ReplayBuffer`` composition (reference:
torchrl/data/replay_buffers/replay_buffers.py:126 — ``add``:1341,
``extend``:1457, ``sample``:1543, ``update_priority``:1498) and its
prioritized/TensorDict variants (:1902, :2187, :2576).

``ReplayBuffer(storage, sampler, writer, transform)`` is static config; all
runtime state lives in one ArrayDict ``{"storage", "sampler", "writer"}``
threading through jit. The reference hides latency with a prefetch thread
pool and an RW-lock; on TPU the buffer ops compile into the train step
itself, so there is nothing to prefetch or lock — the XLA scheduler overlaps
the gather with compute.

Device path only here; host (memmap/list) buffers use the same classes with
python state and ``jit=False`` semantics.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict
from .samplers import RandomSampler, Sampler
from .storages import DeviceStorage, Storage
from .writers import RoundRobinWriter, Writer

__all__ = ["ReplayBuffer"]


class ReplayBuffer:
    """Composable replay buffer (storage × sampler × writer × transform)."""

    def __init__(
        self,
        storage: Storage | None = None,
        sampler: Sampler | None = None,
        writer: Writer | None = None,
        transform: Callable[[ArrayDict], ArrayDict] | None = None,
        batch_size: int | None = None,
    ):
        self.storage = storage if storage is not None else DeviceStorage(10_000)
        self.sampler = sampler if sampler is not None else RandomSampler()
        self.writer = writer if writer is not None else RoundRobinWriter()
        self.transform = transform
        self.batch_size = batch_size

    @property
    def capacity(self) -> int:
        return self.storage.capacity

    # -- state ----------------------------------------------------------------

    def init(self, example: ArrayDict) -> ArrayDict:
        """Build buffer state from one example item (no batch dims)."""
        return ArrayDict(
            storage=self.storage.init(example),
            sampler=self.sampler.init(self.capacity),
            writer=self.writer.init(self.capacity),
        )

    def size(self, state: ArrayDict) -> jax.Array:
        return self.storage.size(state["storage"])

    # -- writes ---------------------------------------------------------------

    def add(self, state: ArrayDict, item: ArrayDict) -> ArrayDict:
        """Insert one item (reference add:1341)."""
        return self.extend(state, item.unsqueeze(0), n=1)

    def extend(self, state: ArrayDict, items: ArrayDict, n: int | None = None) -> ArrayDict:
        """Insert a leading-axis batch of items (reference extend:1457).

        ``n`` (static) overrides the inferred batch length — required under
        jit when items' batch shape is not statically known to this method.
        """
        if n is None:
            n = int(items.batch_shape[0])
        idx, wstate, bstorage = self.writer.assign(
            state["writer"], state["storage"], items, n, self.capacity
        )
        bstorage = self.storage.set(bstorage, idx, items)
        sstate = self.sampler.on_write(state["sampler"], idx, items)
        return ArrayDict(storage=bstorage, sampler=sstate, writer=wstate)

    def make_extend(self, n: int, donate: bool = True) -> Callable[[ArrayDict, ArrayDict], ArrayDict]:
        """Compiled chunked-write entry point for host-driven producers.

        Un-jitted ``extend`` called from a host loop (e.g. draining an
        ``AsyncHostCollector`` queue) dispatches one device op per leaf per
        chunk — writer arange, a scatter per storage leaf, sampler
        bookkeeping. This returns a jitted closure over a fixed chunk size
        so each chunk is ONE fused XLA program, with the old buffer state
        donated (the scatter updates in place instead of copying the whole
        ring). The chunk size is static: feed it batches of exactly ``n``
        items (the async collector's ``frames_per_batch``).

        The returned callable counts its writes into the process metrics
        registry (host-side counters — the jitted program is untouched) so
        ``/metrics`` carries write throughput alongside the collector's
        queue-depth series.
        """
        fn = jax.jit(
            lambda state, items: self.extend(state, items, n=n),
            donate_argnums=(0,) if donate else (),
        )
        from ...obs import get_registry

        reg = get_registry()
        m_ext = reg.counter("rl_tpu_replay_extends_total", "chunked buffer writes")
        m_items = reg.counter("rl_tpu_replay_items_written_total", "items written to replay")

        def counted(state, items):
            out = fn(state, items)
            m_ext.inc()
            m_items.inc(n)
            return out

        return counted

    # -- reads ----------------------------------------------------------------

    def sample(
        self, state: ArrayDict, key: jax.Array, batch_size: int | None = None
    ) -> tuple[ArrayDict, ArrayDict]:
        """Returns (batch, new_state). The batch carries "index" (for
        priority updates) and "_weight" under PER (reference convention)."""
        bs = batch_size or self.batch_size
        if bs is None:
            raise ValueError("batch_size not set on buffer or sample call")
        idx, info, sstate = self.sampler.sample(
            state["sampler"], key, bs, self.size(state), self.capacity
        )
        batch = self.storage.get(state["storage"], idx)
        batch = batch.set("index", idx)
        batch = batch.update(info)
        if self.transform is not None:
            batch = self.transform(batch)
        return batch, state.set("sampler", sstate)

    # -- priorities -----------------------------------------------------------

    def update_priority(
        self, state: ArrayDict, idx: jax.Array, priority: jax.Array
    ) -> ArrayDict:
        sstate = self.sampler.update_priority(state["sampler"], idx, priority)
        return state.set("sampler", sstate)
