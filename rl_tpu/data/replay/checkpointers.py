"""Per-storage checkpointers (reference: torchrl/data/replay_buffers/
checkpointers.py — flat/nested/H5 storage checkpointers).

``save_buffer_state``/``load_buffer_state`` serialize a ReplayBuffer's full
runtime state (storage arrays + sampler priorities + writer cursors) so
off-policy training resumes with its replay intact:

- Buffer state (always an ArrayDict pytree — ReplayBuffer.init wraps even
  host-storage cursor dicts) -> one ``.npz`` of flattened leaves.
- MemmapStorage -> the memmaps themselves already live on disk; a json
  manifest records the scratch dir so a fresh process can reattach them.

The trainer-level checkpoint registry (rl_tpu/checkpoint) handles model/
optimizer state; these functions are the storage-level adapters it plugs in.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from ..arraydict import ArrayDict
from .storages import MemmapStorage

__all__ = ["save_buffer_state", "load_buffer_state"]

_SEP = "\x1f"  # unit separator: safe joiner for nested key paths


def save_buffer_state(buffer, state, path: str) -> None:
    """Serialize buffer runtime state to ``path`` (.npz + optional .json)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays = {}

    def visit(prefix: tuple, node):
        if isinstance(node, ArrayDict):
            for k in node:
                visit(prefix + (k,), node[k])
        else:
            arrays[_SEP.join(prefix)] = np.asarray(node)

    visit((), state)
    np.savez(path + ".npz", **arrays)
    if isinstance(buffer.storage, MemmapStorage):
        buffer.storage.flush()
        with open(path + ".json", "w") as f:
            json.dump({"scratch_dir": buffer.storage.scratch_dir}, f)


def load_buffer_state(buffer, path: str) -> ArrayDict:
    """Rebuild buffer state saved by :func:`save_buffer_state`."""
    flat = {}
    with np.load(path + ".npz") as z:
        for k in z.files:
            flat[tuple(k.split(_SEP))] = jnp.asarray(z[k])
    state = ArrayDict()
    for k, v in flat.items():
        state = state.set(k, v)
    # leaf-less subtrees (e.g. a RandomSampler's empty state) leave no
    # arrays behind — rebuild them from the buffer's components
    if "sampler" not in state:
        state = state.set("sampler", buffer.sampler.init(buffer.capacity))
    if "writer" not in state:
        state = state.set("writer", buffer.writer.init(buffer.capacity))
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            manifest = json.load(f)
        if "scratch_dir" in manifest and isinstance(buffer.storage, MemmapStorage):
            # point the storage at the checkpointed memmaps; the caller's
            # next storage.init(example) reattaches them without truncation
            # (MemmapStorage.init validates the sidecar schema and opens "r+")
            buffer.storage.scratch_dir = manifest["scratch_dir"]
    return state
