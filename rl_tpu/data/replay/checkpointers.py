"""Per-storage checkpointers (reference: torchrl/data/replay_buffers/
checkpointers.py — flat/nested/H5 storage checkpointers).

``save_buffer_state``/``load_buffer_state`` serialize a ReplayBuffer's full
runtime state (storage arrays + sampler priorities + writer cursors) so
off-policy training resumes with its replay intact:

- Device-backed state (an ArrayDict pytree) -> one ``.npz`` of flattened
  leaves.
- MemmapStorage -> the memmaps already live on disk; only the cursor dict
  is written (a json manifest next to the scratch dir).

The trainer-level checkpoint registry (rl_tpu/checkpoint) handles model/
optimizer state; these functions are the storage-level adapters it plugs in.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from ..arraydict import ArrayDict
from .storages import MemmapStorage

__all__ = ["save_buffer_state", "load_buffer_state"]

_SEP = "\x1f"  # unit separator: safe joiner for nested key paths


def save_buffer_state(buffer, state, path: str) -> None:
    """Serialize buffer runtime state to ``path`` (.npz + optional .json)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    host_state = {}
    arrays = {}

    def visit(prefix: tuple, node):
        if isinstance(node, ArrayDict):
            for k in node:
                visit(prefix + (k,), node[k])
        elif isinstance(node, dict):  # memmap/list storage python state
            host_state[_SEP.join(prefix)] = node
        else:
            arrays[_SEP.join(prefix)] = np.asarray(node)

    visit((), state)
    np.savez(path + ".npz", **arrays)
    if host_state or isinstance(buffer.storage, MemmapStorage):
        manifest = {"host_state": host_state}
        if isinstance(buffer.storage, MemmapStorage):
            manifest["scratch_dir"] = buffer.storage.scratch_dir
            buffer.storage.flush()
        with open(path + ".json", "w") as f:
            json.dump(manifest, f)


def load_buffer_state(buffer, path: str) -> ArrayDict:
    """Rebuild buffer state saved by :func:`save_buffer_state`."""
    flat = {}
    with np.load(path + ".npz") as z:
        for k in z.files:
            flat[tuple(k.split(_SEP))] = jnp.asarray(z[k])
    state = ArrayDict()
    for k, v in flat.items():
        state = state.set(k, v)
    # leaf-less subtrees (e.g. a RandomSampler's empty state) leave no
    # arrays behind — rebuild them from the buffer's components
    if "sampler" not in state:
        state = state.set("sampler", buffer.sampler.init(buffer.capacity))
    if "writer" not in state:
        state = state.set("writer", buffer.writer.init(buffer.capacity))
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            manifest = json.load(f)
        for k, node in manifest["host_state"].items():
            state = state.set(tuple(k.split(_SEP)), node)
        if "scratch_dir" in manifest and isinstance(buffer.storage, MemmapStorage):
            # point the storage at the checkpointed memmaps; the caller's
            # next buffer.init(example) reattaches them without truncation
            # (MemmapStorage.init opens existing right-sized files "r+")
            buffer.storage.scratch_dir = manifest["scratch_dir"]
    return state
