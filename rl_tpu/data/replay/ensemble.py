"""Replay buffer ensembles (reference: torchrl/data/replay_buffers/
replay_buffers.py:3064 ``ReplayBufferEnsemble``).

Samples a full batch from EACH member buffer, then composes the final batch
by drawing each row from member ``m`` with probability ``weights[m]`` — the
jit-friendly formulation of the reference's per-sample buffer choice (all
gathers are fixed-shape; the mixture select is a ``where``). Used for
offline-to-online mixes (expert dataset + online buffer).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict
from .buffer import ReplayBuffer

__all__ = ["ReplayBufferEnsemble"]


class ReplayBufferEnsemble:
    def __init__(self, *buffers: ReplayBuffer, weights=None, batch_size: int | None = None):
        if not buffers:
            raise ValueError("need at least one member buffer")
        self.buffers = list(buffers)
        w = jnp.asarray(
            weights if weights is not None else [1.0] * len(buffers), jnp.float32
        )
        self.weights = w / w.sum()
        self.batch_size = batch_size

    def init(self, example: ArrayDict) -> ArrayDict:
        return ArrayDict(
            {f"b{i}": rb.init(example) for i, rb in enumerate(self.buffers)}
        )

    def extend_member(self, state: ArrayDict, which: int, items: ArrayDict, n=None):
        key = f"b{which}"
        return state.set(key, self.buffers[which].extend(state[key], items, n=n))

    def size(self, state: ArrayDict):
        return sum(
            jnp.asarray(rb.size(state[f"b{i}"]))
            for i, rb in enumerate(self.buffers)
        )

    def sample(
        self, state: ArrayDict, key: jax.Array, batch_size: int | None = None
    ) -> tuple[ArrayDict, ArrayDict]:
        bs = batch_size or self.batch_size
        if bs is None:
            raise ValueError("batch_size not set")
        kc, *keys = jax.random.split(key, len(self.buffers) + 1)
        batches, new_state = [], state
        for i, (rb, k) in enumerate(zip(self.buffers, keys)):
            b, s = rb.sample(state[f"b{i}"], k, batch_size=bs)
            # members can disagree on info keys (PER adds _weight) — keep
            # the intersection so the mixture select has one structure
            batches.append(b)
            new_state = new_state.set(f"b{i}", s)
        shared = set(batches[0].keys(nested=True, leaves_only=True))
        for b in batches[1:]:
            shared &= set(b.keys(nested=True, leaves_only=True))
        batches = [b.select(*shared) for b in batches]
        # empty members must not contribute (their samplers clamp to row 0
        # of unwritten storage); zero their weight and renormalize
        sizes = jnp.stack(
            [
                jnp.asarray(rb.size(state[f"b{i}"]), jnp.float32)
                for i, rb in enumerate(self.buffers)
            ]
        )
        w = self.weights * (sizes > 0)
        w = w / jnp.clip(w.sum(), 1e-12)
        which = jax.random.choice(kc, len(self.buffers), (bs,), p=w)
        stacked = ArrayDict.stack(batches, axis=0)  # [M, bs, ...]

        def pick(leaf):
            w = which.reshape((1, bs) + (1,) * (leaf.ndim - 2)).astype(jnp.int32)
            return jnp.take_along_axis(leaf, w, axis=0)[0]

        out = stacked.apply(pick)
        return out.set("buffer_ids", which.astype(jnp.int32)), new_state
