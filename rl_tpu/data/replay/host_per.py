"""Host-side prioritized sampler backed by the C++ segment trees.

The reference's PER architecture (reference:
torchrl/data/replay_buffers/samplers.py:942 ``PrioritizedSampler`` over the
C++ trees): O(log N) point updates and prefix-search sampling on the host.
Use with host storages (MemmapStorage / ListStorage) where the buffer never
enters XLA; the device path is :class:`rl_tpu.data.PrioritizedSampler`.

NOT jit-traceable (mutates native trees) — by construction, like the
reference.
"""

from __future__ import annotations

import numpy as np

import jax

from ...csrc import MinSegmentTree, SumSegmentTree
from ..arraydict import ArrayDict
from .samplers import Sampler

__all__ = ["HostPrioritizedSampler"]


class HostPrioritizedSampler(Sampler):
    def __init__(self, alpha: float = 0.6, beta: float = 0.4, eps: float = 1e-8):
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._sum = None
        self._min = None
        self._max_priority = 1.0

    def init(self, capacity: int) -> ArrayDict:
        self.capacity = capacity
        self._sum = SumSegmentTree(capacity)
        self._min = MinSegmentTree(capacity)
        return ArrayDict()

    def on_write(self, sstate, idx, items):
        idx = np.asarray(idx)
        p = self._max_priority**self.alpha
        self._sum[idx] = np.full(idx.shape, p)
        self._min[idx] = np.full(idx.shape, p)
        return sstate

    def update_priority(self, sstate, idx, priority):
        idx = np.asarray(idx)
        priority = np.abs(np.asarray(priority, np.float64)) + self.eps
        self._max_priority = max(self._max_priority, float(priority.max()))
        p = priority**self.alpha
        self._sum[idx] = p
        self._min[idx] = p
        return sstate

    def sample(self, sstate, key, batch_size, size, capacity):
        total = self._sum.reduce(0, int(size))
        us = np.asarray(jax.random.uniform(key, (batch_size,))) * total
        idx = self._sum.scan(us)
        idx = np.minimum(idx, int(size) - 1)

        n = max(int(size), 1)
        probs = self._sum[idx] / max(total, 1e-12)
        weights = (n * np.clip(probs, 1e-12, None)) ** -self.beta
        min_prob = self._min.reduce(0, int(size)) / max(total, 1e-12)
        max_w = (n * max(min_prob, 1e-12)) ** -self.beta
        weights = weights / max(max_w, 1e-12)
        info = ArrayDict(
            _weight=jax.numpy.asarray(weights, jax.numpy.float32),
            index=jax.numpy.asarray(idx, jax.numpy.int32),
        )
        return jax.numpy.asarray(idx, jax.numpy.int32), info, sstate
