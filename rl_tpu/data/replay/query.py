"""Ordered / range access over replay storage (reference: torchrl/data/
replay_buffers/query.py — range and ordered storage reads outside the
sampler path).

The sampler API answers "give me a random batch"; these helpers answer
"give me rows [a, b)", "iterate the buffer in insertion order", "give me
the most recent k" — needed by offline evaluation, dataset export, and
staleness inspection. All device-path functions are jit-safe fixed-shape
gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = ["read_range", "read_latest", "iterate_ordered", "insertion_order_indices"]


def read_range(buffer, state: ArrayDict, start: int, stop: int) -> ArrayDict:
    """Rows [start, stop) in STORAGE order (static bounds; jit-safe)."""
    idx = jnp.arange(start, stop)
    return buffer.storage.get(state["storage"], idx)


def insertion_order_indices(buffer, state: ArrayDict) -> jax.Array:
    """Storage indices sorted oldest -> newest for a ring-written buffer.

    With a RoundRobinWriter the write cursor wraps: the oldest row is at
    ``cursor`` once the ring is full, else at 0. Returns a full-capacity
    index vector; only the first ``size`` entries are valid.
    """
    cap = buffer.capacity
    cursor = state["storage"]["cursor"]
    size = buffer.size(state)
    full = size >= cap
    startpos = jnp.where(full, cursor, 0)
    return (startpos + jnp.arange(cap)) % cap


def read_latest(buffer, state: ArrayDict, k: int) -> ArrayDict:
    """The k most recently written rows, newest last (static k).

    When fewer than k rows have been written, the OLDEST written row is
    repeated at the front (fixed output shape; never fabricates unwritten
    zero rows).
    """
    cap = buffer.capacity
    cursor = state["storage"]["cursor"]
    size = jnp.asarray(buffer.size(state))
    ring = (cursor - k + jnp.arange(k)) % cap          # size >= cap case
    oldest = jnp.where(size >= cap, cursor % cap, 0)
    lin = jnp.clip(size - k + jnp.arange(k), 0, jnp.maximum(size - 1, 0))
    idx = jnp.where(size >= cap, ring, (oldest + lin) % cap)
    return buffer.storage.get(state["storage"], idx)


def iterate_ordered(buffer, state: ArrayDict, batch_size: int):
    """Host-side generator over the buffer in insertion order (reference
    ordered access / __iter__). Not jit: intended for export/eval loops."""
    import numpy as np

    order = np.asarray(insertion_order_indices(buffer, state))
    size = int(buffer.size(state))
    for i in range(0, size, batch_size):
        idx = jnp.asarray(order[i : min(i + batch_size, size)])
        yield buffer.storage.get(state["storage"], idx)
