"""Replay samplers, including on-device prioritized sampling.

Redesign of the reference sampler suite (reference:
torchrl/data/replay_buffers/samplers.py — ``Sampler``:106,
``RandomSampler``:181, ``SamplerWithoutReplacement``:580,
``PrioritizedSampler``:942 (C++ segment trees), ``SliceSampler``:1696).

**PER as a device sum-tree.** The reference's prioritized sampler does
O(log N) point queries on a host C++ sum-tree — a pointer-chasing,
host-resident structure that is the wrong shape for TPU. Here the same
asymptotics move on device: a *flat level-array* sum-tree (wide fanout,
each level one flat array, see :class:`PrioritizedSampler`) supports
batched stratified inverse-CDF descent as one vectorized ``searchsorted``
plus a ``[B, F]`` gather, and priority write-back as batched segment
scatter-adds — fully vectorized, living inside the same XLA program as the
train step with zero host round-trips (``sample_and_update`` fuses the
whole cycle).

Sampler state (annealed β, without-replacement permutations, PER
priorities) is functional and threads through jit like storage state.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = [
    "Sampler",
    "StalenessAwareSampler",
    "RandomSampler",
    "SamplerWithoutReplacement",
    "PrioritizedSampler",
    "SliceSampler",
    "SliceSamplerWithoutReplacement",
    "PrioritizedSliceSampler",
]


class Sampler:
    """Abstract sampler: ``init(capacity)`` builds state; ``sample`` returns
    (indices, info, new_state); hooks for writes/priority updates."""

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict()

    def sample(
        self, sstate: ArrayDict, key: jax.Array, batch_size: int, size: jax.Array, capacity: int
    ) -> tuple[jax.Array, ArrayDict, ArrayDict]:
        raise NotImplementedError

    def on_write(self, sstate: ArrayDict, idx: jax.Array, items: ArrayDict) -> ArrayDict:
        return sstate

    def update_priority(self, sstate: ArrayDict, idx: jax.Array, priority: jax.Array) -> ArrayDict:
        return sstate


class RandomSampler(Sampler):
    """Uniform with replacement (reference samplers.py:181)."""

    def sample(self, sstate, key, batch_size, size, capacity):
        idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(size, 1))
        return idx, ArrayDict(), sstate


class SamplerWithoutReplacement(Sampler):
    """Epoch-style without-replacement sampling (reference samplers.py:580).

    Keeps a per-epoch random offset + permutation seed; when a pass over the
    data completes, reshuffles. Jit-safe via counter arithmetic: position
    ``p`` in the epoch maps through a pseudorandom permutation derived from
    the epoch seed (feistel-free: regenerated `jax.random.permutation` of a
    fixed capacity, masked to size).
    """

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(
            pos=jnp.asarray(0, jnp.int32),
            epoch=jnp.asarray(0, jnp.int32),
            epoch_key=jax.random.key(0),  # placeholder; replaced on 1st sample
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        from ...utils.seeding import ensure_typed_key

        key = ensure_typed_key(key)
        pos = sstate["pos"]
        # new epoch when the remaining data can't fill this batch, and always
        # on the first sample (the init key is a placeholder, not the
        # caller's randomness)
        need_reshuffle = (pos + batch_size > size) | (sstate["epoch"] == 0)
        epoch_key = jax.lax.select(need_reshuffle, key, sstate["epoch_key"])
        pos = jnp.where(need_reshuffle, 0, pos)
        # random permutation of [0, capacity); keep only values < size, in
        # permutation order, via scatter-by-rank (OOB targets are dropped)
        perm = jax.random.permutation(epoch_key, capacity)
        valid = perm < size
        rank = jnp.cumsum(valid) - 1
        target = jnp.where(valid, rank, capacity)
        filled_order = (
            jnp.zeros((capacity,), perm.dtype).at[target].set(perm, mode="drop")
        )
        wanted = (pos + jnp.arange(batch_size)) % jnp.maximum(size, 1)
        idx = filled_order[wanted]
        new_state = ArrayDict(
            pos=pos + batch_size,
            epoch=sstate["epoch"] + need_reshuffle.astype(jnp.int32),
            epoch_key=epoch_key,
        )
        return idx, ArrayDict(), new_state


class PrioritizedSampler(Sampler):
    """Proportional PER (Schaul et al. 2016; reference samplers.py:942).

    ``P(i) ∝ p_i^α``; importance weights ``w_i = (N·P(i))^{-β}`` normalized
    by the largest weight in the batch (stable-baselines convention — keeps
    the fused cycle free of a min-tree). β anneals linearly to 1 over
    ``beta_annealing_steps`` if set.

    TPU-resident **flat level-array sum-tree**, two levels wide: the leaf
    level stores ``(|p|+eps)^α`` for every slot as one flat f32 array
    (``priorities``, padded to a multiple of the fanout ``F``) and the
    entry level stores per-block sums of ``F`` consecutive leaves
    (``esum``). Sampling is stratified inverse-CDF descent: a block-level
    ``cumsum`` + vectorized ``searchsorted`` picks each draw's block, then
    ONE ``[B, F]`` gather of that block's leaves + a row cumsum + a
    compare resolves the leaf — O(B·(log N/F + F)) fully batched work with
    no host round-trip. Priority write-back is a pair of batched segment
    scatter-adds (leaf delta + block delta) with a last-writer dedup mask,
    so duplicate indices in one batch keep set semantics. ``on_write``
    rebuilds ``esum`` exactly from the leaves (one vectorized row-reduce),
    which also re-zeros any accumulated float drift from the delta path.
    ``sample_and_update`` fuses a sample + learn-priority write-back into
    one traced program so a whole PER cycle admits zero intermediate host
    syncs; with the state donated, XLA updates the tree in place.

    This layout was chosen by measurement over the classic root-to-leaf
    descent tree: on CPU XLA a materialized ``cumsum`` runs ~3 ns/element
    *serially* and every live gather/scatter costs ~10-16 µs dispatch, so
    one small entry cumsum + one row gather + two scatter-adds beats both
    a deep gather-descent tree and any flat-cumsum scheme by 3-10x.
    """

    def __init__(
        self,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-8,
        beta_annealing_steps: int | None = None,
        fanout: int = 16,
    ):
        self.alpha = alpha
        self.beta0 = beta
        self.eps = eps
        self.beta_annealing_steps = beta_annealing_steps
        if fanout < 2:
            raise ValueError(f"fanout must be >= 2, got {fanout}")
        self.fanout = fanout

    def _layout(self, capacity: int) -> tuple[int, int]:
        """(num_blocks, padded_len): leaves live in a flat array of
        ``num_blocks * fanout >= capacity`` slots; the pad slots keep zero
        mass forever so they are never sampled. Static python ints."""
        n_blocks = max(1, -(-capacity // self.fanout))
        return n_blocks, n_blocks * self.fanout

    def init(self, capacity: int) -> ArrayDict:
        n_blocks, padded = self._layout(capacity)
        return ArrayDict(
            priorities=jnp.zeros((padded,), jnp.float32),
            esum=jnp.zeros((n_blocks,), jnp.float32),
            max_priority=jnp.asarray(1.0, jnp.float32),
            step=jnp.asarray(0, jnp.int32),
        )

    def _beta(self, step):
        if self.beta_annealing_steps is None:
            return jnp.asarray(self.beta0, jnp.float32)
        frac = jnp.clip(step.astype(jnp.float32) / self.beta_annealing_steps, 0.0, 1.0)
        return self.beta0 + (1.0 - self.beta0) * frac

    def _pa(self, priority):
        p = jnp.abs(jnp.asarray(priority, jnp.float32)).reshape(-1) + self.eps
        return jnp.power(p, self.alpha)

    def _delta_update(self, sstate, idx, pa_new, *, indices_sorted):
        """Set leaves at ``idx`` to ``pa_new`` via delta scatter-adds on
        both levels. Duplicate indices collapse to the last writer: with
        sorted indices a neighbor compare marks it; otherwise a
        segment-max of positions finds it."""
        idx = jnp.asarray(idx).reshape(-1)
        b = idx.shape[0]
        leaves = sstate["priorities"]
        if b > 1:
            if indices_sorted:
                last = jnp.concatenate(
                    [idx[:-1] != idx[1:], jnp.ones((1,), bool)]
                )
            else:
                pos = jnp.arange(1, b + 1, dtype=jnp.int32)
                win = (
                    jnp.zeros((leaves.shape[0],), jnp.int32).at[idx].max(pos)
                )
                last = win[idx] == pos
            delta = jnp.where(last, pa_new - leaves[idx], 0.0)
        else:
            delta = pa_new - leaves[idx]
        # both tree levels in one pass where the Pallas tier is active;
        # the fallback inside is the two stock scatter-adds (bit-exact
        # either way — tests/test_kernels.py gates it)
        from ...kernels.sumtree import sumtree_update

        priorities, esum = sumtree_update(
            leaves, sstate["esum"], idx, delta, fanout=self.fanout
        )
        return sstate.replace(priorities=priorities, esum=esum)

    def sample(self, sstate, key, batch_size, size, capacity):
        F = self.fanout
        n_blocks, _ = self._layout(capacity)
        esum = sstate["esum"]
        block_csum = jnp.cumsum(esum)
        total = block_csum[-1]
        # stratified draws: one per equal slice of the total mass — same
        # marginal distribution as iid inverse-CDF, lower variance. Also
        # means the returned indices are ascending, which the fused update
        # path exploits for cheap duplicate detection.
        u = (
            (jnp.arange(batch_size) + jax.random.uniform(key, (batch_size,)))
            / batch_size
            * total
        )
        block = jnp.clip(
            jnp.searchsorted(block_csum, u, side="right"), 0, n_blocks - 1
        )
        r = u - jnp.where(block > 0, block_csum[jnp.maximum(block - 1, 0)], 0.0)
        rows = sstate["priorities"].reshape(n_blocks, F)[block]  # [B, F]
        csum = jnp.cumsum(rows, axis=-1)
        # clamp the residual strictly inside this block's total: the
        # running esum and the freshly-reduced row cumsum can disagree in
        # the last ulps, and an over-long residual would step into
        # zero/unwritten trailing leaves
        r = jnp.minimum(r, csum[:, -1] * (1.0 - 1e-6))
        col = jnp.clip(
            jnp.sum((csum <= r[:, None]).astype(jnp.int32), axis=-1), 0, F - 1
        )
        idx = jnp.clip(block * F + col, 0, capacity - 1)

        beta = self._beta(sstate["step"])
        n = jnp.maximum(size.astype(jnp.float32), 1.0)
        total_c = jnp.clip(total, 1e-12)
        p_alpha = jnp.take_along_axis(rows, col[:, None], axis=-1)[:, 0]
        weights = jnp.power(n * jnp.clip(p_alpha / total_c, 1e-12), -beta)
        weights = weights / jnp.clip(jnp.max(weights), 1e-12)
        info = ArrayDict(_weight=weights, index=idx)
        return idx, info, sstate.set("step", sstate["step"] + 1)

    def on_write(self, sstate, idx, items):
        # new samples get max priority (reference behavior); then rebuild
        # the block sums exactly from the leaves — one vectorized
        # row-reduce that also cancels any float drift the delta-add
        # sample/update hot path accumulated since the last write
        idx = jnp.asarray(idx).reshape(-1)
        pa = jnp.broadcast_to(
            jnp.power(sstate["max_priority"], self.alpha), idx.shape
        ).astype(jnp.float32)
        leaves = sstate["priorities"].at[idx].set(pa)
        esum = leaves.reshape(-1, self.fanout).sum(axis=-1)
        return sstate.replace(priorities=leaves, esum=esum)

    def update_priority(self, sstate, idx, priority, *, indices_sorted=False):
        priority = jnp.abs(jnp.asarray(priority, jnp.float32)).reshape(-1)
        sstate = self._delta_update(
            sstate, idx, self._pa(priority), indices_sorted=indices_sorted
        )
        max_p = jnp.maximum(sstate["max_priority"], jnp.max(priority) + self.eps)
        return sstate.set("max_priority", max_p)

    def sample_and_update(
        self,
        sstate: ArrayDict,
        key: jax.Array,
        batch_size: int,
        size: jax.Array,
        capacity: int,
        priority_fn: Callable[[jax.Array, ArrayDict], jax.Array],
    ) -> tuple[jax.Array, ArrayDict, ArrayDict]:
        """One fused PER cycle: sample a batch, derive its new priorities
        (``priority_fn(idx, info) -> [B]`` — typically the learner's
        td-error on the gathered batch), write them back. Everything stays
        in one traced program: jit this (ideally with the state donated)
        and the whole sample→learn→update round runs with zero
        intermediate host transfers. Stratified sampling returns ascending
        indices, so the write-back takes the cheap sorted dedup path."""
        idx, info, sstate = self.sample(sstate, key, batch_size, size, capacity)
        sstate = self.update_priority(
            sstate, idx, priority_fn(idx, info), indices_sorted=True
        )
        return idx, info, sstate

    def jit_sample_and_update(
        self,
        priority_fn: Callable[[jax.Array, ArrayDict], jax.Array],
        batch_size: int,
        capacity: int,
        *,
        donate: bool = True,
        fingerprint: str = "",
        warmup: bool = False,
    ):
        """The fused PER cycle as a registered hot program
        (``per.sample_and_update`` in the
        :class:`~rl_tpu.compile.ProgramRegistry`): named compile
        attribution, ``aot_warmup``, and the persistent executable store,
        instead of an anonymous ``jax.jit`` at every call site.

        Returns ``prog(sstate, key, size) -> (idx, info, sstate)`` with
        ``batch_size``/``capacity`` closed over (they are static) and, by
        default, ``sstate`` donated — XLA updates the tree in place.
        ``fingerprint`` must distinguish callers whose ``priority_fn``
        closures differ (e.g. hash of the learner config); ``warmup=True``
        AOT-compiles eagerly from :meth:`init`'s abstract layout.
        """
        from ...compile import abstract_like, get_program_registry

        def fused(sstate, key, size):
            return self.sample_and_update(
                sstate, key, batch_size, size, capacity, priority_fn
            )

        from ...kernels.registry import kernels_fingerprint

        registry = get_program_registry()
        prog = registry.register(
            "per.sample_and_update",
            fused,
            # kernels_fingerprint: an executable with the fused sum-tree
            # kernel baked in must never be store-loaded by a process
            # running the fallback (or vice versa)
            fingerprint=repr((
                self.alpha, self.beta0, self.eps, self.beta_annealing_steps,
                self.fanout, batch_size, capacity, fingerprint,
                kernels_fingerprint(),
            )),
            donate_argnums=(0,) if donate else (),
            # the PER tree lives on one device; a collective in its
            # lowering means the sampler state was accidentally sharded.
            # kernel_hot_path: R106 flags this program if the backend
            # supports the sumtree kernel but the lowering fell back
            ir_contract={"shard_local": True, "kernel_hot_path": ("sumtree",)},
        )
        if warmup:
            prog.add_signature(
                abstract_like(self.init(capacity)),
                jax.ShapeDtypeStruct((), jax.random.key(0).dtype),
                jax.ShapeDtypeStruct((), jnp.int32),
            )
            registry.aot_warmup(programs=[prog])
        return prog


class StalenessAwareSampler(Sampler):
    """Freshness-weighted sampling (reference StalenessAwareSampler,
    samplers.py:735): each slot records the global write version; sampling
    probability is proportional to ``(1 + staleness)^-eta`` and entries
    older than ``max_staleness`` versions are excluded outright. Samples
    also carry "staleness" for diagnostics.

    When incoming items carry a ``stamp_key`` column (the
    ``("collector", "policy_version")`` stamps emitted per-item by
    ``AsyncHostCollector``), those versions are written per slot instead of
    a single synthetic counter bump — transitions collected with an old
    policy enter the buffer already stale, even when they arrive in the
    same ``extend`` as fresh ones (first-come async batches mix versions)."""

    def __init__(
        self,
        eta: float = 1.0,
        max_staleness: int | None = None,
        stamp_key=("collector", "policy_version"),
    ):
        self.eta = eta
        self.max_staleness = max_staleness
        self.stamp_key = stamp_key

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(
            written=jnp.zeros((capacity,), jnp.int32),
            version=jnp.asarray(0, jnp.int32),
        )

    def on_write(self, sstate, idx, items):
        if self.stamp_key is not None and self.stamp_key in items:
            v_items = items[self.stamp_key].astype(jnp.int32).reshape(jnp.shape(idx))
            # version tracks the freshest stamp seen (never decreases), so
            # staleness = version - written stays ≥ 0 and monotone per slot
            version = jnp.maximum(sstate["version"], jnp.max(v_items))
            return ArrayDict(
                written=sstate["written"].at[idx].set(v_items), version=version
            )
        v = sstate["version"] + 1
        return ArrayDict(written=sstate["written"].at[idx].set(v), version=v)

    def sample(self, sstate, key, batch_size, size, capacity):
        stal_all = (sstate["version"] - sstate["written"]).astype(jnp.float32)
        mask = jnp.arange(capacity) < size
        if self.max_staleness is not None:
            mask = mask & (stal_all <= self.max_staleness)
        w = jnp.where(mask, jnp.power(1.0 + stal_all, -self.eta), 0.0)
        csum = jnp.cumsum(w)
        # fall back to uniform-over-filled when everything is gated out
        any_mass = csum[-1] > 0
        k_w, k_u = jax.random.split(key)
        u = jax.random.uniform(k_w, (batch_size,)) * jnp.where(any_mass, csum[-1], 1.0)
        idx_w = jnp.clip(jnp.searchsorted(csum, u, side="right"), 0, capacity - 1)
        idx_u = jax.random.randint(k_u, (batch_size,), 0, jnp.maximum(size, 1))
        idx = jnp.where(any_mass, idx_w, idx_u)
        info = ArrayDict(staleness=stal_all[idx])
        return idx, info, sstate


class SliceSampler(Sampler):
    """Trajectory-slice sampling for sequence training (reference
    samplers.py:1696): sample windows of ``slice_len`` consecutive steps that
    do not cross episode boundaries.

    Requires the buffer to store ``("collector","traj_ids")`` (written by the
    Collector). Sampling: draw start indices, accept those whose window stays
    within one trajectory id, resampling rejects via a fixed number of
    parallel candidates (jit-safe, no dynamic loop): draw ``oversample``
    candidates per slot and pick the first valid one.
    """

    def __init__(self, slice_len: int, traj_key=("collector", "traj_ids"), oversample: int = 8):
        self.slice_len = slice_len
        self.traj_key = traj_key
        self.oversample = oversample

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(traj_ids=jnp.full((capacity,), -1, jnp.int32))

    def on_write(self, sstate, idx, items):
        if self.traj_key in items:
            tid = items[self.traj_key].astype(jnp.int32)
        else:
            tid = jnp.zeros(jnp.shape(idx), jnp.int32)
        return sstate.set("traj_ids", sstate["traj_ids"].at[idx].set(tid))

    def sample(self, sstate, key, batch_size, size, capacity):
        num_slices = batch_size // self.slice_len
        tids = sstate["traj_ids"]
        hi = jnp.maximum(size - self.slice_len + 1, 1)
        starts = jax.random.randint(
            key, (num_slices, self.oversample), 0, hi
        )

        window = jnp.arange(self.slice_len)

        def valid(start):
            w = tids[start + window]
            return jnp.all(w == w[0]) & (w[0] >= 0)

        ok = jax.vmap(jax.vmap(valid))(starts)  # [num_slices, oversample]
        first = jnp.argmax(ok, axis=1)
        chosen = jnp.take_along_axis(starts, first[:, None], axis=1)[:, 0]
        any_ok = jnp.any(ok, axis=1)
        # fall back to the first candidate when none valid (short buffers);
        # consumers MUST mask those steps out via "mask" (losses here read it
        # by default through their mask_key)
        chosen = jnp.where(any_ok, chosen, starts[:, 0])
        idx = (chosen[:, None] + window[None, :]).reshape(-1)
        step_mask = jnp.repeat(any_ok, self.slice_len)
        info = ArrayDict(valid_slices=any_ok, mask=step_mask)
        return idx, info, sstate


class SliceSamplerWithoutReplacement(SliceSampler):
    """Epoch-style trajectory-slice sampling (reference
    SliceSamplerWithoutReplacement, samplers.py:2789): each epoch permutes
    all candidate start positions and walks them in order, so no slice start
    repeats until the pass completes. Starts whose window crosses an episode
    boundary are masked invalid in "mask"/"valid_slices" (jit-safe
    alternative to dynamic filtering; consumers already honor the mask).
    """

    def init(self, capacity: int) -> ArrayDict:
        base = super().init(capacity)
        return base.update(
            ArrayDict(
                pos=jnp.asarray(0, jnp.int32),
                epoch=jnp.asarray(0, jnp.int32),
                epoch_key=jax.random.key(0),
            )
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        from ...utils.seeding import ensure_typed_key

        key = ensure_typed_key(key)
        num_slices = batch_size // self.slice_len
        hi = jnp.maximum(size - self.slice_len + 1, 1)
        pos = sstate["pos"]
        need_reshuffle = (pos + num_slices > hi) | (sstate["epoch"] == 0)
        epoch_key = jax.lax.select(need_reshuffle, key, sstate["epoch_key"])
        pos = jnp.where(need_reshuffle, 0, pos)

        perm = jax.random.permutation(epoch_key, capacity)
        valid_start = perm < hi
        rank = jnp.cumsum(valid_start) - 1
        target = jnp.where(valid_start, rank, capacity)
        order = jnp.zeros((capacity,), perm.dtype).at[target].set(perm, mode="drop")
        wanted = (pos + jnp.arange(num_slices)) % hi
        starts = order[wanted]

        window = jnp.arange(self.slice_len)
        tids = sstate["traj_ids"]

        def valid(start):
            w = tids[start + window]
            return jnp.all(w == w[0]) & (w[0] >= 0)

        ok = jax.vmap(valid)(starts)
        idx = (starts[:, None] + window[None, :]).reshape(-1)
        info = ArrayDict(valid_slices=ok, mask=jnp.repeat(ok, self.slice_len))
        new_state = sstate.replace(
            pos=pos + num_slices,
            epoch=sstate["epoch"] + need_reshuffle.astype(jnp.int32),
            epoch_key=epoch_key,
        )
        return idx, info, new_state


class PrioritizedSliceSampler(SliceSampler):
    """PER over trajectory slices (reference PrioritizedSliceSampler,
    samplers.py:3091): each start position's priority is its element's PER
    priority; invalid starts (window crossing an episode boundary) get zero
    mass. update_priority is element-wise like PrioritizedSampler.
    """

    def __init__(
        self,
        slice_len: int,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-8,
        traj_key=("collector", "traj_ids"),
    ):
        super().__init__(slice_len, traj_key=traj_key)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps

    def init(self, capacity: int) -> ArrayDict:
        base = super().init(capacity)
        return base.update(
            ArrayDict(
                priorities=jnp.zeros((capacity,), jnp.float32),
                max_priority=jnp.asarray(1.0, jnp.float32),
            )
        )

    def on_write(self, sstate, idx, items):
        sstate = super().on_write(sstate, idx, items)
        prio = sstate["priorities"].at[idx].set(sstate["max_priority"])
        return sstate.set("priorities", prio)

    def update_priority(self, sstate, idx, priority):
        priority = jnp.abs(priority) + self.eps
        prio = sstate["priorities"].at[idx].set(priority)
        return sstate.replace(
            priorities=prio,
            max_priority=jnp.maximum(sstate["max_priority"], jnp.max(priority)),
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        num_slices = batch_size // self.slice_len
        window = jnp.arange(self.slice_len)
        tids = sstate["traj_ids"]
        positions = jnp.arange(capacity)
        hi = jnp.maximum(size - self.slice_len + 1, 1)

        def start_ok(start):
            w = tids[jnp.minimum(start + window, capacity - 1)]
            return jnp.all(w == w[0]) & (w[0] >= 0) & (start < hi)

        valid = jax.vmap(start_ok)(positions)
        p_alpha = jnp.where(
            valid, jnp.power(sstate["priorities"] + self.eps, self.alpha), 0.0
        )
        csum = jnp.cumsum(p_alpha)
        total = jnp.clip(csum[-1], 1e-12)
        u = jax.random.uniform(key, (num_slices,)) * total
        starts = jnp.clip(jnp.searchsorted(csum, u, side="right"), 0, capacity - 1)

        probs = p_alpha / total
        n = jnp.clip(jnp.sum(valid.astype(jnp.float32)), 1.0)
        weights = jnp.power(n * jnp.clip(probs[starts], 1e-12), -self.beta)
        # normalize by the max POSSIBLE weight (min valid prob), like
        # PrioritizedSampler — per-batch max would rescale the loss with
        # sampling luck
        min_prob = jnp.min(jnp.where(valid, probs, jnp.inf))
        max_w = jnp.power(n * jnp.clip(min_prob, 1e-12), -self.beta)
        weights = weights / jnp.clip(max_w, 1e-12)

        idx = (starts[:, None] + window[None, :]).reshape(-1)
        ok = valid[starts]
        info = ArrayDict(
            valid_slices=ok,
            mask=jnp.repeat(ok, self.slice_len),
            _weight=jnp.repeat(weights, self.slice_len),
            start_index=starts,
        )
        return idx, info, sstate
