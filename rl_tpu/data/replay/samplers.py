"""Replay samplers, including on-device prioritized sampling.

Redesign of the reference sampler suite (reference:
torchrl/data/replay_buffers/samplers.py — ``Sampler``:106,
``RandomSampler``:181, ``SamplerWithoutReplacement``:580,
``PrioritizedSampler``:942 (C++ segment trees), ``SliceSampler``:1696).

**PER without segment trees.** The reference's prioritized sampler does
O(log N) point queries on a host C++ sum-tree — a pointer-chasing,
host-resident structure that is the wrong shape for TPU. Here sampling is a
parallel prefix-sum + batched ``searchsorted`` over the whole priority
array: O(N log N) work but fully vectorized on the VPU with zero host
round-trips, and it lives inside the same XLA program as the train step.
At reference-scale capacities (1e5-1e6) this is bandwidth-trivial next to
the gradient step. Priority *updates* are pure scatters.

Sampler state (annealed β, without-replacement permutations, PER
priorities) is functional and threads through jit like storage state.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = [
    "Sampler",
    "StalenessAwareSampler",
    "RandomSampler",
    "SamplerWithoutReplacement",
    "PrioritizedSampler",
    "SliceSampler",
    "SliceSamplerWithoutReplacement",
    "PrioritizedSliceSampler",
]


class Sampler:
    """Abstract sampler: ``init(capacity)`` builds state; ``sample`` returns
    (indices, info, new_state); hooks for writes/priority updates."""

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict()

    def sample(
        self, sstate: ArrayDict, key: jax.Array, batch_size: int, size: jax.Array, capacity: int
    ) -> tuple[jax.Array, ArrayDict, ArrayDict]:
        raise NotImplementedError

    def on_write(self, sstate: ArrayDict, idx: jax.Array, items: ArrayDict) -> ArrayDict:
        return sstate

    def update_priority(self, sstate: ArrayDict, idx: jax.Array, priority: jax.Array) -> ArrayDict:
        return sstate


class RandomSampler(Sampler):
    """Uniform with replacement (reference samplers.py:181)."""

    def sample(self, sstate, key, batch_size, size, capacity):
        idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(size, 1))
        return idx, ArrayDict(), sstate


class SamplerWithoutReplacement(Sampler):
    """Epoch-style without-replacement sampling (reference samplers.py:580).

    Keeps a per-epoch random offset + permutation seed; when a pass over the
    data completes, reshuffles. Jit-safe via counter arithmetic: position
    ``p`` in the epoch maps through a pseudorandom permutation derived from
    the epoch seed (feistel-free: regenerated `jax.random.permutation` of a
    fixed capacity, masked to size).
    """

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(
            pos=jnp.asarray(0, jnp.int32),
            epoch=jnp.asarray(0, jnp.int32),
            epoch_key=jax.random.key(0),  # placeholder; replaced on 1st sample
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        from ...utils.seeding import ensure_typed_key

        key = ensure_typed_key(key)
        pos = sstate["pos"]
        # new epoch when the remaining data can't fill this batch, and always
        # on the first sample (the init key is a placeholder, not the
        # caller's randomness)
        need_reshuffle = (pos + batch_size > size) | (sstate["epoch"] == 0)
        epoch_key = jax.lax.select(need_reshuffle, key, sstate["epoch_key"])
        pos = jnp.where(need_reshuffle, 0, pos)
        # random permutation of [0, capacity); keep only values < size, in
        # permutation order, via scatter-by-rank (OOB targets are dropped)
        perm = jax.random.permutation(epoch_key, capacity)
        valid = perm < size
        rank = jnp.cumsum(valid) - 1
        target = jnp.where(valid, rank, capacity)
        filled_order = (
            jnp.zeros((capacity,), perm.dtype).at[target].set(perm, mode="drop")
        )
        wanted = (pos + jnp.arange(batch_size)) % jnp.maximum(size, 1)
        idx = filled_order[wanted]
        new_state = ArrayDict(
            pos=pos + batch_size,
            epoch=sstate["epoch"] + need_reshuffle.astype(jnp.int32),
            epoch_key=epoch_key,
        )
        return idx, ArrayDict(), new_state


class PrioritizedSampler(Sampler):
    """Proportional PER (Schaul et al. 2016; reference samplers.py:942).

    ``P(i) ∝ p_i^α``; importance weights ``w_i = (N·P(i))^{-β}`` normalized
    by ``max w`` (reference convention: weights relative to the minimum
    priority). β anneals linearly to 1 over ``beta_annealing_steps`` if set.

    TPU-resident two-level prefix sum (the on-device answer to the
    reference's host C++ segment tree): the sampler state carries
    ``p_alpha`` (= ``(p+eps)^α``), per-chunk sums and per-chunk nonzero
    mins, all maintained incrementally by ``on_write``/``update_priority``
    (exact per-chunk recompute of the touched chunks — no float drift).
    Sampling then inverts the CDF hierarchically: cumsum over ``√N`` chunk
    sums, pick a chunk per draw, cumsum within the gathered chunk rows —
    O(B·√N) work per sample instead of O(N) power+cumsum+min over the
    whole buffer. The sampled distribution and weights are bit-identical
    to the flat inversion modulo float summation order.
    """

    def __init__(
        self,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-8,
        beta_annealing_steps: int | None = None,
    ):
        self.alpha = alpha
        self.beta0 = beta
        self.eps = eps
        self.beta_annealing_steps = beta_annealing_steps

    @staticmethod
    def _layout(capacity: int) -> tuple[int, int]:
        """(chunk_size, n_chunks): chunk ≈ √capacity rounded to a power of
        two, capacity padded up to a whole number of chunks."""
        chunk = 1 << max(2, math.ceil(math.log2(max(1.0, math.sqrt(capacity)))))
        chunk = min(chunk, max(4, capacity))
        n_chunks = -(-capacity // chunk)
        return chunk, n_chunks

    def init(self, capacity: int) -> ArrayDict:
        chunk, n_chunks = self._layout(capacity)
        return ArrayDict(
            priorities=jnp.zeros((capacity,), jnp.float32),
            p_alpha=jnp.zeros((chunk * n_chunks,), jnp.float32),
            chunk_sums=jnp.zeros((n_chunks,), jnp.float32),
            chunk_mins=jnp.full((n_chunks,), jnp.inf, jnp.float32),
            max_priority=jnp.asarray(1.0, jnp.float32),
            step=jnp.asarray(0, jnp.int32),
        )

    def _beta(self, step):
        if self.beta_annealing_steps is None:
            return jnp.asarray(self.beta0, jnp.float32)
        frac = jnp.clip(step.astype(jnp.float32) / self.beta_annealing_steps, 0.0, 1.0)
        return self.beta0 + (1.0 - self.beta0) * frac

    def _scatter(self, sstate, idx, priority):
        """Write ``priority`` (already |·|+eps) at ``idx`` and exactly
        refresh the touched chunks' sums/mins (duplicate idx safe: every
        per-chunk quantity is recomputed from the post-scatter array)."""
        capacity = sstate["priorities"].shape[0]
        chunk, n_chunks = self._layout(capacity)
        prio = sstate["priorities"].at[idx].set(priority)
        p_alpha = sstate["p_alpha"].at[idx].set(
            jnp.power(priority, self.alpha).astype(jnp.float32)
        )
        cid = idx // chunk
        rows = p_alpha.reshape(n_chunks, chunk)[cid]  # (B, chunk)
        sums = rows.sum(axis=-1)
        mins = jnp.min(jnp.where(rows > 0, rows, jnp.inf), axis=-1)
        return sstate.replace(
            priorities=prio,
            p_alpha=p_alpha,
            chunk_sums=sstate["chunk_sums"].at[cid].set(sums),
            chunk_mins=sstate["chunk_mins"].at[cid].set(mins),
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        chunk, n_chunks = self._layout(capacity)
        p_alpha = sstate["p_alpha"]
        chunk_csum = jnp.cumsum(sstate["chunk_sums"])
        total = chunk_csum[-1]
        u = jax.random.uniform(key, (batch_size,)) * total
        cidx = jnp.clip(
            jnp.searchsorted(chunk_csum, u, side="right"), 0, n_chunks - 1
        )
        resid = u - jnp.where(cidx > 0, chunk_csum[cidx - 1], 0.0)
        rows = p_alpha.reshape(n_chunks, chunk)[cidx]  # (B, chunk)
        row_csum = jnp.cumsum(rows, axis=-1)
        # chunk_sums (rows.sum) and row_csum (cumsum) can disagree in the
        # last float ulps (different summation order under XLA); clamp the
        # residual strictly inside the row total so searchsorted can never
        # step past the last nonzero element into unwritten padding
        resid = jnp.minimum(resid, row_csum[:, -1] * (1.0 - 1e-6))
        within = jax.vmap(
            lambda c, r: jnp.searchsorted(c, r, side="right")
        )(row_csum, resid)
        idx = jnp.clip(cidx * chunk + jnp.clip(within, 0, chunk - 1),
                       0, capacity - 1)

        beta = self._beta(sstate["step"])
        n = jnp.maximum(size.astype(jnp.float32), 1.0)
        total_c = jnp.clip(total, 1e-12)
        weights = jnp.power(n * jnp.clip(p_alpha[idx] / total_c, 1e-12), -beta)
        # normalize by the max possible weight (min priority) for stability;
        # unwritten slots hold p_alpha=0 and are excluded from chunk_mins
        min_prob = jnp.min(sstate["chunk_mins"]) / total_c
        max_w = jnp.power(n * jnp.clip(min_prob, 1e-12), -beta)
        weights = weights / jnp.clip(max_w, 1e-12)
        info = ArrayDict(_weight=weights, index=idx)
        return idx, info, sstate.set("step", sstate["step"] + 1)

    def on_write(self, sstate, idx, items):
        # new samples get max priority (reference behavior)
        prio = jnp.broadcast_to(sstate["max_priority"], jnp.shape(idx))
        return self._scatter(sstate, idx, prio)

    def update_priority(self, sstate, idx, priority):
        priority = jnp.abs(priority) + self.eps
        sstate = self._scatter(sstate, idx, priority)
        max_p = jnp.maximum(sstate["max_priority"], jnp.max(priority))
        return sstate.set("max_priority", max_p)


class StalenessAwareSampler(Sampler):
    """Freshness-weighted sampling (reference StalenessAwareSampler,
    samplers.py:735): each slot records the global write version; sampling
    probability is proportional to ``(1 + staleness)^-eta`` and entries
    older than ``max_staleness`` versions are excluded outright. Samples
    also carry "staleness" for diagnostics."""

    def __init__(self, eta: float = 1.0, max_staleness: int | None = None):
        self.eta = eta
        self.max_staleness = max_staleness

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(
            written=jnp.zeros((capacity,), jnp.int32),
            version=jnp.asarray(0, jnp.int32),
        )

    def on_write(self, sstate, idx, items):
        v = sstate["version"] + 1
        return ArrayDict(written=sstate["written"].at[idx].set(v), version=v)

    def sample(self, sstate, key, batch_size, size, capacity):
        stal_all = (sstate["version"] - sstate["written"]).astype(jnp.float32)
        mask = jnp.arange(capacity) < size
        if self.max_staleness is not None:
            mask = mask & (stal_all <= self.max_staleness)
        w = jnp.where(mask, jnp.power(1.0 + stal_all, -self.eta), 0.0)
        csum = jnp.cumsum(w)
        # fall back to uniform-over-filled when everything is gated out
        any_mass = csum[-1] > 0
        u = jax.random.uniform(key, (batch_size,)) * jnp.where(any_mass, csum[-1], 1.0)
        idx_w = jnp.clip(jnp.searchsorted(csum, u, side="right"), 0, capacity - 1)
        idx_u = jax.random.randint(key, (batch_size,), 0, jnp.maximum(size, 1))
        idx = jnp.where(any_mass, idx_w, idx_u)
        info = ArrayDict(staleness=stal_all[idx])
        return idx, info, sstate


class SliceSampler(Sampler):
    """Trajectory-slice sampling for sequence training (reference
    samplers.py:1696): sample windows of ``slice_len`` consecutive steps that
    do not cross episode boundaries.

    Requires the buffer to store ``("collector","traj_ids")`` (written by the
    Collector). Sampling: draw start indices, accept those whose window stays
    within one trajectory id, resampling rejects via a fixed number of
    parallel candidates (jit-safe, no dynamic loop): draw ``oversample``
    candidates per slot and pick the first valid one.
    """

    def __init__(self, slice_len: int, traj_key=("collector", "traj_ids"), oversample: int = 8):
        self.slice_len = slice_len
        self.traj_key = traj_key
        self.oversample = oversample

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(traj_ids=jnp.full((capacity,), -1, jnp.int32))

    def on_write(self, sstate, idx, items):
        if self.traj_key in items:
            tid = items[self.traj_key].astype(jnp.int32)
        else:
            tid = jnp.zeros(jnp.shape(idx), jnp.int32)
        return sstate.set("traj_ids", sstate["traj_ids"].at[idx].set(tid))

    def sample(self, sstate, key, batch_size, size, capacity):
        num_slices = batch_size // self.slice_len
        tids = sstate["traj_ids"]
        hi = jnp.maximum(size - self.slice_len + 1, 1)
        starts = jax.random.randint(
            key, (num_slices, self.oversample), 0, hi
        )

        window = jnp.arange(self.slice_len)

        def valid(start):
            w = tids[start + window]
            return jnp.all(w == w[0]) & (w[0] >= 0)

        ok = jax.vmap(jax.vmap(valid))(starts)  # [num_slices, oversample]
        first = jnp.argmax(ok, axis=1)
        chosen = jnp.take_along_axis(starts, first[:, None], axis=1)[:, 0]
        any_ok = jnp.any(ok, axis=1)
        # fall back to the first candidate when none valid (short buffers);
        # consumers MUST mask those steps out via "mask" (losses here read it
        # by default through their mask_key)
        chosen = jnp.where(any_ok, chosen, starts[:, 0])
        idx = (chosen[:, None] + window[None, :]).reshape(-1)
        step_mask = jnp.repeat(any_ok, self.slice_len)
        info = ArrayDict(valid_slices=any_ok, mask=step_mask)
        return idx, info, sstate


class SliceSamplerWithoutReplacement(SliceSampler):
    """Epoch-style trajectory-slice sampling (reference
    SliceSamplerWithoutReplacement, samplers.py:2789): each epoch permutes
    all candidate start positions and walks them in order, so no slice start
    repeats until the pass completes. Starts whose window crosses an episode
    boundary are masked invalid in "mask"/"valid_slices" (jit-safe
    alternative to dynamic filtering; consumers already honor the mask).
    """

    def init(self, capacity: int) -> ArrayDict:
        base = super().init(capacity)
        return base.update(
            ArrayDict(
                pos=jnp.asarray(0, jnp.int32),
                epoch=jnp.asarray(0, jnp.int32),
                epoch_key=jax.random.key(0),
            )
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        from ...utils.seeding import ensure_typed_key

        key = ensure_typed_key(key)
        num_slices = batch_size // self.slice_len
        hi = jnp.maximum(size - self.slice_len + 1, 1)
        pos = sstate["pos"]
        need_reshuffle = (pos + num_slices > hi) | (sstate["epoch"] == 0)
        epoch_key = jax.lax.select(need_reshuffle, key, sstate["epoch_key"])
        pos = jnp.where(need_reshuffle, 0, pos)

        perm = jax.random.permutation(epoch_key, capacity)
        valid_start = perm < hi
        rank = jnp.cumsum(valid_start) - 1
        target = jnp.where(valid_start, rank, capacity)
        order = jnp.zeros((capacity,), perm.dtype).at[target].set(perm, mode="drop")
        wanted = (pos + jnp.arange(num_slices)) % hi
        starts = order[wanted]

        window = jnp.arange(self.slice_len)
        tids = sstate["traj_ids"]

        def valid(start):
            w = tids[start + window]
            return jnp.all(w == w[0]) & (w[0] >= 0)

        ok = jax.vmap(valid)(starts)
        idx = (starts[:, None] + window[None, :]).reshape(-1)
        info = ArrayDict(valid_slices=ok, mask=jnp.repeat(ok, self.slice_len))
        new_state = sstate.replace(
            pos=pos + num_slices,
            epoch=sstate["epoch"] + need_reshuffle.astype(jnp.int32),
            epoch_key=epoch_key,
        )
        return idx, info, new_state


class PrioritizedSliceSampler(SliceSampler):
    """PER over trajectory slices (reference PrioritizedSliceSampler,
    samplers.py:3091): each start position's priority is its element's PER
    priority; invalid starts (window crossing an episode boundary) get zero
    mass. update_priority is element-wise like PrioritizedSampler.
    """

    def __init__(
        self,
        slice_len: int,
        alpha: float = 0.6,
        beta: float = 0.4,
        eps: float = 1e-8,
        traj_key=("collector", "traj_ids"),
    ):
        super().__init__(slice_len, traj_key=traj_key)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps

    def init(self, capacity: int) -> ArrayDict:
        base = super().init(capacity)
        return base.update(
            ArrayDict(
                priorities=jnp.zeros((capacity,), jnp.float32),
                max_priority=jnp.asarray(1.0, jnp.float32),
            )
        )

    def on_write(self, sstate, idx, items):
        sstate = super().on_write(sstate, idx, items)
        prio = sstate["priorities"].at[idx].set(sstate["max_priority"])
        return sstate.set("priorities", prio)

    def update_priority(self, sstate, idx, priority):
        priority = jnp.abs(priority) + self.eps
        prio = sstate["priorities"].at[idx].set(priority)
        return sstate.replace(
            priorities=prio,
            max_priority=jnp.maximum(sstate["max_priority"], jnp.max(priority)),
        )

    def sample(self, sstate, key, batch_size, size, capacity):
        num_slices = batch_size // self.slice_len
        window = jnp.arange(self.slice_len)
        tids = sstate["traj_ids"]
        positions = jnp.arange(capacity)
        hi = jnp.maximum(size - self.slice_len + 1, 1)

        def start_ok(start):
            w = tids[jnp.minimum(start + window, capacity - 1)]
            return jnp.all(w == w[0]) & (w[0] >= 0) & (start < hi)

        valid = jax.vmap(start_ok)(positions)
        p_alpha = jnp.where(
            valid, jnp.power(sstate["priorities"] + self.eps, self.alpha), 0.0
        )
        csum = jnp.cumsum(p_alpha)
        total = jnp.clip(csum[-1], 1e-12)
        u = jax.random.uniform(key, (num_slices,)) * total
        starts = jnp.clip(jnp.searchsorted(csum, u, side="right"), 0, capacity - 1)

        probs = p_alpha / total
        n = jnp.clip(jnp.sum(valid.astype(jnp.float32)), 1.0)
        weights = jnp.power(n * jnp.clip(probs[starts], 1e-12), -self.beta)
        # normalize by the max POSSIBLE weight (min valid prob), like
        # PrioritizedSampler — per-batch max would rescale the loss with
        # sampling luck
        min_prob = jnp.min(jnp.where(valid, probs, jnp.inf))
        max_w = jnp.power(n * jnp.clip(min_prob, 1e-12), -self.beta)
        weights = weights / jnp.clip(max_w, 1e-12)

        idx = (starts[:, None] + window[None, :]).reshape(-1)
        ok = valid[starts]
        info = ArrayDict(
            valid_slices=ok,
            mask=jnp.repeat(ok, self.slice_len),
            _weight=jnp.repeat(weights, self.slice_len),
            start_index=starts,
        )
        return idx, info, sstate
