"""Sampler/storage parameter schedulers (reference: torchrl/data/
replay_buffers/scheduler.py — anneal sampler params like PER α/β over
training).

A scheduler is pure: ``value(step) -> float`` plus ``apply(sstate, step) ->
sstate`` writing into a named field of the sampler state. Because sampler
state threads through jit, schedules compile into the train step (no host
mutation) — the TPU-native form of the reference's in-place ``step()``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = ["LinearScheduler", "StepScheduler", "SchedulerList"]


class LinearScheduler:
    """Linear ramp ``init -> end`` over ``num_steps`` (reference
    LinearScheduler)."""

    def __init__(self, field: str, init_value: float, end_value: float, num_steps: int):
        self.field = field
        self.init_value = init_value
        self.end_value = end_value
        self.num_steps = num_steps

    def value(self, step):
        frac = jnp.clip(jnp.asarray(step, jnp.float32) / self.num_steps, 0.0, 1.0)
        return self.init_value + (self.end_value - self.init_value) * frac

    def apply(self, sstate: ArrayDict, step) -> ArrayDict:
        return sstate.set(self.field, self.value(step))


class StepScheduler:
    """Multiply the field by ``gamma`` every ``n`` steps, clamped (reference
    StepScheduler)."""

    def __init__(
        self,
        field: str,
        init_value: float,
        gamma: float = 0.1,
        n_steps: int = 10_000,
        min_value: float = 0.0,
        max_value: float = float("inf"),
    ):
        self.field = field
        self.init_value = init_value
        self.gamma = gamma
        self.n_steps = n_steps
        self.min_value = min_value
        self.max_value = max_value

    def value(self, step):
        k = jnp.asarray(step, jnp.int32) // self.n_steps
        v = self.init_value * jnp.power(self.gamma, k.astype(jnp.float32))
        return jnp.clip(v, self.min_value, self.max_value)

    def apply(self, sstate: ArrayDict, step) -> ArrayDict:
        return sstate.set(self.field, self.value(step))


class SchedulerList:
    """Apply several schedulers (reference SchedulerList)."""

    def __init__(self, *schedulers):
        self.schedulers = list(schedulers)

    def apply(self, sstate: ArrayDict, step) -> ArrayDict:
        for s in self.schedulers:
            sstate = s.apply(sstate, step)
        return sstate
