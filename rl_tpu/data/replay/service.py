"""Replay buffer served over the TCP control plane.

Redesign of the reference's distributed replay service (reference:
torchrl/_comm/replay_service.py:102 ``_DistributedReplayService`` /
``_DistributedReplayClient``:32 — a ReplayBuffer served to remote trainers
over the transport): here the server owns the buffer state and exposes
extend/sample/size/update_priority over the line-JSON TCP channel
(rl_tpu.comm). This is the DCN path for host-resident buffers;
device-resident buffers move with the program.

Wire format: arrays ride as RAW BINARY FRAMES after the JSON header line
(``extend_bin``/``sample_bin`` + a ``{"leaves": {key: dtype/shape/offset}}``
manifest) — one ``tobytes`` copy out, zero-copy ``frombuffer`` views in.
The original base64-npz handlers (``extend``/``sample``) are kept verbatim
as the compat fallback: base64 inflates every trajectory 33% and
double-copies through ``io.BytesIO``, so new peers only fall back to it
when the far side predates the binary frames. Bytes-on-wire land on
``/metrics`` (``rl_tpu_replay_wire_bytes_total{direction,encoding}``).

The server sheds load instead of queueing unboundedly: with
``max_inflight`` set, extend/sample beyond that many concurrent handlers
get ``{"saturated": True, "retry_after": s}`` — and
:class:`RemoteReplayBuffer` honors that reply the way ``RemoteEngine``
does (sleep + resubmit, bounded), rather than treating it as a transport
error.
"""

from __future__ import annotations

import base64
import io
import threading
import time
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from ...comm import BLOB_KEY, BinaryReply, TCPCommandClient, TCPCommandServer
from ..arraydict import ArrayDict
from .buffer import ReplayBuffer

__all__ = [
    "ReplayService",
    "RemoteReplayBuffer",
    "ReplaySaturated",
]


class ReplaySaturated(RuntimeError):
    """The replay endpoint kept shedding past the bounded resubmit budget."""

    def __init__(self, retry_after: float):
        super().__init__(f"replay service saturated; retry after {retry_after}s")
        self.retry_after = retry_after


# -- wire codecs ---------------------------------------------------------------


def _encode(td: ArrayDict) -> dict:
    buf = io.BytesIO()
    flat = td.flatten_keys("|")
    np.savez(buf, **{k: np.asarray(v) for k, v in flat.items()})
    return {"npz": base64.b64encode(buf.getvalue()).decode()}


def _decode(payload: dict) -> ArrayDict:
    raw = base64.b64decode(payload["npz"])
    with np.load(io.BytesIO(raw)) as z:
        flat = ArrayDict({k: jax.numpy.asarray(z[k]) for k in z.files})
    return flat.unflatten_keys("|")


def _encode_frames(td: ArrayDict) -> tuple[dict, bytes]:
    """ArrayDict -> (manifest, raw bytes): each leaf C-contiguous, laid out
    back to back. One copy out (``tobytes``); no base64, no npz container."""
    flat = td.flatten_keys("|")
    leaves: dict[str, dict] = {}
    parts: list[bytes] = []
    off = 0
    for k, v in flat.items():
        a = np.ascontiguousarray(np.asarray(v))
        b = a.tobytes()
        leaves[k] = {"dtype": str(a.dtype), "shape": list(a.shape), "off": off}
        parts.append(b)
        off += len(b)
    return {"leaves": leaves}, b"".join(parts)


def _decode_frames(meta: dict, blob: bytes) -> ArrayDict:
    """(manifest, raw bytes) -> ArrayDict. ``frombuffer`` views are
    zero-copy; the device upload in ``jnp.asarray`` is the only copy in."""
    flat = {}
    for k, m in meta["leaves"].items():
        dt = np.dtype(m["dtype"])
        n = int(np.prod(m["shape"])) if m["shape"] else 1
        a = np.frombuffer(blob, dtype=dt, count=n, offset=m["off"])
        flat[k] = jnp.asarray(a.reshape(m["shape"]))
    return ArrayDict(flat).unflatten_keys("|")


_WIRE_COUNTER = None


def _count_wire(direction: str, encoding: str, nbytes: int) -> None:
    global _WIRE_COUNTER
    if _WIRE_COUNTER is None:
        from ...obs import get_registry

        _WIRE_COUNTER = get_registry().counter(
            "rl_tpu_replay_wire_bytes_total",
            "replay payload bytes on the wire, by direction and encoding",
            labels=("direction", "encoding"),
        )
    _WIRE_COUNTER.inc(nbytes, labels={"direction": direction, "encoding": encoding})


# -- server --------------------------------------------------------------------


class ReplayService:
    """Own a buffer + its state; serve it over TCP.

    ``max_inflight`` bounds concurrent extend/sample handlers — beyond it
    the service replies ``{"saturated": True, "retry_after": s}`` instead
    of queueing (the shed protocol shared with ``ServingService``).
    """

    def __init__(
        self, buffer: ReplayBuffer, example: ArrayDict, host="127.0.0.1", port=0,
        seed: int = 0, max_inflight: int | None = None, retry_after_s: float = 0.05,
    ):
        self.buffer = buffer
        self.state = buffer.init(example)
        self._key = jax.random.key(seed)
        self._subset_rng = np.random.default_rng(seed ^ 0x5EED)
        # TCPCommandServer is threading: serialize state updates or
        # concurrent extend/sample would read-modify-write the same state
        # and silently drop data
        self._lock = threading.Lock()
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._host = host
        self.server = TCPCommandServer(host, port)
        self._register_handlers(self.server)

    def _register_handlers(self, server: TCPCommandServer) -> None:
        reg = server.register_handler
        reg("extend", self._wrap_handler("extend", self._extend, shed=True))
        reg("extend_bin", self._wrap_handler("extend_bin", self._extend_bin, shed=True))
        reg("sample", self._wrap_handler("sample", self._sample, shed=True))
        reg("sample_bin", self._wrap_handler("sample_bin", self._sample_bin, shed=True))
        reg("size", self._wrap_handler("size", self._size))
        reg("update_priority",
            self._wrap_handler("update_priority", self._update_priority))
        reg("mass", self._wrap_handler("mass", self._mass))
        reg("evict_stale", self._wrap_handler("evict_stale", self._evict_stale))

    def _wrap_handler(self, name: str, fn, shed: bool = False):
        """Seam for subclasses (the shard tier adds fault points here);
        base behavior is the shed guard on the load-bearing handlers."""
        if shed:
            return self._shed_guard(fn)
        return fn

    def _shed_guard(self, fn):
        def guarded(payload):
            if self.max_inflight is not None:
                with self._inflight_lock:
                    if self._inflight >= self.max_inflight:
                        return {"saturated": True, "retry_after": self.retry_after_s}
                    self._inflight += 1
                try:
                    return fn(payload)
                finally:
                    with self._inflight_lock:
                        self._inflight -= 1
            return fn(payload)

        return guarded

    @property
    def address(self):
        return self.server.address

    def start(self) -> "ReplayService":
        self.server.start()
        return self

    def shutdown(self):
        self.server.shutdown()

    # -- handlers --------------------------------------------------------------

    def _size(self, payload):
        return int(self.buffer.size(self.state))

    def _extend_items(self, items: ArrayDict) -> int:
        with self._lock:
            self.state = self.buffer.extend(self.state, items)
            return int(self.buffer.size(self.state))

    def _extend(self, payload):
        _count_wire("received", "base64", len(payload.get("npz", "")))
        return self._extend_items(_decode(payload))

    def _extend_bin(self, payload):
        blob = payload.pop(BLOB_KEY)
        _count_wire("received", "binary", len(blob))
        return self._extend_items(_decode_frames(payload, blob))

    def _sample_batch(self, payload) -> ArrayDict:
        bs = payload.get("batch_size") if payload else None
        # bucket the device draw to the next power of two (>=16): shard
        # coordinators ask for a DIFFERENT count on every request (the
        # mixture split varies per draw), and each distinct batch size
        # would otherwise compile a fresh sample program — a recompile
        # storm that showed up as ~30x sample latency in the A/B bench
        bucket = None
        if bs is not None:
            bs = int(bs)
            bucket = max(16, 1 << max(0, bs - 1).bit_length())
        with self._lock:
            self._key, k = jax.random.split(self._key)
            batch, self.state = self.buffer.sample(self.state, k, bucket)
            sstate = self.state.get("sampler")
        if bucket is not None and bucket != bs:
            # a uniformly-random subset of a stratified draw keeps the
            # PER marginal exact; taking the FIRST bs rows would keep
            # only the low-CDF strata and skew the distribution
            keep = np.sort(self._subset_rng.choice(bucket, size=bs, replace=False))
            batch = batch.apply(lambda x: x[keep])
        if (
            isinstance(sstate, ArrayDict)
            and "priorities" in sstate
            and "index" in batch
        ):
            # the sampled leaves' p^alpha: what a coordinator needs to
            # recompute GLOBAL importance weights across shards (the
            # per-shard "_weight" normalizes by the shard-local batch max)
            batch = batch.set("_p_alpha", jnp.take(sstate["priorities"], batch["index"]))
        return batch

    def _sample(self, payload):
        out = self._sample_batch(payload)
        enc = _encode(out)
        _count_wire("sent", "base64", len(enc["npz"]))
        return enc

    def _sample_bin(self, payload):
        meta, blob = _encode_frames(self._sample_batch(payload))
        _count_wire("sent", "binary", len(blob))
        return BinaryReply(meta, blob)

    def _update_priority(self, payload):
        idx = np.asarray(payload["index"], np.int32)
        prio = np.asarray(payload["priority"], np.float32)
        n = int(idx.shape[0])
        if n:
            # bucket the length like `_sample_batch` buckets the draw:
            # shard coordinators route a DIFFERENT index count per draw
            # and each distinct count would compile a fresh update
            # program. Pad by repeating the final (index, priority)
            # pair — the fused update applies only the LAST duplicate's
            # delta, so the padding is exactly a no-op
            bucket = max(16, 1 << max(0, n - 1).bit_length())
            if bucket != n:
                idx = np.concatenate([idx, np.full(bucket - n, idx[-1], np.int32)])
                prio = np.concatenate([prio, np.full(bucket - n, prio[-1], np.float32)])
        with self._lock:
            self.state = self.buffer.update_priority(
                self.state, jax.numpy.asarray(idx), jax.numpy.asarray(prio)
            )
        return True

    def _mass(self, payload):
        """Shard-tier stats in one hop: total priority mass (the exact
        sum-tree root, ``sum(esum)``), size, freshest policy-version stamp
        in storage, and the handler queue depth."""
        with self._lock:
            size = int(self.buffer.size(self.state))
            sstate = self.state.get("sampler")
            if isinstance(sstate, ArrayDict) and "esum" in sstate:
                mass = float(np.sum(np.asarray(sstate["esum"])))
            else:
                mass = float(size)  # uniform samplers: mass == size
            max_version = 0
            data = self.state["storage"].get("data")
            if (
                size > 0
                and isinstance(data, ArrayDict)
                and ("collector", "policy_version") in data
            ):
                stamps = np.asarray(data[("collector", "policy_version")])[:size]
                max_version = int(stamps.max())
        return {
            "mass": mass,
            "size": size,
            "max_version": max_version,
            "inflight": self._inflight,
        }

    def _evict_stale(self, payload):
        """Staleness-aware eviction: crush the priority mass of items whose
        collector policy-version stamp predates ``min_version``. The ring
        recycles the slots; this removes them from the sampling mixture."""
        min_version = int(payload["min_version"])
        floor = float(payload.get("priority_floor", 1e-6))
        with self._lock:
            size = int(self.buffer.size(self.state))
            data = self.state["storage"].get("data")
            if (
                size == 0
                or not isinstance(data, ArrayDict)
                or ("collector", "policy_version") not in data
            ):
                return {"evicted": 0}
            stamps = np.asarray(data[("collector", "policy_version")])[:size]
            idx = np.nonzero(stamps < min_version)[0].astype(np.int32)
            if idx.size == 0:
                return {"evicted": 0}
            # pad to a chunk multiple: update_priority lowers per index
            # count, and eviction batches vary — repeated indices with the
            # same priority are idempotent
            chunk = 256
            padded = int(-(-idx.size // chunk) * chunk)
            idx_p = np.full((padded,), idx[-1], np.int32)
            idx_p[: idx.size] = idx
            self.state = self.buffer.update_priority(
                self.state,
                jnp.asarray(idx_p),
                jnp.full((padded,), floor, jnp.float32),
            )
        return {"evicted": int(idx.size)}


# -- client --------------------------------------------------------------------


class RemoteReplayBuffer:
    """Client view of a served buffer (reference _DistributedReplayClient).

    With ``retry`` set, ``size``/``update_priority``/``mass``/``evict_stale``
    survive transport failures. ``extend`` and ``sample`` never retry at the
    transport level: the server mutates its state before the reply is
    written, so replaying a call whose reply was lost would double-insert
    (or burn an extra sampler step). Shed replies ARE resubmitted — the
    server explicitly did nothing.

    Binary frames are tried first; an old peer's ``unknown command`` reply
    flips the client to the base64-npz fallback for the connection's
    lifetime.
    """

    def __init__(
        self, host: str, port: int, timeout: float = 30.0, retry: Any = None,
        binary: bool = True, max_shed_retries: int = 8,
    ):
        self.client = TCPCommandClient(host, port, timeout=timeout, retry=retry)
        self._binary = binary
        self.max_shed_retries = max_shed_retries

    def _shed_loop(self, once):
        """Run ``once`` honoring ``{"saturated", "retry_after"}`` replies the
        way ``RemoteEngine.submit`` does: sleep what the server asked,
        resubmit, bounded."""
        retry_after = 0.05
        for _ in range(self.max_shed_retries + 1):
            out = once()
            if isinstance(out, dict) and out.get("saturated"):
                retry_after = float(out.get("retry_after", retry_after))
                time.sleep(retry_after)
                continue
            return out
        raise ReplaySaturated(retry_after)

    def _binary_call(self, bin_cmd, legacy_fn, meta=None, blob=None):
        if self._binary:
            try:
                return self.client.call_binary(
                    bin_cmd, meta, blob=blob, idempotent=False
                )
            except RuntimeError as e:
                if "unknown command" not in str(e):
                    raise
                # old peer: no binary handlers — fall back for good
                self._binary = False
        return legacy_fn()

    def extend(self, items: ArrayDict) -> int:
        if self._binary:
            meta, blob = _encode_frames(items)
            _count_wire("sent", "binary", len(blob))
        else:
            meta = blob = None

        def once():
            def legacy():
                enc = _encode(items)
                _count_wire("sent", "base64", len(enc["npz"]))
                return self.client.call("extend", enc, idempotent=False)

            out = self._binary_call("extend_bin", legacy, meta, blob)
            if isinstance(out, tuple):
                out = out[0]
            return out

        return int(self._shed_loop(once))

    def sample(self, batch_size: int | None = None) -> ArrayDict:
        def once():
            def legacy():
                out = self.client.call(
                    "sample", {"batch_size": batch_size}, idempotent=False
                )
                if isinstance(out, dict) and out.get("saturated"):
                    return out
                _count_wire("received", "base64", len(out["npz"]))
                return _decode(out)

            out = self._binary_call(
                "sample_bin", legacy, {"batch_size": batch_size}
            )
            if isinstance(out, tuple):
                meta, blob = out
                if isinstance(meta, dict) and meta.get("saturated"):
                    return meta
                _count_wire("received", "binary", len(blob))
                return _decode_frames(meta, blob)
            return out

        return self._shed_loop(once)

    def size(self) -> int:
        return self.client.call("size")

    def update_priority(self, index, priority) -> None:
        # idempotent: writing the same priorities twice lands the same state
        self.client.call(
            "update_priority",
            {"index": np.asarray(index).tolist(), "priority": np.asarray(priority).tolist()},
        )

    def mass(self) -> dict:
        """Shard stats: {"mass", "size", "max_version", "inflight"}."""
        return self.client.call("mass")

    def evict_stale(self, min_version: int, priority_floor: float = 1e-6) -> int:
        out = self.client.call(
            "evict_stale",
            {"min_version": int(min_version), "priority_floor": priority_floor},
        )
        return int(out["evicted"])
