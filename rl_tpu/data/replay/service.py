"""Replay buffer served over the TCP control plane.

Redesign of the reference's distributed replay service (reference:
torchrl/_comm/replay_service.py:102 ``_DistributedReplayService`` /
``_DistributedReplayClient``:32 — a ReplayBuffer served to remote trainers
over the transport): here the server owns the buffer state and exposes
extend/sample/size/update_priority over the line-JSON TCP channel
(rl_tpu.comm), with arrays base64-npz encoded. This is the DCN path for
host-resident buffers; device-resident buffers move with the program.
"""

from __future__ import annotations

import base64
import io
import threading
from typing import Any

import numpy as np

import jax

from ...comm import TCPCommandClient, TCPCommandServer
from ..arraydict import ArrayDict
from .buffer import ReplayBuffer

__all__ = ["ReplayService", "RemoteReplayBuffer"]


def _encode(td: ArrayDict) -> dict:
    buf = io.BytesIO()
    flat = td.flatten_keys("|")
    np.savez(buf, **{k: np.asarray(v) for k, v in flat.items()})
    return {"npz": base64.b64encode(buf.getvalue()).decode()}


def _decode(payload: dict) -> ArrayDict:
    raw = base64.b64decode(payload["npz"])
    with np.load(io.BytesIO(raw)) as z:
        flat = ArrayDict({k: jax.numpy.asarray(z[k]) for k in z.files})
    return flat.unflatten_keys("|")


class ReplayService:
    """Own a buffer + its state; serve it over TCP."""

    def __init__(
        self, buffer: ReplayBuffer, example: ArrayDict, host="127.0.0.1", port=0,
        seed: int = 0,
    ):
        self.buffer = buffer
        self.state = buffer.init(example)
        self._key = jax.random.key(seed)
        # TCPCommandServer is threading: serialize state updates or
        # concurrent extend/sample would read-modify-write the same state
        # and silently drop data
        self._lock = threading.Lock()
        self.server = TCPCommandServer(host, port)
        self.server.register_handler("extend", self._extend)
        self.server.register_handler("sample", self._sample)
        self.server.register_handler("size", lambda p: int(self.buffer.size(self.state)))
        self.server.register_handler("update_priority", self._update_priority)

    @property
    def address(self):
        return self.server.address

    def start(self) -> "ReplayService":
        self.server.start()
        return self

    def shutdown(self):
        self.server.shutdown()

    def _extend(self, payload):
        items = _decode(payload)
        with self._lock:
            self.state = self.buffer.extend(self.state, items)
            return int(self.buffer.size(self.state))

    def _sample(self, payload):
        bs = payload.get("batch_size") if payload else None
        with self._lock:
            self._key, k = jax.random.split(self._key)
            batch, self.state = self.buffer.sample(self.state, k, bs)
        return _encode(batch)

    def _update_priority(self, payload):
        idx = np.asarray(payload["index"], np.int32)
        prio = np.asarray(payload["priority"], np.float32)
        with self._lock:
            self.state = self.buffer.update_priority(
                self.state, jax.numpy.asarray(idx), jax.numpy.asarray(prio)
            )
        return True


class RemoteReplayBuffer:
    """Client view of a served buffer (reference _DistributedReplayClient).

    With ``retry`` set, ``size``/``update_priority`` survive transport
    failures. ``extend`` and ``sample`` never retry: the server mutates its
    state before the reply is written, so replaying a call whose reply was
    lost would double-insert (or burn an extra sampler step).
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0, retry: Any = None):
        self.client = TCPCommandClient(host, port, timeout=timeout, retry=retry)

    def extend(self, items: ArrayDict) -> int:
        return self.client.call("extend", _encode(items), idempotent=False)

    def sample(self, batch_size: int | None = None) -> ArrayDict:
        return _decode(
            self.client.call("sample", {"batch_size": batch_size}, idempotent=False)
        )

    def size(self) -> int:
        return self.client.call("size")

    def update_priority(self, index, priority) -> None:
        # idempotent: writing the same priorities twice lands the same state
        self.client.call(
            "update_priority",
            {"index": np.asarray(index).tolist(), "priority": np.asarray(priority).tolist()},
        )
