"""Sharded experience tier: GEAR-style partitioned replay.

N :class:`ReplayShard` servers (each a ``ReplayService`` owning one
partition + its device PER sum-tree) behind one
:class:`ShardedReplayBuffer` coordinator that samples by mixture over the
exact per-shard priority masses, then in-shard by the existing stratified
sum-tree descent. See ``docs/sharded_replay.md``.
"""

from .coordinator import ShardedReplayBuffer, ShardUnavailable
from .shard import ReplayShard

__all__ = ["ReplayShard", "ShardedReplayBuffer", "ShardUnavailable"]
