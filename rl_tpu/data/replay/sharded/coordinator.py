"""GEAR-style sharded replay coordinator: mixture-of-shards sampling.

The single-host choke point (`ReplayService` = one endpoint, one sum-tree)
becomes N :class:`~rl_tpu.data.replay.sharded.shard.ReplayShard` servers,
each owning a partition of experience plus its own device PER sum-tree.
The coordinator samples in two stages (GEAR, arXiv 2310.05205):

1. **shard draw** — stratified inverse-CDF over the mixture of per-shard
   priority masses ``M_s = sum(esum_s)`` (the exact sum-tree roots,
   refreshed on a staleness budget, exact at refresh);
2. **in-shard draw** — the shard's existing stratified inverse-CDF
   sum-tree descent, untouched.

Marginals compose exactly: ``P(i) = (M_s/M) · (p_i/M_s) = p_i/M`` — when
masses are fresh, the two-stage draw is distribution-identical to one
PER tree over the union (property-tested in tests/test_sharded_replay.py).
Importance weights are recomputed GLOBALLY from the shards' returned
``p^alpha`` leaves (a shard-local ``_weight`` normalizes by the wrong
batch max), so ``w_i = (N·p_i/M)^-beta / max`` matches the single tree.

Degradation, not failure: every shard call goes through a per-shard
``RetryPolicy``/``CircuitBreaker``/``Deadline``; a lost shard is dropped
from the mixture (renormalizing it) and its in-flight batch is redrawn
over the survivors — the learner never sees the crash. A per-shard keeper
thread under a ``Supervisor`` probes health; the supervisor's restart of
a raised keeper is what re-admits the shard (restart → probe → re-admit),
with fresh client state so stale breakers don't haunt the new endpoint.

Chaos sites: ``replay.shard_crash.<idx>`` (in the shard server) and
``replay.shard_drop`` (here, before each shard call).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

import jax.numpy as jnp

from ....comm import TCPCommandClient
from ....obs import get_registry
from ....obs.trace import get_tracer
from ....resilience.faults import should_drop
from ....resilience.retry import CircuitBreaker, RetryPolicy
from ....resilience.supervisor import Supervisor
from ...arraydict import ArrayDict
from ..service import RemoteReplayBuffer, ReplaySaturated

__all__ = ["ShardedReplayBuffer", "ShardUnavailable"]

# transport-shaped failures (CircuitOpenError subclasses ConnectionError)
_TRANSPORT = (ConnectionError, TimeoutError, OSError)


class ShardUnavailable(ConnectionError):
    """Every shard is dead (or saturated past the spill budget)."""


class _Shard:
    """One shard's client bundle + last-refreshed stats. Mutable fields are
    guarded by the coordinator's ``_mix_lock`` (reads AND writes) — the one
    lock in this tier, never held across an RPC."""

    __slots__ = (
        "index", "host", "port", "client", "probe",
        "alive", "mass", "size", "max_version", "inflight", "refreshed_at",
    )

    def __init__(self, index: int, host: str, port: int):
        self.index = index
        self.host = host
        self.port = port
        self.client: RemoteReplayBuffer | None = None
        self.probe: TCPCommandClient | None = None
        self.alive = True
        self.mass = 0.0
        self.size = 0
        self.max_version = 0
        self.inflight = 0
        self.refreshed_at = 0.0


class ShardedReplayBuffer:
    """Coordinator client over N replay shards — a drop-in host-side replay
    source (``extend``/``sample``/``update_priority``/``size``) for
    :class:`~rl_tpu.trainers.AsyncOffPolicyTrainer`.

    ``shards`` is a list of ``(host, port)``. All shards share one
    ``shard_capacity`` — the stride of the global index encoding
    ``global = shard * capacity + local`` that routes priority updates
    back to the owning shard.
    """

    def __init__(
        self,
        shards: Sequence[tuple[str, int]],
        shard_capacity: int,
        *,
        batch_size: int | None = None,
        beta: float = 0.4,
        mass_refresh_s: float = 0.25,
        timeout: float = 10.0,
        probe_timeout_s: float = 2.0,
        probe_interval_s: float = 0.2,
        max_shed_retries: int = 8,
        seed: int = 0,
        retry_factory: Callable[[int], Any] | None = None,
        restart_fn: Callable[[int], tuple[str, int]] | None = None,
        registry=None,
    ):
        if not shards:
            raise ValueError("need at least one shard")
        self.shard_capacity = int(shard_capacity)
        self.batch_size = batch_size
        self.beta = float(beta)
        self.mass_refresh_s = float(mass_refresh_s)
        self.timeout = timeout
        self.probe_timeout_s = probe_timeout_s
        self.probe_interval_s = probe_interval_s
        self.max_shed_retries = max_shed_retries
        self._retry_factory = retry_factory or self._default_retry
        self._restart_fn = restart_fn
        self._rng = np.random.default_rng(seed)
        # the mixture lock: guards shard stats + the RR cursor. Leaf lock
        # in the R005 graph — nothing is acquired under it (no RPC, no
        # registry locks beyond the metric cells' own).
        self._mix_lock = threading.Lock()
        self._rr = 0
        self._version = 0
        self._mass_ts = 0.0
        self._stop = threading.Event()
        self._sup: Supervisor | None = None
        self._shards = [
            _Shard(i, host, int(port)) for i, (host, port) in enumerate(shards)
        ]
        for sh in self._shards:
            self._build_clients(sh)

        reg = registry if registry is not None else get_registry()
        self._g_size = reg.gauge(
            "rl_tpu_replay_shard_size", "items resident per shard", ("shard",))
        self._g_mass = reg.gauge(
            "rl_tpu_replay_shard_mass", "priority mass per shard (sum-tree root)",
            ("shard",))
        self._g_healthy = reg.gauge(
            "rl_tpu_replay_shard_healthy", "1 = shard in the mixture", ("shard",))
        self._g_depth = reg.gauge(
            "rl_tpu_replay_shard_queue_depth",
            "in-flight handlers at the shard at last refresh", ("shard",))
        self._g_age = reg.gauge(
            "rl_tpu_replay_shard_staleness_s",
            "age of each shard's mixture mass", ("shard",))
        self._c_extends = reg.counter(
            "rl_tpu_replay_shard_extends_total", "extends routed per shard",
            ("shard",))
        self._c_samples = reg.counter(
            "rl_tpu_replay_shard_samples_total", "samples drawn per shard",
            ("shard",))
        self._c_failover = reg.counter(
            "rl_tpu_replay_shard_failovers_total",
            "times a shard dropped out of the mixture", ("shard",))
        self._c_readmit = reg.counter(
            "rl_tpu_replay_shard_readmits_total",
            "times a shard rejoined the mixture", ("shard",))
        self._c_drops = reg.counter(
            "rl_tpu_replay_shard_drops_total",
            "injected replay.shard_drop link failures", ("shard",))
        self._c_evicted = reg.counter(
            "rl_tpu_replay_shard_evicted_total",
            "stale items evicted across shards")
        self._age_collector = reg.register_collector(self._collect_ages)
        self._registry = reg

    # -- wiring ----------------------------------------------------------------

    def _default_retry(self, idx: int) -> RetryPolicy:
        return RetryPolicy(
            max_attempts=3,
            base_delay_s=0.02,
            max_delay_s=0.2,
            deadline_s=self.timeout,
            breaker=CircuitBreaker(
                f"replay.shard{idx}",
                failure_threshold=3,
                reset_timeout_s=max(2 * self.probe_interval_s, 0.5),
            ),
            seed=idx,
        )

    def _build_clients(self, sh: _Shard) -> None:
        sh.client = RemoteReplayBuffer(
            sh.host, sh.port, timeout=self.timeout,
            retry=self._retry_factory(sh.index),
            max_shed_retries=self.max_shed_retries,
        )
        sh.probe = TCPCommandClient(sh.host, sh.port, timeout=self.probe_timeout_s)

    def _collect_ages(self) -> None:
        now = time.monotonic()
        with self._mix_lock:
            snap = [(sh.index, sh.refreshed_at, sh.alive) for sh in self._shards]
        for idx, ts, alive in snap:
            age = (now - ts) if (alive and ts) else 0.0
            self._g_age.set(age, labels={"shard": str(idx)})

    def close(self) -> None:
        self._stop.set()
        if self._sup is not None:
            self._sup.stop(timeout=2.0)
        self._registry.unregister_collector(self._age_collector)

    # -- failure / health ------------------------------------------------------

    @staticmethod
    def _is_shard_failure(e: BaseException) -> bool:
        if isinstance(e, ReplaySaturated):
            return False  # backpressure, not death
        if isinstance(e, _TRANSPORT):
            return True
        # a crash fault fires INSIDE the handler: the error reply carries
        # the InjectedFault marker while subsequent connects are refused
        return isinstance(e, RuntimeError) and (
            "InjectedFault" in str(e) or "is down" in str(e)
        )

    def _guarded(self, sh: _Shard, fn, *args):
        if should_drop("replay.shard_drop"):
            self._c_drops.inc(labels={"shard": str(sh.index)})
            raise ConnectionError(f"injected drop: shard {sh.index}")
        return fn(*args)

    def _on_shard_failure(self, sh: _Shard, e: BaseException) -> None:
        with self._mix_lock:
            was_alive = sh.alive
            sh.alive = False
        if was_alive:
            self._c_failover.inc(labels={"shard": str(sh.index)})
            self._g_healthy.set(0.0, labels={"shard": str(sh.index)})
            get_tracer().instant(
                "replay/shard_lost", {"shard": sh.index, "error": repr(e)}
            )

    def _readmit(self, sh: _Shard) -> None:
        stats = sh.client.mass()  # raises -> caller (keeper) retries
        with self._mix_lock:
            sh.alive = True
            self._apply_stats(sh, stats)
        self._c_readmit.inc(labels={"shard": str(sh.index)})
        self._g_healthy.set(1.0, labels={"shard": str(sh.index)})
        get_tracer().instant("replay/shard_readmitted", {"shard": sh.index})

    def _rebind(self, sh: _Shard, host: str, port: int) -> None:
        """Point a shard slot at a restarted endpoint, with FRESH retry/
        breaker state — the old breaker's open window belongs to the dead
        host, not this one."""
        sh.host, sh.port = host, int(port)
        self._build_clients(sh)

    def _apply_stats(self, sh: _Shard, stats: dict) -> None:
        # caller holds _mix_lock
        sh.mass = float(stats.get("mass", 0.0))
        sh.size = int(stats.get("size", 0))
        sh.max_version = int(stats.get("max_version", 0))
        sh.inflight = int(stats.get("inflight", 0))
        sh.refreshed_at = time.monotonic()
        lbl = {"shard": str(sh.index)}
        self._g_size.set(sh.size, labels=lbl)
        self._g_mass.set(sh.mass, labels=lbl)
        self._g_depth.set(sh.inflight, labels=lbl)
        self._g_healthy.set(1.0, labels=lbl)

    # -- mixture refresh -------------------------------------------------------

    def refresh_masses(self) -> None:
        """Pull every live shard's exact sum-tree root. The mixture is
        EXACT at this instant; between refreshes it ages within the
        ``mass_refresh_s`` staleness budget."""
        with self._mix_lock:
            live = [sh for sh in self._shards if sh.alive]
        for sh in live:
            try:
                stats = self._guarded(sh, sh.client.mass)
            except Exception as e:  # noqa: BLE001
                if self._is_shard_failure(e):
                    self._on_shard_failure(sh, e)
                    continue
                raise
            with self._mix_lock:
                self._apply_stats(sh, stats)
        self._mass_ts = time.monotonic()

    def _maybe_refresh(self) -> None:
        if time.monotonic() - self._mass_ts > self.mass_refresh_s:
            self.refresh_masses()

    def warm_sample(self, buckets: tuple = (16, 32, 64), alpha: float = 0.6) -> int:
        """Compile-warm each live shard's in-shard sample AND priority-
        update programs for every power-of-two bucket the two-stage
        split can request. Shards bucket both paths (see
        ``ReplayService``), but a COLD bucket still compiles on first
        use — under the shard's service lock, stalling concurrent
        extends for seconds. The warm update re-asserts the probed
        leaves' current priority (``p_alpha ** (1/alpha)``), so it is
        state-neutral when ``alpha`` matches the shard sampler's
        exponent (0.6 is the ``PrioritizedSampler`` default). Call once
        after shards hold data; returns the number of warm calls that
        succeeded. Dead or empty shards are skipped, never fatal."""
        n = 0
        with self._mix_lock:
            live = [sh for sh in self._shards if sh.alive]
        for sh in live:
            for b in buckets:
                try:
                    mb = self._guarded(sh, sh.client.sample, int(b))
                    if "index" in mb and "_p_alpha" in mb:
                        pa = np.asarray(mb["_p_alpha"], np.float64).reshape(-1)
                        prio = pa ** (1.0 / alpha) if alpha else pa
                        self._guarded(
                            sh, sh.client.update_priority,
                            np.asarray(mb["index"]).reshape(-1),
                            prio.astype(np.float32),
                        )
                    n += 1
                except Exception as e:  # noqa: BLE001 - warm is best-effort
                    if self._is_shard_failure(e):
                        self._on_shard_failure(sh, e)
                        break
        return n

    def _mixture(self) -> tuple[list[_Shard], np.ndarray]:
        with self._mix_lock:
            live = [sh for sh in self._shards if sh.alive]
            masses = np.asarray([sh.mass for sh in live], np.float64)
        return live, masses

    def mixture_probs(self) -> dict[int, float]:
        """Current shard-draw probabilities (diagnostics + the parity
        test's exactness assert)."""
        live, masses = self._mixture()
        total = float(masses.sum())
        if total <= 0:
            return {sh.index: 0.0 for sh in live}
        return {sh.index: float(m) / total for sh, m in zip(live, masses)}

    def alive_shards(self) -> list[int]:
        with self._mix_lock:
            return [sh.index for sh in self._shards if sh.alive]

    # -- data plane ------------------------------------------------------------

    def extend(self, items: ArrayDict) -> int:
        """Route a batch to the next live shard (round-robin placement —
        any assignment preserves the two-stage marginal, because the
        mixture re-weights by wherever the mass actually lands). A
        saturated shard spills to the next; a dead one fails over."""
        with get_tracer().ctx_span("replay/shard:extend"):
            self._maybe_refresh()
            for _ in range(len(self._shards)):
                with self._mix_lock:
                    live = [sh for sh in self._shards if sh.alive]
                    if not live:
                        break
                    sh = live[self._rr % len(live)]
                    self._rr += 1
                try:
                    out = int(self._guarded(sh, sh.client.extend, items))
                except ReplaySaturated:
                    continue  # spill to the next shard this round
                except Exception as e:  # noqa: BLE001
                    if self._is_shard_failure(e):
                        self._on_shard_failure(sh, e)
                        continue
                    raise
                self._c_extends.inc(labels={"shard": str(sh.index)})
                return out
            raise ShardUnavailable("no live shard accepted the extend")

    def sample(self, batch_size: int | None = None) -> ArrayDict:
        """Two-stage draw. A shard failing mid-draw renormalizes the
        mixture and the whole batch is redrawn over the survivors — the
        caller sees a complete batch or ``ShardUnavailable``, never a
        partial one."""
        bs = batch_size if batch_size is not None else self.batch_size
        if bs is None:
            raise ValueError("batch_size required (none configured)")
        with get_tracer().ctx_span("replay/shard:sample"):
            self._maybe_refresh()
            for attempt in range(len(self._shards) + 1):
                live, masses = self._mixture()
                if not live:
                    raise ShardUnavailable("no live shard to sample from")
                total = float(masses.sum())
                if total <= 0.0:
                    # stale-zero, not necessarily empty: extends that landed
                    # within the staleness budget aren't in the mixture yet
                    # — force one exact refresh before declaring starvation
                    if attempt == 0:
                        self.refresh_masses()
                        continue
                    raise RuntimeError("sharded replay holds no priority mass")
                # stage 1: stratified inverse-CDF over the shard mixture —
                # the same stratification the in-shard descent uses, so
                # the composed marginal stays p_i / M
                u = (np.arange(bs) + self._rng.random(bs)) / bs * total
                sel = np.searchsorted(np.cumsum(masses), u, side="right")
                counts = np.bincount(
                    np.clip(sel, 0, len(live) - 1), minlength=len(live)
                )
                parts: list[tuple[_Shard, ArrayDict]] = []
                redraw = False
                for sh, c in zip(live, counts):
                    if c == 0:
                        continue
                    try:
                        b = self._guarded(sh, sh.client.sample, int(c))
                    except Exception as e:  # noqa: BLE001
                        if self._is_shard_failure(e):
                            self._on_shard_failure(sh, e)
                            redraw = True
                            break
                        if isinstance(e, ReplaySaturated):
                            time.sleep(0.01)
                            redraw = True
                            break
                        raise
                    self._c_samples.inc(int(c), labels={"shard": str(sh.index)})
                    parts.append((sh, b))
                if redraw or not parts:
                    continue
                return self._merge(parts)
            raise ShardUnavailable("sampling failed across every redraw")

    def _merge(self, parts: list[tuple[_Shard, ArrayDict]]) -> ArrayDict:
        stride = self.shard_capacity
        batches = []
        for sh, b in parts:
            b = b.set("index", b["index"] + sh.index * stride)
            batches.append(b)
        merged = ArrayDict.concat(batches, axis=0)
        if "_p_alpha" in merged:
            # global importance weights: per-shard _weight normalized by
            # the WRONG (shard-local) max; recompute from the leaves
            with self._mix_lock:
                n_total = sum(sh.size for sh in self._shards if sh.alive)
                m_total = sum(sh.mass for sh in self._shards if sh.alive)
            pa = np.maximum(np.asarray(merged["_p_alpha"], np.float64), 1e-12)
            w = (max(n_total, 1) * pa / max(m_total, 1e-12)) ** (-self.beta)
            w = w / max(float(w.max()), 1e-12)
            merged = merged.set(
                "_weight", jnp.asarray(w.astype(np.float32))
            ).delete("_p_alpha")
        return merged

    def update_priority(self, index, priority) -> None:
        """Decode the global stride encoding and route each slice back to
        its owning shard; updates for a dead shard are dropped (its tree
        is gone — degrade, don't raise)."""
        idx = np.asarray(index, np.int64).reshape(-1)
        prio = np.asarray(priority, np.float32).reshape(-1)
        with get_tracer().ctx_span("replay/shard:update_priority"):
            owners = idx // self.shard_capacity
            for o in np.unique(owners):
                sh = self._shards[int(o)]
                with self._mix_lock:
                    alive = sh.alive
                if not alive:
                    continue
                m = owners == o
                try:
                    self._guarded(
                        sh, sh.client.update_priority,
                        idx[m] % self.shard_capacity, prio[m],
                    )
                except Exception as e:  # noqa: BLE001
                    if self._is_shard_failure(e):
                        self._on_shard_failure(sh, e)
                        continue
                    raise

    def size(self) -> int:
        """Total live items, from the last mass refresh (refreshes first
        when past the staleness budget)."""
        self._maybe_refresh()
        with self._mix_lock:
            return sum(sh.size for sh in self._shards if sh.alive)

    # -- staleness-aware eviction ----------------------------------------------

    def note_policy_version(self, version: int) -> None:
        """Learner hook: the freshest policy version, for the eviction
        cutoff (shards also report the freshest stamp they store)."""
        with self._mix_lock:
            self._version = max(self._version, int(version))

    def evict_stale(self, max_staleness: int, priority_floor: float = 1e-6) -> int:
        """Crush the mixture mass of experience older than
        ``current_version - max_staleness`` on every live shard."""
        with self._mix_lock:
            version = max(
                [self._version]
                + [sh.max_version for sh in self._shards if sh.alive]
            )
        live, _ = self._mixture()
        total = 0
        for sh in live:
            try:
                n = self._guarded(
                    sh, sh.client.evict_stale,
                    version - int(max_staleness), priority_floor,
                )
            except Exception as e:  # noqa: BLE001
                if self._is_shard_failure(e):
                    self._on_shard_failure(sh, e)
                    continue
                raise
            total += n
        if total:
            self._c_evicted.inc(total)
            self.refresh_masses()  # eviction moved mass; re-exact the mixture
        return total

    # -- supervision -----------------------------------------------------------

    def start_keepers(self, supervisor: Supervisor | None = None) -> Supervisor:
        """One keeper per shard under a Supervisor. A keeper that loses its
        shard marks it dead (mixture renormalizes) and RAISES — the
        supervisor's backoff-restart re-enters the keeper, which rebuilds
        the shard via ``restart_fn`` (or just re-probes it, for link-level
        drops) and re-admits it. ``escalate=False``: a shard that never
        comes back stays out of the mixture without killing its siblings."""
        if self._sup is not None:
            return self._sup
        self._sup = supervisor or Supervisor(
            "replay-shards", max_restarts=50,
            backoff_base_s=0.05, backoff_max_s=0.5, jitter=0.1,
        )
        for sh in self._shards:
            self._sup.spawn(
                f"shard-keeper-{sh.index}",
                lambda sh=sh: self._keeper(sh),
                escalate=False,
            )
        return self._sup

    def _keeper(self, sh: _Shard) -> None:
        while not self._stop.is_set():
            with self._mix_lock:
                alive = sh.alive
            if not alive:
                try:
                    # a drop isn't a crash: if the endpoint still answers,
                    # re-admit without rebuilding
                    sh.probe.call("size")
                    self._readmit(sh)
                except Exception:  # noqa: BLE001
                    if self._restart_fn is None:
                        raise RuntimeError(
                            f"shard {sh.index} down and no restart_fn"
                        )
                    host, port = self._restart_fn(sh.index)
                    self._rebind(sh, host, port)
                    # raises -> the supervisor backs off and retries us
                    sh.probe.call("size")
                    self._readmit(sh)
            else:
                try:
                    sh.probe.call("size")
                except Exception as e:  # noqa: BLE001
                    self._on_shard_failure(sh, e)
                    raise RuntimeError(
                        f"shard {sh.index} probe failed: {e!r}"
                    ) from e
            self._stop.wait(self.probe_interval_s)
