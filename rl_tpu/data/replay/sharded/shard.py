"""One shard of the partitioned experience tier.

A :class:`ReplayShard` IS a :class:`~rl_tpu.data.replay.service.ReplayService`
— the same buffer-owning TCP endpoint (device PER sum-tree included) — plus
the chaos/restart machinery the sharded tier needs:

- a per-shard seeded fault site ``replay.shard_crash.<idx>`` visited on
  every handled request: a ``crash`` fault marks the shard dead and closes
  its endpoint, so in-flight callers see the injected fault and subsequent
  connects are refused — exactly what a lost shard host looks like;
- :meth:`restart`, the supervisor's re-admission hook: a fresh buffer
  state on a fresh port (a crashed host's experience is gone; the mixture
  re-grows its mass as collectors refill it).
"""

from __future__ import annotations

import threading
from typing import Callable

import jax

from ....comm import TCPCommandServer
from ....resilience.faults import InjectedFault, fault_point, register_site
from ...arraydict import ArrayDict
from ..buffer import ReplayBuffer
from ..service import ReplayService

__all__ = ["ReplayShard"]


class ReplayShard(ReplayService):
    """A ``ReplayService`` that owns ONE partition of the experience tier.

    ``buffer_factory`` (not a buffer) because a restart rebuilds the
    buffer from scratch — shard state does not survive a crash.
    """

    def __init__(
        self,
        index: int,
        buffer_factory: Callable[[], ReplayBuffer],
        example: ArrayDict,
        host: str = "127.0.0.1",
        port: int = 0,
        seed: int = 0,
        max_inflight: int | None = None,
        retry_after_s: float = 0.05,
    ):
        self.index = int(index)
        self.site = f"replay.shard_crash.{self.index}"
        register_site(
            self.site,
            f"replay shard {self.index} handler (crash = this shard dies)",
        )
        self._buffer_factory = buffer_factory
        self._example = example
        self._seed = seed
        self._crashed = False
        super().__init__(
            buffer_factory(), example, host, port, seed=seed,
            max_inflight=max_inflight, retry_after_s=retry_after_s,
        )

    def _wrap_handler(self, name, fn, shed: bool = False):
        fn = super()._wrap_handler(name, fn, shed)

        def guarded(payload, _fn=fn):
            if self._crashed:
                raise InjectedFault(f"shard {self.index} is down")
            try:
                # per-shard AND generic site: a plan can kill this specific
                # shard (deterministic per-site invocation counter) or any
                # shard probabilistically in a soak
                fault_point(self.site)
                fault_point("replay.shard_crash")
            except InjectedFault:
                self._crash()
                raise
            return _fn(payload)

        return guarded

    @property
    def crashed(self) -> bool:
        return self._crashed

    def _crash(self) -> None:
        """Become a dead host: refuse everything, close the endpoint. The
        shutdown runs off-thread — it joins the accept loop, and this is a
        handler thread that still owes the injected-fault reply."""
        self._crashed = True
        threading.Thread(target=self.shutdown, daemon=True).start()

    def restart(self, reset_state: bool = True) -> tuple[str, int]:
        """Re-admission hook for the coordinator's supervisor: rebuild the
        buffer (crashed hosts lose their experience), bind a fresh port,
        serve again. Returns the new ``(host, port)``."""
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001 - already-dead endpoints are fine
            pass
        if reset_state:
            self.buffer = self._buffer_factory()
            self.state = self.buffer.init(self._example)
            self._key = jax.random.key(self._seed)
        self._crashed = False
        self.server = TCPCommandServer(self._host, 0)
        self._register_handlers(self.server)
        self.server.start()
        return self.address
