"""Replay storages.

TPU-native redesign of the reference's storage layer (reference:
torchrl/data/replay_buffers/storages.py — ``Storage``:171, ``ListStorage``
:362, ``TensorStorage``:636, ``LazyTensorStorage``:1335,
``LazyMemmapStorage``:1587).

The north-star storages are re-designed around XLA:

- :class:`DeviceStorage` (LazyTensorStorage analog): a preallocated ArrayDict
  ring on device. All ops are functional (`state -> state`) and jit-safe, so
  a replay buffer can live *inside* a fused off-policy train step; with
  buffer donation XLA updates it in place (``.at[idx].set`` on a donated
  carry compiles to dynamic-update-slice, no copy).
- :class:`MemmapStorage` (LazyMemmapStorage analog): host-side numpy memmap
  ring for capacities beyond HBM; not jit-traceable (host boundary), used by
  host collectors/offline datasets.
- :class:`ListStorage`: host python list (arbitrary payloads, LLM text).

Storage *state* is separated from the storage *object*: the object holds
static config; the state (an ArrayDict: {"data", "cursor", "size"}) threads
through jitted code. Lazy layout inference happens on first write, like the
reference's lazy storages.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..arraydict import ArrayDict

__all__ = [
    "CompressedListStorage",
    "DeviceStorage",
    "ListStorage",
    "MemmapStorage",
    "Storage",
    "StorageEnsemble",
]


class Storage:
    """Abstract storage. ``init`` from an example item; ``set``/``get`` by
    index; ``__len__``-style size lives in the state."""

    def __init__(self, capacity: int):
        self.capacity = capacity

    def init(self, example: ArrayDict) -> Any:
        raise NotImplementedError

    def set(self, state: Any, idx: jax.Array, items: ArrayDict) -> Any:
        raise NotImplementedError

    def get(self, state: Any, idx: jax.Array) -> ArrayDict:
        raise NotImplementedError

    def size(self, state: Any) -> jax.Array:
        raise NotImplementedError


class DeviceStorage(Storage):
    """Preallocated device ring buffer of ArrayDicts (jit-safe).

    ``init(example)`` allocates ``[capacity, *feature]`` zeros per leaf from
    one example item (batch dims of the example are ignored — layout is
    per-item, reference LazyTensorStorage semantics of allocating on first
    write). Optional ``sharding`` places the capacity axis over a mesh axis
    for pod-scale device-resident replay.
    """

    def __init__(self, capacity: int, sharding: Any = None):
        super().__init__(capacity)
        self.sharding = sharding

    def init(self, example: ArrayDict) -> ArrayDict:
        def alloc(x):
            x = jnp.asarray(x)
            buf = jnp.zeros((self.capacity,) + x.shape, x.dtype)
            if self.sharding is not None:
                buf = jax.device_put(buf, self.sharding)
            return buf

        return ArrayDict(
            data=example.apply(alloc),
            cursor=jnp.asarray(0, jnp.int32),
            size=jnp.asarray(0, jnp.int32),
        )

    def set(self, state: ArrayDict, idx: jax.Array, items: ArrayDict) -> ArrayDict:
        data = jax.tree.map(lambda buf, x: buf.at[idx].set(x), state["data"], items)
        return state.set("data", data)

    def get(self, state: ArrayDict, idx: jax.Array) -> ArrayDict:
        return state["data"].apply(lambda buf: buf[idx])

    def size(self, state: ArrayDict) -> jax.Array:
        return state["size"]


class MemmapStorage(Storage):
    """Disk-backed host ring buffer (reference LazyMemmapStorage,
    storages.py:1587): one ``.npy`` memmap per leaf under ``scratch_dir``.

    Host-side only (not jit-traceable); the state is a small python dict
    ``{"cursor": int, "size": int}`` — the memmaps mutate in place.
    """

    def __init__(self, capacity: int, scratch_dir: str | None = None):
        super().__init__(capacity)
        import tempfile

        self.scratch_dir = scratch_dir or tempfile.mkdtemp(prefix="rl_tpu_memmap_")
        self._maps: dict[tuple, np.memmap] = {}

    def init(self, example: ArrayDict) -> dict:
        import json

        os.makedirs(self.scratch_dir, exist_ok=True)
        self._maps = {}
        meta_path = os.path.join(self.scratch_dir, "meta.json")
        old_meta = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                old_meta = json.load(f)
        meta = {}
        for path in example.keys(nested=True, leaves_only=True):
            x = np.asarray(example[path])
            fname = os.path.join(self.scratch_dir, "_".join(path) + ".dat")
            shape = (self.capacity,) + x.shape
            sig = {"dtype": str(x.dtype), "shape": list(shape)}
            meta["_".join(path)] = sig
            # reattach (don't truncate) only when the sidecar metadata proves
            # the file holds the SAME dtype/shape layout — byte size alone
            # would silently reinterpret old data under a changed schema
            mode = (
                "r+"
                if os.path.exists(fname) and old_meta.get("_".join(path)) == sig
                else "w+"
            )
            self._maps[path] = np.memmap(fname, dtype=x.dtype, mode=mode, shape=shape)
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        return {"cursor": 0, "size": 0}

    def set(self, state: dict, idx, items: ArrayDict) -> dict:
        idx = np.asarray(idx)
        for path, mm in self._maps.items():
            mm[idx] = np.asarray(items[path])
        return state

    def get(self, state: dict, idx) -> ArrayDict:
        idx = np.asarray(idx)
        out = ArrayDict()
        for path, mm in self._maps.items():
            out = out.set(path, jnp.asarray(mm[idx]))
        return out

    def size(self, state: dict) -> int:
        return state["size"]

    def flush(self):
        for mm in self._maps.values():
            mm.flush()


class ListStorage(Storage):
    """Host list storage for arbitrary payloads (reference ListStorage,
    storages.py:362). Not jit-traceable."""

    def __init__(self, capacity: int):
        super().__init__(capacity)
        self._items: list = []

    def init(self, example: ArrayDict | None = None) -> dict:
        self._items = []
        return {"cursor": 0, "size": 0}

    @staticmethod
    def _as_items(idx: np.ndarray, items) -> list:
        """Normalize a stacked ArrayDict or a list to a per-index item list."""
        return (
            items
            if isinstance(items, (list, tuple))
            else [items[i] for i in range(idx.size)]
        )

    def set(self, state: dict, idx, items) -> dict:
        idx = np.atleast_1d(np.asarray(idx))
        seq = self._as_items(idx, items)
        for i, item in zip(idx, seq):
            while len(self._items) <= i:
                self._items.append(None)
            self._items[int(i)] = item
        return state

    def get(self, state: dict, idx) -> list:
        idx = np.atleast_1d(np.asarray(idx))
        return [self._items[int(i)] for i in idx]

    def size(self, state: dict) -> int:
        return state["size"]


class CompressedListStorage(ListStorage):
    """Host storage with per-item zlib compression (reference
    CompressedListStorage, storages.py:1953): each item's leaves are packed
    into one compressed blob; decompressed on read. For large image/video
    replay where host RAM, not device HBM, is the bound.
    """

    def __init__(self, capacity: int, level: int = 3):
        super().__init__(capacity)
        self.level = level

    @staticmethod
    def _pack(item: ArrayDict) -> bytes:
        import io
        import zlib

        buf = io.BytesIO()
        flat = {
            "/".join(k): np.asarray(v)
            for k, v in item.items(nested=True, leaves_only=True)
        }
        np.savez(buf, **flat)
        return zlib.compress(buf.getvalue())

    @staticmethod
    def _unpack(blob: bytes) -> ArrayDict:
        import io
        import zlib

        with np.load(io.BytesIO(zlib.decompress(blob))) as z:
            out = ArrayDict()
            for k in z.files:
                out = out.set(tuple(k.split("/")), jnp.asarray(z[k]))
        return out

    def set(self, state: dict, idx, items) -> dict:
        idx = np.atleast_1d(np.asarray(idx))
        blobs = [self._pack(it) for it in self._as_items(idx, items)]
        return super().set(state, idx, blobs)

    def get(self, state: dict, idx):
        return [self._unpack(b) for b in super().get(state, idx)]

    def nbytes(self) -> int:
        return sum(len(b) for b in self._items if b is not None)


class StorageEnsemble(Storage):
    """Fixed collection of storages sampled as one (reference
    StorageEnsemble, storages.py:2266). Reads take a (which, idx) pair;
    writes must target a member explicitly (``set_member``) — members
    typically hold distinct datasets (expert vs online data).
    """

    def __init__(self, *storages: Storage):
        super().__init__(sum(s.capacity for s in storages))
        self.storages = list(storages)

    def init(self, example: ArrayDict):
        return [s.init(example) for s in self.storages]

    def set_member(self, state, which: int, idx, items):
        state = list(state)
        state[which] = self.storages[which].set(state[which], idx, items)
        return state

    def set(self, state, idx, items):
        raise NotImplementedError("StorageEnsemble: use set_member(which, ...)")

    def get(self, state, which_and_idx):
        which, idx = which_and_idx
        # gather member-by-member, then select: jit-safe for DeviceStorages
        outs = [
            self.storages[i].get(state[i], jnp.asarray(idx) % self.storages[i].capacity)
            for i in range(len(self.storages))
        ]
        which = jnp.asarray(which)
        stacked = ArrayDict.stack(outs, axis=0)

        def pick(leaf):
            w = which.reshape(which.shape + (1,) * (leaf.ndim - 1 - which.ndim))
            return jnp.take_along_axis(leaf, w[None].astype(jnp.int32), axis=0)[0]

        return stacked.apply(pick)

    def size(self, state):
        sizes = [s.size(st) for s, st in zip(self.storages, state)]
        return sum(jnp.asarray(s) for s in sizes)
