"""Replay writers (reference: torchrl/data/replay_buffers/writers.py —
``Writer``:43, ``RoundRobinWriter``:148, ``TensorDictMaxValueWriter``:416,
``ImmutableDatasetWriter``:121).

A writer decides *where* incoming items land. Functional: ``assign`` maps
(writer_state, n_items, buffer_size/cursor) -> target indices + new state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..arraydict import ArrayDict

__all__ = ["Writer", "RoundRobinWriter", "MaxValueWriter", "ImmutableDatasetWriter"]


class Writer:
    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict()

    def assign(
        self, wstate: ArrayDict, bstate: ArrayDict, items: ArrayDict, n: int, capacity: int
    ) -> tuple[jax.Array, ArrayDict, ArrayDict]:
        """Returns (indices [n] — entries may be ``capacity`` to drop,
        new writer state, new buffer state with cursor/size advanced)."""
        raise NotImplementedError


class RoundRobinWriter(Writer):
    """Ring-cursor writer (reference writers.py:148)."""

    def assign(self, wstate, bstate, items, n, capacity):
        cursor = bstate["cursor"]
        offs = jnp.arange(n)
        idx = (cursor + offs) % capacity
        if n > capacity:
            # a chunk lapping the ring would scatter duplicate indices, and
            # XLA's .at[].set winner among duplicates is unspecified — route
            # all but the trailing `capacity` items to an always-out-of-bounds
            # sentinel instead (scatter drops OOB indices), so later items
            # deterministically win. INT32_MAX rather than `capacity` because
            # PER's leaf array is padded past capacity and a write at
            # `capacity` would leak mass into a pad slot.
            idx = jnp.where(offs < n - capacity, jnp.iinfo(jnp.int32).max, idx)
        new_b = bstate.replace(
            cursor=(cursor + n) % capacity,
            size=jnp.minimum(bstate["size"] + n, capacity),
        )
        return idx, wstate, new_b


class MaxValueWriter(Writer):
    """Top-k retention by a rank key (reference TensorDictMaxValueWriter,
    writers.py:416): an incoming item replaces the current minimum-valued
    slot only if it ranks higher; fills empty slots first.

    Jit-safe: the replacement decision is a ``where`` on values. Processes
    items one-by-one via ``lax.scan`` (correct multi-eviction semantics).
    """

    def __init__(self, rank_key="value"):
        self.rank_key = rank_key if isinstance(rank_key, tuple) else (rank_key,)

    def init(self, capacity: int) -> ArrayDict:
        return ArrayDict(values=jnp.full((capacity,), -jnp.inf, jnp.float32))

    def assign(self, wstate, bstate, items, n, capacity):
        vals_in = items[self.rank_key].reshape(n).astype(jnp.float32)

        def body(carry, v):
            values, size = carry
            # fill empty slot if any, else candidate = argmin slot
            slot = jnp.where(size < capacity, size, jnp.argmin(values))
            accept = (size < capacity) | (v > values[slot])
            tgt = jnp.where(accept, slot, capacity)  # capacity = dropped
            values = values.at[tgt].set(v, mode="drop")
            size = jnp.minimum(size + accept.astype(jnp.int32), capacity)
            return (values, size), tgt

        (values, size), idx = jax.lax.scan(body, (wstate["values"], bstate["size"]), vals_in)
        new_b = bstate.replace(size=size, cursor=jnp.minimum(size, capacity - 1))
        return idx, ArrayDict(values=values), new_b


class ImmutableDatasetWriter(Writer):
    """Refuses writes (offline datasets; reference writers.py:121)."""

    def assign(self, wstate, bstate, items, n, capacity):
        raise RuntimeError("ImmutableDatasetWriter: this buffer is read-only")
