"""Spec tree: typed descriptions of env/model inputs and outputs.

TPU-native analog of the reference's TensorSpec family
(reference: torchrl/data/tensor_specs.py:607 ``TensorSpec``, :2259 ``Bounded``,
:3053 ``Unbounded``, :1695 ``OneHot``, :3808 ``Categorical``, :4398 ``Binary``,
:4600 ``MultiCategorical``, :2738 ``NonTensor``, :5042 ``Composite``).

Differences by design:

- Specs are **static metadata**, not pytrees: they are consulted at trace time
  (``jax.eval_shape``, ``ShapeDtypeStruct`` construction, sharding layout) and
  never cross into compiled programs.
- Each spec can carry a ``jax.sharding.PartitionSpec`` so the spec tree doubles
  as the sharding annotation source for ``pjit`` — the reference's
  ``device`` attribute generalized to a mesh axis mapping.
- ``rand`` takes an explicit PRNG key (functional randomness).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict

__all__ = [
    "Spec",
    "Bounded",
    "Unbounded",
    "Categorical",
    "MultiCategorical",
    "OneHot",
    "MultiOneHot",
    "Binary",
    "NonTensor",
    "Composite",
    "stack_specs",
    "make_composite_from_arraydict",
]


def _canon_shape(shape) -> tuple[int, ...]:
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


@dataclasses.dataclass(frozen=True)
class Spec:
    """Abstract leaf spec: shape, dtype, optional sharding annotation."""

    shape: tuple[int, ...] = ()
    dtype: Any = jnp.float32
    sharding: Any = None  # jax.sharding.PartitionSpec | None

    def __post_init__(self):
        object.__setattr__(self, "shape", _canon_shape(self.shape))

    # -- core protocol (mirrors TensorSpec: rand/zero/is_in/project/encode) ---

    def rand(self, key: jax.Array, batch_shape: tuple[int, ...] = ()) -> jax.Array:
        raise NotImplementedError

    def zero(self, batch_shape: tuple[int, ...] = ()) -> jax.Array:
        return jnp.zeros(_canon_shape(batch_shape) + self.shape, self.dtype)

    def is_in(self, val) -> bool:
        """Static + value check: shape/dtype statically, domain numerically."""
        val = jnp.asarray(val)
        if not self._shape_ok(val.shape):
            return False
        if val.dtype != jnp.dtype(self.dtype):
            return False
        return bool(self._domain_ok(val))

    def project(self, val: jax.Array) -> jax.Array:
        """Map an arbitrary value into the spec's domain (clip/renorm)."""
        return jnp.asarray(val, self.dtype)

    def encode(self, val) -> jax.Array:
        """Encode a raw (host) value into spec form (e.g. index -> one-hot)."""
        return jnp.asarray(val, self.dtype)

    # -- structure ------------------------------------------------------------

    def to_sds(self, batch_shape: tuple[int, ...] = ()) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(
            _canon_shape(batch_shape) + self.shape, self.dtype, sharding=self.sharding
        )

    def expand(self, *batch_shape: int) -> "Spec":
        bs = _canon_shape(batch_shape[0] if len(batch_shape) == 1 and isinstance(batch_shape[0], (tuple, list)) else batch_shape)
        return dataclasses.replace(self, shape=bs + self.shape)

    def with_sharding(self, pspec) -> "Spec":
        return dataclasses.replace(self, sharding=pspec)

    def _shape_ok(self, shape: tuple[int, ...]) -> bool:
        n = len(self.shape)
        return tuple(shape[len(shape) - n:] if n else ()) == self.shape

    def _domain_ok(self, val: jax.Array) -> Any:
        return True

    @property
    def ndim(self) -> int:
        return len(self.shape)


@dataclasses.dataclass(frozen=True)
class Bounded(Spec):
    """Box space with per-element bounds (reference tensor_specs.py:2259)."""

    low: Any = -1.0
    high: Any = 1.0

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "low", np.broadcast_to(np.asarray(self.low, self.dtype), self.shape).copy())
        object.__setattr__(self, "high", np.broadcast_to(np.asarray(self.high, self.dtype), self.shape).copy())

    def rand(self, key, batch_shape=()):
        bs = _canon_shape(batch_shape)
        if jnp.issubdtype(self.dtype, jnp.integer):
            return jax.random.randint(
                key, bs + self.shape, jnp.asarray(self.low), jnp.asarray(self.high) + 1, self.dtype
            )
        u = jax.random.uniform(key, bs + self.shape, self.dtype)
        return u * (self.high - self.low) + self.low

    def project(self, val):
        return jnp.clip(jnp.asarray(val, self.dtype), jnp.asarray(self.low), jnp.asarray(self.high))

    def _domain_ok(self, val):
        return jnp.all(val >= jnp.asarray(self.low)) & jnp.all(val <= jnp.asarray(self.high))

    def __eq__(self, other):
        return (
            type(other) is Bounded
            and self.shape == other.shape
            and self.dtype == other.dtype
            and np.array_equal(self.low, other.low)
            and np.array_equal(self.high, other.high)
        )

    __hash__ = None


@dataclasses.dataclass(frozen=True)
class Unbounded(Spec):
    """Unbounded continuous/discrete space (reference tensor_specs.py:3053)."""

    def rand(self, key, batch_shape=()):
        bs = _canon_shape(batch_shape)
        if jnp.issubdtype(self.dtype, jnp.integer):
            info = jnp.iinfo(self.dtype)
            return jax.random.randint(key, bs + self.shape, info.min // 2, info.max // 2, self.dtype)
        if self.dtype == jnp.bool_:
            return jax.random.bernoulli(key, 0.5, bs + self.shape)
        return jax.random.normal(key, bs + self.shape, self.dtype)


@dataclasses.dataclass(frozen=True)
class Categorical(Spec):
    """Integer categorical in [0, n) (reference tensor_specs.py:3808).

    ``shape`` excludes the class dimension (scalar action => shape=()).
    n = -1 means "unknown cardinality" (matches reference semantics).
    """

    n: int = -1
    dtype: Any = jnp.int32

    def rand(self, key, batch_shape=()):
        return jax.random.randint(key, _canon_shape(batch_shape) + self.shape, 0, max(self.n, 1), self.dtype)

    def project(self, val):
        val = jnp.asarray(val, self.dtype)
        if self.n < 0:  # unknown cardinality: domain is unconstrained
            return val
        return jnp.clip(val, 0, self.n - 1)

    def _domain_ok(self, val):
        if self.n < 0:
            return True
        return jnp.all((val >= 0) & (val < self.n))

    @property
    def num_actions(self) -> int:
        return self.n


@dataclasses.dataclass(frozen=True)
class MultiCategorical(Spec):
    """Vector of categoricals with per-position cardinalities (ref :4600)."""

    nvec: tuple[int, ...] = ()
    dtype: Any = jnp.int32

    def __post_init__(self):
        object.__setattr__(self, "nvec", tuple(int(n) for n in self.nvec))
        if not self.shape:
            object.__setattr__(self, "shape", (len(self.nvec),))
        super().__post_init__()
        if self.shape[-1] != len(self.nvec):
            raise ValueError("shape[-1] must equal len(nvec)")

    def rand(self, key, batch_shape=()):
        bs = _canon_shape(batch_shape)
        u = jax.random.uniform(key, bs + self.shape)
        return jnp.asarray(u * jnp.asarray(self.nvec), self.dtype)

    def project(self, val):
        return jnp.clip(jnp.asarray(val, self.dtype), 0, jnp.asarray(self.nvec) - 1)

    def _domain_ok(self, val):
        return jnp.all((val >= 0) & (val < jnp.asarray(self.nvec)))


@dataclasses.dataclass(frozen=True)
class OneHot(Spec):
    """One-hot encoded categorical (reference tensor_specs.py:1695).

    ``shape[-1]`` is the number of classes.
    """

    n: int = 0
    dtype: Any = jnp.float32

    def __post_init__(self):
        if not self.shape:
            object.__setattr__(self, "shape", (self.n,))
        super().__post_init__()
        if self.n == 0:
            object.__setattr__(self, "n", int(self.shape[-1]))
        if self.shape[-1] != self.n:
            raise ValueError("shape[-1] must equal n")

    def rand(self, key, batch_shape=()):
        bs = _canon_shape(batch_shape)
        idx = jax.random.randint(key, bs + self.shape[:-1], 0, self.n)
        return jax.nn.one_hot(idx, self.n, dtype=self.dtype)

    def project(self, val):
        idx = jnp.argmax(jnp.asarray(val), axis=-1)
        return jax.nn.one_hot(idx, self.n, dtype=self.dtype)

    def encode(self, val):
        val = jnp.asarray(val)
        if val.shape and val.shape[-1] == self.n and not jnp.issubdtype(val.dtype, jnp.integer):
            return jnp.asarray(val, self.dtype)
        return jax.nn.one_hot(val, self.n, dtype=self.dtype)

    def to_categorical_spec(self) -> Categorical:
        return Categorical(shape=self.shape[:-1], n=self.n)

    def _domain_ok(self, val):
        ones = jnp.sum(val != 0, axis=-1) == 1
        vals = (val == 0) | (val == 1)
        return jnp.all(ones) & jnp.all(vals)


@dataclasses.dataclass(frozen=True)
class MultiOneHot(Spec):
    """Concatenation of one-hot blocks (reference tensor_specs.py:3298)."""

    nvec: tuple[int, ...] = ()
    dtype: Any = jnp.float32

    def __post_init__(self):
        object.__setattr__(self, "nvec", tuple(int(n) for n in self.nvec))
        if not self.shape:
            object.__setattr__(self, "shape", (sum(self.nvec),))
        super().__post_init__()
        if self.shape[-1] != sum(self.nvec):
            raise ValueError("shape[-1] must equal sum(nvec)")

    def rand(self, key, batch_shape=()):
        bs = _canon_shape(batch_shape)
        keys = jax.random.split(key, len(self.nvec))
        parts = []
        for k, n in zip(keys, self.nvec):
            idx = jax.random.randint(k, bs + self.shape[:-1], 0, n)
            parts.append(jax.nn.one_hot(idx, n, dtype=self.dtype))
        return jnp.concatenate(parts, axis=-1)

    def _domain_ok(self, val):
        ok = True
        off = 0
        for n in self.nvec:
            blk = val[..., off : off + n]
            ok = ok & jnp.all(jnp.sum(blk != 0, axis=-1) == 1)
            off += n
        return ok

    def to_categorical_spec(self) -> MultiCategorical:
        return MultiCategorical(shape=self.shape[:-1] + (len(self.nvec),), nvec=self.nvec)


@dataclasses.dataclass(frozen=True)
class Binary(Spec):
    """Binary vector (reference tensor_specs.py:4398)."""

    dtype: Any = jnp.bool_

    def rand(self, key, batch_shape=()):
        return jax.random.bernoulli(key, 0.5, _canon_shape(batch_shape) + self.shape).astype(self.dtype)

    def _domain_ok(self, val):
        return jnp.all((val == 0) | (val == 1))


@dataclasses.dataclass(frozen=True)
class NonTensor(Spec):
    """Arbitrary python payload leaf (strings, objects) — LLM text etc.

    Reference tensor_specs.py:2738. Values never enter compiled programs;
    they live host-side and are excluded from jit inputs.
    """

    example: Any = None

    def rand(self, key, batch_shape=()):
        return self.example

    def zero(self, batch_shape=()):
        return self.example

    def is_in(self, val) -> bool:
        return True

    def to_sds(self, batch_shape=()):
        return None


class Composite(Spec):
    """Nested dict-of-specs with a batch shape — THE env contract object.

    Reference tensor_specs.py:5042. ``shape`` here is the batch shape shared
    by all children (children's own shapes are *feature* shapes appended to
    it, matching the reference convention).
    """

    def __init__(self, specs: dict[str, Spec] | None = None, shape=(), **kw: Spec):
        merged = dict(specs or {})
        merged.update(kw)
        out = {}
        for k, v in merged.items():
            if isinstance(v, dict):
                # Plain-dict children are feature-level groups: they inherit
                # the batch shape at rand/zero time, so their own shape stays
                # empty (avoids double-applying the batch dims).
                v = Composite(v)
            if not isinstance(v, Spec):
                raise TypeError(f"Composite values must be Spec, got {type(v)} for {k!r}")
            out[k] = v
        object.__setattr__(self, "_specs", dict(sorted(out.items())))
        object.__setattr__(self, "shape", _canon_shape(shape))
        object.__setattr__(self, "dtype", None)
        object.__setattr__(self, "sharding", None)

    # -- mapping --------------------------------------------------------------

    def __getitem__(self, key: str | tuple) -> Spec:
        if isinstance(key, tuple):
            node: Spec = self
            for k in key:
                node = node[k]
            return node
        if "." in key:
            return self[tuple(key.split("."))]
        return self._specs[key]

    def __contains__(self, key) -> bool:
        try:
            self[key]
            return True
        except (KeyError, TypeError):
            return False

    def __iter__(self):
        return iter(self._specs)

    def keys(self, nested: bool = False, leaves_only: bool = False):
        if not nested:
            return self._specs.keys()
        out = []
        for k, v in self._specs.items():
            if isinstance(v, Composite):
                if not leaves_only:
                    out.append((k,))
                out.extend((k, *s) for s in v.keys(True, leaves_only))
            else:
                out.append((k,))
        return out

    def items(self):
        return self._specs.items()

    def values(self):
        return self._specs.values()

    def set(self, key: str | tuple, spec: Spec) -> "Composite":
        if isinstance(key, str):
            key = tuple(key.split(".")) if "." in key else (key,)
        head, *rest = key
        specs = dict(self._specs)
        if rest:
            child = specs.get(head)
            if not isinstance(child, Composite):
                child = Composite(shape=self.shape)
            specs[head] = child.set(tuple(rest), spec)
        else:
            specs[head] = spec
        return Composite(specs, shape=self.shape)

    def delete(self, key: str | tuple) -> "Composite":
        if isinstance(key, str):
            key = tuple(key.split(".")) if "." in key else (key,)
        head, *rest = key
        specs = dict(self._specs)
        if rest:
            specs[head] = specs[head].delete(tuple(rest))
        else:
            del specs[head]
        return Composite(specs, shape=self.shape)

    def update(self, other: "Composite") -> "Composite":
        specs = dict(self._specs)
        for k, v in other.items():
            if isinstance(specs.get(k), Composite) and isinstance(v, Composite):
                specs[k] = specs[k].update(v)
            else:
                specs[k] = v
        return Composite(specs, shape=self.shape)

    def select(self, *keys) -> "Composite":
        out = Composite(shape=self.shape)
        for k in keys:
            out = out.set(k, self[k])
        return out

    # -- spec protocol over the tree ------------------------------------------

    def rand(self, key, batch_shape=()) -> ArrayDict:
        bs = _canon_shape(batch_shape) + self.shape
        ks = jax.random.split(key, max(len(self._specs), 1))
        return ArrayDict(
            {k: v.rand(kk, bs) for (k, v), kk in zip(self._specs.items(), ks)}
        )

    def zero(self, batch_shape=()) -> ArrayDict:
        bs = _canon_shape(batch_shape) + self.shape
        return ArrayDict({k: v.zero(bs) for k, v in self._specs.items()})

    def is_in(self, val: ArrayDict) -> bool:
        if not isinstance(val, (ArrayDict, dict)):
            return False
        for k, spec in self._specs.items():
            if k not in val:
                return False
            if not spec.is_in(val[k]):
                return False
        return True

    def project(self, val: ArrayDict) -> ArrayDict:
        out = val
        for k, spec in self._specs.items():
            out = out.set(k, spec.project(val[k]))
        return out

    def encode(self, val) -> ArrayDict:
        out = ArrayDict()
        for k, spec in self._specs.items():
            if k in val:
                out = out.set(k, spec.encode(val[k]))
        return out

    def to_sds(self, batch_shape=()) -> ArrayDict:
        bs = _canon_shape(batch_shape) + self.shape
        return ArrayDict(
            {
                k: v.to_sds(bs)
                for k, v in self._specs.items()
                if not isinstance(v, NonTensor)
            }
        )

    def expand(self, *batch_shape) -> "Composite":
        # Children keep feature shapes; only the shared batch shape grows.
        bs = _canon_shape(batch_shape[0] if len(batch_shape) == 1 and isinstance(batch_shape[0], (tuple, list)) else batch_shape)
        return Composite(dict(self._specs), shape=bs)

    def with_sharding(self, pspec) -> "Composite":
        # Not a dataclass: dataclasses.replace would route kwargs into
        # __init__'s **kw and drop children. Apply to every child instead.
        return Composite(
            {k: v.with_sharding(pspec) for k, v in self._specs.items()},
            shape=self.shape,
        )

    def __repr__(self):
        inner = ", ".join(f"{k}: {v!r}" for k, v in self._specs.items())
        return f"Composite(shape={self.shape}, {{{inner}}})"

    def __eq__(self, other):
        return (
            isinstance(other, Composite)
            and self.shape == other.shape
            and dict(self._specs) == dict(other._specs)
        )

    __hash__ = None

    @property
    def ndim(self):
        return len(self.shape)


def stack_specs(specs: list[Spec], axis: int = 0) -> Spec:
    """Stack specs along a new batch axis (reference Stacked:1496 /
    ``torch.stack`` over specs).

    Homogeneous members produce a plain dense spec with a grown batch
    shape. HETEROGENEOUS members (ragged multi-agent groups: same
    semantics, different shapes/domains) produce the mask-backed
    :class:`~rl_tpu.data.Stacked` / :class:`~rl_tpu.data.StackedComposite`
    (axis 0 only — padding+mask is the TPU-native lazy stack).
    """
    first = specs[0]
    if any(type(s) is not type(first) for s in specs):
        raise ValueError(
            "stack_specs requires same-type specs; wrap mixed types in a "
            "Composite per key"
        )
    if isinstance(first, Composite):
        homogeneous = all(
            set(s.keys()) == set(first.keys())
            and all(s[k] == first[k] for k in first.keys())
            for s in specs[1:]
        )
        if not homogeneous:
            if axis != 0:
                raise ValueError("heterogeneous stacking supports axis=0 only")
            from .hetero import StackedComposite

            return StackedComposite(specs)
        # Children hold feature shapes; only the shared batch shape grows.
        return Composite(
            dict(first.items()),
            shape=first.shape[:axis] + (len(specs),) + first.shape[axis:],
        )
    if any(s != first for s in specs):
        if axis != 0:
            raise ValueError("heterogeneous stacking supports axis=0 only")
        from .hetero import Stacked

        return Stacked(specs=tuple(specs))
    new_shape = first.shape[:axis] + (len(specs),) + first.shape[axis:]
    return dataclasses.replace(first, shape=new_shape)


def make_composite_from_arraydict(td: ArrayDict, unsqueeze_null_shapes: bool = True) -> Composite:
    """Infer a Composite spec from example data (reference envs/utils.py:928)."""

    def leaf_spec(v) -> Spec:
        if not hasattr(v, "dtype"):
            return NonTensor(example=v)
        v = jnp.asarray(v)
        return Unbounded(shape=v.shape, dtype=v.dtype)

    specs = {}
    for k, v in td.items():
        specs[k] = make_composite_from_arraydict(v) if isinstance(v, ArrayDict) else leaf_spec(v)
    return Composite(specs)
