"""Video-codec replay storage: image trajectories stored as encoded video.

Redesign of the reference's video storage (reference: torchrl/data/video.py
— ``VideoClipRef`` tensorclass + torchcodec-backed lazy decode so pixel
replay fits in RAM). TPU-native shape: a :class:`ListStorage` whose items
have their image leaves (uint8 [T, H, W, C]) encoded to MP4 (imageio/ffmpeg
when available, zlib otherwise) at write and decoded at read. Non-image
leaves ride alongside uncompressed, so sampling still returns a normal
ArrayDict and the decode cost is paid only for sampled items.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict
from .replay.storages import ListStorage

__all__ = ["VideoCodecStorage"]


def _is_video_leaf(v) -> bool:
    return v.ndim == 4 and v.dtype == jnp.uint8 and v.shape[-1] in (1, 3)


class _MP4Codec:
    name = "mp4"

    def encode(self, arr: np.ndarray) -> bytes:
        import imageio.v3 as iio

        frames = np.repeat(arr, 3, axis=-1) if arr.shape[-1] == 1 else arr
        # yuv420p needs even H/W: edge-pad bottom/right, crop on decode
        T, H, W, _ = frames.shape
        if H % 2 or W % 2:
            frames = np.pad(
                frames, ((0, 0), (0, H % 2), (0, W % 2), (0, 0)), mode="edge"
            )
        return iio.imwrite("<bytes>", frames, extension=".mp4", fps=30)

    def decode(self, blob: bytes, shape, dtype) -> np.ndarray:
        import imageio.v3 as iio

        T, H, W, C = shape
        frames = np.asarray(iio.imread(blob, extension=".mp4"))
        frames = frames[:T, :H, :W, :C]  # crop encoder padding
        if frames.shape != tuple(shape):
            raise ValueError(
                f"mp4 decode drifted: got {frames.shape}, stored {tuple(shape)}"
                " — use codec='zlib' for this data"
            )
        # lossy codec: shapes match, values are approximate
        return frames.astype(dtype)


class _ZlibCodec:
    name = "zlib"

    def encode(self, arr: np.ndarray) -> bytes:
        import zlib

        return zlib.compress(np.ascontiguousarray(arr).tobytes(), 3)

    def decode(self, blob: bytes, shape, dtype) -> np.ndarray:
        import zlib

        return np.frombuffer(zlib.decompress(blob), dtype=dtype).reshape(shape)


_MP4_PROBE: list = []  # cached (ok, error) probe; cannot change in-process


def _mp4_probe() -> tuple[bool, Exception | None]:
    if not _MP4_PROBE:
        try:
            _MP4Codec().encode(np.zeros((2, 16, 16, 3), np.uint8))
            _MP4_PROBE.append((True, None))
        except Exception as e:  # noqa: BLE001 - kept for diagnosis
            _MP4_PROBE.append((False, e))
    return _MP4_PROBE[0]


def _pick_codec(name: str):
    if name == "zlib":
        return _ZlibCodec()
    if name == "mp4":
        ok, err = _mp4_probe()
        if not ok:
            raise RuntimeError(
                "codec='mp4' but no working ffmpeg backend"
            ) from err
        return _MP4Codec()
    if name == "auto":
        return _MP4Codec() if _mp4_probe()[0] else _ZlibCodec()
    raise ValueError(f"unknown codec {name!r} (mp4/zlib/auto)")


class VideoCodecStorage(ListStorage):
    """ListStorage with image leaves video-encoded per item.

    Args:
        capacity: number of trajectory items.
        codec: "mp4" (lossy, needs ffmpeg), "zlib" (lossless), or "auto"
            (mp4 when ffmpeg probes OK, else zlib).
    """

    def __init__(self, capacity: int, codec: str = "auto"):
        super().__init__(capacity)
        self.codec = _pick_codec(codec)

    def _pack(self, item: ArrayDict) -> Any:
        enc: dict = {}
        rest: dict = {}
        for k, v in item.items(nested=True, leaves_only=True):
            arr = np.asarray(v)
            if _is_video_leaf(arr):
                enc[k] = (self.codec.encode(arr), arr.shape, arr.dtype)
            else:
                rest[k] = arr
        return enc, rest

    def _unpack(self, packed) -> ArrayDict:
        enc, rest = packed
        out = ArrayDict()
        for k, (blob, shape, dtype) in enc.items():
            out = out.set(k, jnp.asarray(self.codec.decode(blob, shape, dtype)))
        for k, v in rest.items():
            out = out.set(k, jnp.asarray(v))
        return out

    def set(self, state: dict, idx, items) -> dict:
        idx = np.atleast_1d(np.asarray(idx))
        packed = [self._pack(it) for it in self._as_items(idx, items)]
        return super().set(state, idx, packed)

    def get(self, state: dict, idx) -> list:
        return [self._unpack(p) for p in super().get(state, idx)]

    def nbytes(self) -> int:
        total = 0
        for p in self._items:
            if p is None:
                continue
            enc, rest = p
            total += sum(len(b) for b, _, _ in enc.values())
            total += sum(v.nbytes for v in rest.values())
        return total
