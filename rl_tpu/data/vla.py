"""Canonical VLA (vision-language-action) ArrayDict schema + chunking.

Redesign of the reference's VLA layer (reference: torchrl/data/vla/ —
schema.py ``validate_vla_tensordict``:79 defines the canonical nested-key
layout shared by OpenX/LeRobot-style datasets, policies and losses;
containers.py ``VLAAction`` carries per-step action chunks). The ArrayDict
form keeps the same key convention so a reference user finds the familiar
layout:

    ArrayDict(
        observation = ArrayDict(
            image = {<camera>: uint8 [*B, T, H, W, C]},   # HWC: TPU/XLA conv
            state = float [*B, T, state_dim],             # proprioception
        ),
        language_instruction = int32 [*B, L] (tokenized) ,
        action = float [*B, T, action_dim],
        vla_action = ArrayDict(chunk=float [*B, T, chunk, action_dim]),
        action_is_pad = bool [*B, T, chunk],
    )

Chunk building is a jit-friendly gather (no Python loops over T), so it can
run inside a replay-side transform.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict

__all__ = [
    "VLA_KEYS",
    "validate_vla_arraydict",
    "build_action_chunks",
    "AddActionChunks",
]

# the shared key defaults (reference schema.py module constants)
VLA_KEYS = {
    "image": ("observation", "image"),
    "state": ("observation", "state"),
    "instruction": ("language_instruction",),
    "action": ("action",),
    "chunk": ("vla_action", "chunk"),
    "pad": ("action_is_pad",),
}


def validate_vla_arraydict(td: ArrayDict, require_chunks: bool = False) -> None:
    """Raise ValueError with an actionable message on schema violations
    (reference validate_vla_tensordict:79)."""
    problems: list[str] = []
    if ("observation",) not in td and "observation" not in td:
        problems.append("missing 'observation' sub-dict")
    else:
        obs = td["observation"]
        if "image" not in obs and "state" not in obs:
            problems.append("observation needs at least one of 'image'/'state'")
        if "image" in obs:
            img = obs["image"]
            leaves = (
                [v for _, v in img.items(nested=True, leaves_only=True)]
                if isinstance(img, ArrayDict)
                else [img]
            )
            for leaf in leaves:
                if leaf.ndim < 4:
                    problems.append(
                        f"image leaves must be [*B, T, H, W, C]; got {leaf.shape}"
                    )
                elif leaf.dtype not in (jnp.uint8, jnp.float32, jnp.bfloat16):
                    problems.append(f"image dtype {leaf.dtype} not in uint8/float")
    if "action" not in td:
        problems.append("missing 'action' [*B, T, action_dim]")
    elif td["action"].ndim < 2:
        problems.append(f"action must be [*B, T, action_dim]; got {td['action'].shape}")
    if require_chunks:
        if ("vla_action", "chunk") not in td:
            problems.append("missing ('vla_action','chunk') — run AddActionChunks")
        elif ("action_is_pad",) not in td and "action_is_pad" not in td:
            problems.append("missing 'action_is_pad' chunk validity mask")
    if problems:
        raise ValueError("invalid VLA ArrayDict: " + "; ".join(problems))


def build_action_chunks(actions, chunk: int, episode_len=None):
    """[..., T, A] -> (chunks [..., T, chunk, A], is_pad [..., T, chunk]).

    Each step t carries the next ``chunk`` actions (ACT/diffusion-policy
    training targets). Steps past the episode tail are flagged in is_pad
    and hold the last valid action repeated (clamped gather — jit-safe).
    """
    T = actions.shape[-2]
    t_idx = jnp.arange(T)[:, None] + jnp.arange(chunk)[None, :]  # [T, chunk]
    if episode_len is None:
        is_pad = t_idx >= T
    else:
        # per-trajectory lengths [*B] broadcast over the trailing [T, chunk]
        limit = jnp.asarray(episode_len).reshape(
            *jnp.shape(episode_len), 1, 1
        )
        is_pad = t_idx >= limit
    gather = jnp.clip(t_idx, 0, T - 1)
    chunks = jnp.take(actions, gather.reshape(-1), axis=-2)
    chunks = chunks.reshape(*actions.shape[:-2], T, chunk, actions.shape[-1])
    # broadcast is_pad over leading batch dims
    pad = jnp.broadcast_to(is_pad, (*actions.shape[:-2], T, chunk))
    return chunks, pad


class AddActionChunks:
    """Replay/postproc transform stamping vla_action.chunk + action_is_pad
    onto trajectory batches (reference vla/preprocessing.py chunk builder)."""

    def __init__(self, chunk: int, episode_len_key: str | None = None):
        self.chunk = chunk
        self.episode_len_key = episode_len_key

    def __call__(self, td: ArrayDict) -> ArrayDict:
        ep_len = td[self.episode_len_key] if self.episode_len_key else None
        chunks, pad = build_action_chunks(td["action"], self.chunk, ep_len)
        return td.set(("vla_action", "chunk"), chunks).set("action_is_pad", pad)
