"""Canonical VLA (vision-language-action) ArrayDict schema + chunking.

Redesign of the reference's VLA layer (reference: torchrl/data/vla/ —
schema.py ``validate_vla_tensordict``:79 defines the canonical nested-key
layout shared by OpenX/LeRobot-style datasets, policies and losses;
containers.py ``VLAAction`` carries per-step action chunks). The ArrayDict
form keeps the same key convention so a reference user finds the familiar
layout:

    ArrayDict(
        observation = ArrayDict(
            image = {<camera>: uint8 [*B, T, H, W, C]},   # HWC: TPU/XLA conv
            state = float [*B, T, state_dim],             # proprioception
        ),
        language_instruction = int32 [*B, L] (tokenized) ,
        action = float [*B, T, action_dim],
        vla_action = ArrayDict(chunk=float [*B, T, chunk, action_dim]),
        action_is_pad = bool [*B, T, chunk],
    )

Chunk building is a jit-friendly gather (no Python loops over T), so it can
run inside a replay-side transform.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict

__all__ = [
    "VLA_KEYS",
    "UniformActionTokenizer",
    "VocabTailActionTokenizer",
    "validate_vla_arraydict",
    "build_action_chunks",
    "AddActionChunks",
]

# documentation of the FIXED canonical layout for consumers building keys
# (reference schema.py module constants); validate/AddActionChunks use the
# same literal paths — remapping this dict does not reconfigure them
VLA_KEYS = {
    "image": ("observation", "image"),
    "state": ("observation", "state"),
    "instruction": ("language_instruction",),
    "action": ("action",),
    "chunk": ("vla_action", "chunk"),
    "pad": ("action_is_pad",),
}


def validate_vla_arraydict(td: ArrayDict, require_chunks: bool = False) -> None:
    """Raise ValueError with an actionable message on schema violations
    (reference validate_vla_tensordict:79)."""
    problems: list[str] = []
    if ("observation",) not in td and "observation" not in td:
        problems.append("missing 'observation' sub-dict")
    else:
        obs = td["observation"]
        if "image" not in obs and "state" not in obs:
            problems.append("observation needs at least one of 'image'/'state'")
        if "image" in obs:
            img = obs["image"]
            leaves = (
                [v for _, v in img.items(nested=True, leaves_only=True)]
                if isinstance(img, ArrayDict)
                else [img]
            )
            for leaf in leaves:
                if leaf.ndim < 4:
                    problems.append(
                        f"image leaves must be [*B, T, H, W, C]; got {leaf.shape}"
                    )
                elif leaf.dtype not in (jnp.uint8, jnp.float32, jnp.bfloat16):
                    problems.append(f"image dtype {leaf.dtype} not in uint8/float")
    if "action" not in td:
        problems.append("missing 'action' [*B, T, action_dim]")
    elif td["action"].ndim < 2:
        problems.append(f"action must be [*B, T, action_dim]; got {td['action'].shape}")
    if require_chunks:
        if ("vla_action", "chunk") not in td:
            problems.append("missing ('vla_action','chunk') — run AddActionChunks")
        elif ("action_is_pad",) not in td and "action_is_pad" not in td:
            problems.append("missing 'action_is_pad' chunk validity mask")
    if problems:
        raise ValueError("invalid VLA ArrayDict: " + "; ".join(problems))


def build_action_chunks(actions, chunk: int, episode_len=None):
    """[..., T, A] -> (chunks [..., T, chunk, A], is_pad [..., T, chunk]).

    Each step t carries the next ``chunk`` actions (ACT/diffusion-policy
    training targets). Slots past the (per-trajectory) episode tail are
    flagged in is_pad and hold the LAST VALID action repeated — the gather
    clamps at episode_len-1, never reading past an episode's end (packed
    buffers can hold a neighboring episode there). jit-safe.
    """
    batch = actions.shape[:-2]
    T = actions.shape[-2]
    t_idx = jnp.arange(T)[:, None] + jnp.arange(chunk)[None, :]  # [T, chunk]
    if episode_len is None:
        limit = jnp.asarray(T)[None, None]
    else:
        # per-trajectory lengths [*B] -> [*B, 1, 1]
        limit = jnp.asarray(episode_len).reshape(*jnp.shape(episode_len), 1, 1)
    is_pad = jnp.broadcast_to(t_idx >= limit, (*batch, T, chunk))
    gather = jnp.minimum(jnp.clip(t_idx, 0, T - 1), limit - 1)  # [*B?, T, chunk]
    idx = jnp.broadcast_to(gather, (*batch, T, chunk)).reshape(*batch, T * chunk)
    chunks = jnp.take_along_axis(actions, idx[..., None], axis=-2)
    return chunks.reshape(*batch, T, chunk, actions.shape[-1]), is_pad


class AddActionChunks:
    """Replay/postproc transform stamping vla_action.chunk + action_is_pad
    onto trajectory batches (reference vla/preprocessing.py chunk builder)."""

    def __init__(self, chunk: int, episode_len_key: str | None = None):
        self.chunk = chunk
        self.episode_len_key = episode_len_key

    def __call__(self, td: ArrayDict) -> ArrayDict:
        ep_len = td[self.episode_len_key] if self.episode_len_key else None
        chunks, pad = build_action_chunks(td["action"], self.chunk, ep_len)
        return td.set(("vla_action", "chunk"), chunks).set("action_is_pad", pad)


# ---------------------------------------------------------------------------
# action tokenizers (reference torchrl/data/vla/tokenizers.py)
# ---------------------------------------------------------------------------


class UniformActionTokenizer:
    """Per-dimension uniform-bin codec (RT-2 / OpenVLA style; reference
    tokenizers.py ``UniformActionTokenizer``:54): each action dim is
    discretized into ``num_bins`` equal-width bins over ``[low, high]``;
    decode returns bin centers (round-trip error <= half a bin width).
    Element-wise over the trailing dim, so per-step actions
    ``[*B, action_dim]`` and chunks ``[*B, T, chunk, action_dim]`` both
    work; encode/decode are pure jnp (jit/vmap-safe).
    """

    def __init__(self, num_bins: int, *, low, high, action_dim: int | None = None):
        if num_bins < 1:
            raise ValueError(f"num_bins must be >= 1, got {num_bins}")
        low = jnp.asarray(low, jnp.float32)
        high = jnp.asarray(high, jnp.float32)
        if action_dim is not None:
            if low.ndim == 0:
                low = jnp.full((action_dim,), low)
            if high.ndim == 0:
                high = jnp.full((action_dim,), high)
        if low.shape != high.shape:
            raise ValueError(f"low/high shape mismatch: {low.shape} vs {high.shape}")
        if not bool(jnp.all(high > low)):
            raise ValueError("high must be strictly greater than low everywhere")
        self.num_bins = int(num_bins)
        self.low, self.high = low, high

    @property
    def vocab_size(self) -> int:
        return self.num_bins

    @property
    def action_dim(self) -> int | None:
        return self.low.shape[-1] if self.low.ndim else None

    def encode(self, actions) -> jnp.ndarray:
        scaled = (jnp.asarray(actions) - self.low) / (self.high - self.low)
        tokens = jnp.floor(scaled * self.num_bins).astype(jnp.int32)
        return jnp.clip(tokens, 0, self.num_bins - 1)

    def decode(self, tokens) -> jnp.ndarray:
        centers = (jnp.asarray(tokens, jnp.float32) + 0.5) / self.num_bins
        return self.low + centers * (self.high - self.low)


class VocabTailActionTokenizer:
    """OpenVLA-style vocab-tail codec (reference tokenizers.py
    ``VocabTailActionTokenizer``:154; arXiv:2406.09246): actions in
    ``[-1, 1]`` are digitized over the EDGES of ``num_bins`` uniform bins
    and written into the tail of the language-model vocabulary:
    ``token = vocab_size - digitize(a)``. Decode maps back to the bin
    center (``num_bins - 1`` centers). Window ids (default) live in
    ``[0, num_bins)``; pass ``full_vocab_size`` (e.g. 32000 for LLaMA-2)
    for raw LM ids.

    Optional OpenVLA ``norm_stats``: the affine q01/q99 map normalizes
    before encoding and un-normalizes after decoding on the dims selected
    by ``norm_mask``; unmasked (gripper) dims can be binarized to ±1
    and/or sign-flipped. The stats are kept in float64 numpy (checkpoint
    JSON precision); jnp decode computes in float32.
    """

    def __init__(
        self,
        num_bins: int = 256,
        *,
        full_vocab_size: int | None = None,
        norm_low=None,
        norm_high=None,
        norm_mask=None,
        gripper_binarize: bool = False,
        gripper_binarize_threshold: float = 0.0,
        gripper_invert: bool = False,
    ):
        if num_bins < 2:
            raise ValueError(f"num_bins must be >= 2, got {num_bins}")
        if full_vocab_size is not None and full_vocab_size < num_bins:
            raise ValueError(
                f"full_vocab_size ({full_vocab_size}) must be >= num_bins"
            )
        if (norm_low is None) != (norm_high is None):
            raise ValueError("norm_low and norm_high go together")
        self.num_bins = int(num_bins)
        self.full_vocab_size = None if full_vocab_size is None else int(full_vocab_size)
        self.bins = jnp.linspace(-1.0, 1.0, num_bins)
        self.bin_centers = (self.bins[:-1] + self.bins[1:]) / 2.0
        self.gripper_binarize = bool(gripper_binarize)
        self.gripper_binarize_threshold = float(gripper_binarize_threshold)
        self.gripper_invert = bool(gripper_invert)
        if norm_low is not None:
            self.norm_low = np.asarray(norm_low, np.float64)
            self.norm_high = np.asarray(norm_high, np.float64)
            self.norm_mask = (
                np.ones_like(self.norm_low, bool)
                if norm_mask is None
                else np.asarray(norm_mask, bool)
            )
        else:
            self.norm_low = self.norm_high = self.norm_mask = None

    @property
    def vocab_size(self) -> int:
        return self.full_vocab_size or self.num_bins

    def encode(self, actions) -> jnp.ndarray:
        a = jnp.asarray(actions, jnp.float32)
        if self.norm_low is not None:
            span = jnp.asarray(
                self.norm_high - self.norm_low + 1e-8, jnp.float32
            )
            lo = jnp.asarray(self.norm_low, jnp.float32)
            normed = 2.0 * (a - lo) / span - 1.0
            a = jnp.where(jnp.asarray(self.norm_mask), normed, a)
        # digitize: index of the first bin edge strictly greater, in
        # [1, num_bins] (np.digitize convention the reference ports)
        d = jnp.clip(
            jnp.digitize(jnp.clip(a, -1.0, 1.0), self.bins), 1, self.num_bins
        )
        return (self.vocab_size - d).astype(jnp.int32)

    def decode(self, tokens) -> jnp.ndarray:
        d = self.vocab_size - jnp.asarray(tokens, jnp.int32)
        idx = jnp.clip(d - 1, 0, self.num_bins - 2)
        a = self.bin_centers[idx]
        if self.norm_low is not None:
            span = jnp.asarray(
                self.norm_high - self.norm_low + 1e-8, jnp.float32
            )
            lo = jnp.asarray(self.norm_low, jnp.float32)
            unnormed = 0.5 * (a + 1.0) * span + lo
            mask = jnp.asarray(self.norm_mask)
            a = jnp.where(mask, unnormed, a)
            if self.gripper_binarize:
                binar = jnp.where(
                    a > self.gripper_binarize_threshold, 1.0, -1.0
                )
                a = jnp.where(mask, a, binar)
            if self.gripper_invert:
                a = jnp.where(mask, a, -a)
        return a
