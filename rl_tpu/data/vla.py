"""Canonical VLA (vision-language-action) ArrayDict schema + chunking.

Redesign of the reference's VLA layer (reference: torchrl/data/vla/ —
schema.py ``validate_vla_tensordict``:79 defines the canonical nested-key
layout shared by OpenX/LeRobot-style datasets, policies and losses;
containers.py ``VLAAction`` carries per-step action chunks). The ArrayDict
form keeps the same key convention so a reference user finds the familiar
layout:

    ArrayDict(
        observation = ArrayDict(
            image = {<camera>: uint8 [*B, T, H, W, C]},   # HWC: TPU/XLA conv
            state = float [*B, T, state_dim],             # proprioception
        ),
        language_instruction = int32 [*B, L] (tokenized) ,
        action = float [*B, T, action_dim],
        vla_action = ArrayDict(chunk=float [*B, T, chunk, action_dim]),
        action_is_pad = bool [*B, T, chunk],
    )

Chunk building is a jit-friendly gather (no Python loops over T), so it can
run inside a replay-side transform.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .arraydict import ArrayDict

__all__ = [
    "VLA_KEYS",
    "validate_vla_arraydict",
    "build_action_chunks",
    "AddActionChunks",
]

# documentation of the FIXED canonical layout for consumers building keys
# (reference schema.py module constants); validate/AddActionChunks use the
# same literal paths — remapping this dict does not reconfigure them
VLA_KEYS = {
    "image": ("observation", "image"),
    "state": ("observation", "state"),
    "instruction": ("language_instruction",),
    "action": ("action",),
    "chunk": ("vla_action", "chunk"),
    "pad": ("action_is_pad",),
}


def validate_vla_arraydict(td: ArrayDict, require_chunks: bool = False) -> None:
    """Raise ValueError with an actionable message on schema violations
    (reference validate_vla_tensordict:79)."""
    problems: list[str] = []
    if ("observation",) not in td and "observation" not in td:
        problems.append("missing 'observation' sub-dict")
    else:
        obs = td["observation"]
        if "image" not in obs and "state" not in obs:
            problems.append("observation needs at least one of 'image'/'state'")
        if "image" in obs:
            img = obs["image"]
            leaves = (
                [v for _, v in img.items(nested=True, leaves_only=True)]
                if isinstance(img, ArrayDict)
                else [img]
            )
            for leaf in leaves:
                if leaf.ndim < 4:
                    problems.append(
                        f"image leaves must be [*B, T, H, W, C]; got {leaf.shape}"
                    )
                elif leaf.dtype not in (jnp.uint8, jnp.float32, jnp.bfloat16):
                    problems.append(f"image dtype {leaf.dtype} not in uint8/float")
    if "action" not in td:
        problems.append("missing 'action' [*B, T, action_dim]")
    elif td["action"].ndim < 2:
        problems.append(f"action must be [*B, T, action_dim]; got {td['action'].shape}")
    if require_chunks:
        if ("vla_action", "chunk") not in td:
            problems.append("missing ('vla_action','chunk') — run AddActionChunks")
        elif ("action_is_pad",) not in td and "action_is_pad" not in td:
            problems.append("missing 'action_is_pad' chunk validity mask")
    if problems:
        raise ValueError("invalid VLA ArrayDict: " + "; ".join(problems))


def build_action_chunks(actions, chunk: int, episode_len=None):
    """[..., T, A] -> (chunks [..., T, chunk, A], is_pad [..., T, chunk]).

    Each step t carries the next ``chunk`` actions (ACT/diffusion-policy
    training targets). Slots past the (per-trajectory) episode tail are
    flagged in is_pad and hold the LAST VALID action repeated — the gather
    clamps at episode_len-1, never reading past an episode's end (packed
    buffers can hold a neighboring episode there). jit-safe.
    """
    batch = actions.shape[:-2]
    T = actions.shape[-2]
    t_idx = jnp.arange(T)[:, None] + jnp.arange(chunk)[None, :]  # [T, chunk]
    if episode_len is None:
        limit = jnp.asarray(T)[None, None]
    else:
        # per-trajectory lengths [*B] -> [*B, 1, 1]
        limit = jnp.asarray(episode_len).reshape(*jnp.shape(episode_len), 1, 1)
    is_pad = jnp.broadcast_to(t_idx >= limit, (*batch, T, chunk))
    gather = jnp.minimum(jnp.clip(t_idx, 0, T - 1), limit - 1)  # [*B?, T, chunk]
    idx = jnp.broadcast_to(gather, (*batch, T, chunk)).reshape(*batch, T * chunk)
    chunks = jnp.take_along_axis(actions, idx[..., None], axis=-2)
    return chunks.reshape(*batch, T, chunk, actions.shape[-1]), is_pad


class AddActionChunks:
    """Replay/postproc transform stamping vla_action.chunk + action_is_pad
    onto trajectory batches (reference vla/preprocessing.py chunk builder)."""

    def __init__(self, chunk: int, episode_len_key: str | None = None):
        self.chunk = chunk
        self.episode_len_key = episode_len_key

    def __call__(self, td: ArrayDict) -> ArrayDict:
        ep_len = td[self.episode_len_key] if self.episode_len_key else None
        chunks, pad = build_action_chunks(td["action"], self.chunk, ep_len)
        return td.set(("vla_action", "chunk"), chunks).set("action_is_pad", pad)
