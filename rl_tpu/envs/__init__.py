from .base import EnvBase, EnvState, VmapEnv, rollout, step_mdp, where_done
from .classic.cartpole import CartPoleEnv
from .classic.pendulum import PendulumEnv
from .model_based import ModelBasedEnv
from .wrappers import FrameSkipEnv, NoopResetEnv
from .transforms.base import Compose, Transform, TransformedEnv
from .transforms.image import CenterCrop, GrayScale, Resize, ToFloatImage
from .transforms.vecnorm import VecNorm
from .transforms.common import (
    TimeMaxPool,
    ActionScaling,
    CatFrames,
    CatTensors,
    DoubleToFloat,
    DTypeCast,
    FlattenObservation,
    InitTracker,
    ObservationNorm,
    RenameTransform,
    RewardClipping,
    RewardScaling,
    RewardSum,
    SqueezeTransform,
    StepCounter,
    UnsqueezeTransform,
)
from .utils import ExplorationType, check_env_specs, exploration_type, set_exploration_type

__all__ = [
    "FrameSkipEnv",
    "NoopResetEnv",
    "TimeMaxPool",
    "ModelBasedEnv",
    "VecNorm",
    "ToFloatImage",
    "GrayScale",
    "Resize",
    "CenterCrop",
    "EnvBase",
    "EnvState",
    "VmapEnv",
    "rollout",
    "step_mdp",
    "where_done",
    "PendulumEnv",
    "CartPoleEnv",
    "Transform",
    "TransformedEnv",
    "Compose",
    "ObservationNorm",
    "RewardScaling",
    "RewardClipping",
    "RewardSum",
    "StepCounter",
    "InitTracker",
    "CatFrames",
    "FlattenObservation",
    "DTypeCast",
    "DoubleToFloat",
    "RenameTransform",
    "CatTensors",
    "UnsqueezeTransform",
    "SqueezeTransform",
    "ActionScaling",
    "check_env_specs",
    "ExplorationType",
    "exploration_type",
    "set_exploration_type",
]
