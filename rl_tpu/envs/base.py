"""Functional environment core.

TPU-native redesign of the reference's ``EnvBase``
(reference: torchrl/envs/common.py:404; public ``step``:2340, ``reset``:3108,
``rollout``:3449, ``step_and_maybe_reset``:4090, ``step_mdp``:3869).

The reference is stateful (`env.step(td)` mutates module state); here every
environment is a **pure function of an explicit state**, which is what lets
XLA stage entire rollouts:

- ``reset(key) -> (state, td)``
- ``step(state, td_with_action) -> (state, td)`` where the returned ``td``
  holds the *pre-step* content plus a ``"next"`` sub-dict — the same data
  layout the reference's collectors emit, so losses/value estimators read
  batches identically.
- ``step_and_reset`` fuses step with masked auto-reset (the
  ``step_and_maybe_reset`` analog): sub-envs that finished are re-seeded via
  ``jnp.where`` masking instead of host-side partial resets.
- ``rollout`` is a ``lax.scan`` over time, vectorization is ``jax.vmap`` via
  :class:`VmapEnv` — no worker processes (the ParallelEnv replacement for
  pure-JAX envs; host envs get a separate pool in rl_tpu.collectors).

Randomness: the env state carries a PRNG key at ``state["rng"]``; stochastic
``_step``/``_reset`` impls split from it functionally.

Conventions vs the reference: reward/done are scalar-shaped ``()`` leaves
(not ``(1,)``) — the natural JAX form; specs document it.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..data import ArrayDict, Composite, Spec, Unbounded
from ..data.specs import Binary

__all__ = ["EnvBase", "VmapEnv", "EnvState", "rollout", "step_mdp"]

EnvState = ArrayDict  # alias: env state is just an ArrayDict carrying "rng"

DONE_KEYS = ("done", "terminated", "truncated")


class EnvBase:
    """Abstract pure-functional environment.

    Subclasses implement :meth:`_reset` and :meth:`_step` and define the spec
    properties. Both hooks receive/return ArrayDicts and must be jit-safe
    (traced shapes only, ``lax`` control flow).

    Subclass contract:

    - ``_reset(key) -> (state, obs_td)``: fresh episode state + observations.
      ``state`` must NOT include "rng" (the base manages it).
    - ``_step(state, action, key) -> (state, obs_td, reward, terminated,
      truncated)``: one transition. ``reward`` scalar f32, flags scalar bool.
    """

    # -- specs (subclass responsibility) --------------------------------------

    @property
    def observation_spec(self) -> Composite:
        raise NotImplementedError

    @property
    def action_spec(self) -> Spec:
        raise NotImplementedError

    @property
    def reward_spec(self) -> Spec:
        return Unbounded(shape=(), dtype=jnp.float32)

    @property
    def done_spec(self) -> Composite:
        return Composite(
            done=Binary(shape=()),
            terminated=Binary(shape=()),
            truncated=Binary(shape=()),
        )

    @property
    def state_spec(self) -> Composite:
        """Spec of the env's carry state (excluding "rng"); optional."""
        return Composite()

    @property
    def full_specs(self) -> Composite:
        """The complete env contract (reference ``env.specs``, common.py:3430)."""
        return Composite(
            observation=self.observation_spec,
            action=Composite(action=self.action_spec),
            reward=Composite(reward=self.reward_spec),
            done=self.done_spec,
            state=self.state_spec,
        )

    @property
    def batch_shape(self) -> tuple[int, ...]:
        return ()

    # -- subclass hooks -------------------------------------------------------

    def _reset(self, key: jax.Array) -> tuple[ArrayDict, ArrayDict]:
        raise NotImplementedError

    def _step(
        self, state: ArrayDict, action: Any, key: jax.Array
    ) -> tuple[ArrayDict, ArrayDict, jax.Array, jax.Array, jax.Array]:
        raise NotImplementedError

    # -- public API -----------------------------------------------------------

    def reset(self, key: jax.Array) -> tuple[EnvState, ArrayDict]:
        """Start an episode: returns (state, td) with observations + done flags."""
        from ..utils.seeding import ensure_typed_key

        reset_key, carry_key = jax.random.split(ensure_typed_key(key))
        state, obs = self._reset(reset_key)
        state = state.set("rng", carry_key)
        zero = jnp.zeros(self.batch_shape, jnp.bool_)
        td = obs.update(
            ArrayDict(done=zero, terminated=zero, truncated=zero)
        )
        return state, td

    def step(self, state: EnvState, td: ArrayDict) -> tuple[EnvState, ArrayDict]:
        """One transition. ``td`` must hold "action"; the result carries the
        input content plus ``"next"`` = {obs…, reward, done, terminated,
        truncated} (the reference's step output layout, common.py:2340)."""
        key = state["rng"]
        step_key, carry_key = jax.random.split(key)
        new_state, obs, reward, terminated, truncated = self._step(
            state.exclude("rng"), td["action"], step_key
        )
        new_state = new_state.set("rng", carry_key)
        next_td = obs.update(
            ArrayDict(
                reward=jnp.asarray(reward, jnp.float32),
                terminated=jnp.asarray(terminated, jnp.bool_),
                truncated=jnp.asarray(truncated, jnp.bool_),
            )
        )
        next_td = next_td.set("done", next_td["terminated"] | next_td["truncated"])
        return new_state, td.set("next", next_td)

    @property
    def _rng_path(self) -> tuple[str, ...]:
        """Where the carried PRNG key lives in the env state."""
        return ("rng",)

    def _spec_state(self, state: EnvState) -> ArrayDict:
        """The slice of ``state`` described by :attr:`state_spec` (wrappers
        strip their bookkeeping)."""
        return state.exclude("rng")

    def step_and_reset(
        self, state: EnvState, td: ArrayDict
    ) -> tuple[EnvState, ArrayDict, ArrayDict]:
        """Step, then auto-reset wherever the episode ended.

        Returns ``(carry_state, full_td, carry_td)``: ``full_td`` is the
        transition for storage (its "next" holds the terminal observation);
        ``carry_td`` holds the observation to act on next (post-reset where
        done). Masked-``where`` equivalent of the reference's
        ``step_and_maybe_reset`` (common.py:4090) — fixed-shape, vmap-safe.
        """
        new_state, full_td = self.step(state, td)
        rng_path = self._rng_path
        rng = new_state[rng_path]
        if rng.shape == ():
            reset_key, carry_key = jax.random.split(rng)
        else:
            # batched carry keys (a wrapped VmapEnv): advance each stream and
            # derive each sub-env's reset key from its OWN stream — a single
            # fleet-wide reset key would correlate every post-done re-seed
            pairs = jax.vmap(jax.random.split)(rng.reshape(-1))
            carry_key = pairs[:, 1].reshape(rng.shape)
            reset_key = pairs[:, 0].reshape(rng.shape)
        reset_state, reset_td = self.reset(reset_key)

        done = full_td["next", "done"]
        carry_td = where_done(done, reset_td, step_mdp(full_td))
        carry_state = where_done(
            done, reset_state.delete(rng_path), new_state.delete(rng_path)
        )
        carry_state = carry_state.set(rng_path, carry_key)
        return carry_state, full_td, carry_td

    # -- conveniences ---------------------------------------------------------

    def rand_action(self, td: ArrayDict, key: jax.Array) -> ArrayDict:
        return td.set("action", self.action_spec.rand(key, self.batch_shape))

    def rollout(
        self,
        key: jax.Array,
        policy: Callable[[ArrayDict, jax.Array], ArrayDict] | None = None,
        max_steps: int = 100,
        auto_reset: bool = True,
        break_when_any_done: bool = False,
    ) -> ArrayDict:
        return rollout(
            self,
            key,
            policy,
            max_steps=max_steps,
            auto_reset=auto_reset,
            break_when_any_done=break_when_any_done,
        )


def where_done(done: jax.Array, on_done, on_not_done):
    """Leaf-wise ``where`` with ``done`` broadcast over trailing feature dims.

    Leaves that cannot be indexed per-env (fewer dims than ``done``) keep the
    continuing value. NOTE: per-env vs global state CANNOT be told apart by
    shape alone (a global stats vector may coincide with the env batch
    shape) — transform state goes through ``Transform.on_done`` instead
    (transforms/base.py), which dispatches per transform.
    """

    def pick(a, b):
        if a.ndim < done.ndim:
            return b
        d = done.reshape(done.shape + (1,) * (a.ndim - done.ndim))
        return jnp.where(d, a, b)

    return jax.tree.map(pick, on_done, on_not_done)


def step_mdp(td: ArrayDict) -> ArrayDict:
    """Project the "next" content to the root for the following step.

    Reference: ``EnvBase.step_mdp`` (common.py:3869) / ``_StepMDP``
    (envs/utils.py:79): next-observations and done flags move to the root,
    action/reward are dropped.
    """
    nxt = td["next"]
    return nxt.exclude("reward")


def rollout(
    env: EnvBase,
    key: jax.Array,
    policy: Callable[[ArrayDict, jax.Array], ArrayDict] | None = None,
    max_steps: int = 100,
    auto_reset: bool = True,
    break_when_any_done: bool = False,
    init: tuple[EnvState, ArrayDict] | None = None,
    policy_state: ArrayDict | None = None,
) -> ArrayDict:
    """Unrolled interaction as a single ``lax.scan`` (reference common.py:3449).

    The result has time as the leading batch axis: ``out.batch_shape ==
    (max_steps, *env.batch_shape)``, with the reference's ``{…, "next": …}``
    per-step layout. ``policy`` maps ``(td, key) -> td`` adding "action" (and
    any extras, e.g. "log_prob"); ``None`` takes random actions.

    ``policy_state`` seeds stateful-policy carry (exploration annealing, OU
    noise, RNN hidden state) under td["exploration"]/td["policy_carry"]; it is
    carried across steps and stripped from the recorded batch. The policy must
    keep its structure fixed (scan requirement).

    ``break_when_any_done=True`` stops *recording* once any sub-env is done
    (steps after the first done are masked invalid via "mask"); the scan
    length stays static — the jit-compatible form of the reference's
    ``_rollout_stop_early``.
    """
    from ..utils.seeding import ensure_typed_key

    if policy is None:
        policy = lambda td, k: env.rand_action(td, k)  # noqa: E731

    reset_key, scan_key = jax.random.split(ensure_typed_key(key))
    if init is not None:
        state, td = init
    else:
        state, td = env.reset(reset_key)
    if policy_state is not None:
        td = td.set("exploration", policy_state)

    def body(carry, step_key):
        state, td, alive = carry
        td = policy(td, step_key)
        td_env = td.exclude("exploration")
        if auto_reset:
            state, full_td, carry_td = env.step_and_reset(state, td_env)
        else:
            state, full_td = env.step(state, td_env)
            carry_td = step_mdp(full_td)
        if "exploration" in td:
            carry_td = carry_td.set("exploration", td["exploration"])
        full_td = full_td.set("mask", alive)
        alive = alive & ~jnp.any(full_td["next", "done"]) if break_when_any_done else alive
        return (state, carry_td, alive), full_td

    keys = jax.random.split(scan_key, max_steps)
    (_, _, _), steps = jax.lax.scan(body, (state, td, jnp.asarray(True)), keys)
    if not break_when_any_done:
        steps = steps.exclude("mask")
    return steps


class VmapEnv(EnvBase):
    """Vectorize a scalar env over a leading batch axis with ``jax.vmap``.

    The replacement for the reference's ``SerialEnv``/``ParallelEnv``
    (batched_envs.py:1433,1805) for pure-JAX envs: N identical envs stepped
    as one XLA program — no worker processes, no shared-memory buffers.
    """

    def __init__(self, env: EnvBase, num_envs: int):
        if env.batch_shape != ():
            raise ValueError("VmapEnv wraps scalar (unbatched) envs")
        self.env = env
        self.num_envs = num_envs

    @property
    def batch_shape(self) -> tuple[int, ...]:
        return (self.num_envs,)

    @property
    def observation_spec(self) -> Composite:
        return self.env.observation_spec

    @property
    def action_spec(self) -> Spec:
        return self.env.action_spec

    @property
    def reward_spec(self) -> Spec:
        return self.env.reward_spec

    @property
    def done_spec(self) -> Composite:
        return self.env.done_spec

    @property
    def state_spec(self) -> Composite:
        return self.env.state_spec

    def reset(self, key: jax.Array) -> tuple[EnvState, ArrayDict]:
        from ..utils.seeding import ensure_typed_key

        key = ensure_typed_key(key)
        if key.shape == ():
            # split ONCE at init: from here on every sub-env owns an
            # independent stream, advanced per step inside its own state
            keys = jax.random.split(key, self.num_envs)
        else:
            # pre-split per-env streams (auto-reset re-seeds, Anakin fleets)
            if key.shape != (self.num_envs,):
                raise ValueError(
                    f"batched reset key shape {key.shape} != ({self.num_envs},)"
                )
            keys = key
        return jax.vmap(self.env.reset)(keys)

    def step(self, state: EnvState, td: ArrayDict) -> tuple[EnvState, ArrayDict]:
        return jax.vmap(self.env.step)(state, td)

    def step_and_reset(self, state, td):
        return jax.vmap(self.env.step_and_reset)(state, td)

    def rand_action(self, td: ArrayDict, key: jax.Array) -> ArrayDict:
        return td.set("action", self.action_spec.rand(key, (self.num_envs,)))
