from .acrobot import AcrobotEnv
from .cartpole import CartPoleEnv
from .mountain_car import MountainCarContinuousEnv, MountainCarEnv
from .pendulum import PendulumEnv

__all__ = [
    "AcrobotEnv",
    "CartPoleEnv",
    "MountainCarContinuousEnv",
    "MountainCarEnv",
    "PendulumEnv",
]
