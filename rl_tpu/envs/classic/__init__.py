from .cartpole import CartPoleEnv
from .pendulum import PendulumEnv

__all__ = ["PendulumEnv", "CartPoleEnv"]
