"""Acrobot swing-up, pure JAX (classic Gym Acrobot-v1 dynamics).

Two-link underactuated pendulum; torque on the middle joint; RK4
integration of the book dynamics (Sutton & Barto form). Part of the
pure-JAX env portfolio (reference keeps this behind the gym wrapper,
torchrl/envs/libs/gym.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["AcrobotEnv"]


def _wrap(x, low, high):
    return low + (x - low) % (high - low)


class AcrobotEnv(EnvBase):
    dt = 0.2
    link_length_1 = 1.0
    link_mass_1 = 1.0
    link_mass_2 = 1.0
    link_com_1 = 0.5
    link_com_2 = 0.5
    link_moi = 1.0
    max_vel_1 = 4 * jnp.pi
    max_vel_2 = 9 * jnp.pi
    torques = (-1.0, 0.0, 1.0)
    g = 9.8

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = max_episode_steps

    @property
    def observation_spec(self) -> Composite:
        high = jnp.array(
            [1.0, 1.0, 1.0, 1.0, float(self.max_vel_1), float(self.max_vel_2)],
            jnp.float32,
        )
        return Composite(observation=Bounded(shape=(6,), low=-high, high=high))

    @property
    def action_spec(self):
        return Categorical(n=3)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            physics=Unbounded(shape=(4,)),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _obs(self, s):
        t1, t2, dt1, dt2 = s
        return ArrayDict(
            observation=jnp.stack(
                [jnp.cos(t1), jnp.sin(t1), jnp.cos(t2), jnp.sin(t2), dt1, dt2]
            )
        )

    def _reset(self, key):
        physics = jax.random.uniform(key, (4,), minval=-0.1, maxval=0.1)
        state = ArrayDict(physics=physics, step_count=jnp.asarray(0, jnp.int32))
        return state, self._obs(physics)

    def _dsdt(self, s, torque):
        m1, m2 = self.link_mass_1, self.link_mass_2
        l1 = self.link_length_1
        lc1, lc2 = self.link_com_1, self.link_com_2
        i1 = i2 = self.link_moi
        g = self.g
        t1, t2, dt1, dt2 = s
        d1 = (
            m1 * lc1**2
            + m2 * (l1**2 + lc2**2 + 2 * l1 * lc2 * jnp.cos(t2))
            + i1
            + i2
        )
        d2 = m2 * (lc2**2 + l1 * lc2 * jnp.cos(t2)) + i2
        phi2 = m2 * lc2 * g * jnp.cos(t1 + t2 - jnp.pi / 2)
        phi1 = (
            -m2 * l1 * lc2 * dt2**2 * jnp.sin(t2)
            - 2 * m2 * l1 * lc2 * dt2 * dt1 * jnp.sin(t2)
            + (m1 * lc1 + m2 * l1) * g * jnp.cos(t1 - jnp.pi / 2)
            + phi2
        )
        ddt2 = (
            torque + d2 / d1 * phi1 - m2 * l1 * lc2 * dt1**2 * jnp.sin(t2) - phi2
        ) / (m2 * lc2**2 + i2 - d2**2 / d1)
        ddt1 = -(d2 * ddt2 + phi1) / d1
        return jnp.stack([dt1, dt2, ddt1, ddt2])

    def _rk4(self, s, torque):
        dt = self.dt
        k1 = self._dsdt(s, torque)
        k2 = self._dsdt(s + dt / 2 * k1, torque)
        k3 = self._dsdt(s + dt / 2 * k2, torque)
        k4 = self._dsdt(s + dt * k3, torque)
        return s + dt / 6 * (k1 + 2 * k2 + 2 * k3 + k4)

    def _step(self, state, action, key):
        torque = jnp.asarray(self.torques)[action]
        s = self._rk4(state["physics"], torque)
        s = jnp.stack(
            [
                _wrap(s[0], -jnp.pi, jnp.pi),
                _wrap(s[1], -jnp.pi, jnp.pi),
                jnp.clip(s[2], -self.max_vel_1, self.max_vel_1),
                jnp.clip(s[3], -self.max_vel_2, self.max_vel_2),
            ]
        )
        count = state["step_count"] + 1
        terminated = -jnp.cos(s[0]) - jnp.cos(s[1] + s[0]) > 1.0
        truncated = count >= self.max_episode_steps
        reward = jnp.where(terminated, 0.0, -1.0)
        new_state = ArrayDict(physics=s, step_count=count)
        return new_state, self._obs(s), reward, terminated, truncated
