"""CartPole balance, pure JAX (classic Gym CartPole-v1 dynamics).

Discrete-action counterpart to :mod:`pendulum` for the DQN/PPO recipes
(BASELINE.md config #1). Euler integration, 500-step truncation,
termination on |x| > 2.4 or |theta| > 12 deg.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["CartPoleEnv"]


class CartPoleEnv(EnvBase):
    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5  # half pole length
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 2 * jnp.pi / 360
    x_threshold = 2.4

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = max_episode_steps

    @property
    def observation_spec(self) -> Composite:
        high = jnp.array(
            [self.x_threshold * 2, 1e5, self.theta_threshold * 2, 1e5],
            jnp.float32,
        )
        return Composite(observation=Bounded(shape=(4,), low=-high, high=high))

    @property
    def action_spec(self):
        return Categorical(n=2)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            physics=Unbounded(shape=(4,)),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _reset(self, key):
        physics = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        state = ArrayDict(physics=physics, step_count=jnp.asarray(0, jnp.int32))
        return state, ArrayDict(observation=physics)

    def _step(self, state, action, key):
        x, x_dot, theta, theta_dot = state["physics"]
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)

        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        physics = jnp.stack([x, x_dot, theta, theta_dot])

        count = state["step_count"] + 1
        terminated = (
            (jnp.abs(x) > self.x_threshold) | (jnp.abs(theta) > self.theta_threshold)
        )
        truncated = count >= self.max_episode_steps
        new_state = ArrayDict(physics=physics, step_count=count)
        return new_state, ArrayDict(observation=physics), jnp.asarray(1.0), terminated, truncated
