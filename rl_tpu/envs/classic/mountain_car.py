"""Mountain Car, pure JAX (classic Gym dynamics, discrete + continuous).

Part of the pure-JAX env portfolio (the reference keeps classic-control via
the gym wrapper, torchrl/envs/libs/gym.py; here the sims are native so whole
rollouts stay inside one XLA program — SURVEY.md §2.13 env-level DP via
``jax.vmap``).

Dynamics (classic): ``v += force + cos(3 p) * (-0.0025)``;
``p += v``; walls at p=-1.2 (velocity zeroed); goal on the right hill.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["MountainCarEnv", "MountainCarContinuousEnv"]


class MountainCarEnv(EnvBase):
    """Discrete 3-action mountain car (reward -1/step, goal at 0.5)."""

    min_position = -1.2
    max_position = 0.6
    max_speed = 0.07
    goal_position = 0.5
    force = 0.001
    gravity = 0.0025

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = max_episode_steps

    @property
    def observation_spec(self) -> Composite:
        low = jnp.array([self.min_position, -self.max_speed], jnp.float32)
        high = jnp.array([self.max_position, self.max_speed], jnp.float32)
        return Composite(observation=Bounded(shape=(2,), low=low, high=high))

    @property
    def action_spec(self):
        return Categorical(n=3)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            physics=Unbounded(shape=(2,)),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _reset(self, key):
        pos = jax.random.uniform(key, (), minval=-0.6, maxval=-0.4)
        physics = jnp.stack([pos, jnp.asarray(0.0)])
        state = ArrayDict(physics=physics, step_count=jnp.asarray(0, jnp.int32))
        return state, ArrayDict(observation=physics)

    def _advance(self, physics, force):
        pos, vel = physics
        vel = vel + force + jnp.cos(3 * pos) * (-self.gravity)
        vel = jnp.clip(vel, -self.max_speed, self.max_speed)
        pos = pos + vel
        pos = jnp.clip(pos, self.min_position, self.max_position)
        vel = jnp.where((pos <= self.min_position) & (vel < 0), 0.0, vel)
        return jnp.stack([pos, vel])

    def _step(self, state, action, key):
        physics = self._advance(
            state["physics"], (action.astype(jnp.float32) - 1.0) * self.force
        )
        count = state["step_count"] + 1
        terminated = physics[0] >= self.goal_position
        truncated = count >= self.max_episode_steps
        new_state = ArrayDict(physics=physics, step_count=count)
        return (
            new_state,
            ArrayDict(observation=physics),
            jnp.asarray(-1.0),
            terminated,
            truncated,
        )


class MountainCarContinuousEnv(MountainCarEnv):
    """Continuous-force variant: action in [-1, 1], +100 at the goal,
    -0.1 a² control cost per step (classic MountainCarContinuous-v0)."""

    force_scale = 0.0015
    goal_position = 0.45

    def __init__(self, max_episode_steps: int = 999):
        super().__init__(max_episode_steps)

    @property
    def action_spec(self):
        return Bounded(shape=(1,), low=-1.0, high=1.0)

    def _step(self, state, action, key):
        a = jnp.clip(action[0], -1.0, 1.0)
        physics = self._advance(state["physics"], a * self.force_scale)
        count = state["step_count"] + 1
        terminated = physics[0] >= self.goal_position
        truncated = count >= self.max_episode_steps
        reward = jnp.where(terminated, 100.0, 0.0) - 0.1 * a**2
        new_state = ArrayDict(physics=physics, step_count=count)
        return (
            new_state,
            ArrayDict(observation=physics),
            reward,
            terminated,
            truncated,
        )
