"""Pendulum swing-up, pure JAX.

Functional re-design of the reference's pure-torch ``PendulumEnv``
(reference: torchrl/envs/custom/pendulum.py) with classic Gym dynamics:
state (theta, theta_dot), action torque in [-2, 2], reward
-(theta^2 + 0.1*thdot^2 + 0.001*u^2), 200-step truncation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Composite, Unbounded
from ..base import EnvBase

__all__ = ["PendulumEnv"]


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class PendulumEnv(EnvBase):
    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    length = 1.0

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = max_episode_steps

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            observation=Bounded(
                shape=(3,),
                low=jnp.array([-1.0, -1.0, -self.max_speed]),
                high=jnp.array([1.0, 1.0, self.max_speed]),
            )
        )

    @property
    def action_spec(self):
        return Bounded(shape=(1,), low=-self.max_torque, high=self.max_torque)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            theta=Unbounded(shape=()),
            theta_dot=Unbounded(shape=()),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _obs(self, theta, theta_dot) -> ArrayDict:
        return ArrayDict(
            observation=jnp.stack([jnp.cos(theta), jnp.sin(theta), theta_dot])
        )

    def _reset(self, key):
        k1, k2 = jax.random.split(key)
        theta = jax.random.uniform(k1, (), minval=-jnp.pi, maxval=jnp.pi)
        theta_dot = jax.random.uniform(k2, (), minval=-1.0, maxval=1.0)
        state = ArrayDict(
            theta=theta, theta_dot=theta_dot, step_count=jnp.asarray(0, jnp.int32)
        )
        return state, self._obs(theta, theta_dot)

    def _step(self, state, action, key):
        th, thdot = state["theta"], state["theta_dot"]
        u = jnp.clip(jnp.squeeze(action, -1), -self.max_torque, self.max_torque)

        cost = _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (
            3.0 * self.g / (2.0 * self.length) * jnp.sin(th)
            + 3.0 / (self.m * self.length**2) * u
        ) * self.dt
        newthdot = jnp.clip(newthdot, -self.max_speed, self.max_speed)
        newth = th + newthdot * self.dt

        count = state["step_count"] + 1
        new_state = ArrayDict(theta=newth, theta_dot=newthdot, step_count=count)
        truncated = count >= self.max_episode_steps
        terminated = jnp.asarray(False)
        return new_state, self._obs(newth, newthdot), -cost, terminated, truncated
