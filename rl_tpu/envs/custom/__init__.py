from .chess import ChessEnv
from .locomotion import HopperEnv, PlanarModel, Walker2dEnv
from .navigation import NavigationEnv
from .tictactoe import TicTacToeEnv
from .trading import TradingEnv
from .vla_env import ToyVLAEnv

__all__ = ["ChessEnv", "HopperEnv", "Walker2dEnv", "PlanarModel", "NavigationEnv", "TicTacToeEnv", "TradingEnv", "ToyVLAEnv"]
