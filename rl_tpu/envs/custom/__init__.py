from .navigation import NavigationEnv
from .tictactoe import TicTacToeEnv
from .trading import TradingEnv

__all__ = ["NavigationEnv", "TicTacToeEnv", "TradingEnv"]
