from .chess import ChessEnv
from .navigation import NavigationEnv
from .tictactoe import TicTacToeEnv
from .trading import TradingEnv
from .vla_env import ToyVLAEnv

__all__ = ["ChessEnv", "NavigationEnv", "TicTacToeEnv", "TradingEnv", "ToyVLAEnv"]
