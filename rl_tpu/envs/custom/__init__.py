from .chess import ChessEnv
from .navigation import NavigationEnv
from .tictactoe import TicTacToeEnv
from .trading import TradingEnv

__all__ = ["ChessEnv", "NavigationEnv", "TicTacToeEnv", "TradingEnv"]
