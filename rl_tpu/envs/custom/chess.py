"""ChessEnv: full chess with a native array move-generation core
(round-3 VERDICT missing #5).

Redesign of the reference's chess env (reference:
torchrl/envs/custom/chess.py — ``ChessEnv`` delegates ALL rules to the
host-side python ``chess`` library and exposes a legal-move ``action_mask``
consumed by the ActionMask transform). A host library cannot live inside an
XLA program, so here the rules engine itself is array-native: precomputed
numpy attack/ray tables + vectorized jnp move generation, with full
legality (pins, checks, castling-through-check, en passant, promotions)
decided by a vmapped make-move + king-attack probe. The entire step —
move-gen, legality mask, termination — is jit/scan-safe, so self-play
rollouts and MCTS run as single fused programs.

Conventions:
- square = rank*8 + file (a1=0, h1=7, a8=56); board is a flat [64] int32,
  white pieces positive (P=1 N=2 B=3 R=4 Q=5 K=6), black negative.
- action = from*64 + to (``Categorical(4096)``); promotions auto-queen
  (the AlphaZero-style underpromotion planes are intentionally dropped:
  one action per (from, to) keeps the mask at 4096 and underpromotion is
  irrelevant for self-play learning; the reference's SAN action list has
  them — documented deviation).
- reward: +1 to the mover for delivering checkmate, 0 otherwise; draws
  (stalemate, 50-move rule) terminate with 0; illegal action = forfeit
  (reward -1, episode ends) like TicTacToeEnv.
- documented deviation: draws by THREEFOLD REPETITION and INSUFFICIENT
  MATERIAL are not implemented (the reference's python-chess backend ends
  games on both). A repetition draw needs a position-hash history table —
  O(history) state per env that the array core deliberately omits;
  episodes stay bounded via the 50-move counter, but terminal values in
  shuffle endgames (e.g. bare-kings) can disagree with the reference
  until the counter trips.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Binary, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["ChessEnv", "fen_to_state", "state_to_fen", "START_FEN"]

START_FEN = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"

# ---------------------------------------------------------------------------
# static tables (numpy, built at import)
# ---------------------------------------------------------------------------

_DIRS = np.array(
    [8, -8, 1, -1, 9, 7, -9, -7]
)  # N S E W NE NW SW SE (0-3 ortho, 4-7 diag)


def _build_tables():
    knight = np.zeros((64, 64), bool)
    king = np.zeros((64, 64), bool)
    ray = np.full((64, 8, 7), -1, np.int32)
    pawn_capt = np.zeros((2, 64, 64), bool)  # 0=white, 1=black
    for s in range(64):
        r, f = divmod(s, 8)
        for dr, df in (
            (2, 1), (2, -1), (-2, 1), (-2, -1),
            (1, 2), (1, -2), (-1, 2), (-1, -2),
        ):
            rr, ff = r + dr, f + df
            if 0 <= rr < 8 and 0 <= ff < 8:
                knight[s, rr * 8 + ff] = True
        for dr in (-1, 0, 1):
            for df in (-1, 0, 1):
                if dr == df == 0:
                    continue
                rr, ff = r + dr, f + df
                if 0 <= rr < 8 and 0 <= ff < 8:
                    king[s, rr * 8 + ff] = True
        for d, (dr, df) in enumerate(
            ((1, 0), (-1, 0), (0, 1), (0, -1), (1, 1), (1, -1), (-1, -1), (-1, 1))
        ):
            rr, ff = r, f
            for i in range(7):
                rr, ff = rr + dr, ff + df
                if not (0 <= rr < 8 and 0 <= ff < 8):
                    break
                ray[s, d, i] = rr * 8 + ff
        for df in (-1, 1):
            if 0 <= f + df < 8:
                if r + 1 < 8:
                    pawn_capt[0, s, (r + 1) * 8 + f + df] = True
                if r - 1 >= 0:
                    pawn_capt[1, s, (r - 1) * 8 + f + df] = True
    return knight, king, ray, pawn_capt


# numpy at module level — import must NOT touch the JAX backend (the
# driver forces platforms after import; see tests/test_import_hygiene.py).
# jnp consumes these as constants inside traced functions.
_KNIGHT_NP, _KING_NP, _RAY_NP, _PAWN_CAPT_NP = _build_tables()
_RANK_NP = np.arange(64) // 8


def _tables():
    """Device-resident copies, materialized on first traced use."""
    return (
        jnp.asarray(_KNIGHT_NP),
        jnp.asarray(_KING_NP),
        jnp.asarray(_RAY_NP),  # [64, 8, 7] target squares, -1 padded
        jnp.asarray(_PAWN_CAPT_NP),
    )


def _ray_reach(board64):
    """[64 src, 8 dir, 7 step] bool: step visible from src (scan stops at
    AND INCLUDES the first occupied square)."""
    _, _, RAY, _ = _tables()
    padded = jnp.concatenate([board64, jnp.ones((1,), board64.dtype)])
    ray_occ = padded[RAY] != 0  # -1 index wraps to the sentinel (occupied)
    blocked_before = jnp.cumsum(ray_occ, axis=-1) - ray_occ.astype(jnp.int32)
    return (blocked_before == 0) & (RAY >= 0)


def square_attacked(board64, sq, by_white):
    """Is ``sq`` attacked by the given color? Inverse-probe form (rays cast
    FROM the square; O(8x7), cheap enough to vmap 4096x for legality)."""
    KNIGHT, KING, RAY, PAWN_CAPT = _tables()
    sgn = jnp.where(by_white, 1, -1)
    enemy = board64 * sgn  # attacker pieces positive
    if_knight = jnp.any(KNIGHT[sq] & (enemy == 2))
    if_king = jnp.any(KING[sq] & (enemy == 6))
    # a pawn of color c attacks sq iff sq is in the pawn's capture set;
    # equivalently the OPPOSITE color's capture set from sq hits the pawn
    opp_idx = jnp.where(by_white, 1, 0)  # white attackers: look "down"
    if_pawn = jnp.any(PAWN_CAPT[opp_idx, sq] & (enemy == 1))
    # sliders: first piece along each ray from sq
    ray_sq = RAY[sq]  # [8, 7]
    padded = jnp.concatenate([board64, jnp.zeros((1,), board64.dtype)])
    ray_pc = jnp.where(ray_sq >= 0, padded[ray_sq], 0)
    occ = ray_pc != 0
    first = (jnp.cumsum(occ, axis=-1) == 1) & occ  # first blocker per ray
    first_pc = jnp.sum(jnp.where(first, ray_pc, 0), axis=-1) * sgn  # [8]
    ortho_hit = jnp.any((first_pc[:4] == 4) | (first_pc[:4] == 5))
    diag_hit = jnp.any((first_pc[4:] == 3) | (first_pc[4:] == 5))
    return if_knight | if_king | if_pawn | ortho_hit | diag_hit


def _attacked_map(board64, by_white):
    """[64] bool: squares attacked by the given color (for castling paths)."""
    return jax.vmap(lambda s: square_attacked(board64, s, by_white))(
        jnp.arange(64)
    )


def make_move_board(board64, frm, to, stm, ep_sq):
    """Apply (frm, to) for side ``stm`` (+1/-1). Auto-queen promotion,
    en passant capture, castling rook shuffle. Returns the new board."""
    piece = board64[frm]
    is_pawn = jnp.abs(piece) == 1
    to_rank = to // 8
    promo = is_pawn & ((to_rank == 7) | (to_rank == 0))
    moved = jnp.where(promo, 5 * stm, piece)
    ep_capture = is_pawn & (to == ep_sq) & (board64[to] == 0) & (
        (to % 8) != (frm % 8)
    )
    out = board64.at[to].set(moved).at[frm].set(0)
    # remove the en-passant victim (one rank behind the landing square)
    victim = to - 8 * stm
    out = jnp.where(ep_capture, out.at[victim].set(0), out)
    # castling: king moves two files -> rook jumps over
    is_king = jnp.abs(piece) == 6
    delta = to - frm
    castle_k = is_king & (delta == 2)
    castle_q = is_king & (delta == -2)
    rook_from = jnp.where(castle_k, frm + 3, frm - 4)
    rook_to = jnp.where(castle_k, frm + 1, frm - 1)
    castled = out.at[rook_to].set(4 * stm).at[rook_from].set(0)
    return jnp.where(castle_k | castle_q, castled, out)


def _pseudo_moves(board64, stm, ep_sq, castling):
    """[64, 64] bool pseudo-legal move matrix for side ``stm``.

    ``castling`` = [wk, wq, bk, bq] bools. Castling entries here already
    include the not-in-check / not-through-check conditions (the final
    king-safety vmap re-checks only the landing square).
    """
    KNIGHT, KING, RAY, PAWN_CAPT = _tables()
    own = board64 * stm  # own pieces positive
    own_occ = own > 0
    empty = board64 == 0
    target_ok = ~own_occ  # empty or enemy

    knights = (own == 2)[:, None] & KNIGHT & target_ok[None, :]
    kings = (own == 6)[:, None] & KING & target_ok[None, :]

    reach = _ray_reach(board64)  # [64, 8, 7]
    # scatter ray visibility into a [64, 64] matrix per direction class
    tgt = jnp.where(reach, RAY, 64)  # pad -> dummy 64

    def vis_matrix(dirs):
        m = jnp.zeros((64, 65), bool)
        flat_src = jnp.repeat(jnp.arange(64), len(dirs) * 7)
        flat_tgt = tgt[:, dirs, :].reshape(-1)
        m = m.at[flat_src, flat_tgt].max(True)
        return m[:, :64]

    ortho_vis = vis_matrix((0, 1, 2, 3))
    diag_vis = vis_matrix((4, 5, 6, 7))
    rooks = ((own == 4) | (own == 5))[:, None] & ortho_vis & target_ok[None, :]
    bishops = ((own == 3) | (own == 5))[:, None] & diag_vis & target_ok[None, :]

    # pawns
    pawns = own == 1
    fwd = jnp.arange(64) + 8 * stm
    fwd_ok = (fwd >= 0) & (fwd < 64)
    fwd_c = jnp.clip(fwd, 0, 63)
    push1 = pawns & fwd_ok & empty[fwd_c]
    pushes = jnp.zeros((64, 64), bool).at[jnp.arange(64), fwd_c].max(push1)
    rank = jnp.asarray(_RANK_NP)
    start_rank = jnp.where(stm > 0, rank == 1, rank == 6)
    fwd2 = jnp.arange(64) + 16 * stm
    fwd2_c = jnp.clip(fwd2, 0, 63)
    push2 = pawns & start_rank & empty[fwd_c] & empty[fwd2_c]
    pushes = pushes.at[jnp.arange(64), fwd2_c].max(push2)
    capt_tbl = jnp.where(stm > 0, PAWN_CAPT[0], PAWN_CAPT[1])
    enemy_occ = own < 0
    ep_tgt = (jnp.arange(64) == ep_sq) & (ep_sq >= 0)
    captures = pawns[:, None] & capt_tbl & (enemy_occ | ep_tgt)[None, :]

    moves = knights | kings | rooks | bishops | pushes | captures

    # castling (king and rook on their original squares is implied by the
    # rights flags, which the env clears on any king/rook move or capture)
    e_sq = jnp.where(stm > 0, 4, 60)
    rights = jnp.where(stm > 0, castling[:2], castling[2:])
    enemy_attacks = _attacked_map(board64, stm < 0)
    f_sq, g_sq = e_sq + 1, e_sq + 2
    d_sq, c_sq, b_sq = e_sq - 1, e_sq - 2, e_sq - 3
    can_k = (
        rights[0]
        & (own[e_sq] == 6)
        & empty[f_sq] & empty[g_sq]
        & ~enemy_attacks[e_sq] & ~enemy_attacks[f_sq] & ~enemy_attacks[g_sq]
    )
    can_q = (
        rights[1]
        & (own[e_sq] == 6)
        & empty[d_sq] & empty[c_sq] & empty[b_sq]
        & ~enemy_attacks[e_sq] & ~enemy_attacks[d_sq] & ~enemy_attacks[c_sq]
    )
    moves = moves.at[e_sq, g_sq].max(can_k).at[e_sq, c_sq].max(can_q)
    return moves


def legal_move_mask(board64, stm, ep_sq, castling):
    """[4096] bool fully-legal (from*64+to) mask: pseudo-legal moves whose
    resulting position leaves the mover's king unattacked."""
    pseudo = _pseudo_moves(board64, stm, ep_sq, castling).reshape(-1)

    def safe(a):
        frm, to = a // 64, a % 64
        nb = make_move_board(board64, frm, to, stm, ep_sq)
        ksq = jnp.argmax(nb * stm == 6)
        return ~square_attacked(nb, ksq, stm < 0)

    # king-safety probe only where pseudo-legal (the rest is already False;
    # computing it anyway keeps the shape static — XLA masks the cost)
    safe_all = jax.vmap(safe)(jnp.arange(4096))
    return pseudo & safe_all


def _in_check(board64, stm):
    ksq = jnp.argmax(board64 * stm == 6)
    return square_attacked(board64, ksq, stm < 0)


# ---------------------------------------------------------------------------
# FEN (host-side setup helper)
# ---------------------------------------------------------------------------

_PIECE_OF = {"P": 1, "N": 2, "B": 3, "R": 4, "Q": 5, "K": 6}


def fen_to_state(fen: str) -> ArrayDict:
    """Parse a FEN string into the env's state ArrayDict (host-side)."""
    parts = fen.split()
    board = np.zeros(64, np.int32)
    for r, row in enumerate(parts[0].split("/")):
        f = 0
        for ch in row:
            if ch.isdigit():
                f += int(ch)
            else:
                sgn = 1 if ch.isupper() else -1
                board[(7 - r) * 8 + f] = sgn * _PIECE_OF[ch.upper()]
                f += 1
    stm = 1 if parts[1] == "w" else -1
    cast = np.array(
        ["K" in parts[2], "Q" in parts[2], "k" in parts[2], "q" in parts[2]]
    )
    ep = -1
    if len(parts) > 3 and parts[3] != "-":
        ep = (int(parts[3][1]) - 1) * 8 + (ord(parts[3][0]) - ord("a"))
    halfmove = int(parts[4]) if len(parts) > 4 else 0
    fullmove = int(parts[5]) if len(parts) > 5 else 1
    return ArrayDict(
        board=jnp.asarray(board),
        stm=jnp.asarray(stm, jnp.int32),
        castling=jnp.asarray(cast),
        ep=jnp.asarray(ep, jnp.int32),
        halfmove=jnp.asarray(halfmove, jnp.int32),
        fullmove=jnp.asarray(fullmove, jnp.int32),
    )


# ---------------------------------------------------------------------------
# env
# ---------------------------------------------------------------------------


class ChessEnv(EnvBase):
    """Two-player chess as a turn-based env (reference chess.py ChessEnv).

    Observation: flat board, side to move, castling rights, en-passant
    square, halfmove clock and the 4096-way legal ``action_mask`` (the
    ActionMask transform and ``rand_action`` consume it). Illegal action =
    forfeit (mover gets -1, episode ends) — TicTacToeEnv convention.
    """

    def __init__(self, max_halfmoves: int = 100):
        self.max_halfmoves = max_halfmoves  # 50-move rule (in half-moves)

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            board=Bounded(shape=(64,), low=-6, high=6, dtype=jnp.int32),
            turn=Bounded(shape=(), low=0, high=1, dtype=jnp.int32),
            castling=Binary(shape=(4,)),
            ep=Bounded(shape=(), low=-1, high=63, dtype=jnp.int32),
            halfmove=Unbounded(shape=(), dtype=jnp.int32),
            action_mask=Binary(shape=(4096,)),
        )

    @property
    def action_spec(self):
        return Categorical(n=4096)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            board=Unbounded(shape=(64,), dtype=jnp.int32),
            stm=Unbounded(shape=(), dtype=jnp.int32),
            castling=Binary(shape=(4,)),
            ep=Unbounded(shape=(), dtype=jnp.int32),
            halfmove=Unbounded(shape=(), dtype=jnp.int32),
            fullmove=Unbounded(shape=(), dtype=jnp.int32),
            legal_mask=Binary(shape=(4096,)),
        )

    def _obs(self, st: ArrayDict, mask=None) -> ArrayDict:
        # the legal mask of the side to move is CARRIED in the state: it
        # was already computed as the previous step's opponent mask, and
        # legal_move_mask (4096 vmapped make-move+king probes) dominates
        # the per-step cost — never compute it twice
        if mask is None:
            mask = (
                st["legal_mask"]
                if "legal_mask" in st
                else legal_move_mask(
                    st["board"], st["stm"], st["ep"], st["castling"]
                )
            )
        return ArrayDict(
            board=st["board"],
            turn=jnp.where(st["stm"] > 0, 0, 1).astype(jnp.int32),
            castling=st["castling"],
            ep=st["ep"],
            halfmove=st["halfmove"],
            action_mask=mask,
        )

    def _reset(self, key):
        st = fen_to_state(START_FEN)
        st = st.set(
            "legal_mask",
            legal_move_mask(st["board"], st["stm"], st["ep"], st["castling"]),
        )
        return st, self._obs(st)

    def reset_from_fen(self, fen: str, key=None):
        """Start from an arbitrary position (host-side helper)."""
        st = fen_to_state(fen)
        st = st.set(
            "legal_mask",
            legal_move_mask(st["board"], st["stm"], st["ep"], st["castling"]),
        )
        state = st.set("rng", jax.random.key(0) if key is None else key)
        zero = jnp.zeros((), jnp.bool_)
        td = self._obs(st).update(
            ArrayDict(done=zero, terminated=zero, truncated=zero)
        )
        return state, td

    def _step(self, state, action, key):
        board, stm = state["board"], state["stm"]
        ep, castling = state["ep"], state["castling"]
        frm, to = action // 64, action % 64

        mask = state["legal_mask"]  # computed when this position was reached
        legal = mask[action]

        nb = make_move_board(board, frm, to, stm, ep)
        board2 = jnp.where(legal, nb, board)

        piece = board[frm]
        is_pawn = jnp.abs(piece) == 1
        captured = board[to] != 0
        # en-passant square: set only on a double push
        new_ep = jnp.where(
            legal & is_pawn & (jnp.abs(to - frm) == 16),
            (frm + to) // 2,
            -1,
        ).astype(jnp.int32)
        # castling rights: clear on king move, rook move, rook capture
        def lost(sq):
            return (frm == sq) | (to == sq)

        is_king = jnp.abs(piece) == 6
        new_castling = jnp.where(
            legal,
            jnp.stack(
                [
                    castling[0] & ~lost(7) & ~(is_king & (stm > 0)),
                    castling[1] & ~lost(0) & ~(is_king & (stm > 0)),
                    castling[2] & ~lost(63) & ~(is_king & (stm < 0)),
                    castling[3] & ~lost(56) & ~(is_king & (stm < 0)),
                ]
            ),
            castling,
        )
        new_half = jnp.where(
            legal & (is_pawn | captured), 0, state["halfmove"] + 1
        ).astype(jnp.int32)

        nstm = -stm
        opp_mask = legal_move_mask(board2, nstm, new_ep, new_castling)
        new_state = ArrayDict(
            board=board2, stm=nstm, castling=new_castling,
            ep=new_ep, halfmove=new_half, legal_mask=opp_mask,
            # the fullmove counter advances after BLACK's move
            fullmove=(state["fullmove"] + (stm < 0)).astype(jnp.int32),
        )

        opp_has_move = jnp.any(opp_mask)
        opp_in_check = _in_check(board2, nstm)
        checkmate = legal & ~opp_has_move & opp_in_check
        stalemate = legal & ~opp_has_move & ~opp_in_check
        fifty = legal & (new_half >= self.max_halfmoves)

        reward = jnp.where(checkmate, 1.0, 0.0) + jnp.where(legal, 0.0, -1.0)
        # the 50-move rule is a game-rule DRAW (true value 0): a
        # termination, not a truncation — value estimators must not
        # bootstrap past it
        terminated = checkmate | stalemate | fifty | ~legal

        return (
            new_state,
            self._obs(new_state, mask=opp_mask),
            reward.astype(jnp.float32),
            terminated,
            jnp.zeros((), jnp.bool_),
        )


_CHAR_OF = {v: k for k, v in _PIECE_OF.items()}


def state_to_fen(state: ArrayDict) -> str:
    """Serialize an env/engine state back to FEN (host-side; the inverse
    of :func:`fen_to_state` — the reference exposes the board as FEN
    strings via ``include_fen``; here the native state is arrays and FEN
    is the debugging/interop view)."""
    board = np.asarray(state["board"]).reshape(8, 8)
    rows = []
    for r in range(7, -1, -1):
        row, run = "", 0
        for f in range(8):
            p = int(board[r, f])
            if p == 0:
                run += 1
                continue
            if run:
                row += str(run)
                run = 0
            ch = _CHAR_OF[abs(p)]
            row += ch if p > 0 else ch.lower()
        if run:
            row += str(run)
        rows.append(row)
    stm = "w" if int(state["stm"]) > 0 else "b"
    cast = "".join(
        ch
        for ch, on in zip("KQkq", np.asarray(state["castling"]))
        if bool(on)
    ) or "-"
    ep = int(state["ep"])
    ep_s = "-" if ep < 0 else chr(ord("a") + ep % 8) + str(ep // 8 + 1)
    half = int(state["halfmove"])
    full = int(state["fullmove"]) if "fullmove" in state else 1
    return f"{'/'.join(rows)} {stm} {cast} {ep_s} {half} {full}"
