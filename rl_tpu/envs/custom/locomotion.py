"""Planar locomotion suite: pure-JAX articulated rigid-body dynamics
(round-4 VERDICT next-step #8 — the MuJoCo-shaped north-star workload).

Native re-design of the reference's custom MuJoCo envs (reference:
torchrl/envs/custom/mujoco/base.py ``MujocoEnv`` over a selectable physics
backend; ``hopper.py`` / ``walker.py`` define obs/reward/termination on
top). The reference delegates dynamics to MuJoCo/mjx; neither is in this
image, and a host physics engine cannot live inside an XLA program — so
the dynamics here are a from-scratch planar Lagrangian simulator built on
autodiff, small enough to read and fully jit/vmap/scan-native:

- Generalized coordinates ``q = [x, z, theta_root, joint angles...]``,
  one kinematic tree of rigid links (2D: position + absolute angle).
- Kinetic energy ``T(q, qdot)`` is computed from link COM velocities via
  ``jax.jvp`` through forward kinematics; the mass matrix is
  ``M(q) = d^2T/dqdot^2`` (one ``jax.hessian``), the bias forces come from
  the Euler-Lagrange equation
  ``M(q) qddot = Q + dT/dq - dV/dq - Mdot(q, qdot) qdot``
  with every derivative taken by autodiff — no hand-derived equations of
  motion to get wrong, and the whole step is one XLA program.
- Ground contact is a smooth spring-damper penalty on named contact
  points with Coulomb-style friction (``-mu N tanh(vx/v_ref)``), mapped
  to generalized forces through ``jax.vjp`` (J^T F).
- Semi-implicit Euler at ``dt=0.002`` with ``frame_skip`` inner steps in
  a ``lax.scan`` (reference FRAME_SKIP=5).

Obs / reward / termination follow the reference exactly:
``obs = [qpos[1:], clip(qvel, +-10)]``; ``reward = forward_vel +
healthy_reward - ctrl_cost_weight * ||a||^2``; done when unhealthy
(hopper: z >= 0.7 and |angle| <= 0.2, hopper.py:28-30; walker:
0.8 <= z <= 2.0 and |angle| <= 1.0, walker.py:28-31).

Deliberate deviations (documented): link masses/inertias are round
approximations of the MuJoCo capsule-density values, contact is a penalty
model rather than MuJoCo's LCP solver, and actuator gears are scaled to
the penalty-contact regime — the task structure, shapes, and reward
semantics match; trajectories are not bit-comparable to MuJoCo.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Composite, Unbounded
from ..base import EnvBase

__all__ = ["PlanarModel", "HopperEnv", "Walker2dEnv", "planar_dynamics_step"]


@dataclasses.dataclass(frozen=True)
class PlanarModel:
    """A planar kinematic tree.

    Link 0 is the floating root (its pose is ``q[0:3] = x, z, theta``).
    Every other link attaches to the DISTAL end of its parent through a
    revolute joint: absolute angle = parent angle + rest_angle + q[3 + j].
    Angles measure from the downward vertical (0 = link hangs down).
    """

    parents: tuple  # per link: parent index (-1 for the root)
    lengths: tuple  # link lengths (m)
    masses: tuple  # link masses (kg)
    rest_angles: tuple  # joint rest offset vs parent (root entry ignored)
    com_fracs: tuple  # COM position as a fraction of length from the
    # proximal end
    contacts: tuple  # (link index, fraction along link) contact points
    gears: tuple  # actuator torque scale per joint (len = n_links - 1)
    joint_ranges: tuple = ()  # (lo, hi) per joint; () = unlimited
    joint_damping: float = 0.1
    root_half: float = 0.2  # root link extends +-root_half from (x, z)

    @property
    def n_links(self) -> int:
        return len(self.parents)

    @property
    def nq(self) -> int:
        return 3 + self.n_links - 1

    def inertias(self):
        # slender-rod inertia about the COM: m L^2 / 12
        return tuple(
            m * (l**2) / 12.0 for m, l in zip(self.masses, self.lengths)
        )


def _link_frames(model: PlanarModel, q):
    """Forward kinematics: per-link (proximal point, absolute angle).

    The root link is centered at (x, z) with absolute angle q[2]; its
    proximal ("hip") end sits ``root_half`` DOWN-link from the center.
    """

    def u(theta):  # down-link direction for absolute angle theta
        return jnp.stack([jnp.sin(theta), -jnp.cos(theta)])

    x, z, th0 = q[0], q[1], q[2]
    center = jnp.stack([x, z])
    # the root's PROXIMAL point is the hip, root_half below the stored
    # center; the torso link extends UPWARD from it (dir sign -1 below)
    starts = [center + model.root_half * u(th0)]
    angles = [th0]
    joint = 3
    for i in range(1, model.n_links):
        p = model.parents[i]
        ang = angles[p] + model.rest_angles[i] + q[joint]
        # child attaches at the parent's distal end
        if p == 0:
            attach = starts[0]  # hip: the root's proximal end
        else:
            attach = starts[p] + model.lengths[p] * u(angles[p])
        starts.append(attach)
        angles.append(ang)
        joint += 1
    return jnp.stack(starts), jnp.stack(angles)


def _dir_signs(model: PlanarModel):
    # the root link extends UP from its proximal (hip) point; every child
    # extends down-link (+u) from its attachment
    return jnp.asarray([-1.0] + [1.0] * (model.n_links - 1))[:, None]


def _coms_and_angles(model: PlanarModel, q):
    starts, angles = _link_frames(model, q)
    dirs = jnp.stack([jnp.sin(angles), -jnp.cos(angles)], axis=-1)
    dirs = dirs * _dir_signs(model)
    fr = jnp.asarray(model.com_fracs)[:, None]
    L = jnp.asarray(model.lengths)[:, None]
    coms = starts + fr * L * dirs
    return coms, angles


def _contact_points(model: PlanarModel, q):
    starts, angles = _link_frames(model, q)
    dirs = jnp.stack([jnp.sin(angles), -jnp.cos(angles)], axis=-1)
    dirs = dirs * _dir_signs(model)
    pts = []
    for link, frac in model.contacts:
        pts.append(starts[link] + frac * model.lengths[link] * dirs[link])
    return jnp.stack(pts)  # [C, 2]


_G = 9.81
_K_P = 2.0e4  # contact spring
_K_D = 300.0  # contact damper
_MU = 1.0  # friction coefficient
_V_REF = 0.1  # friction smoothing velocity
_F_MAX = 5.0e4  # contact-force cap (deep-tunneling impulses stay bounded)
_QVEL_MAX = 100.0  # hard generalized-velocity limit (explicit-integration
# safety net: an aggressive learned policy can otherwise pump energy
# through the stiff contacts faster than dt=0.002 can dissipate it,
# spiraling to inf/NaN — observed ~100 PPO steps into training)


def _kinetic(model: PlanarModel, q, qdot):
    def pose(qq):
        return _coms_and_angles(model, qq)

    (coms, angles), (vels, omegas) = jax.jvp(pose, (q,), (qdot,))
    m = jnp.asarray(model.masses)
    inertia = jnp.asarray(model.inertias())
    return 0.5 * jnp.sum(m * jnp.sum(vels**2, axis=-1)) + 0.5 * jnp.sum(
        inertia * omegas**2
    )


def _potential(model: PlanarModel, q):
    coms, _ = _coms_and_angles(model, q)
    return _G * jnp.sum(jnp.asarray(model.masses) * coms[:, 1])


def planar_dynamics_step(model: PlanarModel, q, qdot, tau_joints, dt):
    """One semi-implicit Euler step of the Euler-Lagrange dynamics.

    ``tau_joints`` [nq-3] are actuator torques on the joint coordinates.
    Returns (q_next, qdot_next).
    """
    nq = model.nq

    # M(q) = Hessian of T in qdot (T is quadratic in qdot, so exact)
    M = jax.hessian(lambda qd: _kinetic(model, q, qd))(qdot)
    dT_dq = jax.grad(lambda qq: _kinetic(model, qq, qdot))(q)
    dV_dq = jax.grad(lambda qq: _potential(model, qq))(q)
    # Mdot qdot via a jvp through q -> M(q)
    Mdot = jax.jvp(
        lambda qq: jax.hessian(lambda qd: _kinetic(model, qq, qd))(qdot), (q,), (qdot,)
    )[1]

    # contact: spring-damper normal + smooth Coulomb friction, J^T F
    def cpts(qq):
        return _contact_points(model, qq)

    pts, vels = jax.jvp(cpts, (q,), (qdot,))
    pen = jnp.maximum(-pts[:, 1], 0.0)  # penetration depth
    active = pen > 0.0
    fz = jnp.where(active, _K_P * pen - _K_D * vels[:, 1], 0.0)
    fz = jnp.clip(fz, 0.0, _F_MAX)
    fx = -_MU * fz * jnp.tanh(vels[:, 0] / _V_REF)
    F = jnp.stack([fx, fz], axis=-1)  # [C, 2]
    _, vjp = jax.vjp(cpts, q)
    (q_contact,) = vjp(F)

    # actuation + joint damping act on the joint coordinates only
    tau = jnp.concatenate([jnp.zeros(3), tau_joints])
    damping = -model.joint_damping * jnp.concatenate([jnp.zeros(3), qdot[3:]])

    # soft joint limits: a stiff restoring torque past the range ends
    # (MuJoCo expresses these as joint range constraints; penalty form here)
    if model.joint_ranges:
        lo = jnp.asarray([r[0] for r in model.joint_ranges])
        hi = jnp.asarray([r[1] for r in model.joint_ranges])
        phi = q[3:]
        k_lim, d_lim = 400.0, 20.0
        over = jnp.maximum(phi - hi, 0.0)
        under = jnp.maximum(lo - phi, 0.0)
        engaged = (over > 0) | (under > 0)
        tau_lim = -k_lim * over + k_lim * under - jnp.where(
            engaged, d_lim * qdot[3:], 0.0
        )
        damping = damping + jnp.concatenate([jnp.zeros(3), tau_lim])

    rhs = tau + damping + q_contact + dT_dq - dV_dq - Mdot @ qdot
    qddot = jnp.linalg.solve(M + 1e-9 * jnp.eye(nq), rhs)
    qdot_next = jnp.clip(qdot + dt * qddot, -_QVEL_MAX, _QVEL_MAX)
    q_next = q + dt * qdot_next
    return q_next, qdot_next


HOPPER_MODEL = PlanarModel(
    # torso, thigh, leg, foot — the MuJoCo hopper tree (hopper.xml)
    parents=(-1, 0, 1, 2),
    lengths=(0.4, 0.45, 0.5, 0.39),
    masses=(3.7, 4.0, 2.8, 5.3),  # ~ capsule-density masses, rounded
    rest_angles=(0.0, 0.0, 0.0, jnp.pi / 2),  # foot sticks out forward
    com_fracs=(0.5, 0.5, 0.5, 0.17),  # foot COM near the ankle
    # heel + toe, plus body points (torso top via root frac, hip, knee,
    # ankle) so a collapsing body rests ON the ground instead of passing
    # through it (MuJoCo collides every geom with the floor)
    contacts=((3, -0.33), (3, 0.67), (0, 1.0), (0, 0.0), (1, 1.0), (2, 1.0)),
    gears=(60.0, 60.0, 40.0),
    joint_ranges=((-1.2, 1.2), (-1.5, 1.5), (-0.8, 0.8)),
)

WALKER_MODEL = PlanarModel(
    # torso, r-thigh, r-leg, r-foot, l-thigh, l-leg, l-foot (walker2d.xml)
    parents=(-1, 0, 1, 2, 0, 4, 5),
    lengths=(0.4, 0.45, 0.5, 0.2, 0.45, 0.5, 0.2),
    masses=(3.7, 4.0, 2.8, 3.2, 4.0, 2.8, 3.2),
    rest_angles=(0.0, 0.0, 0.0, jnp.pi / 2, 0.0, 0.0, jnp.pi / 2),
    com_fracs=(0.5, 0.5, 0.5, 0.17, 0.5, 0.5, 0.17),
    contacts=(
        (3, -0.33), (3, 0.67), (6, -0.33), (6, 0.67),
        (0, 1.0), (0, 0.0), (1, 1.0), (2, 1.0), (4, 1.0), (5, 1.0),
    ),
    gears=(60.0, 60.0, 40.0, 60.0, 60.0, 40.0),
    joint_ranges=(
        (-1.2, 1.2), (-1.5, 1.5), (-0.8, 0.8),
        (-1.2, 1.2), (-1.5, 1.5), (-0.8, 0.8),
    ),
)


class _PlanarLocomotionEnv(EnvBase):
    """Shared env surface (reference mujoco/base.py MujocoEnv)."""

    MODEL: PlanarModel
    FRAME_SKIP = 5  # reference FRAME_SKIP
    DT = 0.002  # per-substep integrator dt
    SKIP_QPOS = 1  # x excluded from obs (reference SKIP_QPOS)
    HEALTHY_REWARD = 1.0
    CTRL_COST_WEIGHT = 1e-3
    INIT_Z = 1.25
    RESET_NOISE = 5e-3

    def __init__(self, max_episode_steps: int = 1000):
        self.max_episode_steps = max_episode_steps

    # -- specs ---------------------------------------------------------------

    @property
    def nq(self) -> int:
        return self.MODEL.nq

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            observation=Unbounded(shape=(2 * self.nq - self.SKIP_QPOS,))
        )

    @property
    def action_spec(self):
        n_act = self.nq - 3
        return Bounded(shape=(n_act,), low=-1.0, high=1.0)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            qpos=Unbounded(shape=(self.nq,)),
            qvel=Unbounded(shape=(self.nq,)),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    # -- reference-structure hooks ------------------------------------------

    def _is_healthy(self, qpos):
        raise NotImplementedError

    def _obs(self, qpos, qvel) -> ArrayDict:
        return ArrayDict(
            observation=jnp.concatenate(
                [qpos[self.SKIP_QPOS:], jnp.clip(qvel, -10.0, 10.0)]
            )
        )

    # -- env protocol --------------------------------------------------------

    def _init_qpos(self):
        q = jnp.zeros(self.nq)
        return q.at[1].set(self.INIT_Z)

    def _reset(self, key):
        kq, kv = jax.random.split(key)
        noise = self.RESET_NOISE
        qpos = self._init_qpos() + jax.random.uniform(
            kq, (self.nq,), minval=-noise, maxval=noise
        )
        qvel = jax.random.uniform(kv, (self.nq,), minval=-noise, maxval=noise)
        state = ArrayDict(
            qpos=qpos, qvel=qvel, step_count=jnp.asarray(0, jnp.int32)
        )
        return state, self._obs(qpos, qvel)

    def _step(self, state, action, key):
        qpos, qvel = state["qpos"], state["qvel"]
        a = jnp.clip(action, -1.0, 1.0)
        tau = a * jnp.asarray(self.MODEL.gears)

        def sub(carry, _):
            q, qd = carry
            q, qd = planar_dynamics_step(self.MODEL, q, qd, tau, self.DT)
            return (q, qd), None

        (q2, qd2), _ = jax.lax.scan(
            sub, (qpos, qvel), None, length=self.FRAME_SKIP
        )

        dt_total = self.DT * self.FRAME_SKIP
        forward_vel = (q2[0] - qpos[0]) / dt_total
        ctrl_cost = self.CTRL_COST_WEIGHT * jnp.sum(a**2)
        healthy = self._is_healthy(q2)
        reward = (
            forward_vel + self.HEALTHY_REWARD * healthy.astype(jnp.float32)
            - ctrl_cost
        )

        count = state["step_count"] + 1
        new_state = ArrayDict(qpos=q2, qvel=qd2, step_count=count)
        terminated = ~healthy
        truncated = count >= self.max_episode_steps
        return new_state, self._obs(q2, qd2), reward, terminated, truncated


class HopperEnv(_PlanarLocomotionEnv):
    """Single-legged hopping (reference hopper.py:14): 4-link chain,
    3 actuators, obs 11 = qpos[1:] (5) + qvel (6)."""

    MODEL = HOPPER_MODEL
    HEALTHY_Z_MIN = 0.7
    HEALTHY_ANGLE_MAX = 0.2

    def _is_healthy(self, qpos):
        return (qpos[1] >= self.HEALTHY_Z_MIN) & (
            jnp.abs(qpos[2]) <= self.HEALTHY_ANGLE_MAX
        )


class Walker2dEnv(_PlanarLocomotionEnv):
    """Two-legged walking (reference walker.py:14): 7-link tree,
    6 actuators, obs 17 = qpos[1:] (8) + qvel (9)."""

    MODEL = WALKER_MODEL
    HEALTHY_Z_LOW = 0.8
    HEALTHY_Z_HIGH = 2.0
    HEALTHY_ANGLE_MAX = 1.0

    def _is_healthy(self, qpos):
        z, angle = qpos[1], qpos[2]
        return (
            (z >= self.HEALTHY_Z_LOW)
            & (z <= self.HEALTHY_Z_HIGH)
            & (jnp.abs(angle) <= self.HEALTHY_ANGLE_MAX)
        )
