"""Vectorized multi-agent navigation, pure JAX (the VMAS-style sim).

Model of the reference's VMAS integration (reference: torchrl/envs/libs/
vmas.py:628 wraps the external vectorized multi-agent simulator; the
"navigation" scenario is the MAPPO/IPPO benchmark in
sota-implementations/multiagent/). Here the sim itself is native JAX so
multi-agent collection runs inside the fused program on device — batching
via ``jax.vmap`` (VmapEnv) replaces VMAS's internal torch batch dim.

N holonomic agents on a [-1, 1]² arena each navigate to a private goal;
actions are per-agent velocity commands; team reward is the sum of per-agent
distance decrease (dense, cooperative), with termination once every agent is
on its goal. Per-agent observations follow the framework's multi-agent
layout (("agents", "observation") with the agent axis leading the feature
dims) so MultiAgentMLP / MAPPO consume them directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Composite, Unbounded
from ..base import EnvBase

__all__ = ["NavigationEnv"]


class NavigationEnv(EnvBase):
    def __init__(
        self,
        n_agents: int = 3,
        max_episode_steps: int = 100,
        dt: float = 0.1,
        goal_radius: float = 0.1,
    ):
        self.n_agents = n_agents
        self.max_episode_steps = max_episode_steps
        self.dt = dt
        self.goal_radius = goal_radius

    @property
    def observation_spec(self) -> Composite:
        n = self.n_agents
        feat = 4 + 2 * (n - 1)  # own pos, goal delta, others' relative pos
        return Composite(
            agents=Composite(observation=Unbounded(shape=(n, feat))),
            state=Unbounded(shape=(4 * n,)),  # central critic input (MAPPO)
        )

    @property
    def action_spec(self):
        return Bounded(shape=(self.n_agents, 2), low=-1.0, high=1.0)

    @property
    def state_spec(self) -> Composite:
        n = self.n_agents
        return Composite(
            pos=Unbounded(shape=(n, 2)),
            goal=Unbounded(shape=(n, 2)),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _obs(self, pos, goal):
        import numpy as np

        n = self.n_agents
        rel = pos[None, :, :] - pos[:, None, :]  # [n, n, 2]
        # drop self-row per agent: gather the n-1 others (static indices —
        # boolean masks are not jit-traceable gathers)
        idx = np.asarray(
            [[j for j in range(n) if j != i] for i in range(n)], np.int32
        )
        others = jnp.take_along_axis(rel, idx[..., None], axis=1).reshape(n, -1)
        feats = jnp.concatenate([pos, goal - pos, others], axis=-1)
        state = jnp.concatenate([pos.reshape(-1), (goal - pos).reshape(-1)])
        return ArrayDict(agents=ArrayDict(observation=feats), state=state)

    def _reset(self, key):
        kp, kg = jax.random.split(key)
        pos = jax.random.uniform(kp, (self.n_agents, 2), minval=-1.0, maxval=1.0)
        goal = jax.random.uniform(kg, (self.n_agents, 2), minval=-1.0, maxval=1.0)
        state = ArrayDict(pos=pos, goal=goal, step_count=jnp.asarray(0, jnp.int32))
        return state, self._obs(pos, goal)

    def _step(self, state, action, key):
        pos, goal = state["pos"], state["goal"]
        vel = jnp.clip(action, -1.0, 1.0)
        new_pos = jnp.clip(pos + self.dt * vel, -1.0, 1.0)
        d_old = jnp.linalg.norm(goal - pos, axis=-1)
        d_new = jnp.linalg.norm(goal - new_pos, axis=-1)
        reward = jnp.sum(d_old - d_new)
        on_goal = d_new < self.goal_radius
        terminated = jnp.all(on_goal)
        count = state["step_count"] + 1
        truncated = count >= self.max_episode_steps
        new_state = ArrayDict(pos=new_pos, goal=goal, step_count=count)
        return (
            new_state,
            self._obs(new_pos, goal),
            reward,
            terminated,
            truncated,
        )
