"""Tic-tac-toe, pure JAX (reference: torchrl/envs/custom/tictactoeenv.py).

Turn-based two-player board game in one env: "turn" says whose move it is,
"action_mask" lists the empty cells (consumed by the ActionMask transform /
masked exploration). Rewards are from player 0's perspective (+1 player-0
win, -1 player-1 win, 0 draw) — the zero-sum scalar-reward convention.

``single_player=True`` makes the env play a uniform-random legal move for
player 1 after every player-0 move (the reference's opponent mode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ...data.specs import Binary
from ..base import EnvBase

__all__ = ["TicTacToeEnv"]

# plain nested list: a module-level jnp.asarray would initialize the JAX
# backend at import time (breaks the driver's platform forcing)
_LINES = [
    [0, 1, 2],
    [3, 4, 5],
    [6, 7, 8],
    [0, 3, 6],
    [1, 4, 7],
    [2, 5, 8],
    [0, 4, 8],
    [2, 4, 6],
]


def _winner(board):
    """+1 / -1 if that player completed a line, else 0."""
    sums = board[jnp.asarray(_LINES)].sum(axis=-1)
    return jnp.where(
        jnp.any(sums == 3), 1, jnp.where(jnp.any(sums == -3), -1, 0)
    ).astype(jnp.int32)


class TicTacToeEnv(EnvBase):
    def __init__(self, single_player: bool = False):
        self.single_player = single_player

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            board=Bounded(shape=(9,), low=-1, high=1, dtype=jnp.int32),
            turn=Bounded(shape=(), low=0, high=1, dtype=jnp.int32),
            action_mask=Binary(shape=(9,)),
        )

    @property
    def action_spec(self):
        return Categorical(n=9)

    @property
    def state_spec(self) -> Composite:
        return Composite(
            board=Unbounded(shape=(9,), dtype=jnp.int32),
            turn=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _obs(self, board, turn):
        return ArrayDict(board=board, turn=turn, action_mask=board == 0)

    def _reset(self, key):
        board = jnp.zeros((9,), jnp.int32)
        turn = jnp.asarray(0, jnp.int32)
        return ArrayDict(board=board, turn=turn), self._obs(board, turn)

    def _place(self, board, cell, mark):
        """Place if the cell is empty; returns (board, was_legal)."""
        legal = board[cell] == 0
        return board.at[cell].set(jnp.where(legal, mark, board[cell])), legal

    def _step(self, state, action, key):
        board, turn = state["board"], state["turn"]
        mark = jnp.where(turn == 0, 1, -1).astype(jnp.int32)
        board, legal = self._place(board, action, mark)
        win = _winner(board)
        full = jnp.all(board != 0)
        over = (win != 0) | full | ~legal
        # illegal move = forfeit: the mover loses
        forfeit = jnp.where(turn == 0, -1, 1) * (~legal).astype(jnp.int32)
        outcome = jnp.where(legal, win, forfeit)
        next_turn = (turn + 1) % 2

        if self.single_player:
            # env answers with a random legal move for player 1
            def opp(args):
                board, key = args
                mask = board == 0
                logits = jnp.where(mask, 0.0, -jnp.inf)
                cell = jax.random.categorical(key, logits)
                return board.at[cell].set(-1)

            board = jax.lax.cond(
                over, lambda a: a[0], opp, (board, key)
            )
            win2 = _winner(board)
            over = over | (win2 != 0) | jnp.all(board != 0)
            outcome = jnp.where(outcome != 0, outcome, win2)
            next_turn = jnp.asarray(0, jnp.int32)

        reward = outcome.astype(jnp.float32)
        new_state = ArrayDict(board=board, turn=next_turn)
        return (
            new_state,
            self._obs(board, next_turn),
            reward,
            over,
            jnp.asarray(False),
        )
