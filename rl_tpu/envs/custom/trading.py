"""Single-asset trading env, pure JAX (reference: torchrl/envs/custom/trading.py).

Price follows a geometric random walk; the agent holds a target position in
{-1 (short), 0 (flat), +1 (long)} and earns the position-weighted log-return
minus transaction costs on position changes. Observation is the last
``window`` log-returns plus the current position — enough for momentum /
mean-reversion policies to be learnable (a drift regime makes "go long"
strictly better than random, giving tests a closed-form learning signal).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["TradingEnv"]


class TradingEnv(EnvBase):
    def __init__(
        self,
        window: int = 8,
        max_episode_steps: int = 200,
        mu: float = 0.0005,
        sigma: float = 0.01,
        cost: float = 0.0001,
    ):
        self.window = window
        self.max_episode_steps = max_episode_steps
        self.mu = mu
        self.sigma = sigma
        self.cost = cost

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            returns=Unbounded(shape=(self.window,)),
            position=Bounded(shape=(), low=-1.0, high=1.0),
            pnl=Unbounded(shape=()),
        )

    @property
    def action_spec(self):
        return Categorical(n=3)  # 0=short, 1=flat, 2=long

    @property
    def state_spec(self) -> Composite:
        return Composite(
            returns=Unbounded(shape=(self.window,)),
            position=Unbounded(shape=()),
            pnl=Unbounded(shape=()),
            step_count=Unbounded(shape=(), dtype=jnp.int32),
        )

    def _obs(self, state):
        return ArrayDict(
            returns=state["returns"], position=state["position"], pnl=state["pnl"]
        )

    def _reset(self, key):
        rets = self.mu + self.sigma * jax.random.normal(key, (self.window,))
        state = ArrayDict(
            returns=rets,
            position=jnp.asarray(0.0),
            pnl=jnp.asarray(0.0),
            step_count=jnp.asarray(0, jnp.int32),
        )
        return state, self._obs(state)

    def _step(self, state, action, key):
        target = action.astype(jnp.float32) - 1.0  # {-1, 0, 1}
        ret = self.mu + self.sigma * jax.random.normal(key, ())
        trade_cost = self.cost * jnp.abs(target - state["position"])
        reward = target * ret - trade_cost
        rets = jnp.concatenate([state["returns"][1:], ret[None]])
        count = state["step_count"] + 1
        new_state = ArrayDict(
            returns=rets,
            position=target,
            pnl=state["pnl"] + reward,
            step_count=count,
        )
        return (
            new_state,
            self._obs(new_state),
            reward,
            jnp.asarray(False),
            count >= self.max_episode_steps,
        )
