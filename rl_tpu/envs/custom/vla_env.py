"""ToyVLAEnv: a synthetic env speaking the canonical VLA schema
(reference: torchrl/envs/custom/vla.py:24 — random camera image +
proprioceptive state echoing the previous action, constant language
instruction; echo mode for plumbing smoke tests, tracking mode with a
per-episode target action and consecutive-success termination).

Pure-JAX redesign: the whole env is jit/vmap/scan-native (images are HWC
uint8, the framework's VLA layout), so TinyVLA + MultiStepActorWrapper +
collectors run as one fused program against it. The language instruction
is a hashed int32 id in the observation (strings cannot cross into XLA);
the string itself stays on the env object for host-side consumers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Binary, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase

__all__ = ["ToyVLAEnv"]


class ToyVLAEnv(EnvBase):
    """Echo mode (``success_steps=None``): reward = −‖action‖, never
    terminates — the VLA plumbing smoke test. Tracking mode
    (``success_steps=k``): a target action sampled at reset sits in
    ``state[action_dim:2*action_dim]``; reward = −‖action − target‖; a
    ``success`` flag turns True (and the episode ends) after ``k``
    consecutive steps within ``success_tol`` (∞-norm). An oracle reading
    the target succeeds surely; uniform random almost never — success
    rate is a real learning signal.
    """

    def __init__(
        self,
        action_dim: int = 4,
        state_dim: int = 6,
        image_shape: tuple[int, int, int] = (16, 16, 3),
        instruction: str = "push the T-shaped block onto the target",
        success_steps: int | None = None,
        success_tol: float = 0.25,
        text_vocab: int = 256,
    ):
        need = 2 * action_dim if success_steps is not None else action_dim
        if state_dim < need:
            raise ValueError(
                f"state_dim ({state_dim}) must be >= {need} for this mode"
            )
        self.action_dim = action_dim
        self.state_dim = state_dim
        self.image_shape = tuple(image_shape)  # HWC (framework VLA layout)
        self.instruction = instruction
        self.success_steps = success_steps
        self.success_tol = success_tol
        self.text_vocab = text_vocab
        from ...modules.vla import hash_instruction

        self._instr_id = hash_instruction(instruction, vocab=text_vocab)[0]

    @property
    def observation_spec(self) -> Composite:
        spec = Composite(
            observation=Composite(
                image=Bounded(
                    shape=self.image_shape, low=0, high=255, dtype=jnp.uint8
                ),
                state=Unbounded(shape=(self.state_dim,)),
            ),
            language_instruction=Categorical(n=self.text_vocab, dtype=jnp.int32),
        )
        if self.success_steps is not None:
            spec = spec.set("success", Binary(shape=()))
        return spec

    @property
    def action_spec(self):
        return Bounded(shape=(self.action_dim,), low=-1.0, high=1.0)

    @property
    def state_spec(self) -> Composite:
        spec = Composite(
            state_vec=Unbounded(shape=(self.state_dim,)),
        )
        if self.success_steps is not None:
            spec = spec.set(
                "hits", Unbounded(shape=(), dtype=jnp.int32)
            ).set("target", Bounded(shape=(self.action_dim,), low=-1.0, high=1.0))
        return spec

    def _obs(self, key, state_vec, success=None):
        image = jax.random.randint(
            key, self.image_shape, 0, 256, jnp.int32
        ).astype(jnp.uint8)
        td = ArrayDict(
            observation=ArrayDict(image=image, state=state_vec),
            language_instruction=self._instr_id,
        )
        if self.success_steps is not None:
            td = td.set(
                "success",
                jnp.asarray(False) if success is None else success,
            )
        return td

    def _reset(self, key):
        k_img, k_tgt = jax.random.split(key)
        state_vec = jnp.zeros((self.state_dim,))
        st = ArrayDict()
        if self.success_steps is not None:
            st = st.set("hits", jnp.asarray(0, jnp.int32))
            target = jax.random.uniform(
                k_tgt, (self.action_dim,), minval=-1.0, maxval=1.0
            )
            state_vec = jax.lax.dynamic_update_slice(
                state_vec, target, (self.action_dim,)
            )
            st = st.set("target", target)
        st = st.set("state_vec", state_vec)
        return st, self._obs(k_img, state_vec)

    def _step(self, state, action, key):
        a = jnp.clip(action, -1.0, 1.0)
        # the state echoes the executed action (chunk cadence observable)
        state_vec = jax.lax.dynamic_update_slice(
            state["state_vec"], a, (0,)
        )
        if self.success_steps is None:
            reward = -jnp.linalg.norm(a)
            new_state = state.set("state_vec", state_vec)
            return (
                new_state,
                self._obs(key, state_vec),
                reward,
                jnp.asarray(False),
                jnp.asarray(False),
            )
        target = state["target"]
        err = jnp.max(jnp.abs(a - target))
        reward = -jnp.linalg.norm(a - target)
        hits = jnp.where(err <= self.success_tol, state["hits"] + 1, 0)
        success = hits >= self.success_steps
        new_state = state.replace(state_vec=state_vec, hits=hits.astype(jnp.int32))
        return (
            new_state,
            self._obs(key, state_vec, success=success),
            reward,
            success,
            jnp.asarray(False),
        )
