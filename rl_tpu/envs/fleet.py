"""On-device env fleets: the Anakin collection substrate.

The classic/custom envs are pure-array state machines, so a "parallel env"
is just ``VmapEnv`` — N identical envs stepped as one XLA program. This
module is the one-call factory that turns an env *name* into a
fleet ready for the fused Anakin trainer (trainers/anakin.py):

    env = make_fleet("cartpole", num_envs=4096)

The fleet is ``TransformedEnv(VmapEnv(base, num_envs), RewardSum())``:
``RewardSum`` accumulates per-env episode returns under
``("next", "episode_reward")`` — the key the trainers' episode-return
metrics (and Anakin's in-program ``DeviceMetrics``) read at done edges.

Adding a new array env to the fleet = registering its constructor here
(see ``register_fleet_env``); the only contract is the ``EnvBase`` one —
pure ``_reset``/``_step``, fixed shapes, ``lax`` control flow — which
``check_env_specs`` + ``check_vmap_autoreset`` (envs/utils.py) validate.
"""

from __future__ import annotations

from typing import Callable

from .base import EnvBase, VmapEnv
from .transforms.base import TransformedEnv
from .transforms.common import RewardSum

__all__ = ["make_fleet", "register_fleet_env", "fleet_env_names"]


def _registry() -> dict[str, Callable[..., EnvBase]]:
    # built lazily so importing rl_tpu.envs.fleet never pays for env modules
    # the caller doesn't use
    from .classic.acrobot import AcrobotEnv
    from .classic.cartpole import CartPoleEnv
    from .classic.mountain_car import MountainCarContinuousEnv, MountainCarEnv
    from .classic.pendulum import PendulumEnv
    from .custom import (
        ChessEnv,
        HopperEnv,
        NavigationEnv,
        TicTacToeEnv,
        ToyVLAEnv,
        TradingEnv,
        Walker2dEnv,
    )

    return {
        "acrobot": AcrobotEnv,
        "cartpole": CartPoleEnv,
        "chess": ChessEnv,
        "hopper": HopperEnv,
        "mountain_car": MountainCarEnv,
        "mountain_car_continuous": MountainCarContinuousEnv,
        "navigation": NavigationEnv,
        "pendulum": PendulumEnv,
        "tictactoe": TicTacToeEnv,
        "toy_vla": ToyVLAEnv,
        "trading": TradingEnv,
        "walker2d": Walker2dEnv,
    }


_EXTRA: dict[str, Callable[..., EnvBase]] = {}


def register_fleet_env(name: str, ctor: Callable[..., EnvBase]) -> None:
    """Register a constructor for :func:`make_fleet` (``ctor(**kwargs)`` must
    return a scalar, pure-array :class:`EnvBase`)."""
    _EXTRA[name] = ctor


def fleet_env_names() -> tuple[str, ...]:
    return tuple(sorted({**_registry(), **_EXTRA}))


def make_fleet(
    env: str | EnvBase,
    num_envs: int,
    *,
    episode_return: bool = True,
    **env_kwargs,
) -> TransformedEnv | VmapEnv:
    """Build an on-device fleet of ``num_envs`` identical array envs.

    ``env`` is a registry name (see :func:`fleet_env_names`) or a scalar
    ``EnvBase`` instance (then ``env_kwargs`` must be empty). With
    ``episode_return=True`` (default) the fleet is wrapped in ``RewardSum``
    so done-edge episode returns are available to metrics.
    """
    if isinstance(env, EnvBase):
        if env_kwargs:
            raise TypeError("env_kwargs only apply when env is a registry name")
        base = env
    else:
        reg = {**_registry(), **_EXTRA}
        if env not in reg:
            raise KeyError(
                f"unknown fleet env {env!r}; known: {', '.join(sorted(reg))}"
            )
        base = reg[env](**env_kwargs)
    if base.batch_shape != ():
        raise ValueError("make_fleet wraps scalar (unbatched) envs")
    fleet = VmapEnv(base, num_envs)
    if episode_return:
        return TransformedEnv(fleet, RewardSum())
    return fleet
