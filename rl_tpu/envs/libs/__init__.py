from .gym import GymEnv, GymWrapper, spec_from_gym_space

__all__ = ["GymWrapper", "GymEnv", "spec_from_gym_space"]
