from .gym import GymEnv, GymWrapper, spec_from_gym_space

__all__ = ["GymWrapper", "GymEnv", "spec_from_gym_space", "PettingZooEnv", "PettingZooWrapper"]


def __getattr__(name):
    # pettingzoo import is optional; load the bridge lazily
    if name in ("PettingZooEnv", "PettingZooWrapper"):
        from . import pettingzoo as _pz

        return getattr(_pz, name)
    raise AttributeError(name)
