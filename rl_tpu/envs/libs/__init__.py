from .gym import GymEnv, GymWrapper, spec_from_gym_space

__all__ = [
    "GymWrapper",
    "GymEnv",
    "spec_from_gym_space",
    "PettingZooEnv",
    "PettingZooWrapper",
    "BraxEnv",
    "JumanjiEnv",
    "spec_from_jumanji",
    "DMControlEnv",
    "DMControlWrapper",
    "spec_from_dm_spec",
]


def __getattr__(name):
    # third-party imports are optional; load each bridge lazily
    if name in ("PettingZooEnv", "PettingZooWrapper"):
        from . import pettingzoo as _pz

        return getattr(_pz, name)
    if name == "BraxEnv":
        from .brax import BraxEnv

        return BraxEnv
    if name in ("JumanjiEnv", "spec_from_jumanji"):
        from . import jumanji as _jm

        return getattr(_jm, name)
    if name in ("DMControlEnv", "DMControlWrapper", "spec_from_dm_spec"):
        from . import dm_control as _dmc

        return getattr(_dmc, name)
    raise AttributeError(name)
