"""Shared helpers for carrying third-party pytree env states inside
EnvState ArrayDicts (used by the brax and jumanji bridges)."""

from __future__ import annotations

import jax

from ...data import ArrayDict

__all__ = ["flatten_state", "unflatten_state"]


def flatten_state(state) -> ArrayDict:
    """Any pytree (brax.State, jumanji state dataclass) -> flat ArrayDict of
    its leaves, keyed leaf_0..leaf_{n-1} in tree-flatten order."""
    leaves, _ = jax.tree.flatten(state)
    return ArrayDict({f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})


def unflatten_state(struct, td: ArrayDict):
    """Rebuild the original pytree from stored leaves; ``struct`` is an
    eval_shape template with the same treedef."""
    _, treedef = jax.tree.flatten(struct)
    n = len(td.keys())
    return jax.tree.unflatten(treedef, [td[f"leaf_{i}"] for i in range(n)])
