"""Brax bridge: physics env as a pure-functional EnvBase.

Redesign of the reference's BraxWrapper (reference: torchrl/envs/libs/
brax.py:70 — wraps brax's functional API back into the stateful torch env
protocol, shuttling tensors across a device boundary). Here no inversion is
needed: brax is already (reset, step) over pytree states in JAX, so the
bridge is a thin relabeling that carries ``brax.State`` inside the EnvState
pytree — the whole env runs INSIDE the fused program (collectors scan it,
vmap batches it, shard_map shards it).

Import-gated: brax is optional; construction raises ImportError without it.

STATUS — EXPERIMENTAL: brax is not in this image, so this bridge has
never executed against the real library. It IS contract-tested against
an in-repo fake implementing exactly the API surface it touches
(tests/fakes/, tests/test_brax_jumanji.py) — spec extraction, step
conversion, and termination/truncation mapping all run; real-library
behavior may still differ in untested corners.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Composite, Unbounded
from ..base import EnvBase
from ._pytree import flatten_state, unflatten_state

__all__ = ["BraxEnv"]


class BraxEnv(EnvBase):
    """``BraxEnv("ant")`` — any `brax.envs` registry name.

    Episode ends map: brax ``done`` is termination. Pass
    ``episode_length=N`` to get time-limit truncation: the env is then
    built via ``brax.envs.create`` with the EpisodeWrapper (which writes
    info["truncation"]) and WITHOUT brax's auto-reset — EnvBase owns
    autoreset (reference BraxWrapper does the same inversion). Without
    ``episode_length`` the raw env never truncates.
    """

    def __init__(
        self,
        env_name: str,
        backend: str | None = None,
        episode_length: int | None = None,
        **kwargs,
    ):
        try:
            from brax import envs as brax_envs
        except ImportError as e:  # pragma: no cover - optional dep
            raise ImportError(
                "BraxEnv requires the 'brax' package (not in this image)"
            ) from e
        if backend is not None:
            kwargs["backend"] = backend
        if episode_length is not None:
            self._env = brax_envs.create(
                env_name,
                episode_length=episode_length,
                auto_reset=False,
                **kwargs,
            )
        else:
            # raw env: no brax-side wrappers at all
            self._env = brax_envs.get_environment(env_name, **kwargs)
        self.env_name = env_name

    # -- specs ----------------------------------------------------------------

    @property
    def observation_spec(self) -> Composite:
        return Composite(
            observation=Unbounded(shape=(self._env.observation_size,))
        )

    @property
    def action_spec(self):
        n = self._env.action_size
        return Bounded(shape=(n,), low=-1.0, high=1.0)

    # -- hooks ----------------------------------------------------------------

    def _reset(self, key: jax.Array):
        bstate = self._env.reset(key)
        state = ArrayDict(brax=flatten_state(bstate))
        return state, ArrayDict(observation=bstate.obs)

    def _step(self, state: ArrayDict, action: Any, key: jax.Array):
        bstate = unflatten_state(self._raw_state_struct(), state["brax"])
        bstate = self._env.step(bstate, jnp.asarray(action))
        term = bstate.done.astype(bool)
        trunc = jnp.asarray(
            bstate.info.get("truncation", jnp.zeros_like(bstate.done)), bool
        )
        # brax folds truncation into done; termination = done and not trunc
        term = jnp.logical_and(term, jnp.logical_not(trunc))
        return (
            ArrayDict(brax=flatten_state(bstate)),
            ArrayDict(observation=bstate.obs),
            bstate.reward.astype(jnp.float32),
            term,
            trunc,
        )

    def _raw_state_struct(self):
        if not hasattr(self, "_struct"):
            self._struct = jax.eval_shape(self._env.reset, jax.random.key(0))
        return self._struct
