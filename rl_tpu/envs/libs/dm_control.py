"""dm_control bridge: DeepMind Control Suite behind the host-env protocol.

Redesign of the reference's dm_control wrapper (reference:
torchrl/envs/libs/dm_control.py — ``DMControlWrapper``:168 /
``DMControlEnv``:390 with ``_dmcontrol_to_torchrl_spec_transform``:57 spec
conversion and pixel rendering via ``render_kwargs``). The reference builds
a TensorDict env; here dm_control sims are HOST envs (numpy in/out, not
jit-traceable) that plug into :class:`rl_tpu.collectors.HostCollector` /
``ThreadedEnvPool`` exactly like the gym bridge.

dm_env TimeStep semantics are mapped to the framework's flags (reference
dm_control.py:362: only discount≈1 at a last step is a time limit):
- ``ts.last() and ts.discount ≈ 1``  -> truncated  (time limit)
- ``ts.last()`` otherwise (any discount < 1, incl. 0) -> terminated

Pixels: ``from_pixels=True`` renders ``physics.render(**render_kwargs)``
into a "pixels" observation (the reference's pixels path).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ...data import Bounded, Composite, Unbounded

__all__ = ["DMControlWrapper", "DMControlEnv", "spec_from_dm_spec"]


def spec_from_dm_spec(dm_spec) -> Any:
    """dm_env specs -> rl_tpu Spec (reference dm_control.py:57).

    ``BoundedArray`` -> Bounded; plain ``Array`` -> Unbounded. dm_control
    observation scalars (shape ()) keep their scalar shape — VmapEnv-style
    batching happens at the pool level.
    """
    kind = type(dm_spec).__name__
    dtype = np.dtype(dm_spec.dtype)
    if dtype == np.float64:
        dtype = np.dtype(np.float32)  # device-friendly; sim stays f64 on host
    if "BoundedArray" in kind:
        return Bounded(
            shape=tuple(dm_spec.shape),
            low=np.broadcast_to(dm_spec.minimum, dm_spec.shape).astype(dtype),
            high=np.broadcast_to(dm_spec.maximum, dm_spec.shape).astype(dtype),
            dtype=dtype,
        )
    return Unbounded(shape=tuple(dm_spec.shape), dtype=dtype)


class DMControlWrapper:
    """Wrap a constructed ``dm_env.Environment`` into the host-env protocol:

    - ``reset(seed) -> obs_dict``
    - ``step(action) -> (obs_dict, reward, terminated, truncated)``

    Observation keys keep dm_control's own names (position, velocity, …),
    mirroring the reference's key passthrough.
    """

    def __init__(
        self,
        env: Any,
        from_pixels: bool = False,
        render_kwargs: dict | None = None,
    ):
        self.env = env
        self.from_pixels = from_pixels
        self.render_kwargs = {"height": 84, "width": 84, "camera_id": 0}
        if render_kwargs:
            self.render_kwargs.update(render_kwargs)
        obs_specs = {
            k: spec_from_dm_spec(v) for k, v in env.observation_spec().items()
        }
        if from_pixels:
            h, w = self.render_kwargs["height"], self.render_kwargs["width"]
            obs_specs["pixels"] = Bounded(
                shape=(h, w, 3), low=0, high=255, dtype=np.uint8
            )
        self._obs_spec = Composite(obs_specs)
        self._action_spec = spec_from_dm_spec(env.action_spec())

    # -- specs ----------------------------------------------------------------

    @property
    def observation_spec(self) -> Composite:
        return self._obs_spec

    @property
    def action_spec(self):
        return self._action_spec

    @property
    def batch_shape(self) -> tuple:
        return ()

    # -- host protocol --------------------------------------------------------

    def _obs_dict(self, ts) -> dict:
        out = {}
        for k, v in ts.observation.items():
            a = np.asarray(v)
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            out[k] = a
        if self.from_pixels:
            out["pixels"] = self.env.physics.render(**self.render_kwargs)
        return out

    def reset(self, seed: int | None = None) -> dict:
        if seed is not None:
            # dm_control fixes the seed at task construction; re-seed the
            # task's RandomState in place (reference re-creates the env)
            task = getattr(self.env, "task", None)
            if task is not None and hasattr(task, "_random"):
                task._random = np.random.RandomState(seed)
        return self._obs_dict(self.env.reset())

    def step(self, action) -> tuple[dict, float, bool, bool]:
        a = np.asarray(action, np.float64)
        ts = self.env.step(a)
        reward = float(ts.reward if ts.reward is not None else 0.0)
        last = bool(ts.last())
        # reference dm_control.py:362: only discount≈1 at a last step is a
        # time-limit truncation; any other discount (incl. 0<d<1) terminates
        truncated = last and bool(np.isclose(float(ts.discount or 0.0), 1.0))
        terminated = last and not truncated
        return self._obs_dict(ts), reward, terminated, truncated

    def close(self) -> None:
        close = getattr(self.env, "close", None)
        if close is not None:
            close()


class DMControlEnv(DMControlWrapper):
    """Build from (domain, task) names (reference DMControlEnv:390).

    >>> env = DMControlEnv("cheetah", "run")
    >>> obs = env.reset(seed=0)
    >>> obs2, r, term, trunc = env.step(env.action_spec.rand(key))
    """

    def __init__(
        self,
        domain: str,
        task: str,
        from_pixels: bool = False,
        render_kwargs: dict | None = None,
        seed: int | None = None,
        **task_kwargs,
    ):
        from dm_control import suite

        kwargs = dict(task_kwargs)
        if seed is not None:
            kwargs["random"] = seed
        env = suite.load(domain, task, task_kwargs=kwargs or None)
        super().__init__(env, from_pixels=from_pixels, render_kwargs=render_kwargs)
        self.domain, self.task = domain, task

    @staticmethod
    def available_envs() -> list[tuple[str, str]]:
        from dm_control import suite

        return sorted(suite.BENCHMARKING)
