"""Gymnasium bridge: host envs behind the framework's spec/data contract.

Redesign of the reference's gym wrapper (reference: torchrl/envs/libs/gym.py
— ``GymWrapper``:972/``GymEnv``:1805 with ``set_gym_backend`` version
dispatch :138; spec conversion helpers; ``GymLikeEnv`` protocol
gym_like.py:153). The version-dispatch machinery collapses: only gymnasium's
five-tuple API is supported (the reference's `implement_for` handles a
decade of gym drift we don't inherit).

These are HOST envs: numpy in/out, not jit-traceable. They plug into
:class:`rl_tpu.collectors.HostCollector` (threads + jitted policy), the
Sebulba-style split for sims that cannot live inside XLA.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ...data import (
    Binary,
    Bounded,
    Categorical,
    Composite,
    MultiCategorical,
    Unbounded,
)

__all__ = ["GymWrapper", "GymEnv", "spec_from_gym_space"]


def spec_from_gym_space(space) -> Any:
    """gymnasium.Space -> rl_tpu Spec (reference gym.py spec converters)."""
    import gymnasium.spaces as S

    if isinstance(space, S.Box):
        return Bounded(shape=space.shape, low=space.low, high=space.high, dtype=space.dtype)
    if isinstance(space, S.Discrete):
        # start offset is applied in GymWrapper.step (actions stay [0, n))
        return Categorical(n=int(space.n))
    if isinstance(space, S.MultiDiscrete):
        return MultiCategorical(nvec=tuple(int(n) for n in space.nvec))
    if isinstance(space, S.MultiBinary):
        return Binary(shape=(int(space.n),) if np.isscalar(space.n) else tuple(space.n), dtype=np.int8)
    if isinstance(space, S.Dict):
        return Composite({k: spec_from_gym_space(v) for k, v in space.spaces.items()})
    if isinstance(space, S.Tuple):
        return Composite({str(i): spec_from_gym_space(v) for i, v in enumerate(space.spaces)})
    return Unbounded(shape=getattr(space, "shape", ()) or (), dtype=getattr(space, "dtype", np.float32))


class GymWrapper:
    """Wrap a constructed gymnasium env into the host-env protocol:

    - ``reset(seed) -> obs_dict``
    - ``step(action) -> (obs_dict, reward, terminated, truncated)``
    - spec properties matching :class:`rl_tpu.envs.EnvBase`'s contract.

    Observations are exposed under "observation" (Dict spaces keep their
    own keys), mirroring the reference's key conventions.
    """

    def __init__(self, env: Any):
        self.env = env
        self._obs_spec = spec_from_gym_space(env.observation_space)
        self._action_spec = spec_from_gym_space(env.action_space)
        self._action_start = int(getattr(env.action_space, "start", 0) or 0)
        self._obs_is_tuple = type(env.observation_space).__name__ == "Tuple"

    # -- specs ----------------------------------------------------------------

    @property
    def observation_spec(self) -> Composite:
        if isinstance(self._obs_spec, Composite):
            return self._obs_spec
        return Composite(observation=self._obs_spec)

    @property
    def action_spec(self):
        return self._action_spec

    @property
    def batch_shape(self) -> tuple:
        return ()

    # -- host protocol --------------------------------------------------------

    def _obs_dict(self, obs) -> dict:
        if isinstance(obs, dict):
            return dict(obs)
        if self._obs_is_tuple:  # keys match the Composite spec ("0","1",…)
            return {str(i): np.asarray(o) for i, o in enumerate(obs)}
        return {"observation": np.asarray(obs)}

    def reset(self, seed: int | None = None) -> dict:
        obs, _info = self.env.reset(seed=seed)
        return self._obs_dict(obs)

    def step(self, action) -> tuple[dict, float, bool, bool]:
        a = np.asarray(action)
        if isinstance(self._action_spec, Categorical):
            a = a + self._action_start  # gym Discrete.start offset
            if a.ndim == 0:
                a = a.item()
        obs, reward, terminated, truncated, _info = self.env.step(a)
        return self._obs_dict(obs), float(reward), bool(terminated), bool(truncated)

    def close(self) -> None:
        self.env.close()


class GymEnv(GymWrapper):
    """Build from an env id (reference GymEnv, gym.py:1805)."""

    def __init__(self, env_id: str, **kwargs):
        import gymnasium

        super().__init__(gymnasium.make(env_id, **kwargs))
        self.env_id = env_id
