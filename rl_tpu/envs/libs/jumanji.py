"""Jumanji bridge: JAX logic/routing envs as pure-functional EnvBase.

Redesign of the reference's JumanjiEnv (reference: torchrl/envs/libs/
jumanji.py:765 — converts jumanji's functional (state, timestep) protocol
to the stateful torch env, with spec translation from jumanji.specs). Like
brax, jumanji is already functional JAX, so the bridge relabels:
``env.reset(key) -> (state, timestep)`` / ``env.step(state, action)`` map
directly onto the EnvBase hooks and run inside the fused program.

Import-gated: jumanji is optional; construction raises ImportError.

STATUS — EXPERIMENTAL: jumanji is not in this image, so this bridge has
never executed against the real library. It IS contract-tested against
an in-repo fake implementing exactly the API surface it touches
(tests/fakes/, tests/test_brax_jumanji.py) — spec extraction, step
conversion, and termination/truncation mapping all run; real-library
behavior may still differ in untested corners.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ...data import ArrayDict, Bounded, Categorical, Composite, Unbounded
from ..base import EnvBase
from ._pytree import flatten_state, unflatten_state

__all__ = ["JumanjiEnv", "spec_from_jumanji"]


def spec_from_jumanji(spec) -> Any:
    """jumanji.specs.* -> rl_tpu spec (reference _jumanji_to_torchrl_spec)."""
    kind = type(spec).__name__
    if kind == "DiscreteArray":
        return Categorical(n=int(spec.num_values), shape=(), dtype=jnp.int32)
    if kind == "BoundedArray":
        return Bounded(
            shape=tuple(spec.shape),
            low=jnp.asarray(spec.minimum),
            high=jnp.asarray(spec.maximum),
            dtype=spec.dtype,
        )
    if kind == "Array":
        return Unbounded(shape=tuple(spec.shape), dtype=spec.dtype)
    if hasattr(spec, "_specs"):  # nested dict spec
        return Composite(**{k: spec_from_jumanji(v) for k, v in spec._specs.items()})
    raise NotImplementedError(f"jumanji spec {kind} not mapped")


class JumanjiEnv(EnvBase):
    """``JumanjiEnv("Snake-v1")`` — any registered jumanji env."""

    def __init__(self, env_name: str, **kwargs):
        try:
            import jumanji
        except ImportError as e:  # pragma: no cover - optional dep
            raise ImportError(
                "JumanjiEnv requires the 'jumanji' package (not in this image)"
            ) from e
        self._env = jumanji.make(env_name, **kwargs)
        self.env_name = env_name

    @property
    def observation_spec(self) -> Composite:
        spec = spec_from_jumanji(self._env.observation_spec)
        if not isinstance(spec, Composite):
            spec = Composite(observation=spec)
        return spec

    @property
    def action_spec(self):
        return spec_from_jumanji(self._env.action_spec)

    def _obs_td(self, timestep) -> ArrayDict:
        obs = timestep.observation
        if hasattr(obs, "_asdict"):
            return ArrayDict({k: v for k, v in obs._asdict().items()})
        return ArrayDict(observation=obs)

    def _reset(self, key: jax.Array):
        state, timestep = self._env.reset(key)
        return ArrayDict(jumanji=flatten_state(state)), self._obs_td(timestep)

    def _step(self, state: ArrayDict, action: Any, key: jax.Array):
        jstate = unflatten_state(self._state_struct(), state["jumanji"])
        jstate, timestep = self._env.step(jstate, action)
        # dm_env semantics: step_type LAST(2) = episode end; discount>0 at
        # LAST means truncation (bootstrap survives), discount==0 termination
        last = timestep.step_type == 2
        disc = jnp.asarray(timestep.discount, jnp.float32)
        disc0 = disc if disc.ndim == 0 else disc.reshape(-1)[0]
        term = jnp.logical_and(last, disc0 == 0.0)
        trunc = jnp.logical_and(last, disc0 > 0.0)
        return (
            ArrayDict(jumanji=flatten_state(jstate)),
            self._obs_td(timestep),
            jnp.asarray(timestep.reward, jnp.float32),
            term,
            trunc,
        )

    def _state_struct(self):
        if not hasattr(self, "_struct"):
            self._struct = jax.eval_shape(
                lambda k: self._env.reset(k)[0], jax.random.key(0)
            )
        return self._struct
