"""PettingZoo bridge: multi-agent host envs behind the framework contract.

Redesign of the reference's wrapper (reference: torchrl/envs/libs/
pettingzoo.py:852 ``PettingZooEnv`` — supports both AEC turn-based and
parallel APIs with group-mapping machinery). Host-side like the gym bridge:
numpy in/out, consumed by HostCollector / ThreadedEnvPool.

Two modes, mirroring the reference:

- **AEC (turn-based)**: one agent acts per step; the observation exposes the
  current agent's view, its "action_mask" (legal moves), and "turn" (agent
  index). The scalar "reward" is the ACTING agent's reward accumulated
  since its previous turn (including this step); because other agents can
  accrue rewards during someone else's turn (zero-sum terminal credit),
  every transition also exposes the full per-agent outstanding-reward
  vector under "agent_rewards" — learners for turn-based games should read
  their column from it.
- **Parallel**: all agents act each step; per-agent leaves are stacked on a
  leading agent axis under ("agents", ...), team reward = sum — matching the
  native multi-agent layout (NavigationEnv).
"""

from __future__ import annotations

import numpy as np

from ...data import Categorical, Composite
from ...data.specs import Binary
from .gym import spec_from_gym_space

__all__ = ["PettingZooWrapper", "PettingZooEnv"]


class PettingZooWrapper:
    """Wrap a constructed PettingZoo env (AEC or parallel API)."""

    def __init__(self, env):
        self.env = env
        self._acc: dict = {}
        self._saw_term = False
        # AEC envs expose per-agent ``observe``; parallel envs do not
        self.is_parallel = not hasattr(env, "observe")
        self.agents = list(env.possible_agents)
        self._agent_obs_specs = [
            spec_from_gym_space(env.observation_space(a)) for a in self.agents
        ]
        self._agent_action_specs = [
            spec_from_gym_space(env.action_space(a)) for a in self.agents
        ]
        # ragged groups (different per-agent spaces) take the mask-backed
        # Stacked/StackedComposite path (reference pettingzoo.py stacks
        # hetero agents lazily; here: dense padding + static masks).
        # Tracked per side: obs and action spaces can be ragged independently
        self.hetero_obs = any(
            s != self._agent_obs_specs[0] for s in self._agent_obs_specs[1:]
        )
        self.hetero_act = any(
            s != self._agent_action_specs[0]
            for s in self._agent_action_specs[1:]
        )
        self.heterogeneous = self.hetero_obs or self.hetero_act
        self._stacked_obs_spec = None  # built lazily once (static afterwards)
        self._per_agent_obs_spec = self._agent_obs_specs[0]
        self._action_spec = self._agent_action_specs[0]
        # AEC envs with masked discrete actions expose Dict({observation, action_mask})
        self._masked = (
            isinstance(self._per_agent_obs_spec, Composite)
            and "action_mask" in self._per_agent_obs_spec
        )

    # -- specs ----------------------------------------------------------------

    @property
    def observation_spec(self) -> Composite:
        if self.is_parallel:
            # static after __init__ and cached BEFORE any construction —
            # _pad_rows reads this on the host hot path every step
            if self._stacked_obs_spec is not None:
                return self._stacked_obs_spec
            import dataclasses

            from ...data import stack_specs

            per_all = [
                s if isinstance(s, Composite) else Composite(observation=s)
                for s in self._agent_obs_specs
            ]
            if self.hetero_obs:
                # ragged group: StackedComposite via stack_specs (padded +
                # static masks; see data/hetero.py)
                spec = Composite(agents=stack_specs(per_all))
            else:
                n = len(self.agents)
                per = per_all[0]
                spec = Composite(
                    agents=Composite(
                        {
                            k: dataclasses.replace(v, shape=(n,) + v.shape)
                            for k, v in per.items()
                        }
                    )
                )
            self._stacked_obs_spec = spec
            return spec
        import numpy as np

        from ...data import Unbounded

        spec = self._per_agent_obs_spec
        if not isinstance(spec, Composite):
            spec = Composite(observation=spec)
        if "action_mask" in spec:
            spec = spec.set("action_mask", Binary(shape=spec["action_mask"].shape))
        spec = spec.set("turn", Categorical(n=len(self.agents)))
        return spec.set(
            "agent_rewards", Unbounded(shape=(len(self.agents),), dtype=np.float32)
        )

    @property
    def action_spec(self):
        if self.is_parallel:
            import dataclasses

            if self.hetero_act:
                from ...data import stack_specs

                return stack_specs(list(self._agent_action_specs))
            return dataclasses.replace(
                self._action_spec, shape=(len(self.agents),) + self._action_spec.shape
            )
        return self._action_spec

    @property
    def batch_shape(self) -> tuple:
        return ()

    # -- host protocol (AEC) ---------------------------------------------------

    def _aec_obs(self) -> dict:
        agent = self.env.agent_selection
        raw = self.env.observe(agent)
        out = {}
        if isinstance(raw, dict):
            for k, v in raw.items():
                out[k] = np.asarray(v)
        else:
            out["observation"] = np.asarray(raw)
        if "action_mask" in out:
            out["action_mask"] = out["action_mask"].astype(bool)
        out["turn"] = np.asarray(self.agents.index(agent), np.int32)
        out["agent_rewards"] = np.asarray(
            [self._acc.get(a, 0.0) for a in self.agents], np.float32
        )
        return out

    def reset(self, seed: int | None = None) -> dict:
        self._acc = {a: 0.0 for a in self.agents}
        self._saw_term = False  # any true termination this episode (AEC)
        if self.is_parallel:
            obs, _ = self.env.reset(seed=seed)
            return self._stack_parallel(obs)
        self.env.reset(seed=seed)
        return self._aec_obs()

    def step(self, action):
        if self.is_parallel:
            return self._step_parallel(action)
        agent = self.env.agent_selection
        # AEC API: a terminated/truncated agent's only legal action is None
        dead = self.env.terminations.get(agent, False) or self.env.truncations.get(
            agent, False
        )
        if dead:
            self.env.step(None)
        else:
            a = np.asarray(action)
            self.env.step(a.item() if a.ndim == 0 else a)
        # rewards can be assigned to ANY agent on this step (terminal credit
        # in zero-sum games lands during the winner's move) — accumulate all,
        # emit + clear the acting agent's total
        for ag, r in self.env.rewards.items():
            self._acc[ag] = self._acc.get(ag, 0.0) + float(r)
        reward = self._acc.get(agent, 0.0)
        self._acc[agent] = 0.0
        # accumulate: pettingzoo deletes a dead agent's dict entries once it
        # is removed, so the final step can no longer see who terminated
        self._saw_term = self._saw_term or any(self.env.terminations.values())
        trunc = bool(self.env.truncations.get(agent, False))
        done_all = not self.env.agents or all(
            self.env.terminations.get(a, False) or self.env.truncations.get(a, False)
            for a in self.env.agents
        )
        if done_all:
            obs = self._aec_obs() if self.env.agents else self._terminal_obs()
            # terminated only if some agent truly terminated; a pure
            # time-limit end must stay truncation-only (bootstrap survives)
            return obs, reward, self._saw_term, trunc or not self._saw_term
        return self._aec_obs(), reward, False, trunc

    def _terminal_obs(self) -> dict:
        spec = self.observation_spec
        out = {}
        for k in spec.keys(nested=True, leaves_only=True):
            leaf = spec[k]
            out[k[0] if len(k) == 1 else k] = np.zeros(
                leaf.shape, getattr(leaf, "dtype", np.float32)
            )
        if not self.is_parallel:
            # surface outstanding terminal credit (e.g. the loser's -1)
            out["agent_rewards"] = np.asarray(
                [self._acc.get(a, 0.0) for a in self.agents], np.float32
            )
        return out

    # -- host protocol (parallel) ----------------------------------------------

    def _pad_rows(self, rows: list, key: tuple) -> np.ndarray:
        """Stack per-agent leaves; hetero groups pad each row into its
        member region of the spec's padded shape (dense + static mask —
        the mask itself comes from observation_spec["agents"].masks())."""
        if not self.hetero_obs:
            return np.stack(rows)
        spec = self.observation_spec["agents"][key]
        out = np.zeros(spec.shape, np.asarray(rows[0]).dtype)
        for i, r in enumerate(rows):
            r = np.asarray(r)
            out[(i,) + tuple(slice(0, d) for d in r.shape)] = r
        return out

    def _stack_parallel(self, obs: dict) -> dict:
        # fixed (n_agents, ...) layout: dead agents' rows are zero-filled
        # (parallel envs drop them from the obs dict mid-episode)
        example = next(iter(obs.values()))
        specs = self._agent_obs_specs
        per = [obs.get(a) for a in self.agents]

        def zero_fill(i, k):
            """Dead-agent / absent-key fill with the SPEC's shape+dtype —
            never a float32 guess (the stacked data must stay in-spec)."""
            s = specs[i]
            if isinstance(s, Composite) and k in s:
                leaf = s[k]
                return np.zeros(leaf.shape, leaf.dtype)
            if not isinstance(s, Composite) and k == "observation":
                return np.zeros(s.shape, s.dtype)
            # the member genuinely lacks this key: zero-size region of the
            # dtype some other member declares for it
            for so in specs:
                if isinstance(so, Composite) and k in so:
                    leaf = so[k]
                    return np.zeros((0,) * len(leaf.shape), leaf.dtype)
            return np.zeros((0,), np.float32)

        if isinstance(example, dict):
            keys = {k for p in per if isinstance(p, dict) for k in p}
            return {
                ("agents", k): self._pad_rows(
                    [
                        np.asarray(p[k])
                        if p is not None and k in p
                        else zero_fill(i, k)
                        for i, p in enumerate(per)
                    ],
                    (k,),
                )
                for k in keys
            }
        return {
            ("agents", "observation"): self._pad_rows(
                [
                    np.asarray(p) if p is not None else zero_fill(i, "observation")
                    for i, p in enumerate(per)
                ],
                ("observation",),
            )
        }

    def _step_parallel(self, action):
        # only LIVE agents receive actions (dead ones are dropped by the env)
        live = list(self.env.agents)

        def member_action(i):
            a = np.asarray(action[i])
            spec = self._agent_action_specs[i]
            if self.hetero_act and a.shape != tuple(spec.shape):
                # padded hetero row: the agent's true action is its member
                # region (leading slice per dim)
                a = a[tuple(slice(0, d) for d in spec.shape)]
            return a

        acts = {
            a: member_action(self.agents.index(a)) for a in live
        }
        obs, rewards, terms, truncs, _ = self.env.step(acts)
        reward = float(sum(rewards.values()))
        # standard parallel envs return the FINAL obs together with the done
        # flags (agents may or may not already be dropped from env.agents)
        done = not self.env.agents or (
            bool(terms)
            and all(terms.get(a, False) or truncs.get(a, False) for a in terms)
        )
        if done:
            # slot 3 of the host protocol is TERMINATED (cuts value
            # bootstrap): ANY true termination must cut it, even if other
            # agents were only truncated; a pure time-limit end stays
            # truncation-only
            term = bool(any(terms.values()))
            trunc = bool(any(truncs.values())) or not term
            final = self._stack_parallel(obs) if obs else self._terminal_obs()
            return final, reward, term, trunc
        return self._stack_parallel(obs), reward, False, False

    def close(self) -> None:
        self.env.close()


class PettingZooEnv(PettingZooWrapper):
    """Build from a task name, e.g. ``PettingZooEnv("classic/tictactoe_v3")``
    (reference PettingZooEnv's task= constructor)."""

    def __init__(self, task: str, parallel: bool = False, **kwargs):
        import importlib

        family, name = task.split("/")
        mod = importlib.import_module(f"pettingzoo.{family}.{name}")
        if parallel:
            env = mod.parallel_env(**kwargs)
        else:
            env = mod.env(**kwargs)
        super().__init__(env)
        self.task = task
