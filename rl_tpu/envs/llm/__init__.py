from .chat import ChatEnv, DatasetChatEnv
from .datasets import QADataset, arithmetic_dataset, copy_dataset
from .reward import ExactMatchScorer, FormatScorer, SumScorer, combine_scorers
from .transforms import KLRewardTransform, PolicyVersion, PythonToolTransform

__all__ = [
    "ChatEnv",
    "DatasetChatEnv",
    "QADataset",
    "arithmetic_dataset",
    "copy_dataset",
    "ExactMatchScorer",
    "FormatScorer",
    "SumScorer",
    "combine_scorers",
    "KLRewardTransform",
    "PolicyVersion",
    "PythonToolTransform",
]
