from .chat import ChatEnv, DatasetChatEnv

__all__ = ["ChatEnv", "DatasetChatEnv"]
