from .chat import ChatEnv, DatasetChatEnv
from .datasets import (QADataset, TopKRewardSelector, arithmetic_dataset,
                       copy_dataset, countdown_dataset, gsm8k_dataset,
                       ifeval_dataset, math_expression_dataset)
from .reward import (CountdownScorer, ExactMatchScorer, FormatScorer,
                     GSM8KScorer, IFEvalScorer,
                     SumScorer, combine_scorers, extract_gsm8k_answer)
from .transforms import (AdaptiveKLController, ConstantKLController,
                         KLRewardTransform, PolicyVersion, PythonToolTransform)

__all__ = [
    "ChatEnv",
    "DatasetChatEnv",
    "QADataset",
    "arithmetic_dataset",
    "copy_dataset",
    "countdown_dataset",
    "gsm8k_dataset",
    "ifeval_dataset",
    "math_expression_dataset",
    "ExactMatchScorer",
    "FormatScorer",
    "CountdownScorer",
    "GSM8KScorer",
    "IFEvalScorer",
    "SumScorer",
    "extract_gsm8k_answer",
    "combine_scorers",
    "AdaptiveKLController",
    "ConstantKLController",
    "KLRewardTransform",
    "TopKRewardSelector",
    "PolicyVersion",
    "PythonToolTransform",
]
