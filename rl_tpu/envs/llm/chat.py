"""Conversation environments for RLHF.

Redesign of the reference's LLM env layer (reference: torchrl/envs/llm/
chat.py:60 ``ChatEnv`` — conversation-state env over ``History``;
``DatasetChatEnv``:542; reward scorers under envs/llm/reward/).

These are **host-side** envs (strings and tokenizers never enter XLA): reset
serves tokenized prompts, step receives generated response tokens, decodes,
appends to the history, scores. The device side (generation, loss) consumes
the produced arrays; the :class:`rl_tpu.collectors.LLMCollector` owns the
handoff.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from ...data.llm.history import History

__all__ = ["ChatEnv", "DatasetChatEnv"]


class ChatEnv:
    """Single/multi-turn chat env over History.

    Args:
        tokenizer: object with ``encode(str)->list[int]`` and optionally
            ``decode(list[int])->str`` (identity fallback for token-level
            rewards).
        reward_fn: ``(history, response_tokens) -> float`` scored at each
            step (rule-based scorers, reward models, format checks).
        max_turns: episode ends after this many assistant turns.
    """

    def __init__(
        self,
        tokenizer: Any,
        reward_fn: Callable[[History, np.ndarray], float],
        max_prompt_len: int = 256,
        max_turns: int = 1,
    ):
        self.tokenizer = tokenizer
        self.reward_fn = reward_fn
        self.max_prompt_len = max_prompt_len
        self.max_turns = max_turns

    # -- protocol -------------------------------------------------------------

    def reset(self, histories: Sequence[History]) -> dict:
        """Tokenize prompt histories (left-padded, generation prompt added)."""
        batch = History.batch_tokenize(
            list(histories),
            self.tokenizer,
            max_len=self.max_prompt_len,
            add_generation_prompt=True,
        )
        return {
            "histories": list(histories),
            "turns": np.zeros(len(histories), np.int32),
            **batch,
        }

    def _score_one(self, history: History, tokens_row: np.ndarray, mask_row: np.ndarray) -> tuple[History, float]:
        toks = tokens_row[mask_row.astype(bool)]
        text = (
            self.tokenizer.decode(toks.tolist())
            if hasattr(self.tokenizer, "decode")
            else " ".join(map(str, toks.tolist()))
        )
        h2 = history.append("assistant", text)
        return h2, self.reward_fn(h2, toks)

    def score_rows(
        self,
        state: dict,
        response_tokens: np.ndarray,
        response_mask: np.ndarray,
        rows: Sequence[int],
    ) -> np.ndarray:
        """Score a SUBSET of the batch (first-come group harvesting: the
        collector scores each prompt group as its last response completes,
        overlapping host reward work with the remaining decode). Row
        arrays are indexed by the FULL batch position; returns rewards
        aligned with ``rows``. State histories are not advanced — this is
        the scoring half of :meth:`step` only."""
        rewards = np.zeros(len(rows), np.float32)
        for j, i in enumerate(rows):
            _, rewards[j] = self._score_one(
                state["histories"][i], response_tokens[i], response_mask[i]
            )
        return rewards

    def step(self, state: dict, response_tokens: np.ndarray, response_mask: np.ndarray) -> tuple[dict, np.ndarray, np.ndarray]:
        """Append responses, score, report done. Returns (state, reward, done)."""
        histories = []
        rewards = np.zeros(len(state["histories"]), np.float32)
        for i, h in enumerate(state["histories"]):
            h2, rewards[i] = self._score_one(h, response_tokens[i], response_mask[i])
            histories.append(h2)
        turns = state["turns"] + 1
        done = turns >= self.max_turns
        new_state = dict(state)
        new_state.update(histories=histories, turns=turns)
        return new_state, rewards, done


class DatasetChatEnv(ChatEnv):
    """ChatEnv over a prompt dataset (reference DatasetChatEnv:542): each
    reset draws a batch of prompts (optionally repeated ``group_repeats``
    times for GRPO prompt groups)."""

    def __init__(
        self,
        prompts: Sequence[History],
        tokenizer: Any,
        reward_fn: Callable,
        group_repeats: int = 1,
        seed: int = 0,
        **kw,
    ):
        super().__init__(tokenizer, reward_fn, **kw)
        self.prompts = list(prompts)
        self.group_repeats = group_repeats
        self._rng = np.random.default_rng(seed)

    def sample_batch(self, num_prompts: int) -> tuple[dict, np.ndarray]:
        """Draw prompts and repeat each ``group_repeats`` times.
        Returns (reset state, group_ids [num_prompts*repeats])."""
        idx = self._rng.integers(0, len(self.prompts), num_prompts)
        hs = []
        gids = []
        for g, i in enumerate(idx):
            for _ in range(self.group_repeats):
                hs.append(self.prompts[int(i)])
                gids.append(g)
        return self.reset(hs), np.asarray(gids, np.int32)
